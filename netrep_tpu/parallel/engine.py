"""The TPU-native permutation engine — the rebuild of the reference's C++
``PermutationProcedure`` hot path (SURVEY.md §2.2, §3.1; BASELINE.json:5).

Reference design → TPU design:

- OpenMP threads claiming permutation chunks → ``vmap`` over a permutation
  chunk, jit-compiled once per module-size bucket, dispatched chunk-by-chunk
  from the host (SURVEY.md §2.3 row "data parallelism over permutations").
- Per-permutation Armadillo submatrix gathers + SVD → fused XLA gather +
  masked power iteration inside the vmapped kernel
  (:func:`netrep_tpu.ops.stats.gather_and_stats`).
- Disjoint null-array slices per thread → functional: each chunk returns its
  slice, the host writes it into the preallocated null array.
- Progress/interrupt polling from the R-facing thread → chunked dispatch:
  Python regains control between device calls, so ``KeyboardInterrupt``
  aborts cleanly with partial nulls retained (SURVEY.md §5).
- Variable module sizes vs XLA static shapes → pad-to-bucket + masks
  (SURVEY.md §7 "Hard parts"): modules are grouped into power-of-two-capacity
  buckets; each bucket traces/compiles exactly once per chunk shape.

Optional SPMD scale-out: pass a :class:`jax.sharding.Mesh` and the chunk's
per-permutation key array is sharded along the mesh's permutation axis, so
XLA partitions the whole chunk computation across devices over ICI
(SURVEY.md §2.3, §5 "distributed communication backend").
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import time
from functools import partial
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import stats as jstats
from ..ops.oracle import N_STATS
from ..utils import faults as flt
from ..utils import telemetry as tm
from ..utils.config import EngineConfig

logger = logging.getLogger("netrep_tpu")


def _telemetry_profile(telemetry, profile):
    """Resolve the run's telemetry bus (explicit or ambient, ONCE — the
    disabled hot path pays a single ``None`` check per run) and, when
    telemetry is on, ensure a :class:`~netrep_tpu.utils.profiling.NullProfile`
    exists so dispatch/host-byte counters can fold into chunk events even
    when the caller didn't ask for one."""
    tel = tm.resolve(telemetry)
    if tel is not None and profile is None:
        from ..utils.profiling import NullProfile

        profile = NullProfile()
    return tel, profile


def _profile_totals(profile) -> tuple[int, int]:
    return (
        (profile.dispatches, profile.host_bytes)
        if profile is not None else (0, 0)
    )


def _mem_probe(telemetry):
    """Per-run device-memory gauge probe (None when telemetry is off or
    the backend exposes no memory accounting) — resolved once per run so
    the per-chunk cost is a dict build, not a capability probe. The
    always-on flight bus (ISSUE 20) does NOT arm the probe: flight-only
    capture must stay pure host-side bookkeeping."""
    if telemetry is None or getattr(telemetry, "flight_only", False):
        return None
    from ..utils.profiling import make_memory_probe

    return make_memory_probe()


def _engine_fingerprint_key(base) -> str:
    """The engine's autotune/compile-cache fingerprint (backend × gather
    mode × bucket signature × chunk) — the key compile_span events and
    perf-ledger entries are grouped by; '' for engines without one (the
    native C++ tier)."""
    key_fn = getattr(base, "autotune_key", None)
    if not callable(key_fn):
        return ""
    try:
        return str(key_fn())
    # netrep: allow(exception-taxonomy) — third-party engine key probe: '' only disables fingerprint grouping, never the run
    except Exception:
        return ""


#: which acquired program drives each null-loop mode — the ``source``
#: tag on its ``compile_span`` event comes from that program's
#: acquisition (:meth:`PermutationEngine._acquire_program`)
_MODE_PROGRAM = {
    "materialized": "chunk",
    "adaptive": "chunk",
    "streaming": "super",
    "adaptive-streaming": "count",
}


def _run_program_source(base, mode: str) -> str:
    """``aot`` (deserialized from the AOT store), ``memo`` (in-process
    reuse — warm pool / repeat run), or ``jit`` (compiled fresh; also
    every engine without the acquisition seam). Adaptive retirement
    re-acquires shrunken programs mid-run, so the tag reflects the most
    recent acquisition of the mode's program."""
    srcs = getattr(base, "_program_sources", None)
    if not srcs:
        return "jit"
    return srcs.get(_MODE_PROGRAM.get(mode, "chunk"), "jit")


def _run_cost_tracker(base, telemetry):
    """Per-run roofline cost tracker (ISSUE 18), resolved once inside the
    telemetry branch — the disabled hot path keeps its single ``None``
    check (PR 3 contract) and native engines without the analytic model
    get None (cost fields omitted, never guessed). Flight-only runs
    (ISSUE 20) get None too: the recorder must not write the process
    roofline note an explicitly-instrumented run would otherwise own."""
    if telemetry is None or getattr(telemetry, "flight_only", False):
        return None
    from ..utils import costmodel

    return costmodel.tracker_for(base)


def _finish_run_accounting(base, telemetry, run_sid, t_marks, t_run0,
                           start_perm, n_perm, mode,
                           tracker=None) -> None:
    """End-of-run compile estimate + perf-ledger feed (ISSUE 5), emitted
    only when telemetry is on and at least two chunks landed.

    The null loops have always distinguished the first (compile-absorbing)
    interval from steady state for the autotune cache; this promotes the
    distinction into an explicit ``compile_span`` event: the steady-state
    rate over marks 0→last prices the first chunk's *compute*, and the
    first interval's surplus over that price is the jit-compile estimate,
    keyed by the engine's autotune/compile-cache fingerprint. Since
    ISSUE 15 the event also carries the run program's acquisition
    ``source`` (``aot``/``jit``/``memo``) and the ledger fingerprint is
    suffixed with it — warm and cold compile histories never mix in the
    regression check or the time split. The same numbers feed the
    append-only perf ledger (:mod:`netrep_tpu.utils.perfledger`) when
    ``NETREP_PERF_LEDGER`` names one — every telemetry-enabled run leaves
    a throughput fingerprint CI can regression-check."""
    if telemetry is None or len(t_marks) < 2:
        return
    (c0, t0), (c1, t1) = t_marks[0], t_marks[-1]
    if t1 <= t0 or c1 <= c0:
        return
    rate = (c1 - c0) / (t1 - t0)
    first_s = t_marks[0][1] - t_run0
    compile_s = max(0.0, first_s - (t_marks[0][0] - start_perm) / rate)
    fp = _engine_fingerprint_key(base)
    src = _run_program_source(base, mode)
    telemetry.emit("compile_span", parent=run_sid, s=compile_s, key=fp,
                   mode=mode, source=src)
    roofline = None
    if tracker is not None:
        # the run's roofline verdict (ISSUE 18): the analytic per-perm
        # model against the device's speed of light, judged at the
        # steady-state rate (same marks as the ledger entry). Recorded as
        # the process's last-run note so bench rows and fleet stats()
        # read the gauge without re-deriving it.
        from ..utils import costmodel

        roofline = tracker.roofline_block(rate)
        telemetry.emit("roofline", parent=run_sid, mode=mode, **roofline)
        costmodel.record_run_note(roofline)
    if getattr(telemetry, "flight_only", False):
        # flight-only runs (ISSUE 20) never feed the perf ledger: the
        # always-on recorder must not grow regression history that only
        # deliberately-instrumented runs used to produce
        return
    from ..utils import perfledger

    perfledger.maybe_record_run(
        run_id=telemetry.run_id,
        fingerprint=f"{fp}|src:{src}" if fp else fp, mode=mode,
        perms_per_sec=rate, compile_s=compile_s, n_perm=int(n_perm),
        backend=jax.default_backend(), roofline=roofline,
    )


def run_checkpointed_chunks(
    base: "PermutationEngine",
    n_perm: int,
    key,
    fn: Callable,
    alloc_shape: tuple[int, ...],
    write: Callable[[np.ndarray, list, int, int], None],
    progress: Callable[[int, int], None] | None = None,
    nulls_init: np.ndarray | None = None,
    start_perm: int = 0,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 8192,
    perm_axis: int = 0,
    fingerprint_extra: bytes = b"",
    profile=None,
    telemetry=None,
    fault_policy=None,
    extra_state=None,
) -> tuple[np.ndarray, int]:
    """The single chunked/interruptible/checkpointable null loop shared by
    :class:`PermutationEngine` and ``MultiTestEngine`` (one implementation so
    the two paths cannot drift — SURVEY.md §5 "failure detection",
    "checkpoint/resume").

    ``fn(keys) -> outs`` evaluates one chunk; ``write(nulls, outs, done,
    take)`` scatters the chunk into the preallocated ``nulls`` array;
    ``alloc_shape`` allocates it when neither ``nulls_init`` nor a readable
    checkpoint provides one; ``perm_axis`` locates the permutation axis in
    the null array; ``fingerprint_extra`` extends the engine fingerprint for
    wrappers whose problem has extra structure (e.g. the test-dataset count);
    ``profile`` (a :class:`~netrep_tpu.utils.profiling.NullProfile`) counts
    the dispatches this loop issues — two per chunk: key derivation + the
    chunk program (host-transfer bytes are counted by ``write``);
    ``telemetry`` (a :class:`~netrep_tpu.utils.telemetry.Telemetry`, or the
    ambient bus when None) gets per-chunk events with the profile's
    dispatch/host-byte deltas folded in, a run start/end envelope, and a
    stall watchdog armed for the run.

    ``fault_policy`` (a :class:`~netrep_tpu.utils.config.FaultPolicy` /
    :class:`~netrep_tpu.utils.faults.FaultRuntime`, or None for the
    bit-identical default path) wraps every chunk dispatch in the
    retry/abandon/degrade ladder of :mod:`netrep_tpu.utils.faults` —
    transient failures re-dispatch the same ``fold_in`` keys after
    backoff, hung dispatches are abandoned after an emergency checkpoint,
    and device-loss failures raise
    :class:`~netrep_tpu.utils.faults.DeviceLostError` past the
    failure-save hook below. With a policy active the dispatch is also
    blocked-until-ready inside the retry scope, trading the
    double-buffer overlap for a retryable failure envelope.

    ``extra_state`` (ISSUE 16): an object with ``state_arrays() -> dict``
    / ``restore_state(extras)`` whose arrays ride the checkpoint ``extra``
    dict — the screened-null rescue tally uses it so a resumed run
    reports whole-run screening statistics.
    """
    key = _resolve_key(base, key)
    telemetry, profile = _telemetry_profile(telemetry, profile)
    ft = flt.resolve_runtime(fault_policy)

    save = None
    loaded = None
    writer = None
    if checkpoint_path is not None:
        from ..utils import checkpoint as ckpt

        kd, fp = _checkpoint_identity(base, key, fingerprint_extra)
        loaded = ckpt.load_null_checkpoint(checkpoint_path)
        if loaded is not None:
            nulls_init, start_perm = ckpt.validate_resume(
                loaded, n_perm, kd, fp, checkpoint_path, perm_axis=perm_axis
            )
            if extra_state is not None:
                extra_state.restore_state(loaded.get("extras") or {})
        if ft is not None and ft.policy.async_checkpoint:
            # periodic saves ride a background writer so the loop never
            # stalls between dispatches on serialization (ISSUE 6);
            # rescue/failure paths flush it, finally closes it — after
            # which save() degrades to the synchronous path
            writer = ckpt.AsyncCheckpointWriter(telemetry)

        def save(nulls, done):
            extra = (
                extra_state.state_arrays() if extra_state is not None
                else None
            )
            ckpt.save_null_checkpoint(checkpoint_path, nulls, done, kd, fp,
                                      extra=extra, writer=writer)

    C = base.effective_chunk()
    # JAX engines keep the full chunk shape on the tail (fixed shapes hit the
    # compile cache); dynamic-shape engines (the native C++ backend) opt into
    # clamping so the tail doesn't burn up to chunk-1 wasted permutations.
    dynamic = getattr(base, "dynamic_chunk", False)
    nulls = nulls_init if nulls_init is not None else np.full(alloc_shape, np.nan)
    # Double-buffered loop: dispatch chunk k+1 (async on accelerators) BEFORE
    # the synchronous host transfer of chunk k in `write`, so device compute
    # overlaps the device→host copy. On the tunneled TPU backend the serial
    # transfer gap was ~25% of wall-clock (round-2 profile); on synchronous
    # backends (native C++) the order change is a no-op.
    dispatched = start_perm
    completed = start_perm
    last_saved = completed
    pending: tuple | None = None  # (outs, at, take)
    # (completed, wall-time) after each chunk lands: the steady-state
    # throughput between the first and last marks (first chunk's compile
    # excluded) feeds the persistent autotune cache (utils/autotune.py)
    t_marks: list[tuple[int, float]] = []

    def rescue():
        # emergency checkpoint of completed work — called from the fault
        # runtime (abandon path) or the watchdog thread (warn→act); only
        # committed state is touched, so it is safe while the loop thread
        # hangs inside a dispatch. Flushed: an emergency save must be on
        # disk, not queued, when the abandon/degrade decision lands.
        if save is not None and completed > last_saved:
            save(nulls, completed)
            if writer is not None:
                writer.flush()

    if ft is not None:
        action, act_factor = ft.watchdog_escalation(rescue)
        wd = tm.arm_watchdog(telemetry, action=action,
                             action_factor=act_factor)
    else:
        wd = tm.arm_watchdog(telemetry)
    prev_t = t_run0 = time.perf_counter()
    d0, b0 = prev_d, prev_b = _profile_totals(profile)
    run_sid = None
    mem = None
    tracker = _run_cost_tracker(base, telemetry)
    if telemetry is not None:
        run_sid = telemetry.begin_span(
            "null_run_start", mode="materialized", n_perm=int(n_perm),
            start_perm=int(start_perm),
        )
        mem = _mem_probe(telemetry)
    try:
        while dispatched < n_perm or pending is not None:
            if ft is not None and save is not None:
                # elastic grow-back (ISSUE 6): capacity returned — stop at
                # this chunk boundary; the failure-save hook below
                # checkpoints (pending chunk flushed first) and the API
                # layer rebuilds the grown mesh and resumes
                ft.check_grow()
            nxt = None
            if dispatched < n_perm:
                take = min(C, n_perm - dispatched)

                def _dispatch():
                    keys = base.perm_keys(
                        key, dispatched, take if dynamic else C
                    )
                    if ft is None:
                        return fn(keys)
                    return ft.run_dispatch(
                        lambda: fn(keys), start=dispatched, take=take,
                        telemetry=telemetry, rescue=rescue,
                    )

                if telemetry is None:
                    sid_c = None
                    outs = _dispatch()
                else:
                    # the chunk's span id is allocated at DISPATCH time and
                    # pushed for the dispatch's extent, so retry/fault/
                    # stall events fired inside nest under this chunk
                    sid_c = telemetry.new_span_id()
                    t_d0 = time.perf_counter()
                    with telemetry.pushed(sid_c):
                        outs = _dispatch()
                    telemetry.emit(
                        "dispatch", parent=sid_c,
                        s=time.perf_counter() - t_d0,
                        start=int(dispatched), take=int(take),
                    )
                nxt = (outs, dispatched, take, sid_c)
                dispatched += take
                if profile is not None:
                    profile.record_dispatch(2)  # key derivation + chunk
            if pending is not None:
                outs, at, take_p, sid_p = pending
                t_w0 = time.perf_counter() if telemetry is not None else 0.0
                write(nulls, outs, at, take_p)
                completed = at + take_p
                t_marks.append((completed, time.perf_counter()))
                if telemetry is not None:
                    now = t_marks[-1][1]
                    d, b = _profile_totals(profile)
                    telemetry.emit(
                        "chunk", done=int(completed), total=int(n_perm),
                        take=int(take_p), s=now - prev_t,
                        dispatches=d - prev_d, host_bytes=b - prev_b,
                        transfer_s=now - t_w0, span=sid_p, parent=run_sid,
                        **(tracker.chunk_fields(int(take_p), now - prev_t,
                                                profile)
                           if tracker is not None else {}),
                        **(mem() if mem is not None else {}),
                    )
                    prev_t, prev_d, prev_b = now, d, b
                    wd.beat()
                if progress is not None:
                    progress(completed, n_perm)
                if save is not None and completed - last_saved >= checkpoint_every:
                    save(nulls, completed)
                    last_saved = completed
            pending = nxt
    except KeyboardInterrupt:
        # the reference's clean Ctrl-C path (SURVEY.md §5): flush the
        # pending chunk (its compute is finished on synchronous backends and
        # already dispatched on async ones — write blocks only until the
        # device drains), then return the partial null; callers read the
        # completed count and keep finished work. A second Ctrl-C during the
        # flush abandons the pending chunk instead.
        if pending is not None:
            try:
                outs, at, take_p, _sid = pending
                write(nulls, outs, at, take_p)
                completed = at + take_p
            except KeyboardInterrupt:
                pass
    except BaseException:
        # failure-save hook (ISSUE 4): a crash or an unrecoverable fault
        # (incl. DeviceLostError headed for the CPU-degradation ladder)
        # must never lose completed permutations. The pending chunk's
        # compute finished before the failing dispatch — flush it too if
        # its transfer still succeeds (a truly dead device fails here;
        # the committed prefix is kept either way).
        if pending is not None:
            try:
                outs, at, take_p, _sid = pending
                write(nulls, outs, at, take_p)
                completed = at + take_p
            # netrep: allow(exception-taxonomy) — failure-unwind flush of already-computed work on a possibly-dead device; the original error re-raises just below
            except Exception:
                pass
        if save is not None and completed > last_saved:
            save(nulls, completed)
            last_saved = completed
        raise
    finally:
        if wd is not None:
            wd.stop()
        if writer is not None:
            # drains the queue (failure-saves included) BEFORE any raised
            # error reaches the resume logic; later saves run synchronously
            writer.close()
    if save is not None and completed > last_saved:
        save(nulls, completed)
    if telemetry is not None:
        d, b = _profile_totals(profile)
        _finish_run_accounting(base, telemetry, run_sid, t_marks, t_run0,
                               start_perm, n_perm, "materialized",
                               tracker=tracker)
        el = time.perf_counter() - t_run0
        telemetry.end_span(
            run_sid, "null_run_end", mode="materialized",
            completed=int(completed), n_perm=int(n_perm),
            s=el, dispatches=d - d0, host_bytes=b - b0,
            **(tracker.run_fields(el) if tracker is not None else {}),
        )
    record = getattr(base, "record_chunk_throughput", None)
    if record is not None:
        if len(t_marks) >= 2:
            # the interval BEFORE mark 0 absorbed the first chunk's compile,
            # so the span mark 0 → last mark is pure steady state — two
            # marks (one post-compile chunk interval) already measure a real
            # rate. The old `>= 3` guard silently dropped every short
            # autotuned run (e.g. superchunk-era chunk counts), starving the
            # cache of exactly the configurations it was added to learn.
            (c0, t0), (c1, t1) = t_marks[0], t_marks[-1]
            if t1 > t0 and c1 > c0:
                record((c1 - c0) / (t1 - t0))
        elif t_marks:
            logger.debug(
                "throughput not recorded: only %d chunk(s) completed, so no "
                "interval excludes the first chunk's compile time; run at "
                "least 2 chunks to feed the autotune cache", len(t_marks),
            )
    return nulls, completed


def _resolve_key(base, key):
    """Key-handling hooks let non-JAX engines (the native C++ backend) reuse
    the chunk loops with their own RNG-stream identity: ``prepare_key``
    normalizes the user seed, ``key_data`` (see
    :func:`_checkpoint_identity`) yields the array stored in checkpoints to
    refuse cross-stream resume."""
    prepare = getattr(base, "prepare_key", None)
    if prepare is not None:
        return prepare(key)
    if isinstance(key, int):
        # netrep: allow(rng-discipline) — THE seeding contract's root-key site: every fold_in stream derives from exactly this key
        return jax.random.key(key)
    return key


def _checkpoint_identity(base, key, fingerprint_extra: bytes):
    """(key_data, fingerprint) pair stored in / validated against null
    checkpoints — one derivation shared by the fixed and adaptive loops."""
    from ..utils import checkpoint as ckpt

    fp = ckpt.engine_fingerprint(base)
    if fingerprint_extra:
        fp = np.concatenate(
            [fp, np.frombuffer(fingerprint_extra, dtype=np.uint8)]
        )
    key_data = getattr(base, "key_data", None)
    kd = (
        np.asarray(key_data(key)) if key_data is not None
        else np.asarray(jax.random.key_data(key))
    )
    return kd, fp


# ---------------------------------------------------------------------------
# Superchunk executor / streaming tallies (store_nulls=False)
# ---------------------------------------------------------------------------

#: namespace prefix of the streaming-counts checkpoint identity: a
#: streaming checkpoint must never resume into a materialized run (its
#: "nulls" array is an empty placeholder — the resumed rows would be NaN)
#: and vice versa, so the two modes get disjoint fingerprints and the
#: mismatch raises before any work is lost.
_STREAM_FP = b"stream-counts|"


@dataclasses.dataclass
class StreamCounts:
    """Result of a streaming (``store_nulls=False``) null run: per-(module,
    statistic) exceedance tallies instead of the materialized null array.

    ``hi``/``lo`` count null draws ``>=`` / ``<=`` the observed statistic
    (both tails are kept — two-sided needs ``min`` of the *totals*) and
    ``eff`` the valid (non-NaN) draws per cell; shapes match one null row:
    ``(n_modules, 7)``, or ``(T, n_modules, 7)`` for the multi-test engine.
    Feed them to :func:`netrep_tpu.ops.pvalues.counts_pvalues` — for the
    same key they are bit-identical to
    :func:`~netrep_tpu.ops.pvalues.tail_counts` of the materialized run.
    ``n_perm_used``/``finished`` are set by the adaptive streaming loop.
    """

    hi: np.ndarray
    lo: np.ndarray
    eff: np.ndarray
    completed: int
    n_perm_used: np.ndarray | None = None
    finished: bool = True


def make_count_buckets(perm_axis: int):
    """Per-bucket on-device tally fold shared by the single-test
    (``perm_axis=0``: outputs ``(C, K_b, 7)``) and multi-test
    (``perm_axis=1``: outputs ``(T, C, K_b, 7)``) streaming paths: compare
    each chunk output against the observed statistics and reduce the
    permutation axis to ``(hi, lo, eff)`` int32 counts.

    Parity contract: comparisons run f32-vs-f32 on exactly the values the
    materialized path widens to f64 on the host (widening is exact), and
    NaN compares False on both tails there as here — so these counts equal
    :func:`netrep_tpu.ops.pvalues.tail_counts` of the same chunk's
    materialized rows, bit for bit. ``mask`` (perm-axis validity) excludes
    the padded tail draws the materialized path discards host-side.
    """

    def count_buckets(outs, obs, mask):
        res = []
        for o, ob in zip(outs, obs):
            shape = [1] * o.ndim
            shape[perm_axis] = mask.shape[0]
            sel = mask.reshape(shape)
            ob_b = jnp.expand_dims(ob, perm_axis)
            res.append((
                jnp.sum((o >= ob_b) & sel, axis=perm_axis, dtype=jnp.int32),
                jnp.sum((o <= ob_b) & sel, axis=perm_axis, dtype=jnp.int32),
                jnp.sum(~jnp.isnan(o) & sel, axis=perm_axis,
                        dtype=jnp.int32),
            ))
        return res
    return count_buckets


def shard_chunk_offset(axis_name, local_count: int):
    """Global permutation-column offset of THIS shard's chunk slice inside
    ``shard_map`` — shared by the count fold's validity mask and the fused
    counter. ``axis_name`` may be one mesh axis (the perm-sharded fused
    gather path) or a tuple (the ring path, where the chunk splits over
    perm × row): the combined shard index follows the same major-to-minor
    order ``P((a0, a1))`` splits an array axis by."""
    names = (
        axis_name if isinstance(axis_name, (tuple, list)) else (axis_name,)
    )
    idx = jnp.int32(0)
    for nm in names:
        idx = idx * jax.lax.psum(jnp.int32(1), nm) + jax.lax.axis_index(nm)
    return idx * local_count


def chunk_count_deltas(chunk, count_buckets, axis_name, keys_c, valid_c,
                       chunk_ops, obs):
    """Evaluate one chunk and reduce it to per-bucket ``(hi, lo, eff)``
    count deltas on device — the shared body of the fixed superchunk scan
    and the adaptive per-chunk count dispatch. ``axis_name`` is set only
    under ``shard_map`` (the fused replicated-matrices path, or — as an
    axis tuple — the ring-exchange row-sharded path): the validity mask
    then offsets by the shard's column position and the per-shard partial
    counts ``psum`` into full-chunk counts."""
    outs = chunk(keys_c, *chunk_ops)
    col = jnp.arange(keys_c.shape[0], dtype=jnp.int32)
    if axis_name is not None:
        col = col + shard_chunk_offset(axis_name, keys_c.shape[0])
    mask = col < valid_c
    deltas = count_buckets(outs, obs, mask)
    if axis_name is not None:
        deltas = jax.lax.psum(deltas, axis_name)
    return deltas


def build_stream_super(chunk, count_buckets, axis_name=None,
                       count_chunk=None):
    """The superchunk program: ``jax.lax.scan`` over K consecutive
    permutation chunks in ONE device dispatch, the carry holding the
    running per-(module, statistic) tallies — K× fewer host round-trips
    than the chunk-by-chunk loop while the working set stays one chunk of
    HBM (the scan body materializes a single chunk's statistics at a
    time). Callers jit with ``donate_argnums=(0,)`` so the carry is
    updated in place instead of doubling the tally footprint.

    The per-chunk count computation defaults to
    :func:`chunk_count_deltas` over ``(chunk, count_buckets, axis_name)``;
    ``count_chunk(keys_c, valid_c, chunk_ops, obs) -> deltas`` overrides
    it — the fused-statistics mega-kernel supplies a counter whose tally
    fold happens in VMEM (ISSUE 8) instead of an XLA reduction, while the
    scan/carry contract here stays byte-identical.

    Signature of the returned function:
    ``super_fn(tallies, keys, valid, chunk_ops, obs) -> tallies`` with
    ``keys`` ``(K, C)`` per-permutation PRNG keys and ``valid`` ``(K,)``
    per-chunk valid-permutation counts (the tail superchunk keeps the
    compiled ``(K, C)`` shape — trailing chunks simply carry ``valid=0``,
    so one program serves the whole run).
    """
    if count_chunk is None:
        def count_chunk(keys_c, valid_c, chunk_ops, obs):
            return chunk_count_deltas(
                chunk, count_buckets, axis_name, keys_c, valid_c,
                chunk_ops, obs,
            )

    def super_fn(tallies, keys, valid, chunk_ops, obs):
        def body(carry, xs):
            keys_c, valid_c = xs
            deltas = count_chunk(keys_c, valid_c, chunk_ops, obs)
            new = [
                tuple(t + d for t, d in zip(ts, ds))
                for ts, ds in zip(carry, deltas)
            ]
            return new, None

        out, _ = jax.lax.scan(body, tallies, (keys, valid))
        return out

    return super_fn


def run_stream_superchunks(
    base,
    n_perm: int,
    key,
    fn: Callable,
    superchunk: int,
    chunk_size: int,
    init_tallies: Callable,
    pull_tallies: Callable,
    progress: Callable[[int, int], None] | None = None,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 8192,
    fingerprint_extra: bytes = b"",
    profile=None,
    telemetry=None,
    fault_policy=None,
    extra_state=None,
) -> StreamCounts:
    """Fixed-``n_perm`` streaming loop shared by :class:`PermutationEngine`
    and ``MultiTestEngine``: dispatch one scan-fused superchunk of
    ``superchunk`` chunks at a time (``fn`` from
    :func:`build_stream_super`, jitted with a donated carry), pulling only
    the O(modules·7) tallies back per superchunk — vs O(chunk·modules·7)
    null rows per chunk in the materialized loop.

    ``init_tallies(host_or_None)`` builds the device carry (zeros, or
    restored from a checkpoint's host tallies); ``pull_tallies(carry)``
    returns global ``(hi, lo, eff)`` numpy arrays. Checkpoints reuse the
    null-checkpoint container (format version unchanged) with the tallies
    riding ``x_``-prefixed extras and an empty placeholder null array; the
    identity fingerprint is namespaced so streaming and materialized
    checkpoints can never cross-resume. Resume is exact: tallies are saved
    only at superchunk boundaries, and per-permutation keys depend only on
    ``(key, index)``.

    A ``KeyboardInterrupt`` returns the tallies of the last completed
    superchunk (the tally fold and the ``completed`` counter commit in one
    statement), mirroring the materialized loop's clean Ctrl-C contract.
    ``telemetry`` gets one ``superchunk`` event per fused dispatch (the
    dispatch/host-byte counters :class:`NullProfile` folds) plus the run
    envelope and a stall watchdog, exactly like the materialized loop.

    ``fault_policy`` applies the same retry/abandon/degrade ladder as
    :func:`run_checkpointed_chunks`; a retried superchunk first rebuilds
    the (donated, hence possibly consumed) device tally carry from the
    last committed host tallies, so a failed fused dispatch re-folds from
    exactly the state an unfaulted run had at that boundary.
    """
    key = _resolve_key(base, key)
    telemetry, profile = _telemetry_profile(telemetry, profile)
    ft = flt.resolve_runtime(fault_policy)
    K, C = int(superchunk), int(chunk_size)
    completed = 0
    host0 = None
    save = None
    writer = None
    if checkpoint_path is not None:
        from ..utils import checkpoint as ckpt

        kd, fp = _checkpoint_identity(
            base, key, _STREAM_FP + fingerprint_extra
        )
        loaded = ckpt.load_null_checkpoint(checkpoint_path)
        if loaded is not None:
            extras = loaded.get("extras") or {}
            if "stream_hi" not in extras:
                raise ValueError(
                    f"checkpoint {checkpoint_path!r} has no streaming "
                    "tallies (it was written by a store_nulls=True run); "
                    "resume it with store_nulls=True or delete it"
                )
            ckpt.validate_identity(loaded, kd, fp, checkpoint_path)
            completed = min(int(loaded["completed"]), n_perm)
            host0 = (extras["stream_hi"], extras["stream_lo"],
                     extras["stream_eff"])
            if extra_state is not None:
                extra_state.restore_state(extras)
        if ft is not None and ft.policy.async_checkpoint:
            writer = ckpt.AsyncCheckpointWriter(telemetry)

        def save(hi, lo, eff, done):
            extra = {"stream_hi": hi, "stream_lo": lo, "stream_eff": eff}
            if extra_state is not None:
                extra.update(extra_state.state_arrays())
            ckpt.save_null_checkpoint(
                checkpoint_path, np.zeros((0,)), done, kd, fp,
                extra=extra, writer=writer,
            )

    tallies = init_tallies(host0)
    hi = lo = eff = None
    last_saved = completed
    t_marks: list[tuple[int, float]] = []

    def rescue():
        # emergency checkpoint of the last committed superchunk's tallies
        # (safe from the watchdog thread: only committed host state)
        if save is not None and hi is not None and completed > last_saved:
            save(hi, lo, eff, completed)
            if writer is not None:
                writer.flush()

    def reset():
        # a failed fused dispatch may have consumed the donated carry:
        # rebuild it from the last committed host tallies (bit-identical
        # to the carry an unfaulted run held at this boundary)
        nonlocal tallies
        tallies = init_tallies((hi, lo, eff) if hi is not None else host0)

    if ft is not None:
        action, act_factor = ft.watchdog_escalation(rescue)
        wd = tm.arm_watchdog(telemetry, action=action,
                             action_factor=act_factor)
    else:
        wd = tm.arm_watchdog(telemetry)
    prev_t = t_run0 = time.perf_counter()
    d0, b0 = _profile_totals(profile)
    start0 = completed
    run_sid = None
    mem = None
    tracker = _run_cost_tracker(base, telemetry)
    if telemetry is not None:
        run_sid = telemetry.begin_span(
            "null_run_start", mode="streaming", n_perm=int(n_perm),
            start_perm=int(completed), superchunk=K, chunk=C,
        )
        mem = _mem_probe(telemetry)
    try:
        while completed < n_perm:
            if ft is not None and save is not None:
                # elastic grow-back at the superchunk boundary (ISSUE 6):
                # committed tallies are failure-saved below, the API layer
                # rebuilds the grown mesh and resumes
                ft.check_grow()
            take = min(K * C, n_perm - completed)
            keys = base.perm_keys2d(key, completed, K, C)
            # per-chunk valid counts: the tail superchunk keeps the
            # compiled (K, C) shape, trailing chunks run with valid=0 and
            # the padded draws are computed and discarded (same policy as
            # the materialized loop's full-shape tail chunk)
            valid = np.clip(
                n_perm - completed - np.arange(K, dtype=np.int64) * C, 0, C
            ).astype(np.int32)
            if telemetry is not None:
                sid_c = telemetry.new_span_id()
                t_d0 = time.perf_counter()
                span_cm = telemetry.pushed(sid_c)
            else:
                sid_c = None
                span_cm = contextlib.nullcontext()
            # fold + counter commit in one statement (clean-Ctrl-C
            # contract: a consistent partial result at any interrupt);
            # retries/faults fired inside nest under this superchunk span
            with span_cm:
                if ft is None:
                    tallies, completed = (
                        fn(tallies, keys, valid), completed + take
                    )
                else:
                    # the lambda reads `tallies` at call time, so a retry
                    # after `reset` folds into the rebuilt carry
                    tallies, completed = ft.run_dispatch(
                        lambda: fn(tallies, keys, valid), start=completed,
                        take=take, telemetry=telemetry, rescue=rescue,
                        reset=reset, label="superchunk",
                    ), completed + take
            if telemetry is not None:
                telemetry.emit(
                    "dispatch", parent=sid_c,
                    s=time.perf_counter() - t_d0,
                    start=int(completed - take), take=int(take),
                )
                t_p0 = time.perf_counter()
            hi, lo, eff = pull_tallies(tallies)
            t_marks.append((completed, time.perf_counter()))
            if profile is not None:
                nbytes = hi.nbytes + lo.nbytes + eff.nbytes
                profile.record_dispatch(2)  # key derivation + superchunk
                profile.record_transfer(nbytes)
                profile.record_superchunk(2, nbytes, take)
            if telemetry is not None:
                now = t_marks[-1][1]
                telemetry.emit(
                    "superchunk", done=int(completed), total=int(n_perm),
                    perms=int(take), s=now - prev_t, dispatches=2,
                    host_bytes=int(hi.nbytes + lo.nbytes + eff.nbytes),
                    transfer_s=now - t_p0, span=sid_c, parent=run_sid,
                    **(tracker.chunk_fields(int(take), now - prev_t,
                                            profile)
                       if tracker is not None else {}),
                    **(mem() if mem is not None else {}),
                )
                prev_t = now
                wd.beat()
            if progress is not None:
                progress(completed, n_perm)
            if save is not None and completed - last_saved >= checkpoint_every:
                save(hi, lo, eff, completed)
                last_saved = completed
    except KeyboardInterrupt:
        pass
    except BaseException:
        # failure-save hook (ISSUE 4): committed tallies survive any crash
        if save is not None and hi is not None and completed > last_saved:
            save(hi, lo, eff, completed)
            last_saved = completed
        raise
    finally:
        if wd is not None:
            wd.stop()
        if writer is not None:
            writer.close()
    if hi is None:
        # resumed-already-complete, or interrupted before the first
        # superchunk landed: report the carry as initialized
        hi, lo, eff = pull_tallies(tallies)
    if save is not None and completed > last_saved:
        save(hi, lo, eff, completed)
    record = getattr(base, "record_stream_throughput", None)
    if record is not None and len(t_marks) >= 2:
        # same steady-state rule as the materialized loop: the interval
        # before mark 0 absorbed the compile
        (c0, t0), (c1, t1) = t_marks[0], t_marks[-1]
        if t1 > t0 and c1 > c0:
            record((c1 - c0) / (t1 - t0))
    if telemetry is not None:
        d, b = _profile_totals(profile)
        _finish_run_accounting(base, telemetry, run_sid, t_marks, t_run0,
                               start0, n_perm, "streaming",
                               tracker=tracker)
        el = time.perf_counter() - t_run0
        telemetry.end_span(
            run_sid, "null_run_end", mode="streaming",
            completed=int(completed), n_perm=int(n_perm),
            s=el, dispatches=d - d0, host_bytes=b - b0,
            **(tracker.run_fields(el) if tracker is not None else {}),
        )
    return StreamCounts(hi=hi, lo=lo, eff=eff, completed=completed)


def run_adaptive_stream_chunks(
    base,
    n_perm: int,
    key,
    fn_builder: Callable[[], Callable],
    counts_to_active: Callable,
    monitor,
    rebucket: Callable[[np.ndarray], None],
    progress: Callable[[int, int], None] | None = None,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 8192,
    fingerprint_extra: bytes = b"",
    profile=None,
    telemetry=None,
    fault_policy=None,
    extra_state=None,
) -> tuple:
    """Adaptive (sequential early-stopping) streaming loop: one chunk per
    dispatch — decisions must land at CHUNK boundaries exactly as the
    materialized adaptive loop takes them, so retirement is bit-identical
    between ``store_nulls`` modes — but the dispatch returns per-bucket
    ``(hi, lo, eff)`` *counts* and the
    :class:`~netrep_tpu.ops.sequential.StopMonitor` folds them directly
    (:meth:`~netrep_tpu.ops.sequential.StopMonitor.update_counts`) instead
    of re-tallying host-side null slices: the device→host transfer drops
    from O(chunk·modules·cells) to O(modules·cells) per chunk.

    ``fn_builder() -> fn(keys, valid)`` jits the count program for the
    current bucket set (re-invoked after each retirement re-bucketing);
    ``counts_to_active(outs, pos)`` assembles its output into
    ``(hi, lo, eff)`` host arrays over the active modules in
    :meth:`~netrep_tpu.ops.sequential.StopMonitor.active_positions` order.
    Checkpoints carry the monitor state (tallies + retired set + per-cell
    ``eff``) in ``x_``-prefixed extras; there is no written-but-unfolded
    gap to re-fold on resume — counts and monitor commit in one statement.

    Returns ``(monitor, completed, finished)``. ``fault_policy`` wraps
    each count dispatch in the retry/abandon/degrade ladder (no carry to
    rebuild here — counts and monitor commit in one statement, so a retry
    simply re-dispatches the chunk).
    """
    key = _resolve_key(base, key)
    telemetry, profile = _telemetry_profile(telemetry, profile)
    ft = flt.resolve_runtime(fault_policy)
    # retirement events come from the monitor itself (per-module tallies
    # live there); the loop only provides the bus
    monitor.telemetry = telemetry
    completed = 0
    save = None
    writer = None
    if checkpoint_path is not None:
        from ..utils import checkpoint as ckpt

        kd, fp = _checkpoint_identity(
            base, key, _STREAM_FP + fingerprint_extra
        )
        loaded = ckpt.load_null_checkpoint(checkpoint_path)
        if loaded is not None:
            ckpt.validate_identity(loaded, kd, fp, checkpoint_path)
            monitor.restore_state(loaded.get("extras") or {})
            if extra_state is not None:
                extra_state.restore_state(loaded.get("extras") or {})
            completed = min(int(loaded["completed"]), n_perm)
        if ft is not None and ft.policy.async_checkpoint:
            writer = ckpt.AsyncCheckpointWriter(telemetry)

        def save(done):
            # monitor state is read (and snapshotted by the writer path)
            # on THIS thread at submit time — the background write never
            # races the monitor's in-place tally folds
            extra = monitor.state_arrays()
            if extra_state is not None:
                extra = {**extra, **extra_state.state_arrays()}
            ckpt.save_null_checkpoint(
                checkpoint_path, np.zeros((0,)), done, kd, fp,
                extra=extra, writer=writer,
            )

    pos = monitor.active_positions()
    if pos.size and pos.size < monitor.n_modules:
        rebucket(pos)  # resumed mid-run: shrink to the restored active set
    fn = fn_builder() if monitor.any_active() else None
    C = base.effective_chunk()
    last_saved = completed
    finished = True

    def rescue():
        # the monitor folds counts atomically at chunk boundaries, so its
        # state is always consistent from the watchdog thread's view
        if save is not None and completed > last_saved:
            save(completed)
            if writer is not None:
                writer.flush()

    if ft is not None:
        action, act_factor = ft.watchdog_escalation(rescue)
        wd = tm.arm_watchdog(telemetry, action=action,
                             action_factor=act_factor)
    else:
        wd = tm.arm_watchdog(telemetry)
    prev_t = t_run0 = time.perf_counter()
    d0, b0 = _profile_totals(profile)
    start0 = completed
    t_marks: list[tuple[int, float]] = []
    run_sid = None
    mem = None
    tracker = _run_cost_tracker(base, telemetry)
    if telemetry is not None:
        run_sid = telemetry.begin_span(
            "null_run_start", mode="adaptive-streaming", n_perm=int(n_perm),
            start_perm=int(completed), chunk=C,
        )
        mem = _mem_probe(telemetry)
    try:
        while completed < n_perm and monitor.any_active():
            if ft is not None and save is not None:
                # elastic grow-back at the chunk boundary (ISSUE 6)
                ft.check_grow()
            pos = monitor.active_positions()
            take = min(C, n_perm - completed)

            def _dispatch():
                keys = base.perm_keys(key, completed, C)
                if ft is None:
                    return fn(keys, np.int32(take))
                return ft.run_dispatch(
                    lambda: fn(keys, np.int32(take)), start=completed,
                    take=take, telemetry=telemetry, rescue=rescue,
                )

            if telemetry is None:
                sid_c = None
                outs = _dispatch()
            else:
                sid_c = telemetry.new_span_id()
                t_d0 = time.perf_counter()
                with telemetry.pushed(sid_c):
                    outs = _dispatch()
                telemetry.emit(
                    "dispatch", parent=sid_c,
                    s=time.perf_counter() - t_d0,
                    start=int(completed), take=int(take),
                )
                t_p0 = time.perf_counter()
            hi_a, lo_a, eff_a = counts_to_active(outs, pos)
            pull_s = (
                time.perf_counter() - t_p0 if telemetry is not None else 0.0
            )
            if profile is not None:
                profile.record_dispatch(2)
                profile.record_transfer(
                    hi_a.nbytes + lo_a.nbytes + eff_a.nbytes
                )
            newly = monitor.update_counts(hi_a, lo_a, take, eff=eff_a)
            completed = monitor.folded
            if telemetry is not None:
                now = time.perf_counter()
                t_marks.append((completed, now))
                telemetry.emit(
                    "chunk", done=int(completed), total=int(n_perm),
                    take=int(take), s=now - prev_t, dispatches=2,
                    host_bytes=int(
                        hi_a.nbytes + lo_a.nbytes + eff_a.nbytes
                    ),
                    active_modules=int(monitor.active.sum()),
                    transfer_s=pull_s, span=sid_c, parent=run_sid,
                    **(tracker.chunk_fields(int(take), now - prev_t,
                                            profile)
                       if tracker is not None else {}),
                    **(mem() if mem is not None else {}),
                )
                prev_t = now
                wd.beat()
            if progress is not None:
                progress(completed, n_perm)
            if newly.size and monitor.any_active():
                rebucket(monitor.active_positions())
                fn = fn_builder()
                if tracker is not None:
                    # retirement shrank the bucket list — re-price the
                    # chunk program so later spans carry the smaller cost
                    tracker.refresh(base)
            if save is not None and completed - last_saved >= checkpoint_every:
                save(completed)
                last_saved = completed
    except KeyboardInterrupt:
        # chunk-boundary abort: the monitor folds counts atomically, so
        # the checkpoint below resumes exactly
        finished = False
        completed = monitor.folded
    except BaseException:
        # failure-save hook (ISSUE 4): folded chunks survive any crash
        completed = monitor.folded
        if save is not None and completed > last_saved:
            save(completed)
            last_saved = completed
        raise
    finally:
        if wd is not None:
            wd.stop()
        if writer is not None:
            writer.close()
    if save is not None and completed > last_saved:
        save(completed)
    if telemetry is not None:
        d, b = _profile_totals(profile)
        _finish_run_accounting(base, telemetry, run_sid, t_marks, t_run0,
                               start0, n_perm, "adaptive-streaming",
                               tracker=tracker)
        el = time.perf_counter() - t_run0
        telemetry.end_span(
            run_sid, "null_run_end", mode="adaptive-streaming",
            completed=int(completed), n_perm=int(n_perm),
            s=el, dispatches=d - d0,
            host_bytes=b - b0, perms_evaluated=int(monitor.total_evaluated()),
            **(tracker.run_fields(el) if tracker is not None else {}),
        )
    return monitor, completed, finished


#: one-shot flag for the unknown-sharding downgrade below — the benign
#: case repeats every chunk of a run, so warn/emit once per process
_UNKNOWN_SHARDING_SEEN = False


def _trim_tail_shards(out, take: int, axis: int = 0):
    """Multi-host tail chunks only: drop whole trailing perm-axis shards
    of a chunk output before the cross-host allgather, so the final
    (``take < C``) chunk does not move its padded tail over DCN. Slicing
    happens only where the sharding allows it — on whole-shard boundaries
    (a mid-shard slice would trigger a resharding collective instead of
    saving one) — and never on fully-addressable arrays, keeping the
    documented eager-op-avoidance on tunneled single-host backends (each
    eager device op costs ~1 s there; the host-side ``[:take]`` slice in
    ``write`` stays the single-host policy)."""
    global _UNKNOWN_SHARDING_SEEN
    if take >= out.shape[axis] or getattr(out, "is_fully_addressable", True):
        return out
    try:
        rows = out.sharding.shard_shape(out.shape)[axis]
    except (AttributeError, TypeError, ValueError) as e:
        # a sharding object that doesn't speak shard_shape: transfer the
        # full chunk as before, but say so once — while a genuine backend
        # failure (RuntimeError/XlaRuntimeError) now PROPAGATES instead of
        # being silently swallowed as "transfer as before"
        if not _UNKNOWN_SHARDING_SEEN:
            _UNKNOWN_SHARDING_SEEN = True
            logger.warning(
                "tail-shard trim skipped: %s sharding does not expose "
                "shard_shape (%s: %s); transferring the full tail chunk",
                type(getattr(out, "sharding", None)).__name__,
                type(e).__name__, e,
            )
            tel = tm.current()
            if tel is not None:
                tel.emit("tail_trim_skipped", error=type(e).__name__)
        return out
    if not rows or rows <= 0:
        return out
    keep = -(-take // rows) * rows
    if keep >= out.shape[axis]:
        return out
    sel = [slice(None)] * out.ndim
    sel[axis] = slice(0, keep)
    return out[tuple(sel)]


def _globalize_replicated(mesh, tree):
    """Multi-host meshes: every operand of a jitted computation must be a
    global array. Host-local operands are identical on every process (the
    SPMD contract — keys from the same seed, replicated matrices), so
    replicate them over the mesh; operands already carrying global
    shardings (e.g. row-sharded matrices) pass through untouched."""
    from .distributed import to_global

    rep = NamedSharding(mesh, P())
    if rep.is_fully_addressable:
        return tree

    def _globalize(a):
        if not hasattr(a, "shape"):
            return a
        sh = getattr(a, "sharding", None)
        if sh is not None and not sh.is_fully_addressable:
            return a  # already global (e.g. row-sharded)
        return to_global(a, rep)

    return jax.tree.map(_globalize, tree)


def run_adaptive_chunks(
    base: "PermutationEngine",
    n_perm: int,
    key,
    fn_builder: Callable[[], Callable],
    alloc_shape: tuple[int, ...],
    write: Callable[[np.ndarray, list, int, int], None],
    slice_vals: Callable[[np.ndarray, int, int, np.ndarray], np.ndarray],
    monitor,
    rebucket: Callable[[np.ndarray], None],
    progress: Callable[[int, int], None] | None = None,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 8192,
    perm_axis: int = 0,
    fingerprint_extra: bytes = b"",
    telemetry=None,
    fault_policy=None,
    extra_state=None,
) -> tuple[np.ndarray, int, bool]:
    """Adaptive scheduling layer around the shared chunked null loop: after
    each chunk a host-side :class:`~netrep_tpu.ops.sequential.StopMonitor`
    folds the chunk's per-(module, statistic) exceedance counts into running
    tallies and retires decided modules; retired modules *drop out* of
    subsequent chunks — ``rebucket`` rebuilds the engine's buckets for the
    remaining set (fewer vmap lanes, smaller buckets) and ``fn_builder``
    re-jits the shrunken chunk program — rather than merely masking work.

    RNG contract: every chunk still draws ``fold_in(key, i)`` permutations
    over the full pool, and re-bucketing preserves each surviving module's
    original slice offsets into the drawn permutation
    (:meth:`PermutationEngine.rebucket`), so an active module's null rows
    are bit-identical to the fixed-``n_perm`` run's rows at the same
    permutation indices.

    ``slice_vals(nulls, done, take, positions)`` views the chunk just
    written as the ``(take, n_active, n_cells)`` array the monitor tallies
    (engines with extra axes — the multi-test T axis — fold them into the
    cell axis here). Checkpoints carry the monitor's tallies + retired set
    (``extra=`` in :func:`~netrep_tpu.utils.checkpoint.save_null_checkpoint`)
    and are written only at chunk boundaries, where decisions are
    deterministic — so a mid-run checkpoint resumes to the same final
    result as an uninterrupted run.

    Returns ``(nulls, completed, finished)``; rows past each module's
    retirement stay NaN (that is the per-module ``n_perm_used`` record —
    :func:`netrep_tpu.ops.pvalues.effective_nperm`). ``finished`` is False
    only for a ``KeyboardInterrupt`` partial result.

    Double-buffering is deliberately absent here (unlike
    :func:`run_checkpointed_chunks`): the monitor must see chunk *k* before
    chunk *k+1*'s module set is known, so the dispatch chain is inherently
    synchronous. The throughput cost is bounded by the device→host copy of
    chunks that shrink as modules retire.

    ``fault_policy`` wraps each chunk dispatch in the retry/abandon/
    degrade ladder; decisions are unaffected (tallies fold only from
    committed chunks, and a retried chunk regenerates identical keys).
    """
    key = _resolve_key(base, key)
    telemetry = tm.resolve(telemetry)
    ft = flt.resolve_runtime(fault_policy)
    monitor.telemetry = telemetry
    nulls = np.full(alloc_shape, np.nan)
    completed = 0
    save = None
    writer = None
    if checkpoint_path is not None:
        from ..utils import checkpoint as ckpt

        kd, fp = _checkpoint_identity(base, key, fingerprint_extra)
        loaded = ckpt.load_null_checkpoint(checkpoint_path)
        if loaded is not None:
            nulls, completed = ckpt.validate_resume(
                loaded, n_perm, kd, fp, checkpoint_path, perm_axis=perm_axis
            )
            if completed:
                monitor.restore_state(loaded.get("extras") or {})
                if extra_state is not None:
                    extra_state.restore_state(loaded.get("extras") or {})
                gap = completed - monitor.folded
                if gap > 0:
                    # an interrupt landed between a chunk's null write and
                    # its tally fold: re-fold the written-but-unfolded rows
                    # so decisions match an uninterrupted run exactly
                    monitor.update(
                        slice_vals(nulls, monitor.folded, gap,
                                   monitor.active_positions()),
                        gap,
                    )

        if ft is not None and ft.policy.async_checkpoint:
            writer = ckpt.AsyncCheckpointWriter(telemetry)

        def save(nulls, done):
            extra = monitor.state_arrays()
            if extra_state is not None:
                extra = {**extra, **extra_state.state_arrays()}
            ckpt.save_null_checkpoint(
                checkpoint_path, nulls, done, kd, fp,
                extra=extra, writer=writer,
            )

    pos = monitor.active_positions()
    if pos.size and pos.size < monitor.n_modules:
        rebucket(pos)  # resumed mid-run: shrink to the restored active set
    fn = fn_builder() if monitor.any_active() else None
    C = base.effective_chunk()
    dynamic = getattr(base, "dynamic_chunk", False)
    last_saved = completed
    finished = True

    def rescue():
        # completed counts only fully-written-and-folded chunks, so the
        # watchdog thread checkpoints a consistent prefix
        if save is not None and completed > last_saved:
            save(nulls, completed)
            if writer is not None:
                writer.flush()

    if ft is not None:
        action, act_factor = ft.watchdog_escalation(rescue)
        wd = tm.arm_watchdog(telemetry, action=action,
                             action_factor=act_factor)
    else:
        wd = tm.arm_watchdog(telemetry)
    prev_t = t_run0 = time.perf_counter()
    start0 = completed
    t_marks: list[tuple[int, float]] = []
    run_sid = None
    mem = None
    # chunk-cost hook (ISSUE 13): monitors that attribute pack costs per
    # request (serve's PackMonitor) receive each chunk's measured
    # dispatch/transfer seconds — resolved once, telemetry-path only, so
    # the disabled hot loop keeps its single None check
    note_cost = (
        getattr(monitor, "note_chunk_cost", None)
        if telemetry is not None else None
    )
    tracker = _run_cost_tracker(base, telemetry)
    if telemetry is not None:
        run_sid = telemetry.begin_span(
            "null_run_start", mode="adaptive", n_perm=int(n_perm),
            start_perm=int(completed), chunk=C,
        )
        mem = _mem_probe(telemetry)
    try:
        while completed < n_perm and monitor.any_active():
            if ft is not None and save is not None:
                # elastic grow-back at the chunk boundary (ISSUE 6)
                ft.check_grow()
            pos = monitor.active_positions()
            take = min(C, n_perm - completed)

            def _dispatch():
                keys = base.perm_keys(key, completed, take if dynamic else C)
                if ft is None:
                    return fn(keys)
                return ft.run_dispatch(
                    lambda: fn(keys), start=completed, take=take,
                    telemetry=telemetry, rescue=rescue,
                )

            if telemetry is None:
                sid_c = None
                outs = _dispatch()
            else:
                sid_c = telemetry.new_span_id()
                t_d0 = time.perf_counter()
                with telemetry.pushed(sid_c):
                    outs = _dispatch()
                disp_s = time.perf_counter() - t_d0
                telemetry.emit(
                    "dispatch", parent=sid_c, s=disp_s,
                    start=int(completed), take=int(take),
                )
                t_w0 = time.perf_counter()
            write(nulls, outs, completed, take)
            write_s = (
                time.perf_counter() - t_w0 if telemetry is not None else 0.0
            )
            completed += take
            newly = monitor.update(
                slice_vals(nulls, completed - take, take, pos), take
            )
            if note_cost is not None:
                note_cost(disp_s, write_s)
            if telemetry is not None:
                now = time.perf_counter()
                t_marks.append((completed, now))
                telemetry.emit(
                    "chunk", done=int(completed), total=int(n_perm),
                    take=int(take), s=now - prev_t,
                    active_modules=int(monitor.active.sum()),
                    transfer_s=write_s, span=sid_c, parent=run_sid,
                    **(tracker.chunk_fields(int(take), now - prev_t)
                       if tracker is not None else {}),
                    **(mem() if mem is not None else {}),
                )
                prev_t = now
                wd.beat()
            if progress is not None:
                progress(completed, n_perm)
            if newly.size and monitor.any_active():
                rebucket(monitor.active_positions())
                fn = fn_builder()
                if tracker is not None:
                    # retirement shrank the bucket list — re-price the
                    # chunk program so later spans carry the smaller cost
                    tracker.refresh(base)
            if save is not None and completed - last_saved >= checkpoint_every:
                save(nulls, completed)
                last_saved = completed
    except KeyboardInterrupt:
        # chunk-boundary abort: tallies were only ever folded for fully
        # written chunks, so the checkpoint below resumes exactly
        finished = False
    except BaseException:
        # failure-save hook (ISSUE 4): written chunks survive any crash
        if save is not None and completed > last_saved:
            save(nulls, completed)
            last_saved = completed
        raise
    finally:
        if wd is not None:
            wd.stop()
        if writer is not None:
            writer.close()
    if save is not None and completed > last_saved:
        save(nulls, completed)
    if telemetry is not None:
        _finish_run_accounting(base, telemetry, run_sid, t_marks, t_run0,
                               start0, n_perm, "adaptive",
                               tracker=tracker)
        el = time.perf_counter() - t_run0
        telemetry.end_span(
            run_sid, "null_run_end", mode="adaptive",
            completed=int(completed),
            n_perm=int(n_perm), s=el,
            perms_evaluated=int(monitor.total_evaluated()),
            **(tracker.run_fields(el) if tracker is not None else {}),
        )
    return nulls, completed, finished


#: store-backed per-(kind, static-shape) key-derivation programs: the
#: AOT analogue of the old ``static_argnums`` jit cache — bounded by the
#: distinct (chunk, superchunk, group) shapes a process runs, exactly
#: like the jit cache it replaces
_KEYS_FNS: dict = {}


def _keys_fn(kind: str, static_sig: str, body, example):
    """Resolve one grouped-keys helper program through the AOT store
    (ISSUE 15): memoized per (kind, static shape, backend); the store
    serves a deserialized entry when one is warm, else jits ``body`` as
    the ``static_argnums`` decorators always did. Key derivation runs
    once per chunk on the hot path, so the helpers' compiles are part of
    the cold-start tax the warm start has to erase."""
    memo_key = (kind, static_sig, jax.default_backend())
    fn = _KEYS_FNS.get(memo_key)
    if fn is None:
        from ..utils import aot

        store = aot.get_store()
        if store is None:
            fn = jax.jit(body)
        else:
            fn, _src = store.acquire(
                aot.program_key(
                    f"keys:{kind}|{static_sig}|{jax.default_backend()}",
                    "", "mesh:none",
                ),
                lambda: jax.jit(body), export_fn=body, example_args=example,
            )
        _KEYS_FNS[memo_key] = fn
    return fn


def _perm_keys_jit(key: jax.Array, start, count: int) -> jax.Array:
    def body(k, s):
        return jax.vmap(lambda i: jax.random.fold_in(k, i))(
            s + jnp.arange(count, dtype=jnp.uint32)
        )

    start = jnp.uint32(start)
    return _keys_fn("perm", str(int(count)), body, (key, start))(key, start)


def _perm_keys_grouped_jit(keys_g: jax.Array, start, count: int):
    """(C, G) per-permutation keys for one PACKED chunk (ISSUE 7): column
    g holds group g's solo-run keys ``fold_in(key_g, start + i)`` — each
    packed request keeps its own RNG stream, so its permutations are
    exactly the ones its stand-alone run draws at the same indices. Row
    layout (perm axis leading) matches what ``lax.map`` consumes in the
    packed chunk body, so no eager transpose of a typed-key array is ever
    needed."""
    def body(kg, s):
        idx = s + jnp.arange(count, dtype=jnp.uint32)
        return jax.vmap(
            lambda i: jax.vmap(lambda k1: jax.random.fold_in(k1, i))(kg)
        )(idx)

    start = jnp.uint32(start)
    g = int(keys_g.shape[0])
    return _keys_fn(
        "grouped", f"{int(count)}x{g}", body, (keys_g, start)
    )(keys_g, start)


def _perm_keys2d_jit(key: jax.Array, start, k: int, c: int):
    """(K, C) per-permutation keys for one superchunk — the same
    ``fold_in(key, i)`` contract as :func:`_perm_keys_jit`, reshaped
    INSIDE the jit (an eager reshape of a typed-key array would cost a
    ~1 s dispatch per superchunk on tunneled backends)."""
    def body(k1, s):
        ks = jax.vmap(lambda i: jax.random.fold_in(k1, i))(
            s + jnp.arange(k * c, dtype=jnp.uint32)
        )
        return ks.reshape(k, c)

    start = jnp.uint32(start)
    return _keys_fn(
        "perm2d", f"{int(k)}x{int(c)}", body, (key, start)
    )(key, start)


def check_derived_network(corr, net, net_beta, what: str) -> None:
    """Check that ``net`` matches the claimed soft-threshold construction
    before the engine commits to deriving network submatrices on device
    (``EngineConfig.network_from_correlation``; β or (β, kind) — see
    :func:`netrep_tpu.ops.stats.derived_net`): exhaustive for matrices up
    to 64k entries, a fixed-seed random flat sample of 64k entries beyond
    (any *strided* sample would alias onto the columns divisible by
    gcd(stride, n), leaving most of the matrix unchecked). A mismatch means
    the knob contradicts the data the user actually supplied. The expected
    values come from :func:`~netrep_tpu.ops.stats.derived_net` itself (on
    the host sample) — ONE formula site, so this check can never validate
    a different construction than the device derives."""
    beta, kind = jstats.normalize_net_beta(net_beta)
    c = np.asarray(corr).reshape(-1)
    m = np.asarray(net).reshape(-1)
    if c.size > 65536:
        # netrep: allow(rng-discipline) — fixed-seed cache-busting probe indices for autotune timing; never touches null results
        ii = np.random.default_rng(0).integers(0, c.size, size=65536)
        c, m = c[ii], m[ii]
    # Evaluate the expected sample on the host CPU: on tunneled TPU
    # backends each eager dispatch costs ~1 s, and this runs at engine
    # construction inside a ~5-7 min measurement window (advisor r4).
    # Under JAX_PLATFORMS=axon only the axon platform is initialized and
    # jax.devices("cpu") RAISES — fall back to the default device there
    # (the pre-optimization behavior) rather than dying in construction.
    try:
        cpu_dev = jax.devices("cpu")[0]
    except RuntimeError:
        cpu_dev = None
    with jax.default_device(cpu_dev) if cpu_dev is not None else contextlib.nullcontext():
        want = np.asarray(jstats.derived_net(jnp.asarray(c), net_beta))
    if not np.allclose(m, want, rtol=1e-3, atol=1e-4):
        worst = float(np.max(np.abs(m - want)))
        formula = jstats.DERIVED_FORMULA[kind].format(b=beta)
        raise ValueError(
            f"network_from_correlation={net_beta!r} but the supplied {what} "
            f"network is not {formula} (max sampled deviation "
            f"{worst:.3g}); drop the config knob or fix the inputs"
        )


def make_row_sharded_observed(gather_rep, net_beta: float | None = None) -> Callable:
    """Jitted observed-pass kernel over row-sharded matrices: collective
    gather + exact-eigh statistics. Shared by :class:`PermutationEngine` and
    ``MultiTestEngine`` so the two observed paths cannot drift. With
    ``net_beta`` the network submatrix derives from the gathered correlation
    (``tn`` is None then)."""

    from .sharded import gather_corr_net

    @jax.jit
    def _obs(disc, idx, tc, tn, tdT):
        sub_c, sub_n = gather_corr_net(gather_rep, tc, tn, idx, net_beta)
        zd = (
            jstats.gather_zdata(tdT, idx, disc.mask)
            if tdT is not None else None
        )
        return jstats.module_stats_masked(
            disc, sub_c, sub_n, zd, summary_method="eigh"
        )

    return _obs


@dataclasses.dataclass(frozen=True)
class ModuleSpec:
    """One discovery module's overlap bookkeeping (SURVEY.md §3.1).

    ``disc_idx`` / ``test_idx`` are aligned: position i refers to the same
    node (by name) in the discovery and test datasets. Their common length is
    ``nVarsPresent`` for this module.
    """

    label: str
    disc_idx: np.ndarray
    test_idx: np.ndarray

    @property
    def size(self) -> int:
        return len(self.test_idx)


@dataclasses.dataclass
class _Bucket:
    cap: int
    module_pos: list[int]          # positions in the global module order
    disc: jstats.DiscProps         # batched (K, cap[, cap]) discovery props
    obs_idx: jnp.ndarray           # (K, cap) observed test indices (padded)
    slices: list[tuple[int, int]]  # (offset, size) into the pooled permutation


def _pad_to(a: np.ndarray, cap: int, axes: Sequence[int]) -> np.ndarray:
    pad = [(0, 0)] * a.ndim
    for ax in axes:
        pad[ax] = (0, cap - a.shape[ax])
    return np.pad(a, pad)


def make_fused_gather(cfg: EngineConfig):
    """Backend-gated partial of the fused Pallas gather shared by the
    single-test and multi-test fused chunk paths: CPU runs the interpreter
    (CI coverage), and ``fused_exact`` applies only off-CPU where plain
    dots are not already exact — one definition so the precision gating
    cannot drift between engines. ``fused_exact='always'`` overrides the
    CPU gate so CI exercises the hi/lo engine path in interpret mode
    (VERDICT r3: its first execution must not be on a TPU mid-benchmark)."""
    from ..ops.fused_gather import gather_submatrix_fused as _gsf

    on_cpu = jax.default_backend() == "cpu"
    exact = bool(cfg.fused_exact) and (
        cfg.fused_exact == "always" or not on_cpu
    )
    return partial(_gsf, interpret=on_cpu, exact=exact)


def make_fused_stats(cfg: EngineConfig):
    """Backend-gated partials of the fused-statistics mega-kernel
    (:mod:`netrep_tpu.ops.fused_stats`), mirroring :func:`make_fused_gather`
    — CPU runs the Pallas interpreter (the tier-1 parity surface) and
    ``fused_exact`` applies off-CPU only (plain dots are already exact
    there), with ``'always'`` forcing the hi/lo split for CI coverage.
    Returns ``(values_fn, counts_fn)`` with the kernel statics
    (power-iteration count, summary method, interpret/exact gates) bound;
    call sites supply matrices, indices, net_beta, and row_block."""
    from ..ops.fused_stats import fused_stats_counts, fused_stats_values

    on_cpu = jax.default_backend() == "cpu"
    exact = bool(cfg.fused_exact) and (
        cfg.fused_exact == "always" or not on_cpu
    )
    kw = dict(
        n_iter=cfg.power_iters, summary_method=cfg.summary_method,
        interpret=on_cpu, exact=exact,
    )
    return (
        partial(fused_stats_values, **kw),
        partial(fused_stats_counts, **kw),
    )


def fused_scan(keys, B: int, batch_body):
    """Pad the chunk's key array up to whole ``B``-batches (padded
    permutations are computed and discarded — a divisor search would
    collapse prime chunk sizes to batch 1), scan ``batch_body`` over the
    batches, and return ``(outs, Cp)``: the stacked per-batch outputs and
    the padded count. Shared by the fused chunk paths so the pad/scan
    semantics cannot drift."""
    C = keys.shape[0]
    B = min(B, C)
    Cp = -(-C // B) * B
    kp = (
        jnp.concatenate([keys, keys[-1:].repeat(Cp - C, axis=0)])
        if Cp != C else keys
    )
    _, outs = jax.lax.scan(batch_body, None, kp.reshape(Cp // B, B))
    return outs, Cp


def _idx_blocks_grouped(perms, cap: int, slices, groups) -> jnp.ndarray:
    """Grouped variant of :func:`_idx_blocks` for PACKED chunks (ISSUE 7):
    ``perms`` is ``(G, P)`` — one drawn permutation per key group (=
    packed request) — and module k slices ``[off, off + size)`` out of
    ITS group's permutation (``groups[k]``, a static int). Offsets are
    request-local, so every packed module sees exactly the index sets its
    stand-alone run gathers; slices from *different* groups may overlap —
    the requests are independent analyses sharing one dispatch, not one
    disjoint label shuffle. Result ``(K, cap)``, padded slots masked
    downstream like :func:`_idx_blocks`."""
    cols = []
    for (off, size), g in zip(slices, groups):
        idx = perms[g, off: off + size]
        cols.append(jnp.pad(idx, [(0, cap - size)]))
    return jnp.stack(cols, axis=-2)


def _idx_blocks(perm, cap: int, slices) -> jnp.ndarray:
    """Slice one bucket's per-module index sets out of a drawn permutation
    and zero-pad each to the bucket capacity: ``perm`` is ``(..., P)``,
    result ``(..., K, cap)``. The single definition of the chunk paths'
    module-index layout (replicated / row-sharded / fused branches all use
    it — padding semantics must not drift between them; padded slots are
    masked downstream)."""
    cols = []
    for off, size in slices:
        idx = perm[..., off: off + size]
        pad = [(0, 0)] * (idx.ndim - 1) + [(0, cap - size)]
        cols.append(jnp.pad(idx, pad))
    return jnp.stack(cols, axis=-2)


class ObservedCache:
    """Digest-keyed dedup cache for the all-pairs grid (ISSUE 17).

    Two maps, both content-addressed so a stale hit is impossible:

    - **discovery props** — per-bucket discovery-side property pytrees
      (:meth:`PermutationEngine._bucket_disc_props`), keyed on the
      discovery matrices' content digest + the bucket's padded module
      index/mask bytes + the mode bits that change the computation.
      Every cell of one grid row (same discovery dataset, same module
      assignments) maps to the same keys, so the row's module buckets
      are built ONCE and the device arrays are shared across engines.
    - **observed stats** — the (n_modules, 7) observed array, keyed on
      the full six-matrix engine fingerprint + the module spec digest:
      re-building an engine for the same cell (checkpoint resume, grid
      re-entry) skips the observed pass entirely.

    Hits emit a ``grid_dedup_hit`` telemetry event (ambient bus) with
    the map kind; ``stats()`` reports hit/miss counters for the bench.
    Thread-safe: the grid's fleet spread may build engines concurrently.
    """

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._props: dict = {}
        self._obs: dict = {}
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _digest(*arrays) -> str:
        import hashlib

        h = hashlib.blake2b(digest_size=8)
        for a in arrays:
            a = np.ascontiguousarray(a)
            h.update(str(a.shape).encode() + str(a.dtype).encode())
            h.update(a.tobytes())
        return h.hexdigest()

    def props_key(self, disc_digest: str, mode: str, cap: int,
                  didx: np.ndarray, mask: np.ndarray) -> tuple:
        return ("props", disc_digest, mode, int(cap),
                self._digest(didx, mask))

    def observed_key(self, fingerprint: str, spec_sig: str,
                     mode: str) -> tuple:
        return ("observed", fingerprint, spec_sig, mode)

    def _note(self, hit: bool, kind: str) -> None:
        if hit:
            self.hits += 1
            tel = tm.current()
            if tel is not None:
                tel.emit("grid_dedup_hit", kind=kind)
        else:
            self.misses += 1

    def get_props(self, key: tuple):
        with self._lock:
            v = self._props.get(key)
        self._note(v is not None, "props")
        return v

    def put_props(self, key: tuple, props) -> None:
        with self._lock:
            self._props[key] = props

    def get_observed(self, key: tuple) -> np.ndarray | None:
        with self._lock:
            v = self._obs.get(key)
        self._note(v is not None, "observed")
        return None if v is None else v.copy()

    def put_observed(self, key: tuple, observed: np.ndarray) -> None:
        with self._lock:
            self._obs[key] = np.asarray(observed, dtype=np.float64).copy()

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": int(self.hits), "misses": int(self.misses),
                "props_entries": len(self._props),
                "observed_entries": len(self._obs),
            }


class PermutationEngine:
    """Permutation-null engine for one (discovery, test) dataset pair.

    Parameters
    ----------
    disc_corr, disc_net : (n_d, n_d) discovery correlation / network.
    disc_data : (n_samples_d, n_d) discovery data, or None (data-less
        variant, SURVEY.md §2.2).
    test_corr, test_net : (n_t, n_t) test correlation / network.
    test_data : (n_samples_t, n_t) test data, or None.
    modules : ordered module specs (global module order = this order).
    pool : candidate test-node indices the null draws from — the overlap set
        for ``null='overlap'`` or all test nodes for ``null='all'``
        (SURVEY.md §3.1).
    config : engine tuning knobs.
    mesh : optional device mesh; when given, permutation chunks are sharded
        along ``config.mesh_axis``.
    """

    def __init__(
        self,
        disc_corr: np.ndarray,
        disc_net: np.ndarray,
        disc_data: np.ndarray | None,
        test_corr: np.ndarray,
        test_net: np.ndarray,
        test_data: np.ndarray | None,
        modules: Sequence[ModuleSpec],
        pool: np.ndarray,
        config: EngineConfig = EngineConfig(),
        mesh: Mesh | None = None,
        discovery_only: bool = False,
        observed_cache: "ObservedCache | None" = None,
    ):
        """``discovery_only=True`` builds only the discovery-side buckets and
        pool bookkeeping (test matrices may be None) — used by wrappers like
        :class:`~netrep_tpu.parallel.multitest.MultiTestEngine` that supply
        their own test side; ``observed``/``run_null`` must not be called.

        ``observed_cache`` (ISSUE 17, the grid's discovery-side dedup): a
        shared :class:`ObservedCache` — per-bucket discovery properties
        and observed statistics are looked up by content digest before
        being recomputed, so engines over the same discovery dataset
        (one grid row) build their module buckets once. None (default)
        computes everything locally, bit-identically."""
        self.config = config
        self._observed_cache = observed_cache
        self.mesh = mesh
        self.modules = list(modules)
        self.discovery_only = discovery_only
        self.has_data = disc_data is not None and (
            discovery_only or test_data is not None
        )
        self.n_modules = len(self.modules)
        #: data-only mode (ISSUE 9, the atlas module plane): no stored
        #: correlation/network at all — every k×k submatrix derives from
        #: gathered data columns (zᵀz/(s-1) + the soft-threshold
        #: construction config.network_from_correlation names), so the
        #: engine's device footprint is O(n·s) instead of O(n²).
        self.data_only = (
            disc_corr is None and disc_net is None
            and (discovery_only or (test_corr is None and test_net is None))
        )
        if self.data_only:
            if config.network_from_correlation is None:
                raise ValueError(
                    "data-only engines (correlation=None, network=None) "
                    "need the derivation spec: set EngineConfig."
                    "network_from_correlation to the soft-threshold β "
                    "(or (β, kind))"
                )
            if not self.has_data:
                raise ValueError(
                    "data-only engines need discovery AND test data "
                    "matrices — with no matrices and no data there is "
                    "nothing to test"
                )
            if config.matrix_sharding == "row":
                raise ValueError(
                    "matrix_sharding='row' shards the n×n matrices the "
                    "data-only mode exists to never materialize; use "
                    "'replicated' (the data matrix is O(n·samples))"
                )
            if config.gather_mode == "fused":
                raise ValueError(
                    "gather_mode='fused' DMAs stored matrix rows; the "
                    "data-only mode derives submatrices from data columns "
                    "— use gather_mode='auto'"
                )
            if config.stat_mode == "fused":
                raise ValueError(
                    "stat_mode='fused' is not yet taught the data-only "
                    "derivation; use stat_mode='auto' (resolves to the "
                    "XLA composition here)"
                )

        # Mesh-shape-independent checkpoint identity (ISSUE 6): digest the
        # ORIGINAL host inputs before any padding / sharding / transpose,
        # so the same problem fingerprints identically on every mesh shape
        # — a checkpoint written on an N-device mesh resumes on N−1
        # devices, 1 device, or the replicated CPU rebuild without the
        # fingerprint-acceptance escape hatch.
        from ..utils.checkpoint import content_digest

        # raw arrays, not np.asarray: content_digest samples on device and
        # pulling a genome-scale device matrix to the host here would cost
        # a full transfer per engine build
        self._fingerprint_digest = content_digest(
            [disc_corr, disc_net, disc_data, test_corr, test_net, test_data]
        )
        #: discovery-side-only digest — the ObservedCache key component
        #: shared by every engine over the same discovery dataset
        self._disc_digest = content_digest([disc_corr, disc_net, disc_data])

        self.row_sharded = (
            mesh is not None and config.matrix_sharding == "row"
        )
        if config.matrix_sharding not in ("replicated", "row"):
            raise ValueError(
                f"matrix_sharding must be 'replicated' or 'row', got "
                f"{config.matrix_sharding!r}"
            )
        if config.matrix_sharding == "row" and mesh is None:
            raise ValueError("matrix_sharding='row' requires a mesh")

        dtype = jnp.dtype(config.dtype)
        # One gather-mode rule for replicated AND row-sharded paths (VERDICT
        # r1 item 3 lifted the old row_sharded → 'direct' force): 'mxu' on
        # accelerators, 'direct' on CPU, per EngineConfig.gather_mode.
        self.gather_mode = config.resolved_gather_mode(jax.default_backend())
        # Statistics execution mode (ISSUE 8): 'fused' routes null chunks
        # through the Pallas mega-kernel (gather + seven statistics [+ tally
        # fold] in VMEM, ops/fused_stats.py); resolved BEFORE effective_chunk
        # is first consulted — the row-sharded ring path rounds the chunk
        # over BOTH mesh axes. Data-only pins the XLA composition: the
        # mega-kernel consumes stored matrix rows (explicit 'fused' was
        # rejected above; 'auto' resolves here).
        self.stat_mode = (
            "xla" if self.data_only
            else config.resolved_stat_mode(jax.default_backend())
        )
        # Screened null loop (ISSUE 16): explicit bf16_rescue is refused on
        # the paths the screen is not taught — the fused mega-kernel folds
        # tallies in VMEM (no per-value screen point), gather_mode='fused'
        # DMAs rows at a precision the kernel owns, and the row-sharded
        # ring splits the chunk over two mesh axes the rescue worklist
        # re-dispatch does not reproduce. 'auto' silently resolves to
        # 'f32' on those paths (checked per run in _resolve_null_precision).
        if config.null_precision == "bf16_rescue":
            if self.stat_mode == "fused" or config.gather_mode == "fused":
                raise ValueError(
                    "null_precision='bf16_rescue' screens the XLA chunk "
                    "composition; the fused Pallas paths (stat_mode/"
                    "gather_mode='fused') fold tallies in VMEM with no "
                    "screen point — use null_precision='auto' or 'f32'"
                )
            if mesh is not None and config.matrix_sharding == "row":
                raise ValueError(
                    "null_precision='bf16_rescue' is not taught the "
                    "row-sharded ring path; use matrix_sharding="
                    "'replicated' or null_precision='f32'"
                )
        #: fused-stats row-block override from the persistent autotune cache
        #: (None = the kernel's minimal-padding heuristic); the streaming
        #: loop records measured perms/s back against the applied block
        self._fused_rowblock = None
        self._fused_rb_record = None
        if self.stat_mode == "fused" and config.autotune:
            from ..utils.autotune import make_key, resolve_fused_rowblock

            rb_key = make_key(
                jax.default_backend(), "fused-stats",
                ",".join(str(config.rounded_cap(m.size)) for m in modules),
                config.chunk_size, "rowblock",
            )
            rb, rb_cache = resolve_fused_rowblock(config, rb_key)
            self._fused_rowblock = rb
            if rb_cache is not None:
                self._fused_rb_record = (rb_cache, rb_key)
        # Derived-network mode: never store/gather the n×n network on device
        # (EngineConfig.network_from_correlation) — submatrices come from
        # |gathered corr|**β. Sample-check the claim against the supplied
        # matrices first.
        self.net_beta = config.network_from_correlation
        if self.net_beta is not None and not self.data_only:
            check_derived_network(
                disc_corr, disc_net, self.net_beta, "discovery"
            )
            if not discovery_only:
                check_derived_network(
                    test_corr, test_net, self.net_beta, "test"
                )
        if self.row_sharded:
            # bound for the sharded gatherer's per-dispatch working set on
            # the LOCAL permutation axis (mirrors the replicated path's
            # lax.map batching; the mxu row buffers are (K·cap, n) per perm)
            local_chunk = self.effective_chunk() // mesh.shape[config.mesh_axis]
            ref_mat = test_corr if test_corr is not None else disc_corr
            self._gather_perm_batch = config.resolved_perm_batch(
                self.gather_mode, jax.default_backend(), max(local_chunk, 1),
                bytes_per_perm=self._mxu_bytes_per_perm(
                    int(np.asarray(ref_mat).shape[-1]),
                    None if test_data is None
                    else int(np.asarray(test_data).shape[0]),
                ),
            )
        if self.data_only and not discovery_only:
            # no stored test matrices: the chunk/observed kernels derive
            # both submatrices from the transposed data gathered below
            self._test_corr = self._test_net = None
        elif discovery_only:
            self._test_corr = self._test_net = None
            if self.row_sharded:
                from .sharded import make_sharded_gatherer

                self._gather_perm = make_sharded_gatherer(
                    mesh, config.mesh_axis, mode=self.gather_mode,
                    perm_batch=self._gather_perm_batch,
                )
                self._gather_rep = make_sharded_gatherer(
                    mesh, None, mode=self.gather_mode
                )
        elif self.row_sharded:
            from .mesh import ROW_AXIS
            from .sharded import (
                make_sharded_gatherer, pad_square_to_multiple, shard_rows,
            )

            d_row = mesh.shape[ROW_AXIS]
            self._test_corr = shard_rows(
                jnp.asarray(pad_square_to_multiple(test_corr, d_row), dtype), mesh
            )
            self._test_net = (
                None if self.net_beta is not None
                else shard_rows(
                    jnp.asarray(pad_square_to_multiple(test_net, d_row), dtype),
                    mesh,
                )
            )
            self._gather_perm = make_sharded_gatherer(
                mesh, config.mesh_axis, mode=self.gather_mode,
                perm_batch=self._gather_perm_batch,
            )
            self._gather_rep = make_sharded_gatherer(
                mesh, None, mode=self.gather_mode
            )
        else:
            self._test_corr = jnp.asarray(test_corr, dtype)
            self._test_net = (
                None if self.net_beta is not None
                else jnp.asarray(test_net, dtype)
            )
        # The data matrix is transposed ONCE at init and ONLY the transposed
        # copy is kept on device: every mode then slices per-module data as a
        # row gather of (n, n_samples). Gathering columns of the
        # (n_samples, n) layout lowers to strided per-element loads on TPU
        # (measured ~10x whole-chunk slowdown in round 1's direct mode), and
        # keeping the untransposed copy too would double the data matrix's
        # HBM footprint at Config D scale.
        self._test_dataT = (
            jnp.asarray(np.asarray(test_data).T, dtype)
            if (self.has_data and test_data is not None)
            else None
        )

        sizes = [m.size for m in self.modules]
        if min(sizes, default=1) < 2:
            bad = [m.label for m in self.modules if m.size < 2]
            raise ValueError(
                f"modules {bad} have fewer than 2 nodes present in the test "
                "dataset; preservation statistics are undefined"
            )
        self.total_take = int(np.sum(sizes))
        self.pool = np.asarray(pool, dtype=np.int32)
        self._check_pool()
        self._pool_dev = jnp.asarray(self.pool)

        # --- bucket construction: jit once per module-size bucket [B:5] ---
        # Discovery submatrices are gathered on device (jnp.take) so large
        # discovery matrices never need a host round-trip (Config D scale,
        # SURVEY.md §6). Discovery inputs may be numpy or jax arrays.
        offsets = self._slice_offsets(sizes)
        by_cap: dict[int, list[int]] = {}
        for k, m in enumerate(self.modules):
            by_cap.setdefault(config.rounded_cap(m.size), []).append(k)

        d_data = (
            jnp.asarray(disc_data, jnp.float32) if self.has_data else None
        )
        # The discovery matrices ride as jit ARGUMENTS (not closure
        # captures — captured device arrays become compile-time constants:
        # 3.2 GB baked into the bucket-build executable at Config D scale).
        net_beta = self.net_beta
        if self.data_only:
            from ..atlas.modules import (
                make_disc_props_data_only, normalize_beta_static,
            )

            beta_static = normalize_beta_static(net_beta)
            # transposed ONCE, like the test side: per-module data slices
            # are then contiguous row gathers (see _test_dataT below)
            d_corr = d_net = None
            d_dataT = jnp.asarray(np.asarray(disc_data).T, jnp.float32)

            def _disc_bucket(dc, dn, dd, idx, mask, _dT=d_dataT):
                return make_disc_props_data_only(
                    _dT, idx, mask, net_beta=beta_static,
                )
        elif self.row_sharded:
            from .mesh import ROW_AXIS
            from .sharded import pad_square_to_multiple, shard_rows

            d_row = mesh.shape[ROW_AXIS]
            d_corr = shard_rows(
                jnp.asarray(pad_square_to_multiple(disc_corr, d_row), jnp.float32),
                mesh,
            )
            d_net = (
                None if net_beta is not None
                else shard_rows(
                    jnp.asarray(
                        pad_square_to_multiple(disc_net, d_row), jnp.float32
                    ),
                    mesh,
                )
            )
            gather_rep = self._gather_rep

            from .sharded import gather_corr_net

            @jax.jit
            def _disc_bucket(dc, dn, dd, idx, mask):
                corr_b, net_b = gather_corr_net(
                    gather_rep, dc, dn, idx, net_beta
                )
                data_b = (
                    jax.vmap(lambda ix: jnp.take(dd, ix, axis=1))(idx)
                    if dd is not None
                    else None
                )
                return jstats.make_disc_props(corr_b, net_b, data_b, mask)
        else:
            d_corr = jnp.asarray(disc_corr, jnp.float32)
            d_net = (
                None if net_beta is not None
                else jnp.asarray(disc_net, jnp.float32)
            )

            @jax.jit
            def _disc_bucket(dc, dn, dd, idx, mask):
                # idx: (K, cap) padded discovery indices; mask: (K, cap)
                sub = lambda mat, ix: mat[ix[:, None], ix[None, :]]
                corr_b = jax.vmap(partial(sub, dc))(idx)
                net_b = (
                    jstats.derived_net(corr_b, net_beta) if dn is None
                    else jax.vmap(partial(sub, dn))(idx)
                )
                data_b = (
                    jax.vmap(lambda ix: jnp.take(dd, ix, axis=1))(idx)
                    if dd is not None
                    else None
                )
                return jstats.make_disc_props(corr_b, net_b, data_b, mask)

        # the closure + its device operands are kept so the bucket-props
        # hook below (and subclass overrides — the grid packed engine
        # substitutes per-request discovery sources) can recompute props
        # for arbitrary module subsets
        self._disc_bucket_fn = _disc_bucket
        self._d_corr, self._d_net, self._d_data = d_corr, d_net, d_data

        self.buckets: list[_Bucket] = []
        for cap in sorted(by_cap):
            pos = by_cap[cap]
            didx_b, mask_b, obs_b, slices = [], [], [], []
            for k in pos:
                mod = self.modules[k]
                didx_b.append(_pad_to(mod.disc_idx.astype(np.int32), cap, (0,)))
                mask = np.zeros(cap, np.float32)
                mask[: mod.size] = 1.0
                mask_b.append(mask)
                obs_b.append(_pad_to(mod.test_idx.astype(np.int32), cap, (0,)))
                slices.append((int(offsets[k]), mod.size))

            disc = self._bucket_disc_props(
                cap, pos, np.stack(didx_b), np.stack(mask_b)
            )
            self.buckets.append(
                _Bucket(cap, pos, disc, jnp.asarray(np.stack(obs_b)), slices)
            )

        self._chunk_fn_cached: Callable | None = None
        self._observed_fn: Callable | None = None
        #: pristine full-module bucket list — `rebucket` always filters from
        #: this, so successive retirements never compound filtering error
        self._buckets_full: list[_Bucket] = self.buckets
        #: (cache, key, perm_batch) set by chunk_body when autotune applies;
        #: `record_chunk_throughput` writes the measured rate back to it
        self._autotune_record: tuple | None = None
        #: (cache, key, superchunk) set by run_null_streaming when autotune
        #: applies; `record_stream_throughput` writes the measured rate back
        self._stream_autotune_record: tuple | None = None
        #: jitted streaming programs, keyed by the observed-statistics bytes
        #: (a fresh closure per call would re-trace/re-compile every run —
        #: the same reason _chunk_fn_cached exists); invalidated by rebucket
        self._stream_super_cached: tuple | None = None
        self._stream_count_cached: tuple | None = None
        #: per-program acquisition source (aot|jit|memo), keyed by program
        #: name — the `source` tag on compile_span events and perf-ledger
        #: fingerprints (ISSUE 15)
        self._program_sources: dict[str, str] = {}
        #: perm batch the chunk body actually closed over (resolved from
        #: the autotune cache or the byte-budget heuristic) — a program
        #: CONSTANT, so part of the AOT program identity
        self._applied_perm_batch: int | None = None
        #: screened null loop (ISSUE 16): True while a bf16_rescue run is
        #: in flight — autotune/AOT/perf-ledger keys grow a precision
        #: component so compile histories never mix precisions
        self._screen_active: bool = False
        #: cached max|test operand| for the screen's cushion amplitude
        self._screen_amp: float | None = None

    def _bucket_disc_props(self, cap: int, pos, didx: np.ndarray,
                           mask: np.ndarray):
        """Discovery-side properties for one module-size bucket — ``pos``
        are the bucket's global module positions and ``didx``/``mask``
        the (K, cap) padded discovery index / node mask stacks. Consults
        the shared :class:`ObservedCache` (when one was given) before
        computing: props depend only on the discovery matrices and the
        module index content, so every engine of one grid row reuses the
        first one's device arrays. Overridden by the grid packed engine
        (serve/packer.py) to substitute per-request discovery sources."""
        return self._props_for(
            self._disc_digest, self._d_corr, self._d_net, self._d_data,
            cap, didx, mask,
        )

    def _props_for(self, disc_digest: str, dc, dn, dd, cap: int,
                   didx: np.ndarray, mask: np.ndarray):
        """Cache-aware props computation for ONE discovery source — the
        shared core of :meth:`_bucket_disc_props` and the grid packed
        engine's per-request override."""
        cache = self._observed_cache
        if cache is None:
            return self._disc_bucket_fn(
                dc, dn, dd, jnp.asarray(didx), jnp.asarray(mask)
            )
        key = cache.props_key(disc_digest, self._props_mode(),
                              cap, didx, mask)
        hit = cache.get_props(key)
        if hit is not None:
            return hit
        props = self._disc_bucket_fn(
            dc, dn, dd, jnp.asarray(didx), jnp.asarray(mask)
        )
        cache.put_props(key, props)
        return props

    def _props_mode(self) -> str:
        """Cache-key mode bits for :meth:`_bucket_disc_props`: anything
        beyond (discovery content, module indices) that changes the
        computed props must appear here, or two engines could share props
        they'd compute differently."""
        return (
            f"{'data_only' if self.data_only else 'dense'}|"
            f"row:{int(self.row_sharded)}|beta:{self.net_beta!r}|"
            f"data:{int(self.has_data)}"
        )

    def _check_pool(self) -> None:
        """Permutation-pool oversubscription check. The packed serve engine
        (ISSUE 7) overrides it with a per-request check: packed requests'
        slices legitimately overlap (each request re-slices the drawn
        permutation from offset 0, as its stand-alone run would), so the
        UNION of their module sizes may exceed the pool while every
        individual request stays valid."""
        if self.total_take > self.pool.size:
            raise ValueError(
                f"module sizes (total {self.total_take}) exceed the null "
                f"candidate pool ({self.pool.size}); use null='all' or drop "
                "modules"
            )

    def _slice_offsets(self, sizes) -> np.ndarray:
        """Per-module offsets into the drawn permutation — cumulative module
        sizes, the reference's disjoint label-shuffle semantics. Indexable
        by global module position. The packed serve engine (ISSUE 7)
        overrides this with request-local offsets so every packed module
        slices exactly where its stand-alone run would."""
        return np.concatenate([[0], np.cumsum(sizes)])

    def rebucket(self, active) -> None:
        """Rebuild the bucket list for the module subset ``active`` (global
        positions) — the adaptive engine's retirement path: later chunks
        run genuinely smaller bucket programs (fewer vmap lanes), not
        masked work.

        The RNG contract survives because each surviving module keeps its
        ORIGINAL ``(offset, size)`` slice into the drawn permutation
        (slices are copied, never recomputed from the shrunken module set),
        and permutations are still drawn over the full pool — so a
        surviving module's index sets for permutation ``i`` are identical
        to the fixed-``n_perm`` run's. Per-bucket discovery properties and
        observed indices are row-filtered on device (cheap gathers).
        ``rebucket(range(n_modules))`` restores the full set.
        """
        keep = {int(a) for a in np.asarray(active, dtype=np.int64).ravel()}
        bad = keep - set(range(self.n_modules))
        if bad:
            raise ValueError(f"unknown module positions: {sorted(bad)}")
        if keep == set(range(self.n_modules)) and sum(
            len(b.module_pos) for b in self.buckets
        ) == self.n_modules:
            # already at full strength: a no-op restore must not discard
            # the cached jitted programs — the serve warm pool (ISSUE 7)
            # relies on a retirement-free run leaving the engine compiled
            return
        new = []
        for b in self._buckets_full:
            sel = [i for i, p in enumerate(b.module_pos) if p in keep]
            if not sel:
                continue
            if len(sel) == len(b.module_pos):
                new.append(b)
                continue
            sel_a = np.asarray(sel)
            new.append(_Bucket(
                b.cap,
                [b.module_pos[i] for i in sel],
                jax.tree.map(lambda a: a[sel_a], b.disc),
                b.obs_idx[sel_a],
                [b.slices[i] for i in sel],
            ))
        if not new:
            raise ValueError("rebucket needs at least one active module")
        self.buckets = new
        self._chunk_fn_cached = None
        self._stream_super_cached = None
        self._stream_count_cached = None

    def release(self) -> None:
        """Drop every device-array reference and cached jitted program this
        engine holds (ISSUE 6 satellite): a superseded engine — mesh
        shrink, grow-back, CPU degradation — must free its HBM *before*
        the replacement engine allocates, not whenever GC gets around to
        it; on a 16 GiB chip the old matrices plus the new ones may not
        coexist. The engine is unusable afterwards; build a new one."""
        self.buckets = []
        self._buckets_full = []
        self._test_corr = self._test_net = self._test_dataT = None
        self._pool_dev = None
        self._chunk_fn_cached = None
        self._observed_fn = None
        self._stream_super_cached = None
        self._stream_count_cached = None
        self._autotune_record = None
        self._stream_autotune_record = None
        self._fused_rb_record = None
        self._gather_perm = None
        self._gather_rep = None
        self.mesh = None

    def autotune_key(self, extra: str = "") -> str:
        """Problem-shape key for the persistent throughput cache: backend ×
        gather mode × per-bucket (cap, module count) signature × chunk.
        The fused-stats mode suffixes the mode component so its
        compile-span, perf-ledger, and throughput histories never mix
        with the XLA composition's (ISSUE 8); a screened bf16_rescue run
        suffixes it the same way (ISSUE 16) — its per-chunk cost profile
        (bf16 fast pass + rescue dispatches) must never feed the f32
        path's autotune/perf-ledger/AOT histories."""
        from ..utils.autotune import make_key

        caps = ",".join(
            f"{b.cap}x{len(b.module_pos)}" for b in self.buckets
        )
        if getattr(self, "data_only", False):
            # the data-only derivation has its own cost profile — its
            # throughput/compile histories must never mix with the
            # stored-matrix gather modes' (ISSUE 9)
            mode = "data-only"
        elif self.stat_mode == "fused":
            mode = f"{self.gather_mode}+fusedstats"
        else:
            mode = self.gather_mode
        if getattr(self, "_screen_active", False):
            mode += "+bf16rescue"
        return make_key(
            jax.default_backend(), mode, caps,
            self.effective_chunk(), extra,
        )

    # ------------------------------------------------------------------
    # AOT program acquisition (ISSUE 15)
    # ------------------------------------------------------------------

    def _program_constants(self) -> str:
        """Closed-over constants of the jitted programs that the abstract
        argument signature cannot see — part of the AOT entry identity
        (:func:`netrep_tpu.utils.aot.program_key`): two engines differing
        in ANY of these trace different programs and must never share a
        serialized entry. The packed serve engine extends this with its
        per-module key-group assignment."""
        cfg = self.config
        slices = ";".join(
            f"{b.cap}@" + ",".join(repr(s) for s in b.slices)
            for b in self.buckets
        )
        return "|".join([
            type(self).__name__,
            f"beta:{self.net_beta!r}",
            f"sum:{cfg.summary_method}:{cfg.power_iters}",
            f"dtype:{cfg.dtype}",
            f"stat:{self.stat_mode}",
            f"gather:{self.gather_mode}",
            f"fx:{cfg.fused_exact}",
            f"pb:{self._applied_perm_batch}",
            f"data_only:{getattr(self, 'data_only', False)}",
            f"nullprec:"
            f"{'bf16_rescue' if self._screen_active else 'f32'}",
            f"slices:{slices}",
        ])

    def _mesh_spec_str(self) -> str:
        if self.mesh is None:
            return "mesh:none"
        return "mesh:" + ",".join(
            f"{k}={v}" for k, v in sorted(self.mesh.shape.items())
        )

    def _example_run_key(self):
        """A shape-representative run key for abstract program signatures
        (value irrelevant: programs take keys as arguments). The packed
        engine's ``prepare_key`` hook stacks one per key group."""
        return _resolve_key(self, 0)

    def program_cache_key(self, name: str) -> str:
        """The AOT store identity of one of this engine's programs —
        every ``autotune_key()`` component (backend, gather/stat mode,
        bucket caps, chunk), every closed-over program constant, and the
        mesh spec participate, so engines differing in ANY of them never
        share a serialized entry (pinned in tests/test_aot.py)."""
        from ..utils import aot

        return aot.program_key(
            self.autotune_key(extra=f"prog:{name}"),
            self._program_constants(), self._mesh_spec_str(),
        )

    def _acquire_program(self, name: str, body, build, example_args):
        """The single program-acquisition seam over the jit sites: resolve
        through the AOT store (:mod:`netrep_tpu.utils.aot`) when one is
        active and the program is exportable (mesh-free), else jit as
        before. Records the acquisition source for the run accounting's
        ``compile_span`` tag. ``body`` is the unjitted program (export +
        raw-key bridge), ``build`` the jit fallback builder,
        ``example_args`` shape-representative call arguments (None ⇒ jit
        only)."""
        from ..utils import aot

        store = aot.get_store()
        if (store is None or example_args is None or body is None
                or not self._programs_exportable()):
            self._program_sources[name] = "jit"
            return build()
        fn, src = store.acquire(
            self.program_cache_key(name),
            build, export_fn=body, example_args=example_args,
        )
        self._program_sources[name] = src
        return fn

    def _programs_exportable(self) -> bool:
        """The store only serves/exports the pristine full-module bucket
        set: adaptive retirement re-buckets mid-run into shrunken
        signatures that rarely recur across boots — exporting each would
        trade a one-off trace tax per retirement step for entries nobody
        reloads, so those re-acquisitions stay on the plain jit path
        (exactly the PR 14 cost)."""
        return sum(len(b.module_pos) for b in self.buckets) == self.n_modules

    def warmup_export(self, n_perm: int = 0) -> dict:
        """Pre-export this engine's program grid into the AOT store (the
        ``netrep warmup`` CLI): the chunk body (materialized + adaptive
        loops), the superchunk scan and the adaptive counter (streaming
        loops), and the observed pass — each traced, serialized, and
        compiled once so a fresh process (or a respawned fleet replica)
        answers its first request with ``compile_span ~0``. Returns a
        per-program ``{name: "aot"|"jit"}`` report (``jit`` = that
        program could not be exported and stays on the fallback ladder).
        """
        from ..utils import aot

        store = aot.get_store()
        report: dict[str, str] = {}
        if store is None:
            return report
        # force re-acquisition through the store: an engine that already
        # ran holds its programs in the instance caches, and warmup must
        # still reach the acquire seam to persist them
        self._chunk_fn_cached = None
        self._stream_super_cached = None
        self._stream_count_cached = None
        self._observed_fn = None
        obs0 = np.zeros((self.n_modules, N_STATS))
        builders = {
            # acquire exports the primary signature on miss and loads it
            # straight back (export parity exercised at export time, not
            # first discovered by a later boot)
            "chunk": self._chunk_fn,
            "super": lambda: self._stream_super_fn(obs0),
            "count": lambda: self._stream_count_fn(obs0),
            "observed": self.observed,
        }
        with store.exporting():
            for name in self._warm_programs():
                try:
                    builders[name]()
                    report[name] = self._program_sources.get(name, "jit")
                # netrep: allow(exception-taxonomy) — warmup is an optimization pass over a shape GRID: one unexportable/unbuildable program must report 'error' and let the rest of the grid warm, never kill the CLI
                except Exception as e:
                    logger.warning("warmup of %r failed (%s: %s); it "
                                   "stays on the jit path", name,
                                   type(e).__name__, e)
                    report[name] = "error"
        return report

    def _warm_programs(self) -> tuple[str, ...]:
        """Programs :meth:`warmup_export` pre-exports for this engine
        class — the packed serve engine overrides this (its runs use the
        materialized chunk + observed programs only; it has no grouped
        streaming path)."""
        return ("chunk", "super", "count", "observed")

    def record_chunk_throughput(self, perms_per_sec: float) -> None:
        """Steady-state chunk throughput callback from the null loop —
        persists the measurement for the (key, perm_batch) this engine
        resolved, so the next build with the same problem shape reuses the
        best-measured batch instead of the static byte-budget heuristic."""
        if self._autotune_record is not None:
            cache, key, pb = self._autotune_record
            cache.record(key, pb, perms_per_sec)

    def record_stream_throughput(self, perms_per_sec: float) -> None:
        """Steady-state streaming throughput callback
        (:func:`run_stream_superchunks`) — persists the measurement for the
        (key, superchunk) this run resolved, so the next streaming run with
        the same problem shape reuses the best-measured fused dispatch
        depth (:func:`netrep_tpu.utils.autotune.resolve_superchunk`). On
        the fused-stats path the same rate is also recorded against the
        mega-kernel's applied row block, converging the DMA/select grid
        per problem shape (ISSUE 8 autotune satellite)."""
        if self._stream_autotune_record is not None:
            cache, key, k = self._stream_autotune_record
            cache.record(key, k, perms_per_sec)
        if self._fused_rb_record is not None and self.stat_mode == "fused":
            cache, key = self._fused_rb_record
            rb = self._fused_rowblock
            if rb is None:
                # record the heuristic block actually applied to the
                # dominant (largest-cap) bucket so sweeps have a baseline
                from ..ops.fused_stats import resolve_row_block

                try:
                    cap = max(b.cap for b in self.buckets)
                    ref = self._test_corr
                    n_cols = int(ref.shape[-1]) if ref is not None else 0
                    if n_cols:
                        rb = resolve_row_block(
                            cap, n_cols, jnp.dtype(self.config.dtype).itemsize,
                            s_pad=128, has_net=self._test_net is not None,
                            has_data=self._test_dataT is not None,
                        )
                except (ValueError, AttributeError):
                    rb = None
            if rb:
                cache.record(key, int(rb), perms_per_sec)

    # ------------------------------------------------------------------
    # Observed pass (SURVEY.md §3.1 "observed pass")
    # ------------------------------------------------------------------

    def fingerprint_digest(self) -> str:
        """Content digest of the ORIGINAL host inputs, computed once at
        construction (:func:`netrep_tpu.utils.checkpoint.content_digest`)
        — a completed checkpoint is never silently reused against changed
        data, while the digest stays independent of mesh shape, matrix
        sharding, and padding (the elastic-resume contract, ISSUE 6)."""
        return self._fingerprint_digest

    # -- shared chunk/key contract (single source of truth for the
    #    reproducibility guarantee; also used by MultiTestEngine) ----------

    def effective_chunk(self) -> int:
        """Chunk size, rounded to a multiple of the mesh's permutation axis
        — or of the FULL mesh (perm × row) on the ring-exchange path, where
        the row axis carries its own permutation shard (ISSUE 8: each row
        shard evaluates 1/R of the chunk while the matrix blocks stream
        around the ring)."""
        C = self.config.chunk_size
        if self.mesh is not None:
            ax = self.mesh.shape[self.config.mesh_axis]
            if self._stat_fused_ring():
                from .mesh import ROW_AXIS

                ax *= self.mesh.shape.get(ROW_AXIS, 1)
            C = max(ax, (C // ax) * ax)
        return C

    def _stat_fused_ring(self) -> bool:
        """Whether null chunks run the ring-exchange row-sharded fused-stats
        path: the chunk splits over BOTH mesh axes and the row-sharded
        matrices ring-rotate between neighbors instead of psum-assembling
        every gather (ISSUE 8)."""
        return self.stat_mode == "fused" and self.row_sharded

    def _stat_fused_rep(self) -> bool:
        """Fused-stats over replicated matrices on a perm-axis mesh: XLA
        cannot auto-partition a pallas_call, so the chunk/streaming
        programs run under shard_map (same rule as the fused GATHER
        mode's ``_stream_fused_rep``)."""
        return (
            self.stat_mode == "fused" and not self.row_sharded
            and self.mesh is not None
        )

    @staticmethod
    def perm_keys(key: jax.Array, start: int, count: int) -> jax.Array:
        """Per-permutation keys ``fold_in(key, i)`` for i in [start, start+count)
        — the chunk-size- and mesh-independent seeding contract
        (SURVEY.md §7 "RNG semantics"). Jitted (static count, traced start):
        eager dispatch costs ~1s per op on tunneled TPU backends, which
        would dwarf the chunk compute in the hot loop."""
        return _perm_keys_jit(key, jnp.uint32(start), int(count))

    @staticmethod
    def perm_keys2d(key: jax.Array, start: int, k: int, c: int) -> jax.Array:
        """(k, c) per-permutation keys for one superchunk — row j holds
        chunk j's keys ``fold_in(key, start + j*c + i)``, so the streaming
        executor draws exactly the permutations the chunk-by-chunk loop
        draws at the same indices (the RNG contract is shared, not
        re-derived)."""
        return _perm_keys2d_jit(key, jnp.uint32(start), int(k), int(c))

    def _module_sig(self) -> str:
        """Content digest of the module specs (labels, sizes, index sets)
        — the ObservedCache key component beside the matrix fingerprint."""
        import hashlib

        h = hashlib.blake2b(digest_size=8)
        for m in self.modules:
            h.update(str(m.label).encode() + b"|")
            h.update(np.ascontiguousarray(m.disc_idx, dtype=np.int64))
            h.update(np.ascontiguousarray(m.test_idx, dtype=np.int64))
        return h.hexdigest()

    def observed(self) -> np.ndarray:
        """(n_modules, 7) observed statistics on the actual overlap sets."""
        if self.discovery_only:
            raise RuntimeError(
                "engine was built discovery_only; test-side passes live in "
                "the wrapping engine"
            )
        cache = self._observed_cache
        # only the pristine full-module bucket list is cacheable — a
        # retirement-filtered engine would compute (and poison) NaN rows
        okey = None
        if cache is not None and self.buckets is self._buckets_full:
            okey = cache.observed_key(
                self._fingerprint_digest, self._module_sig(),
                f"{self._props_mode()}|g:{self.gather_mode}"
                f"|dt:{self.config.dtype}",
            )
            hit = cache.get_observed(okey)
            if hit is not None:
                return hit
        if self._observed_fn is None:
            b0 = self.buckets[0]
            if self.data_only:
                from ..atlas.modules import (
                    data_only_gather_and_stats, normalize_beta_static,
                )

                body = jax.vmap(
                    partial(
                        data_only_gather_and_stats,
                        net_beta=normalize_beta_static(self.net_beta),
                        n_iter=self.config.power_iters,
                        summary_method="eigh",  # observed: exact
                    ),
                    in_axes=(0, 0, None),
                )
                inner = self._acquire_program(
                    "observed", body, lambda: jax.jit(body),
                    (b0.disc, b0.obs_idx, self._test_dataT),
                )
                self._observed_fn = (
                    lambda disc, idx, _tc, _tn, tdT: inner(disc, idx, tdT)
                )
            elif self.row_sharded:
                self._program_sources["observed"] = "jit"
                self._observed_fn = make_row_sharded_observed(
                    self._gather_rep, self.net_beta
                )
            else:
                body = jax.vmap(
                    partial(
                        jstats.gather_and_stats_mxu
                        if self.gather_mode == "mxu"
                        else jstats.gather_and_stats,
                        n_iter=self.config.power_iters,
                        summary_method="eigh",  # observed: exact, runs once
                        net_beta=self.net_beta,
                    ),
                    in_axes=(0, 0, None, None, None),
                )
                self._observed_fn = self._acquire_program(
                    "observed", body, lambda: jax.jit(body),
                    (b0.disc, b0.obs_idx, self._test_corr,
                     self._test_net, self._test_dataT),
                )
        out = np.full((self.n_modules, N_STATS), np.nan)
        for b in self.buckets:
            res = self._observed_fn(
                b.disc, b.obs_idx, self._test_corr, self._test_net,
                self._test_dataT,
            )
            out[b.module_pos] = np.asarray(res, dtype=np.float64)
        if okey is not None:
            cache.put_observed(okey, out)
        return out

    # ------------------------------------------------------------------
    # Null chunks
    # ------------------------------------------------------------------

    def _mxu_bytes_per_perm(self, n_cols: int, n_samples: int | None) -> int:
        """Per-permutation working set of the mxu gather: the (Σ cap, n) row
        blocks for each stored matrix (one when the network derives from the
        correlation) plus the (Σ cap, s) data blocks. Sizes the lax.map
        batch against ``EngineConfig.mxu_batch_budget_bytes`` — a fixed
        small batch leaves small problems latency-bound, an unbounded one
        OOMs at genome scale."""
        itemsize = jnp.dtype(self.config.dtype).itemsize
        cap_rows = sum(self.config.rounded_cap(m.size) for m in self.modules)
        n_mats = 1 if self.net_beta is not None else 2
        total = cap_rows * n_cols * itemsize * n_mats
        if n_samples:
            total += cap_rows * n_samples * itemsize
        return total

    def chunk_args(self) -> tuple:
        """Device operands of the chunk program. Passed to the jitted chunk
        as ARGUMENTS, never captured in its closure: closure-captured device
        arrays become compile-time constants, and baking the n×n matrices
        into the executable (3+ GB at Config D scale) multiplies compile
        time and HBM footprint."""
        return (
            self._pool_dev,
            self._test_corr,
            self._test_net,
            self._test_dataT,
            [b.disc for b in self.buckets],
        )

    def chunk_body(self) -> Callable:
        """The unjitted chunk program: draw a node permutation per chunk
        element, slice per-module index sets in the fixed module order
        (disjoint within a permutation — the reference's label-shuffle
        semantics, SURVEY.md §3.1), and run all bucket kernels. Signature:
        ``chunk(keys, *chunk_args) -> [per-bucket (C, K_b, 7) arrays]``
        with ``chunk_args`` as produced by :meth:`chunk_args` (used by
        ``__graft_entry__.entry``)."""
        if self.discovery_only:
            # explicit contract error; without it the _test_corr.shape deref
            # below surfaces as an opaque AttributeError on None
            raise RuntimeError(
                "engine was built discovery_only and has no test matrices; "
                "the wrapping engine owns the chunk program"
            )
        if self.stat_mode == "fused":
            return self._fused_stats_chunk_body()
        if self.data_only:
            return self._data_only_chunk_body()
        cfg = self.config
        # only static structure may be closed over (see chunk_args)
        caps_slices = [(b.cap, tuple(b.slices)) for b in self.buckets]
        row_sharded = self.row_sharded
        gather_perm = self._gather_perm if row_sharded else None
        if row_sharded:
            from .sharded import gather_corr_net as _gcn
        gather_mode = self.gather_mode
        heuristic = cfg.resolved_perm_batch(
            gather_mode, jax.default_backend(), self.effective_chunk(),
            bytes_per_perm=self._mxu_bytes_per_perm(
                int(self._test_corr.shape[-1]),
                None if self._test_dataT is None
                else int(self._test_dataT.shape[-1]),
            ),
        )
        # measured-throughput override of the static byte-budget heuristic
        # (utils/autotune.py): reuse the best-recorded batch for this
        # problem shape; the null loop records what this run measures
        from ..utils.autotune import resolve_perm_batch

        at_key = self.autotune_key()
        perm_batch, at_cache = resolve_perm_batch(cfg, at_key, heuristic)
        self._applied_perm_batch = perm_batch
        self._autotune_record = (
            (at_cache, at_key, perm_batch) if at_cache is not None else None
        )
        net_beta = self.net_beta
        kernel = partial(
            jstats.gather_and_stats_mxu if gather_mode == "mxu"
            else jstats.gather_and_stats,
            n_iter=cfg.power_iters,
            summary_method=cfg.summary_method,
            net_beta=net_beta,
        )

        def chunk(keys: jax.Array, pool, tc, tn, td, discs) -> list[jax.Array]:
            # keys: (C,) typed PRNG keys, one per permutation
            if row_sharded:
                perm = jax.vmap(lambda k: jax.random.permutation(k, pool))(keys)
                outs = []
                for (cap, slices), disc in zip(caps_slices, discs):
                    idx_b = _idx_blocks(perm, cap, slices)  # (C, K, cap)
                    # collective-assembled gathers from the row-sharded
                    # matrices; statistics batch over (C, K) by broadcasting
                    # (disc props carry the K axis).
                    sub_c, sub_n = _gcn(gather_perm, tc, tn, idx_b, net_beta)
                    zd = (
                        jstats.gather_zdata(td, idx_b, disc.mask)
                        if td is not None else None
                    )
                    outs.append(
                        jstats.module_stats_masked(
                            disc, sub_c, sub_n, zd,
                            n_iter=cfg.power_iters,
                            summary_method=cfg.summary_method,
                        )
                    )
                return outs

            if gather_mode == "fused":
                # Fused-kernel path: scan over perm sub-batches; each batch
                # flattens (B, K) instances into the Pallas kernel's grid
                # (ops/fused_gather.py — one HBM pass per row set, one-hot
                # select in VMEM). Structure mirrors the row-sharded branch:
                # batched indices, broadcast-batched statistics.
                gather_submatrix_fused = make_fused_gather(cfg)

                def batch_body(_, keys_b):
                    perm = jax.vmap(
                        lambda k: jax.random.permutation(k, pool)
                    )(keys_b)
                    outs_b = []
                    for (cap, slices), disc in zip(caps_slices, discs):
                        idx_b = _idx_blocks(perm, cap, slices)  # (B, K, cap)
                        sub_c = gather_submatrix_fused(tc, idx_b)
                        sub_n = (
                            jstats.derived_net(sub_c, net_beta)
                            if tn is None
                            else gather_submatrix_fused(tn, idx_b)
                        )
                        zd = (
                            jstats.gather_zdata(td, idx_b, disc.mask)
                            if td is not None else None
                        )
                        outs_b.append(jstats.module_stats_masked(
                            disc, sub_c, sub_n, zd,
                            n_iter=cfg.power_iters,
                            summary_method=cfg.summary_method,
                        ))
                    return None, outs_b

                C = keys.shape[0]
                outs, _ = fused_scan(keys, perm_batch, batch_body)
                # (Cp//B, B, K, 7) -> (C, K, 7) per bucket (drop pad tail)
                return [o.reshape((-1,) + o.shape[2:])[:C] for o in outs]

            # Replicated path: sequence permutations with lax.map (one device
            # dispatch; batch_size bounds the mxu path's (batch, rows, n)
            # gather working set in HBM), vmap over each bucket's modules.
            def per_perm(key):
                perm = jax.random.permutation(key, pool)
                outs_p = []
                for (cap, slices), disc in zip(caps_slices, discs):
                    idx_b = _idx_blocks(perm, cap, slices)  # (K, cap)
                    over_mods = jax.vmap(kernel, in_axes=(0, 0, None, None, None))
                    outs_p.append(over_mods(disc, idx_b, tc, tn, td))
                return outs_p

            return jax.lax.map(per_perm, keys, batch_size=perm_batch)

        return chunk

    def _data_only_chunk_body(self) -> Callable:
        """Unjitted chunk program for the data-only mode (ISSUE 9, the
        atlas module plane): per permutation, every bucket gathers ONLY
        the (s, m) data slice and derives both test submatrices from it
        (:func:`netrep_tpu.atlas.modules.data_only_gather_and_stats` —
        ``zᵀz/(s-1)`` on the MXU + the elementwise soft-threshold
        construction). Same output contract as the stored-matrix chunk
        (per-bucket ``(C, K, 7)``), so every null loop — materialized,
        streaming, adaptive, monitored — consumes it unchanged."""
        from ..atlas.modules import (
            data_only_gather_and_stats, normalize_beta_static,
        )

        cfg = self.config
        caps_slices = [(b.cap, tuple(b.slices)) for b in self.buckets]
        # the working set per permutation is (K, cap, s) slices + (K, cap,
        # cap) submatrices — the 'direct' profile, no stored-matrix rows
        heuristic = cfg.resolved_perm_batch(
            "direct", jax.default_backend(), self.effective_chunk()
        )
        from ..utils.autotune import resolve_perm_batch

        at_key = self.autotune_key()
        perm_batch, at_cache = resolve_perm_batch(cfg, at_key, heuristic)
        self._applied_perm_batch = perm_batch
        self._autotune_record = (
            (at_cache, at_key, perm_batch) if at_cache is not None else None
        )
        kernel = partial(
            data_only_gather_and_stats,
            net_beta=normalize_beta_static(self.net_beta),
            n_iter=cfg.power_iters,
            summary_method=cfg.summary_method,
        )

        def chunk(keys: jax.Array, pool, tc, tn, td, discs) -> list[jax.Array]:
            # tc/tn ride as None placeholders so the chunk signature (and
            # every loop built on chunk_args) stays mode-independent
            def per_perm(key):
                perm = jax.random.permutation(key, pool)
                outs_p = []
                for (cap, slices), disc in zip(caps_slices, discs):
                    idx_b = _idx_blocks(perm, cap, slices)  # (K, cap)
                    over_mods = jax.vmap(kernel, in_axes=(0, 0, None))
                    outs_p.append(over_mods(disc, idx_b, td))
                return outs_p

            return jax.lax.map(per_perm, keys, batch_size=perm_batch)

        return chunk

    def _fused_stats_chunk_body(self) -> Callable:
        """Unjitted chunk program for ``stat_mode='fused'`` (ISSUE 8): per
        permutation sub-batch, each bucket's index blocks go straight into
        the Pallas mega-kernel — one HBM pass gathers the module rows and
        the seven statistics are computed in VMEM
        (:func:`netrep_tpu.ops.fused_stats.fused_stats_values`). On the
        row-sharded path the body instead runs INSIDE ``shard_map`` over
        (perm × row): the chunk splits over both axes and each shard
        assembles full submatrices by streaming the matrix row blocks
        around the neighbor ring
        (:func:`netrep_tpu.ops.fused_stats.ring_gather_all` — the exchange
        that replaces the per-gather psum collective), then computes the
        statistics on its local permutation slice. Returns per-bucket
        ``(C[, _loc], K, 7)`` arrays — the same contract as the XLA chunk
        body, so every null loop consumes it unchanged."""
        import os

        cfg = self.config
        caps_slices = [(b.cap, tuple(b.slices)) for b in self.buckets]
        net_beta = self.net_beta
        from ..utils.autotune import resolve_perm_batch

        at_key = self.autotune_key()
        heuristic = cfg.resolved_perm_batch(
            "fused", jax.default_backend(), self.effective_chunk()
        )
        perm_batch, at_cache = resolve_perm_batch(cfg, at_key, heuristic)
        self._applied_perm_batch = perm_batch
        self._autotune_record = (
            (at_cache, at_key, perm_batch) if at_cache is not None else None
        )

        if self._stat_fused_ring():
            from ..ops.fused_stats import ring_gather_all
            from .mesh import ROW_AXIS

            R = self.mesh.shape[ROW_AXIS]
            on_cpu = jax.default_backend() == "cpu"
            exact = bool(cfg.fused_exact) and (
                cfg.fused_exact == "always" or not on_cpu
            )
            use_dma = (
                not on_cpu and os.environ.get("NETREP_RING_DMA") == "1"
            )
            axis_names = tuple(self.mesh.axis_names)

            def chunk(keys, pool, tc, tn, td, discs):
                # keys: THIS shard's local slice of the chunk (the caller
                # shards the chunk over perm × row, so the row axis carries
                # its own permutation share — R× more perm parallelism from
                # the same mesh, paid for by streaming the matrix once
                # around the ring per chunk)
                perm = jax.vmap(
                    lambda k: jax.random.permutation(k, pool)
                )(keys)
                idx_list = [
                    _idx_blocks(perm, cap, slices)
                    for cap, slices in caps_slices
                ]
                mats = [tc] + ([] if tn is None else [tn])
                subs = ring_gather_all(
                    mats, idx_list, ROW_AXIS, R, tc.shape[0],
                    interpret=on_cpu, exact=exact, use_dma=use_dma,
                    mesh_axis_names=axis_names,
                )
                outs = []
                for i, ((cap, slices), disc) in enumerate(
                        zip(caps_slices, discs)):
                    sub_c = subs[0][i]
                    sub_n = (
                        subs[1][i] if tn is not None
                        else jstats.derived_net(sub_c, net_beta)
                    )
                    zd = (
                        jstats.gather_zdata(td, idx_list[i], disc.mask)
                        if td is not None else None
                    )
                    outs.append(jstats.module_stats_masked(
                        disc, sub_c, sub_n, zd, n_iter=cfg.power_iters,
                        summary_method=cfg.summary_method,
                    ))
                return outs

            return chunk

        vals_fn, _ = make_fused_stats(cfg)
        rb = self._fused_rowblock

        def chunk(keys, pool, tc, tn, td, discs):
            def batch_body(_, keys_b):
                perm = jax.vmap(
                    lambda k: jax.random.permutation(k, pool)
                )(keys_b)
                outs_b = []
                for (cap, slices), disc in zip(caps_slices, discs):
                    idx_b = _idx_blocks(perm, cap, slices)  # (B, K, cap)
                    outs_b.append(vals_fn(
                        tc, tn, td, disc, idx_b, net_beta=net_beta,
                        row_block=rb,
                    ))
                return None, outs_b

            C = keys.shape[0]
            outs, _ = fused_scan(keys, perm_batch, batch_body)
            return [o.reshape((-1,) + o.shape[2:])[:C] for o in outs]

        return chunk

    def _fused_count_chunk(self, axis_name) -> Callable:
        """Counter for the fused-stats streaming paths (ISSUE 8): one
        ``count_chunk(keys_c, valid_c, chunk_ops, obs) -> deltas`` whose
        tally fold happens INSIDE the mega-kernel's VMEM accumulator —
        only O(modules·7) int32 counts per kernel sweep reach HBM, and the
        superchunk scan / adaptive dispatch add them into the carry
        exactly as the XLA counter's deltas. ``axis_name`` (under
        shard_map on a perm-axis mesh) offsets the validity mask by the
        shard's chunk slice and psums the per-shard deltas. The counts
        compare the very registers the values output writes, so streaming
        tallies equal ``tail_counts`` of the fused materialized null
        bit-for-bit (pinned in tests/test_fused_stats.py)."""
        cfg = self.config
        caps_slices = [(b.cap, tuple(b.slices)) for b in self.buckets]
        sizes_k = [len(b.module_pos) for b in self.buckets]
        net_beta = self.net_beta
        _, counts_fn = make_fused_stats(cfg)
        rb = self._fused_rowblock
        perm_batch = cfg.resolved_perm_batch(
            "fused", jax.default_backend(), self.effective_chunk()
        )

        def count_chunk(keys_c, valid_c, chunk_ops, obs_b):
            pool, tc, tn, td, discs = chunk_ops
            C = keys_c.shape[0]
            B = min(perm_batch, C)
            nb = -(-C // B)
            Cp = nb * B
            keys_p = (
                jnp.concatenate([keys_c, keys_c[-1:].repeat(Cp - C, axis=0)])
                if Cp != C else keys_c
            )
            pos = jnp.arange(Cp, dtype=jnp.int32)
            col0 = (
                shard_chunk_offset(axis_name, C)
                if axis_name is not None else 0
            )
            # two gates: padded scan-tail perms (pos >= C — repeats of the
            # last key) and the run's tail-chunk validity mask
            pvalid = ((pos < C) & ((pos + col0) < valid_c)).astype(jnp.int32)
            init = [
                tuple(jnp.zeros((k, N_STATS), jnp.int32) for _ in range(3))
                for k in sizes_k
            ]

            def body(carry, xs):
                keys_b, pv_b = xs
                perm = jax.vmap(
                    lambda kk: jax.random.permutation(kk, pool)
                )(keys_b)
                new = []
                for (cap, slices), disc, ob, ts in zip(
                        caps_slices, discs, obs_b, carry):
                    idx_b = _idx_blocks(perm, cap, slices)
                    _v, hi, lo, eff = counts_fn(
                        tc, tn, td, disc, idx_b, pv_b, ob,
                        net_beta=net_beta, row_block=rb,
                    )
                    new.append((ts[0] + hi, ts[1] + lo, ts[2] + eff))
                return new, None

            deltas, _ = jax.lax.scan(
                body, init,
                (keys_p.reshape(nb, B, *keys_p.shape[1:]),
                 pvalid.reshape(nb, B)),
            )
            if axis_name is not None:
                deltas = jax.lax.psum(deltas, axis_name)
            return deltas

        return count_chunk

    def _build_chunk_fn(self) -> Callable:
        """Jit the chunk body (operands as arguments, :meth:`chunk_args`),
        sharding the per-permutation key array (and outputs) along the
        mesh's permutation axis when a mesh is present — XLA then partitions
        the whole chunk across devices over ICI (SURVEY.md §2.3)."""
        chunk = self.chunk_body()
        cfg = self.config
        args = self.chunk_args()
        if self.mesh is not None:
            from .distributed import to_global

            keys_sharding = NamedSharding(self.mesh, P(cfg.mesh_axis))
            out_shardings = [
                NamedSharding(self.mesh, P(cfg.mesh_axis))
                for _ in self.buckets
            ]
            if self._stat_fused_ring():
                # Ring-exchange path (ISSUE 8): the chunk splits over BOTH
                # mesh axes — each (perm, row) shard evaluates its own
                # permutation slice against ring-streamed matrix blocks —
                # so keys and outputs shard over the combined axes and the
                # row-sharded matrices enter with their storage specs
                # (ring_chunk_specs — the single spec contract shared with
                # the streaming builders).
                from .sharded import _NO_CHECK_KW, _shard_map, ring_chunk_specs

                spec_c, op_specs = ring_chunk_specs(cfg.mesh_axis)
                keys_sharding = NamedSharding(self.mesh, spec_c)
                out_shardings = [
                    NamedSharding(self.mesh, spec_c) for _ in self.buckets
                ]
                smapped = _shard_map(
                    chunk,
                    mesh=self.mesh,
                    # (keys, pool, tc, tn, td, discs)
                    in_specs=(spec_c,) + op_specs,
                    out_specs=spec_c,
                    **_NO_CHECK_KW,
                )
                jitted = jax.jit(smapped, out_shardings=out_shardings)
            elif (self.gather_mode == "fused" or self._stat_fused_rep()) \
                    and not self.row_sharded:
                # Replicated matrices + perm-axis mesh: XLA's automatic
                # partitioner cannot split a pallas_call, so the whole chunk
                # runs under shard_map instead — each device evaluates its
                # local key shard against the full (replicated) matrices;
                # permutations are embarrassingly parallel, so the body
                # needs no collectives. Specs: keys split on the perm axis,
                # every matrix/disc-prop operand replicated (single specs
                # broadcast over pytree operands).
                from .sharded import _NO_CHECK_KW, _shard_map

                smapped = _shard_map(
                    chunk,
                    mesh=self.mesh,
                    # derive the replicated-spec count from the operand
                    # tuple so a chunk-signature change cannot desync
                    in_specs=(P(cfg.mesh_axis),) + (P(),) * len(args),
                    out_specs=P(cfg.mesh_axis),
                    **_NO_CHECK_KW,
                )
                jitted = jax.jit(smapped, out_shardings=out_shardings)
            else:
                jitted = jax.jit(chunk, out_shardings=out_shardings)
            if not keys_sharding.is_fully_addressable:
                # Multi-host mesh: every operand of the jitted computation
                # must be a global array (_globalize_replicated replicates
                # the host-local ones; row-sharded inputs already carry
                # global shardings).
                args = _globalize_replicated(self.mesh, args)

            def fn(keys):
                # shard keys explicitly; the matrix operands keep their own
                # (committed) shardings — replicated or row-sharded
                return jitted(to_global(keys, keys_sharding), *args)

            self._program_sources["chunk"] = "jit"
            return fn
        # mesh-free: the chunk program resolves through the AOT store
        # (ISSUE 15) — a warm store serves the serialized program and the
        # first chunk dispatch pays no trace/lower/compile
        example = (
            self.perm_keys(self._example_run_key(), 0,
                           self.effective_chunk()),
        ) + args
        jitted = self._acquire_program(
            "chunk", chunk, lambda: jax.jit(chunk), example
        )
        return lambda keys: jitted(keys, *args)

    def _chunk_fn(self) -> Callable:
        if self._chunk_fn_cached is None:
            self._chunk_fn_cached = self._build_chunk_fn()
        else:
            # in-process reuse (repeat run / warm-pool engine): the run's
            # compile_span history must not mix with cold or AOT history
            self._program_sources["chunk"] = "memo"
        return self._chunk_fn_cached

    def run_null(
        self,
        n_perm: int,
        key: jax.Array | int = 0,
        progress: Callable[[int, int], None] | None = None,
        nulls_init: np.ndarray | None = None,
        start_perm: int = 0,
        checkpoint_path: str | None = None,
        checkpoint_every: int = 8192,
        profile=None,
        telemetry=None,
        fault_policy=None,
        observed: np.ndarray | None = None,
    ) -> tuple[np.ndarray, int]:
        """Compute the permutation null distribution.

        Parameters
        ----------
        n_perm : total permutations.
        key : PRNG key (or integer seed) — the engine's reproducibility
            contract: same key + same inputs = same null, independent of
            chunk size and mesh (SURVEY.md §7 "RNG semantics").
        progress : optional callback ``(done, total)`` per chunk.
        nulls_init, start_perm : resume support — a partially-filled null
            array and the index to continue from (SURVEY.md §5
            "checkpoint/resume").
        checkpoint_path : when set, the partial null is persisted there
            (atomic ``.npz``) every ``checkpoint_every`` permutations, on
            interrupt, and on completion; an existing compatible checkpoint
            at the path is resumed from automatically (exact: per-permutation
            keys depend only on (key, index)). Mismatched problem/seed
            raises (SURVEY.md §5 "checkpoint/resume").
        checkpoint_every : checkpoint cadence in permutations (rounded up to
            whole chunks).
        profile : optional :class:`~netrep_tpu.utils.profiling.NullProfile`
            accumulating dispatch counts and device→host transfer bytes —
            the denominators of the streaming executor's amortization claims
            (``bench.py --config superchunk``).
        telemetry : optional :class:`~netrep_tpu.utils.telemetry.Telemetry`
            event bus (defaults to the ambient bus when one is active —
            e.g. under ``module_preservation(telemetry=...)``): per-chunk
            events, run envelope, stall watchdog. Off (None, no ambient
            bus) costs one ``None`` check per run and results are
            bit-identical.
        fault_policy : optional
            :class:`~netrep_tpu.utils.config.FaultPolicy` (or a shared
            :class:`~netrep_tpu.utils.faults.FaultRuntime`): transient
            dispatch failures retry with backoff (exact — chunk *i*
            regenerates identical keys), hung dispatches are abandoned
            after an emergency checkpoint, device loss raises
            :class:`~netrep_tpu.utils.faults.DeviceLostError` for the
            caller's CPU-degradation ladder. None (default) is
            bit-identical to previous releases.
        observed : optional ``(n_modules, 7)`` observed statistics —
            required for the screened bf16_rescue null loop (ISSUE 16:
            the screen decides exceedance comparisons against them);
            ignored by the f32 path. ``null_precision='auto'`` without
            ``observed`` runs the f32 path; explicit
            ``null_precision='bf16_rescue'`` without it raises.

        Returns
        -------
        (nulls, completed) — ``(n_perm, n_modules, 7)`` array (NaN rows
        beyond ``completed`` if interrupted) and the number of completed
        permutations. A ``KeyboardInterrupt`` during the loop returns the
        partial result instead of raising (the reference's Ctrl-C path,
        SURVEY.md §5 "failure detection").
        """
        if self.discovery_only:
            raise RuntimeError(
                "engine was built discovery_only; test-side passes live in "
                "the wrapping engine"
            )
        # resolve BEFORE building the write closure: when telemetry is on
        # and the caller passed no profile, the auto-created one must be
        # the instance `write` records transfer bytes to
        telemetry, profile = _telemetry_profile(telemetry, profile)
        if self._resolve_null_precision(observed) == "bf16_rescue":
            from . import screened as scr

            state = scr.RescueState()
            self._screen_active = True
            try:
                fn = self._screened_fn(observed, state, telemetry, profile)
                nulls, completed = run_checkpointed_chunks(
                    self, n_perm, key, fn,
                    (n_perm, self.n_modules, N_STATS),
                    self._null_write(profile),
                    progress=progress, nulls_init=nulls_init,
                    start_perm=start_perm, checkpoint_path=checkpoint_path,
                    checkpoint_every=checkpoint_every, profile=profile,
                    telemetry=telemetry, fault_policy=fault_policy,
                    fingerprint_extra=scr.SCREEN_FP, extra_state=state,
                )
            finally:
                self._screen_active = False
            self._emit_null_pass_end(telemetry, "materialized", state)
            return nulls, completed
        return run_checkpointed_chunks(
            self, n_perm, key, self._chunk_fn(),
            (n_perm, self.n_modules, N_STATS), self._null_write(profile),
            progress=progress, nulls_init=nulls_init, start_perm=start_perm,
            checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
            profile=profile, telemetry=telemetry, fault_policy=fault_policy,
        )

    def _null_write(self, profile=None) -> Callable:
        """Chunk→null scatter shared by the fixed and adaptive loops. Reads
        ``self.buckets`` at call time, so after a `rebucket` it scatters
        exactly the surviving modules."""

        def write(nulls, outs, done, take):
            from .distributed import gather_to_host

            for b, out in zip(self.buckets, outs):
                # transfer the whole chunk output and slice on the HOST: a
                # device-side `out[:take]` is an eager op, and eager dispatch
                # on tunneled backends costs ~1s per op (the arrays are tiny).
                # On MULTI-HOST meshes only, _trim_tail_shards first drops
                # whole trailing perm-axis shards of a tail chunk so the
                # padded tail never crosses DCN; gather_to_host then
                # allgathers across processes (the perm-axis shards live on
                # other hosts' devices and np.asarray alone would fail).
                arr = gather_to_host(
                    _trim_tail_shards(out, take)
                ).astype(np.float64)
                if profile is not None:
                    profile.record_transfer(arr.nbytes)
                nulls[done: done + take, b.module_pos] = arr[:take]

        return write

    def run_null_adaptive(
        self,
        n_perm: int,
        observed: np.ndarray,
        key: jax.Array | int = 0,
        alternative: str = "greater",
        rule=None,
        progress: Callable[[int, int], None] | None = None,
        checkpoint_path: str | None = None,
        checkpoint_every: int = 8192,
        telemetry=None,
        fault_policy=None,
        priors=None,
    ) -> tuple[np.ndarray, int, bool]:
        """Sequential early-stopping variant of :meth:`run_null`
        (:func:`run_adaptive_chunks`): ``n_perm`` becomes a *ceiling* —
        modules whose accept/reject decision at the stop rule's alpha is
        settled retire early and drop out of later chunks, leaving their
        remaining null rows NaN (per-module counts:
        :func:`netrep_tpu.ops.pvalues.effective_nperm`).

        ``observed`` are this engine's observed statistics (the monitor
        tallies exceedances against them) and ``alternative`` must match
        the tail the final p-values will use. ``priors`` — optional
        ``(hi, lo, n_used)`` count-space tallies from a prior run of the
        same cell, seeded into the stop monitor's decision rules
        (:meth:`~netrep_tpu.ops.sequential.StopMonitor.seed_priors`, the
        grid's incremental-re-analysis warm start); reported tallies and
        p-values stay fresh-draw-only. Returns ``(nulls, completed,
        finished)`` — ``completed`` is the *deepest* module's permutation
        count, ``finished`` False only on ``KeyboardInterrupt``.
        """
        from ..ops.sequential import StopMonitor, StopRule

        if self.discovery_only:
            raise RuntimeError(
                "engine was built discovery_only; test-side passes live in "
                "the wrapping engine"
            )
        monitor = StopMonitor(
            np.asarray(observed, dtype=np.float64).reshape(
                self.n_modules, -1
            ),
            alternative, rule or StopRule(),
        )
        if priors is not None:
            monitor.seed_priors(*priors)
        return self.run_null_monitored(
            n_perm, key, monitor, progress=progress,
            checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every, telemetry=telemetry,
            fault_policy=fault_policy,
        )

    def run_null_monitored(
        self,
        n_perm: int,
        key,
        monitor,
        progress: Callable[[int, int], None] | None = None,
        checkpoint_path: str | None = None,
        checkpoint_every: int = 8192,
        telemetry=None,
        fault_policy=None,
    ) -> tuple[np.ndarray, int, bool]:
        """Chunked null under a CALLER-SUPPLIED retirement monitor — the
        packed-run entry point (ISSUE 7). ``monitor`` implements the
        :class:`~netrep_tpu.ops.sequential.StopMonitor` update surface
        (``update``/``active_positions``/``any_active``/``active``/
        ``folded``/``total_evaluated``; plus ``state_arrays``/
        ``restore_state`` when checkpointing): after each chunk it folds
        the chunk's values for the active modules and returns the global
        positions to retire — which then *drop out* of later dispatches
        via the same retirement re-bucketing the adaptive engine uses.

        The serve scheduler's pack monitor
        (:class:`netrep_tpu.serve.packer.PackMonitor`) rides this to run
        MANY requests' modules in shared module-size-bucket dispatches:
        each request's modules retire at its own ``n_perm`` ceiling (and
        by its own stop rule when adaptive), so cheap requests exit the
        shared dispatch after a few hundred permutations instead of the
        pack's maximum. The engine is restored to full strength on exit,
        keeping warm-pool instances reusable."""
        if self.discovery_only:
            raise RuntimeError(
                "engine was built discovery_only; test-side passes live in "
                "the wrapping engine"
            )

        def slice_vals(nulls, done, take, pos):
            return nulls[done: done + take][:, pos, :]

        # Screened bf16 fast pass (ISSUE 16): the monitor's observed
        # statistics drive the screen when the shape matches the
        # single-test layout (the packed serve monitor tallies other
        # cell shapes — those runs stay f32 under 'auto').
        obs_arr = getattr(monitor, "observed", None)
        if self._resolve_null_precision(obs_arr) == "bf16_rescue":
            from . import screened as scr

            telemetry = tm.resolve(telemetry)
            state = scr.RescueState()
            self._screen_active = True
            try:
                return run_adaptive_chunks(
                    self, n_perm, key,
                    lambda: self._screened_fn(obs_arr, state, telemetry),
                    (n_perm, self.n_modules, N_STATS), self._null_write(),
                    slice_vals, monitor, self.rebucket,
                    progress=progress, checkpoint_path=checkpoint_path,
                    checkpoint_every=checkpoint_every, telemetry=telemetry,
                    fault_policy=fault_policy,
                    fingerprint_extra=scr.SCREEN_FP, extra_state=state,
                )
            finally:
                self._screen_active = False
                self.rebucket(range(self.n_modules))
                self._emit_null_pass_end(telemetry, "adaptive", state)
        try:
            return run_adaptive_chunks(
                self, n_perm, key, self._chunk_fn,
                (n_perm, self.n_modules, N_STATS), self._null_write(),
                slice_vals, monitor, self.rebucket,
                progress=progress, checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every, telemetry=telemetry,
                fault_policy=fault_policy,
            )
        finally:
            # leave the engine reusable at full strength (e.g. a fixed-n
            # run after an adaptive one on the same instance, or the next
            # pack on a warm-pool engine)
            self.rebucket(range(self.n_modules))

    # ------------------------------------------------------------------
    # Streaming tallies (store_nulls=False) — superchunk executor
    # ------------------------------------------------------------------

    def _obs_buckets(self, observed) -> list:
        """Per-bucket observed statistics as device f32 operands of the
        streaming count programs. The f64→f32 cast is exact for statistics
        the engine itself computed (they are widened f32 values), which is
        what keeps device-side comparisons bit-identical to the
        materialized path's host-side f64 comparisons."""
        obs = np.asarray(observed, dtype=np.float64).reshape(
            self.n_modules, N_STATS
        )
        return [
            jnp.asarray(obs[b.module_pos], jnp.float32) for b in self.buckets
        ]

    def _stream_fused_rep(self) -> bool:
        """Whether the chunk program runs under shard_map (fused kernel +
        perm-axis mesh over replicated matrices) — the streaming programs
        must then shard the same way and psum their per-shard counts."""
        return (
            self.gather_mode == "fused" and not self.row_sharded
            and self.mesh is not None
        )

    def _stream_super_fn(self, observed) -> Callable:
        """Cached :meth:`_build_stream_super` — jit caches by function
        identity, so handing it a fresh closure per run would re-trace and
        re-compile the whole superchunk program every call (measured ~7×
        the steady-state run time at toy scale)."""
        sig = np.asarray(observed, dtype=np.float64).tobytes()
        if (self._stream_super_cached is None
                or self._stream_super_cached[0] != sig):
            self._stream_super_cached = (
                sig, self._build_stream_super(observed)
            )
        else:
            self._program_sources["super"] = "memo"
        return self._stream_super_cached[1]

    def _stream_count_fn(self, observed) -> Callable:
        """Cached :meth:`_build_stream_count_fn` (see
        :meth:`_stream_super_fn`); the cache is invalidated by
        :meth:`rebucket`, so each retirement still re-jits the shrunken
        program exactly once."""
        sig = np.asarray(observed, dtype=np.float64).tobytes()
        if (self._stream_count_cached is None
                or self._stream_count_cached[0] != sig):
            self._stream_count_cached = (
                sig, self._build_stream_count_fn(observed)
            )
        else:
            self._program_sources["count"] = "memo"
        return self._stream_count_cached[1]

    def _stream_program_parts(self, adaptive: bool):
        """Mode-resolved pieces shared by :meth:`_build_stream_super` and
        :meth:`_build_stream_count_fn` (ISSUE 8 refactor — the three
        statistics paths must compose with the mesh identically in both
        streaming loops):

        - ``count_chunk(keys_c, valid_c, chunk_ops, obs) -> deltas``;
        - the keys PartitionSpec (1-D for the adaptive per-chunk program,
          2-D ``(K, C)`` for the superchunk scan);
        - the shard_map in_specs for the chunk operands (None when the
          program needs no explicit shard_map).

        stat_mode='xla': the chunk program + XLA count fold (shard_map
        only on the fused-GATHER replicated path, as before).
        stat_mode='fused' replicated: the mega-kernel counter (tallies
        fold in VMEM); shard_map over the perm axis when a mesh is
        present. stat_mode='fused' row-sharded: the ring body under
        shard_map over (perm × row), keys split over both axes, matrices
        entering with their row-sharded storage specs."""
        cfg = self.config
        key_axes: object = cfg.mesh_axis
        op_specs = None
        if self._stat_fused_ring():
            from .mesh import ROW_AXIS
            from .sharded import ring_chunk_specs

            chunk = self.chunk_body()
            axis = (cfg.mesh_axis, ROW_AXIS)
            count_buckets = make_count_buckets(0)

            def count_chunk(keys_c, valid_c, chunk_ops, obs_b):
                return chunk_count_deltas(
                    chunk, count_buckets, axis, keys_c, valid_c,
                    chunk_ops, obs_b,
                )

            key_axes = axis
            _, op_specs = ring_chunk_specs(cfg.mesh_axis)
        elif self.stat_mode == "fused":
            axis = cfg.mesh_axis if self.mesh is not None else None
            count_chunk = self._fused_count_chunk(axis)
            if self.mesh is not None:
                op_specs = (P(), P(), P(), P(), P())
        else:
            chunk = self.chunk_body()
            fused_rep = self._stream_fused_rep()
            axis = cfg.mesh_axis if fused_rep else None
            count_buckets = make_count_buckets(0)

            def count_chunk(keys_c, valid_c, chunk_ops, obs_b):
                return chunk_count_deltas(
                    chunk, count_buckets, axis, keys_c, valid_c,
                    chunk_ops, obs_b,
                )

            if fused_rep:
                op_specs = (P(), P(), P(), P(), P())
        keys_spec = P(key_axes) if adaptive else P(None, key_axes)
        return count_chunk, keys_spec, op_specs

    def _build_stream_super(self, observed) -> Callable:
        """Jit the superchunk program (scan-fused chunks + donated tally
        carry) with the same mesh composition rules as
        :meth:`_build_chunk_fn`; returns ``fn(tallies, keys, valid)``."""
        args = self.chunk_args()
        obs = self._obs_buckets(observed)
        count_chunk, keys_spec, op_specs = self._stream_program_parts(
            adaptive=False
        )
        super_fn = build_stream_super(None, None, count_chunk=count_chunk)
        # donate the carry only on the XLA path: the fused counter's
        # tallies are O(K·7) int32 (nothing to save), and donating inputs
        # into a program whose body inlines interpret-mode pallas state
        # machinery proved alias-unsafe on XLA:CPU (intermittent
        # wrong-counts/aborts in the resume test)
        donate = () if self.stat_mode == "fused" else (0,)
        if self.mesh is not None:
            from .distributed import to_global

            ksh = NamedSharding(self.mesh, keys_spec)
            if op_specs is not None:
                from .sharded import _NO_CHECK_KW, _shard_map

                super_fn = _shard_map(
                    super_fn,
                    mesh=self.mesh,
                    in_specs=(P(), keys_spec, P(), op_specs, P()),
                    out_specs=P(),
                    **_NO_CHECK_KW,
                )
            jitted = jax.jit(super_fn, donate_argnums=donate)
            args, obs = _globalize_replicated(self.mesh, (args, obs))
            self._program_sources["super"] = "jit"
            return lambda tallies, keys, valid: jitted(
                tallies, to_global(keys, ksh), valid, args, obs
            )
        # mesh-free: resolve through the AOT store (ISSUE 15). The AOT
        # path drops the carry donation (the exported calling convention
        # has none) — O(K·7) int32 tallies, values identical.
        from ..utils.autotune import peek_superchunk

        K = peek_superchunk(
            self.config, self.autotune_key(extra="superchunk")
        )
        C = self.effective_chunk()
        example = (
            self._stream_tallies_init(),
            self.perm_keys2d(self._example_run_key(), 0, K, C),
            np.full((K,), C, np.int32), args, obs,
        )
        jitted = self._acquire_program(
            "super", super_fn,
            lambda: jax.jit(super_fn, donate_argnums=donate), example,
        )
        return lambda tallies, keys, valid: jitted(
            tallies, keys, valid, args, obs
        )

    def _build_stream_count_fn(self, observed) -> Callable:
        """Jit the per-chunk count program of the ADAPTIVE streaming path
        (one chunk per dispatch — decisions stay at chunk boundaries, so
        retirement is bit-identical to the materialized adaptive loop);
        returns ``fn(keys, valid) -> [per-bucket (hi, lo, eff)]``. Reads
        ``self.buckets`` at build time: re-invoked after each retirement
        re-bucketing."""
        args = self.chunk_args()
        obs = self._obs_buckets(observed)
        count_chunk, keys_spec, op_specs = self._stream_program_parts(
            adaptive=True
        )

        def count_fn(keys, valid, chunk_ops, obs_b):
            return count_chunk(keys, valid, chunk_ops, obs_b)

        if self.mesh is not None:
            from .distributed import to_global

            ksh = NamedSharding(self.mesh, keys_spec)
            if op_specs is not None:
                from .sharded import _NO_CHECK_KW, _shard_map

                count_fn = _shard_map(
                    count_fn,
                    mesh=self.mesh,
                    in_specs=(keys_spec, P(), op_specs, P()),
                    out_specs=P(),
                    **_NO_CHECK_KW,
                )
            jitted = jax.jit(count_fn)
            args, obs = _globalize_replicated(self.mesh, (args, obs))
            self._program_sources["count"] = "jit"
            return lambda keys, valid: jitted(
                to_global(keys, ksh), valid, args, obs
            )
        # mesh-free: the adaptive streaming counter resolves through the
        # AOT store (ISSUE 15)
        C = self.effective_chunk()
        example = (
            self.perm_keys(self._example_run_key(), 0, C),
            np.int32(C), args, obs,
        )
        jitted = self._acquire_program(
            "count", count_fn, lambda: jax.jit(count_fn), example
        )
        return lambda keys, valid: jitted(keys, valid, args, obs)

    def _stream_tallies_init(self, host=None) -> list:
        """Device tally carry for :func:`run_stream_superchunks`: per-bucket
        ``(hi, lo, eff)`` int32 zeros, or a checkpoint's host tallies
        re-bucketed. int32 holds exceedance counts to 2^31 permutations —
        far past any feasible run."""
        out = []
        for b in self.buckets:
            shape = (len(b.module_pos), N_STATS)
            if host is None:
                vals = [np.zeros(shape, np.int32) for _ in range(3)]
            else:
                vals = [
                    np.asarray(a)[b.module_pos].astype(np.int32)
                    for a in host
                ]
            out.append(tuple(jnp.asarray(v) for v in vals))
        if self.mesh is not None:
            out = _globalize_replicated(self.mesh, out)
        return out

    def _stream_tallies_pull(self, tallies) -> tuple:
        """Device tallies → global ``(hi, lo, eff)`` int64 host arrays —
        the O(modules·7) per-superchunk transfer (cross-host allgather on
        multi-host meshes)."""
        from .distributed import gather_to_host

        hi = np.zeros((self.n_modules, N_STATS), np.int64)
        lo = np.zeros_like(hi)
        eff = np.zeros_like(hi)
        for b, (h, l, e) in zip(self.buckets, tallies):
            hi[b.module_pos] = gather_to_host(h)
            lo[b.module_pos] = gather_to_host(l)
            eff[b.module_pos] = gather_to_host(e)
        return hi, lo, eff

    def _counts_to_active(self, outs, pos) -> tuple:
        """Adaptive streaming: per-bucket count deltas → ``(hi, lo, eff)``
        host arrays over the active modules in ``pos`` order (the bucket
        set covers exactly the active modules after re-bucketing)."""
        hi, lo, eff = self._stream_tallies_pull(outs)
        return hi[pos], lo[pos], eff[pos]

    # ------------------------------------------------------------------
    # Mixed-precision null screening (ISSUE 16) — see parallel/screened.py
    # ------------------------------------------------------------------

    def _resolve_null_precision(self, observed) -> str:
        """Per-run resolution of ``EngineConfig.null_precision``: the
        screen engages only when the backend resolution says bf16_rescue,
        the statistics path is the XLA composition (the fused Pallas and
        row-sharded ring paths raised at init for explicit bf16_rescue
        and degrade silently under 'auto'), and the caller supplied
        single-test-shaped observed statistics to screen against."""
        cfg = self.config
        if cfg.resolved_null_precision(jax.default_backend()) != "bf16_rescue":
            return "f32"
        if (self.stat_mode == "fused" or self.gather_mode == "fused"
                or self.row_sharded):
            return "f32"
        if observed is None:
            if cfg.null_precision == "bf16_rescue":
                raise ValueError(
                    "null_precision='bf16_rescue' screens null statistics "
                    "against the observed values — pass observed= to "
                    "run_null (the adaptive/streaming entry points take "
                    "it already)"
                )
            return "f32"
        if np.asarray(observed).size != self.n_modules * N_STATS:
            # caller-supplied monitors (the packed serve path) may tally
            # other cell shapes; the screen understands only the
            # single-test (n_modules, 7) layout
            return "f32"
        return "bf16_rescue"

    def _screen_amplitude(self) -> float:
        """Max |test operand| (>= 1), the cushion's operand-amplitude
        factor — one eager reduction per engine, cached."""
        if self._screen_amp is None:
            vals = [1.0]
            for a in (self._test_corr, self._test_net, self._test_dataT):
                if a is not None:
                    vals.append(float(jnp.max(jnp.abs(a))))
            self._screen_amp = max(vals)
        return self._screen_amp

    def _screened_obs_cush(self, observed) -> tuple[list, list]:
        """Per-bucket (observed, cushion) f32 device operands of the
        screened programs — reads ``self.buckets`` at call time so the
        adaptive loops re-slice after each retirement re-bucketing."""
        from . import screened as scr

        obs = np.asarray(observed, dtype=np.float64).reshape(
            self.n_modules, N_STATS
        )
        cush = scr.null_cushions(obs, self._screen_amplitude())
        return (
            self._obs_buckets(obs),
            [jnp.asarray(cush[b.module_pos]) for b in self.buckets],
        )

    def _screened_chunk_parts(self):
        """The screened chunk evaluation shared by all four screened
        loops: the EXISTING chunk body called on bf16-rounded test
        operands (f32 arithmetic on rounded inputs — deterministic and
        platform-portable, so CPU pinning tests exercise the real TPU
        rounding), plus the per-permutation ambiguity reduction."""
        from . import screened as scr

        chunk = self.chunk_body()

        def screened_outs(keys, chunk_ops):
            pool, tc, tn, td, discs = chunk_ops
            return chunk(
                keys, pool, scr.bf16_round(tc), scr.bf16_round(tn),
                scr.bf16_round(td), discs,
            )

        return screened_outs

    def _build_screened_chunk_fn(self, observed) -> Callable:
        """Jit the bf16 fast-pass program of the screened materialized
        and adaptive loops: ``fn(keys) -> (outs, amb)`` with ``outs`` the
        per-bucket screened statistics and ``amb`` the ``(C,)`` ambiguous
        worklist mask. Screened programs stay on the plain jit path (the
        AOT store's warmup grid is not extended to them); the f32 rescue
        reuses the engine's acquired chunk program."""
        from . import screened as scr

        screened_outs = self._screened_chunk_parts()

        def screened(keys, chunk_ops, obs_b, cush_b):
            outs = screened_outs(keys, chunk_ops)
            return outs, scr.ambiguous_perms(outs, obs_b, cush_b)

        args = self.chunk_args()
        obs_b, cush_b = self._screened_obs_cush(observed)
        jitted = jax.jit(screened)
        if self.mesh is not None:
            from .distributed import to_global

            ksh = NamedSharding(self.mesh, P(self.config.mesh_axis))
            if not ksh.is_fully_addressable:
                args, obs_b, cush_b = _globalize_replicated(
                    self.mesh, (args, obs_b, cush_b)
                )
            return lambda keys: jitted(
                to_global(keys, ksh), args, obs_b, cush_b
            )
        return lambda keys: jitted(keys, args, obs_b, cush_b)

    def _screen_rescue_outs(self, f32_fn, keys, idx) -> list:
        """Re-dispatch one chunk's ambiguous permutations through the f32
        chunk program: pad the worklist to the chunk length (same
        compiled executable — zero extra compiles), gather those keys,
        and return the first ``len(idx)`` rows per bucket on the host."""
        from . import screened as scr
        from .distributed import gather_to_host

        pad = scr.pad_worklist(idx, self.effective_chunk())
        routs = f32_fn(scr.take_keys(keys, pad))
        return [np.asarray(gather_to_host(o))[: idx.size] for o in routs]

    def _screened_fn(self, observed, state, telemetry=None,
                     profile=None) -> Callable:
        """Screened ``fn(keys)`` for the materialized and adaptive null
        loops: bf16 fast pass, host-side worklist gather, f32 rescue of
        the ambiguous rows — returning host numpy per-bucket arrays whose
        rescued rows are bit-identical to the all-f32 run (the loops'
        write/slice paths pass numpy through unchanged). The worklist
        synchronization trades the materialized loop's double-buffer
        overlap for the screened fast pass."""
        from .distributed import gather_to_host

        bf = self._build_screened_chunk_fn(observed)
        f32 = self._chunk_fn()

        def fn(keys):
            outs, amb = bf(keys)
            amb_h = np.asarray(gather_to_host(amb)).astype(bool)
            # np.array (copy): the device export may be read-only and
            # rescued rows are scattered in place below
            outs_h = [np.array(gather_to_host(o)) for o in outs]
            state.total += int(amb_h.size)
            idx = np.flatnonzero(amb_h)
            if idx.size:
                t0 = time.perf_counter()
                routs = self._screen_rescue_outs(f32, keys, idx)
                for oh, ro in zip(outs_h, routs):
                    oh[idx] = ro
                state.rescued += int(idx.size)
                state.dispatches += 1
                if profile is not None:
                    profile.record_dispatch(1)
                if telemetry is not None:
                    telemetry.emit(
                        "rescue_dispatch", s=time.perf_counter() - t0,
                        rescued=int(idx.size), chunk=int(amb_h.size),
                    )
            return outs_h

        return fn

    def _build_screened_stream_super(self, observed) -> Callable:
        """Screened superchunk scan: each chunk folds its DECIDED
        comparisons into the on-device tally carry (the count fold's
        validity mask additionally excludes ambiguous columns) and stacks
        the per-chunk ambiguous masks as scan outputs — the ``(K, C)``
        worklist the wrapper re-dispatches. ``fn(tallies, keys, valid)
        -> (tallies, amb)``."""
        from . import screened as scr

        screened_outs = self._screened_chunk_parts()
        count_buckets = make_count_buckets(0)
        args = self.chunk_args()
        obs_b, cush_b = self._screened_obs_cush(observed)

        def super_fn(tallies, keys, valid, chunk_ops, obs_sc, cush_sc):
            def body(carry, xs):
                keys_c, valid_c = xs
                outs = screened_outs(keys_c, chunk_ops)
                col = jnp.arange(keys_c.shape[0], dtype=jnp.int32)
                valid_mask = col < valid_c
                amb = (
                    scr.ambiguous_perms(outs, obs_sc, cush_sc) & valid_mask
                )
                deltas = count_buckets(outs, obs_sc, valid_mask & ~amb)
                new = [
                    tuple(t + d for t, d in zip(ts, ds))
                    for ts, ds in zip(carry, deltas)
                ]
                return new, amb

            out, amb_ys = jax.lax.scan(body, tallies, (keys, valid))
            return out, amb_ys

        jitted = jax.jit(super_fn)
        if self.mesh is not None:
            from .distributed import to_global

            ksh = NamedSharding(
                self.mesh, P(None, self.config.mesh_axis)
            )
            if not ksh.is_fully_addressable:
                args, obs_b, cush_b = _globalize_replicated(
                    self.mesh, (args, obs_b, cush_b)
                )
            return lambda tallies, keys, valid: jitted(
                tallies, to_global(keys, ksh), valid, args, obs_b, cush_b
            )
        return lambda tallies, keys, valid: jitted(
            tallies, keys, valid, args, obs_b, cush_b
        )

    def _screened_stream_fns(self, observed, state, telemetry=None,
                             profile=None) -> tuple:
        """``(fn, init_fn, pull_fn)`` for the screened
        :func:`run_stream_superchunks`: device tallies hold decided
        comparisons only; each superchunk's ambiguous worklist is rescued
        per scan row through the f32 chunk program and its exact host
        counts fold into wrapper-held accumulators that ``pull_fn`` adds
        back — so pulled tallies (and therefore checkpoints and the
        returned :class:`StreamCounts`) are bit-identical to the all-f32
        run. The accumulator commit happens LAST in ``fn`` and
        ``init_fn`` subtracts the accumulator from restored host tallies,
        so the fault runtime's carry rebuild never double-counts
        rescues."""
        from . import screened as scr
        from .distributed import gather_to_host

        sup = self._build_screened_stream_super(observed)
        f32 = self._chunk_fn()
        obs = np.asarray(observed, dtype=np.float64).reshape(
            self.n_modules, N_STATS
        )
        shape = (self.n_modules, N_STATS)
        acc = {k: np.zeros(shape, np.int64) for k in ("hi", "lo", "eff")}

        def fn(tallies, keys, valid):
            new_tallies, amb = sup(tallies, keys, valid)
            amb_h = np.asarray(gather_to_host(amb)).astype(bool)
            state.total += int(np.sum(valid))
            d_hi = np.zeros(shape, np.int64)
            d_lo = np.zeros(shape, np.int64)
            d_eff = np.zeros(shape, np.int64)
            rescued = 0
            t0 = time.perf_counter()
            for r in np.flatnonzero(amb_h.any(axis=1)):
                idx = np.flatnonzero(amb_h[r])
                routs = self._screen_rescue_outs(f32, keys[r], idx)
                for b, ro in zip(self.buckets, routs):
                    hi, lo, eff = scr.host_tail_counts(
                        ro, obs[b.module_pos]
                    )
                    d_hi[b.module_pos] += hi
                    d_lo[b.module_pos] += lo
                    d_eff[b.module_pos] += eff
                rescued += int(idx.size)
                state.dispatches += 1
                if profile is not None:
                    profile.record_dispatch(1)
            if rescued:
                if telemetry is not None:
                    telemetry.emit(
                        "rescue_dispatch", s=time.perf_counter() - t0,
                        rescued=int(rescued), chunk=int(amb_h.size),
                    )
                state.rescued += rescued
                # commit LAST: a faulted superchunk retries the whole fn
                # from the rebuilt carry, so partial rescue work must not
                # have leaked into the accumulators
                acc["hi"] += d_hi
                acc["lo"] += d_lo
                acc["eff"] += d_eff
            return new_tallies

        def init_fn(host):
            if host is not None:
                host = (
                    np.asarray(host[0]) - acc["hi"],
                    np.asarray(host[1]) - acc["lo"],
                    np.asarray(host[2]) - acc["eff"],
                )
            return self._stream_tallies_init(host)

        def pull_fn(tallies):
            hi, lo, eff = self._stream_tallies_pull(tallies)
            return hi + acc["hi"], lo + acc["lo"], eff + acc["eff"]

        return fn, init_fn, pull_fn

    def _build_screened_stream_count(self, observed) -> Callable:
        """Screened per-chunk count program of the adaptive streaming
        path: ``fn(keys, valid) -> (deltas, amb)`` — decided counts per
        bucket plus the chunk's ambiguous worklist mask."""
        from . import screened as scr

        screened_outs = self._screened_chunk_parts()
        count_buckets = make_count_buckets(0)
        args = self.chunk_args()
        obs_b, cush_b = self._screened_obs_cush(observed)

        def count_fn(keys, valid, chunk_ops, obs_sc, cush_sc):
            outs = screened_outs(keys, chunk_ops)
            col = jnp.arange(keys.shape[0], dtype=jnp.int32)
            valid_mask = col < valid
            amb = scr.ambiguous_perms(outs, obs_sc, cush_sc) & valid_mask
            deltas = count_buckets(outs, obs_sc, valid_mask & ~amb)
            return deltas, amb

        jitted = jax.jit(count_fn)
        if self.mesh is not None:
            from .distributed import to_global

            ksh = NamedSharding(self.mesh, P(self.config.mesh_axis))
            if not ksh.is_fully_addressable:
                args, obs_b, cush_b = _globalize_replicated(
                    self.mesh, (args, obs_b, cush_b)
                )
            return lambda keys, valid: jitted(
                to_global(keys, ksh), valid, args, obs_b, cush_b
            )
        return lambda keys, valid: jitted(keys, valid, args, obs_b, cush_b)

    def _screened_count_fn_builder(self, observed, state, telemetry=None,
                                   profile=None) -> Callable:
        """``fn_builder`` for the screened adaptive streaming loop:
        rebuilds the screened count program for the current bucket set
        (re-invoked after each retirement re-bucketing); the returned
        ``fn(keys, valid)`` rescues the chunk's ambiguous permutations
        through the f32 chunk program BEFORE returning, so the monitor
        folds exact counts and retirement decisions match the f32 run."""
        from . import screened as scr
        from .distributed import gather_to_host

        obs = np.asarray(observed, dtype=np.float64).reshape(
            self.n_modules, N_STATS
        )

        def build():
            cf = self._build_screened_stream_count(observed)
            f32 = self._chunk_fn()

            def fn(keys, valid):
                deltas, amb = cf(keys, valid)
                amb_h = np.asarray(gather_to_host(amb)).astype(bool)
                state.total += int(valid)
                out = [
                    tuple(
                        np.array(gather_to_host(x), dtype=np.int64)
                        for x in ds
                    )
                    for ds in deltas
                ]
                idx = np.flatnonzero(amb_h)
                if idx.size:
                    t0 = time.perf_counter()
                    routs = self._screen_rescue_outs(f32, keys, idx)
                    for j, (b, ro) in enumerate(
                        zip(self.buckets, routs)
                    ):
                        hi, lo, eff = scr.host_tail_counts(
                            ro, obs[b.module_pos]
                        )
                        h, l, e = out[j]
                        out[j] = (h + hi, l + lo, e + eff)
                    state.rescued += int(idx.size)
                    state.dispatches += 1
                    if profile is not None:
                        profile.record_dispatch(1)
                    if telemetry is not None:
                        telemetry.emit(
                            "rescue_dispatch",
                            s=time.perf_counter() - t0,
                            rescued=int(idx.size), chunk=int(amb_h.size),
                        )
                return out

            return fn

        return build

    def _emit_null_pass_end(self, telemetry, mode: str, state) -> None:
        """Per-run screening summary event (ISSUE 16): the rescued
        fraction is the screen's economics — rescued·f32-cost on top of
        total·bf16-cost vs total·f32-cost unscreened."""
        if telemetry is not None:
            telemetry.emit(
                "null_pass_end", mode=mode, precision="bf16_rescue",
                total=int(state.total), rescued=int(state.rescued),
                rescue_dispatches=int(state.dispatches),
                fraction=float(state.fraction()),
            )

    def run_null_streaming(
        self,
        n_perm: int,
        observed: np.ndarray,
        key: jax.Array | int = 0,
        progress: Callable[[int, int], None] | None = None,
        checkpoint_path: str | None = None,
        checkpoint_every: int = 8192,
        profile=None,
        telemetry=None,
        fault_policy=None,
    ) -> StreamCounts:
        """Streaming-mode (``store_nulls=False``) variant of
        :meth:`run_null` — the superchunk executor: K consecutive chunks
        fuse into one ``lax.scan`` dispatch whose donated carry holds the
        per-(module, statistic) exceedance tallies against ``observed``,
        so the host issues ~K× fewer dispatches and pulls O(modules·7)
        counts per superchunk instead of O(chunk·modules·7) null rows —
        while host memory drops from O(n_perm·modules·7) to O(modules·7).

        K is ``config.superchunk``, autotune-resolved when None
        (:func:`netrep_tpu.utils.autotune.resolve_superchunk`). For the
        same key the returned tallies are bit-identical to
        :func:`~netrep_tpu.ops.pvalues.tail_counts` of :meth:`run_null`'s
        materialized null — feed them to
        :func:`~netrep_tpu.ops.pvalues.counts_pvalues` for identical exact
        Phipson–Smyth p-values. Checkpoint/interrupt contracts mirror
        :meth:`run_null` (:func:`run_stream_superchunks`)."""
        if self.discovery_only:
            raise RuntimeError(
                "engine was built discovery_only; test-side passes live in "
                "the wrapping engine"
            )
        from ..utils.autotune import resolve_superchunk

        telemetry, profile = _telemetry_profile(telemetry, profile)
        if self._resolve_null_precision(observed) == "bf16_rescue":
            from . import screened as scr

            state = scr.RescueState()
            # active BEFORE autotune_key: the superchunk depth K resolves
            # under the precision-suffixed key, so screened and f32
            # throughput histories never mix
            self._screen_active = True
            try:
                sk_key = self.autotune_key(extra="superchunk")
                K, cache = resolve_superchunk(self.config, sk_key)
                self._stream_autotune_record = (
                    (cache, sk_key, K) if cache is not None else None
                )
                fn, init_fn, pull_fn = self._screened_stream_fns(
                    observed, state, telemetry, profile
                )
                result = run_stream_superchunks(
                    self, n_perm, key, fn, K, self.effective_chunk(),
                    init_fn, pull_fn,
                    progress=progress, checkpoint_path=checkpoint_path,
                    checkpoint_every=checkpoint_every,
                    fingerprint_extra=scr.SCREEN_FP, profile=profile,
                    telemetry=telemetry, fault_policy=fault_policy,
                    extra_state=state,
                )
            finally:
                self._screen_active = False
            self._emit_null_pass_end(telemetry, "streaming", state)
            return result
        sk_key = self.autotune_key(extra="superchunk")
        K, cache = resolve_superchunk(self.config, sk_key)
        self._stream_autotune_record = (
            (cache, sk_key, K) if cache is not None else None
        )
        return run_stream_superchunks(
            self, n_perm, key, self._stream_super_fn(observed), K,
            self.effective_chunk(),
            self._stream_tallies_init, self._stream_tallies_pull,
            progress=progress, checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every, profile=profile,
            telemetry=telemetry, fault_policy=fault_policy,
        )

    def run_null_adaptive_streaming(
        self,
        n_perm: int,
        observed: np.ndarray,
        key: jax.Array | int = 0,
        alternative: str = "greater",
        rule=None,
        progress: Callable[[int, int], None] | None = None,
        checkpoint_path: str | None = None,
        checkpoint_every: int = 8192,
        profile=None,
        telemetry=None,
        fault_policy=None,
    ) -> StreamCounts:
        """Streaming-mode variant of :meth:`run_null_adaptive`: the
        :class:`~netrep_tpu.ops.sequential.StopMonitor` folds
        device-computed counts directly
        (:func:`run_adaptive_stream_chunks`) — decisions land at the same
        chunk boundaries on the same tallies, so retirement is
        bit-identical to the materialized adaptive run at the same key.
        Returns a :class:`StreamCounts` with per-module ``n_perm_used``
        and the Ctrl-C ``finished`` flag."""
        from ..ops.sequential import StopMonitor, StopRule

        if self.discovery_only:
            raise RuntimeError(
                "engine was built discovery_only; test-side passes live in "
                "the wrapping engine"
            )
        monitor = StopMonitor(
            np.asarray(observed, dtype=np.float64).reshape(
                self.n_modules, -1
            ),
            alternative, rule or StopRule(),
        )
        telemetry, profile = _telemetry_profile(telemetry, profile)
        state = None
        if self._resolve_null_precision(observed) == "bf16_rescue":
            from . import screened as scr

            state = scr.RescueState()
            self._screen_active = True
        try:
            if state is not None:
                monitor, completed, finished = run_adaptive_stream_chunks(
                    self, n_perm, key,
                    self._screened_count_fn_builder(
                        observed, state, telemetry, profile
                    ),
                    self._counts_to_active, monitor, self.rebucket,
                    progress=progress, checkpoint_path=checkpoint_path,
                    checkpoint_every=checkpoint_every,
                    fingerprint_extra=scr.SCREEN_FP, profile=profile,
                    telemetry=telemetry, fault_policy=fault_policy,
                    extra_state=state,
                )
            else:
                monitor, completed, finished = run_adaptive_stream_chunks(
                    self, n_perm, key,
                    lambda: self._stream_count_fn(observed),
                    self._counts_to_active, monitor, self.rebucket,
                    progress=progress, checkpoint_path=checkpoint_path,
                    checkpoint_every=checkpoint_every, profile=profile,
                    telemetry=telemetry, fault_policy=fault_policy,
                )
        finally:
            self._screen_active = False
            self.rebucket(range(self.n_modules))
        if state is not None:
            self._emit_null_pass_end(telemetry, "adaptive-streaming", state)
        eff = monitor.eff if monitor.eff is not None else np.zeros_like(
            monitor.hi
        )
        return StreamCounts(
            hi=monitor.hi.copy(), lo=monitor.lo.copy(), eff=eff.copy(),
            completed=completed, n_perm_used=monitor.n_used.copy(),
            finished=finished,
        )
