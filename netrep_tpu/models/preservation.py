"""`module_preservation` — the framework's main entry point, the rebuild of
the reference's top-level orchestrator (SURVEY.md §2.1, call stack §3.1):
validate inputs, loop over (discovery, test) dataset pairs, run the
permutation engine (the TPU-native ``PermutationProcedure``), aggregate exact
permutation p-values, and shape results.

Argument names follow the reference's documented surface
(``modulePreservation(network, data, correlation, moduleAssignments,
modules, backgroundLabel, discovery, test, selfPreservation, nThreads,
nPerm, null, alternative, simplify, verbose)`` — SURVEY.md §2.1) in
snake_case. ``n_threads`` sizes the thread pool of ``backend='native'``
(the C++ permutation procedure); on the default JAX backend it is ignored
because XLA owns device parallelism (SURVEY.md §2.3 intra-op row).
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import time
from typing import Callable

import numpy as np

from ..ops import pvalues as pv
from ..parallel.engine import ModuleSpec, PermutationEngine
from ..utils import telemetry as tm
from ..utils.config import EngineConfig
from ..utils.faults import (
    CapacityRestoredError, DeviceLostError, resolve_runtime,
)
from ..utils.profiling import PairTimer, device_trace, resolve_profile_dir
from . import dataset as ds
from .results import PreservationResult, shape_results

logger = logging.getLogger("netrep_tpu")


def _overlap_setup(disc_ds, test_ds, assignments, modules, background_label, null):
    """Resolve kept modules, specs, pool, and overlap bookkeeping for one
    (discovery, test) pair (SURVEY.md §3.1)."""
    labels, specs, counts = ds.module_overlap(
        disc_ds, test_ds, assignments, modules, background_label
    )
    dropped = [lab for lab, _di, ti in specs if len(ti) < 2]
    if dropped:
        logger.warning(
            "discovery %r → test %r: dropping module(s) %s with <2 nodes "
            "present in the test dataset", disc_ds.name, test_ds.name, dropped,
        )
    kept = [(lab, di, ti) for lab, di, ti in specs if len(ti) >= 2]
    if not kept:
        raise ValueError(
            f"no module of discovery {disc_ds.name!r} has ≥2 nodes present "
            f"in test {test_ds.name!r}; nothing to test"
        )
    labels = [lab for lab, _, _ in kept]
    mod_specs = [ModuleSpec(lab, di, ti) for lab, di, ti in kept]

    tpos = test_ds.index_of()
    if null == "overlap":
        pool = np.asarray(
            [tpos[nm] for nm in disc_ds.node_names if nm in tpos],
            dtype=np.int32,
        )
    else:
        pool = np.arange(test_ds.n_nodes, dtype=np.int32)
    return labels, mod_specs, counts, pool


def _make_result(d_name, t_name, labels, counts, observed, nulls, completed,
                 np_this, alternative, total_space, profile=None,
                 p_type="fixed", stream=None, nulls_exact=True):
    hi = lo = eff = None
    if stream is not None:
        # streaming run (store_nulls=False): exact Phipson–Smyth from the
        # device-tallied exceedance counts — identical to the materialized
        # path's p-values for the same key (ops.pvalues.counts_pvalues)
        p_values = pv.counts_pvalues(
            observed, stream.hi, stream.lo, stream.eff, alternative,
            total_nperm=total_space,
        )
        hi, lo, eff = stream.hi, stream.lo, stream.eff
        n_perm_used = (
            np.asarray(stream.n_perm_used) if p_type == "sequential" else None
        )
    elif p_type == "sequential":
        # adaptive run: retired modules' null rows are NaN past their
        # retirement — Phipson–Smyth at each module's own count
        p_values, n_perm_used = pv.sequential_pvalues(
            observed, nulls[:completed], alternative, total_nperm=total_space
        )
    else:
        p_values = pv.permutation_pvalues(
            observed, nulls[:completed], alternative, total_nperm=total_space
        )
        n_perm_used = None
    n_present = np.array([counts[lab][0] for lab in labels])
    tot = np.array([counts[lab][1] for lab in labels])
    return PreservationResult(
        n_perm_used=n_perm_used,
        p_type=p_type,
        discovery=d_name,
        test=t_name,
        module_labels=labels,
        observed=observed,
        nulls=nulls,
        counts_hi=hi,
        counts_lo=lo,
        counts_eff=eff,
        p_values=p_values,
        n_vars_present=n_present,
        prop_vars_present=n_present / tot,
        total_size=tot,
        alternative=alternative,
        n_perm=np_this,
        completed=completed,
        profile=profile,
        total_space=total_space,
        nulls_exact=nulls_exact,
    )


def _nulls_exact(engine, observed, nulls) -> bool:
    """Whether a pair's materialized null array carries exact f32 VALUES.

    The bf16 screened fast-pass (ISSUE 16) keeps counts and p-values
    bit-identical to the f32 run but stores decided permutations'
    bf16-rounded statistics — so a screened run's null array must not
    feed the GPD tail fit (:meth:`PreservationResult.tail_pvalues`).
    Resolution is asked of the engine the run STARTED on: a mid-run
    elastic downgrade to CPU flips later chunks to f32, but the earlier
    screened chunks already quantized part of the array — the
    conservative answer stays False."""
    if nulls is None:
        return True  # streaming runs carry counts only; nothing to gate
    resolve = getattr(engine, "_resolve_null_precision", None)
    if resolve is None:
        return True
    return resolve(observed) != "bf16_rescue"


def module_preservation(
    network,
    data=None,
    correlation=None,
    module_assignments=None,
    modules=None,
    background_label: str = "0",
    discovery=None,
    test=None,
    self_preservation: bool = False,
    n_threads: int | None = None,  # used by backend='native'; JAX/XLA owns
                                   # device parallelism otherwise
    n_perm: int | None = None,
    null: str = "overlap",
    alternative: str = "greater",
    simplify: bool = True,
    verbose: bool = False,
    seed: int = 0,
    config: EngineConfig | None = None,
    mesh=None,
    vmap_tests: bool = False,
    progress: Callable[[int, int], None] | None = None,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 8192,
    backend: str = "jax",
    profile=None,
    adaptive: bool = False,
    adaptive_rule=None,
    adaptive_priors=None,
    store_nulls: bool = True,
    telemetry=None,
    fault_policy=None,
    data_only=None,
):
    """Permutation test of network module preservation across datasets.

    Parameters mirror the reference (SURVEY.md §2.1); TPU-specific additions:

    - ``data_only`` — the atlas module plane (ISSUE 9): pass the
      soft-threshold power β (or a ``(β, kind)`` pair,
      :func:`netrep_tpu.ops.stats.derived_net`) and ONLY ``data``; the
      correlation and network are never materialized — every observed and
      per-permutation k×k submatrix derives from gathered data columns as
      one MXU matmul (``zᵀz/(s-1)`` + the elementwise construction), so
      the device footprint is O(n·samples) and 100k-gene atlas inputs fit
      where a dense n×n pair (~80 GB) cannot. ``network``/``correlation``
      must be omitted; requires the default ``backend='jax'``; all seven
      statistics are computed. At dense-representable sizes the results
      match a dense run on the materialized ``|corr|**β`` pair within
      float32 rounding (pinned in tests/test_atlas.py). The thin named
      wrapper :func:`netrep_tpu.models.atlas_api.module_preservation`
      exposes the same path with ``data`` leading the signature.

    - ``seed`` — PRNG seed; same seed ⇒ identical nulls regardless of chunk
      size or device mesh (SURVEY.md §7 "RNG semantics").
    - ``config`` — :class:`~netrep_tpu.utils.config.EngineConfig` TPU knobs
      (chunk size, summary method, dtype, matrix sharding).
    - ``mesh`` — optional :class:`jax.sharding.Mesh`; permutation chunks are
      sharded across ``config.mesh_axis``, and with
      ``config.matrix_sharding='row'`` the n×n matrices are row-sharded with
      collective module gathers (SURVEY.md §2.3, §5).
    - ``vmap_tests`` — Config C fast path (BASELINE.json:9): when one
      discovery is tested against several cohorts sharing an identical node
      universe, run them as a single vmapped kernel instead of sequential
      pairs.
    - ``progress`` — callback ``(done, total)`` per chunk.
    - ``checkpoint_dir`` — when set, each pair's partial null is persisted to
      ``<dir>/null_<discovery>__<test>.npz`` every ``checkpoint_every``
      permutations and on interrupt; re-running the same call resumes
      exactly (SURVEY.md §5 "checkpoint/resume" — an improvement over the
      reference's all-or-nothing runs).
    - ``adaptive`` — sequential early-stopping nulls (Besag & Clifford
      1991; :mod:`netrep_tpu.ops.sequential`): ``n_perm`` becomes a
      ceiling, and each module stops drawing permutations once its
      accept/reject decision at the stop rule's alpha is statistically
      settled — clearly-preserved and clearly-null modules retire after a
      few hundred draws instead of the full budget, and retired modules
      drop out of subsequent device chunks entirely. P-values are then
      Phipson–Smyth at each module's own count (``p_type='sequential'``,
      per-module counts in ``result.n_perm_used``). Off by default: the
      default path is bit-identical to previous releases. Requires the
      default ``backend='jax'``.
    - ``adaptive_rule`` — optional
      :class:`~netrep_tpu.ops.sequential.StopRule` overriding the stopping
      knobs (exceedance budget ``h``, decision ``alpha``, CP interval
      ``confidence``, ``min_perms`` floor).
    - ``adaptive_priors`` — warm-start tallies for ONE (discovery, test)
      pair's adaptive run (ISSUE 17 incremental re-analysis): a
      ``(counts_hi, counts_lo, n_perm_used)`` triple from a prior run of
      the same cell, seeded into the
      :class:`~netrep_tpu.ops.sequential.StopMonitor` decision rules
      (:meth:`~netrep_tpu.ops.sequential.StopMonitor.seed_priors`)
      before any fresh permutation folds. Decisions then settle on
      prior+fresh evidence — a stable module retires after a few hundred
      fresh draws instead of re-earning its whole tally — while every
      REPORTED number (counts, p-values, ``n_perm_used``) stays
      fresh-draw-only, so the result is a valid standalone analysis at
      its own (smaller) permutation count. Requires ``adaptive=True``,
      the default ``backend='jax'``, ``store_nulls=True``, and exactly
      one (discovery, test) pair.
    - ``store_nulls`` — ``False`` streams the null: the engine fuses
      ``config.superchunk`` chunks per device dispatch (``jax.lax.scan``)
      and folds per-(module, statistic) exceedance tallies on device, so
      only O(modules·7) counts ever reach the host — ~superchunk× fewer
      dispatches, ~chunk× less device→host traffic, and host memory
      independent of ``n_perm``. P-values are the identical exact
      Phipson–Smyth numbers (they only ever need the counts); the result
      carries ``counts_hi/counts_lo/counts_eff`` and ``nulls=None``, so
      keep the default ``True`` when you want the materialized null for
      plots or diagnostics. Composes with ``adaptive`` (decisions are
      bit-identical to the materialized adaptive run) and ``vmap_tests``;
      requires the default ``backend='jax'``.
    - ``profile`` — tracing/profiling (SURVEY.md §5; the reference offers
      only ``verbose=`` + ``system.time``): ``True`` captures a
      ``jax.profiler`` trace under ``./netrep_profile``, a string names the
      trace directory, and either also attaches per-pair timings (observed/
      null wall-clock, per-chunk ms, first-chunk compile time, steady-state
      median) to each result as ``result.profile``. Inspect the trace with
      TensorBoard/Perfetto or
      :func:`netrep_tpu.utils.profiling.summarize_trace`.
    - ``telemetry`` — unified run telemetry (ISSUE 3;
      :mod:`netrep_tpu.utils.telemetry`): ``True`` appends structured
      events (run/pair/observed spans, per-chunk and per-superchunk
      dispatch+transfer counters, checkpoint saves/resumes, adaptive
      retirements, backend fallbacks, stall-watchdog alerts) to
      ``./netrep_telemetry.jsonl``; a string names the JSONL path; an
      existing :class:`~netrep_tpu.utils.telemetry.Telemetry` bus is used
      as-is. While the run executes, the bus is also *ambient*, so every
      layer (engine loops, checkpoints, autotune, backend) emits to it. A
      stall watchdog is armed per null run: when no chunk completes within
      ``stall_factor``× the measured steady-state chunk time it emits
      ``stall_suspected`` and warns once — the dead-tunnel hang the
      backend code documents. Aggregate the file offline with
      ``python -m netrep_tpu telemetry <run.jsonl>``. Off by default;
      disabled runs are bit-identical and pay only a ``None`` check.
      ``result.profile`` gains a ``"telemetry"`` pointer to the sink path.
    - ``fault_policy`` — fault-tolerant null execution (ISSUE 4;
      :mod:`netrep_tpu.utils.faults`): ``True`` or a
      :class:`~netrep_tpu.utils.config.FaultPolicy` wraps every null
      chunk dispatch in a recovery ladder — *transient* backend failures
      (gRPC deadline, dropped tunnel) re-dispatch with exponential
      backoff and deterministic jitter (exact by construction: chunk *i*
      regenerates identical ``fold_in`` keys), hung dispatches are
      abandoned after an emergency checkpoint (``hang_timeout_s``, or
      the telemetry stall watchdog escalated from warn to act), and a
      lost device climbs the elastic ladder (ISSUE 6): completed work
      is failure-saved, then the mesh is rebuilt over the SURVIVING
      devices and the null resumes on it bit-identically — growing back
      to the original mesh at the next chunk boundary once capacity
      returns — and only a total loss forces the CPU platform
      (:func:`netrep_tpu.utils.backend.degrade_to_cpu`). Checkpoint
      writes ride a background writer while a policy is active
      (``async_checkpoint``), so saves never stall the device. Without a
      ``checkpoint_dir`` a run-scoped temporary directory holds the
      emergency checkpoints (removed on success). Every recovery
      decision emits telemetry (``retry_attempt``, ``chunk_abandoned``,
      ``degraded_to_cpu``, ``fault_injected``, ...) when a bus is
      active. The deterministic fault-injection harness
      (``FaultPolicy(plan=...)`` or the ``NETREP_FAULT_PLAN`` env var,
      which also activates a default policy) drives CI/bench drills.
      Off (None, env unset) the null loops are bit-identical to
      previous releases.

    Returns
    -------
    ``{discovery: {test: PreservationResult}}``, collapsed by ``simplify``.
    """
    if null not in ("overlap", "all"):
        raise ValueError(f"null must be 'overlap' or 'all', got {null!r}")
    if alternative not in ("greater", "less", "two.sided"):
        raise ValueError(
            "alternative must be one of 'greater', 'less', 'two.sided', "
            f"got {alternative!r}"
        )
    if backend not in ("jax", "native"):
        raise ValueError(f"backend must be 'jax' or 'native', got {backend!r}")
    if adaptive and backend != "jax":
        raise ValueError(
            "adaptive=True requires backend='jax' (the native C++ tier has "
            "no retirement re-bucketing); run it fixed-n or switch backends"
        )
    if not store_nulls and backend != "jax":
        raise ValueError(
            "store_nulls=False requires backend='jax' (the streaming "
            "tallies are folded on device inside the scan-fused dispatch); "
            "run the native backend with store_nulls=True"
        )
    if data_only is not None:
        # the atlas module plane (ISSUE 9): matrices derive from data
        if network is not None or correlation is not None:
            raise ValueError(
                "data_only derives the correlation and network from data "
                "— drop the network/correlation arguments (or drop "
                "data_only to run on materialized matrices)"
            )
        if data is None:
            raise ValueError("data_only runs need data")
        if backend != "jax":
            raise ValueError(
                "data_only requires backend='jax' (the native C++ tier "
                "slices materialized host matrices)"
            )
        cfg0 = config or EngineConfig()
        if (cfg0.network_from_correlation is not None
                and cfg0.network_from_correlation != data_only):
            raise ValueError(
                "config.network_from_correlation "
                f"({cfg0.network_from_correlation!r}) disagrees with "
                f"data_only ({data_only!r}); pass the derivation spec once"
            )
        config = dataclasses.replace(
            cfg0, network_from_correlation=(
                tuple(data_only) if isinstance(data_only, list)
                else data_only
            ),
        )
    if backend == "native":
        # the threaded C++ permutation procedure (netrep_tpu/native) — the
        # CPU tier mirroring the reference's OpenMP PermutationProcedure
        # (SURVEY.md §2.2); n_threads is honored here, unlike the JAX path
        from ..native import NativePermutationEngine
        engine_cls = lambda *a, **kw: NativePermutationEngine(
            *a, **kw, n_threads=n_threads or 0
        )
    else:
        engine_cls = PermutationEngine
    config = config or EngineConfig()
    if config.null_precision == "auto":
        # pin the screened-null decision (ISSUE 16) ONCE for the whole
        # analysis: the elastic ladder's CPU rung rebuilds engines on a
        # different backend, and a precision flip there would change the
        # checkpoint fingerprint mid-recovery and refuse its own resume.
        # The pin mirrors the engine's own degrade conditions (fused
        # statistics/gather, row sharding) so the explicit value never
        # trips the engine's unsupported-combination init error.
        import jax

        platform = jax.default_backend()
        prec = config.resolved_null_precision(platform)
        if prec == "bf16_rescue" and (
            config.resolved_stat_mode(platform) == "fused"
            or config.resolved_gather_mode(platform) == "fused"
            or config.matrix_sharding == "row"
        ):
            prec = "f32"
        config = dataclasses.replace(config, null_precision=prec)

    ft = resolve_runtime(fault_policy)
    emergency_dir = None
    if ft is not None and checkpoint_dir is None:
        # the failure-save hook and the CPU-degradation resume need the
        # checkpoints to land somewhere even when the caller didn't ask
        # for any: a run-scoped tempdir, removed after a clean finish
        import tempfile

        emergency_dir = tempfile.mkdtemp(prefix="netrep_ckpt_")
        checkpoint_dir = emergency_dir

    def ckpt_path(d_name, t_name):
        if checkpoint_dir is None:
            return None
        import os
        import re

        safe = lambda s: re.sub(r"[^A-Za-z0-9_.-]", "_", str(s))
        return os.path.join(
            checkpoint_dir, f"null_{safe(d_name)}__{safe(t_name)}.npz"
        )

    datasets = (
        ds.build_data_only_datasets(data) if data_only is not None
        else ds.build_datasets(network, data=data, correlation=correlation)
    )
    pairs = ds.resolve_pairs(datasets, discovery, test, self_preservation)
    if adaptive_priors is not None:
        if not adaptive:
            raise ValueError(
                "adaptive_priors seeds the sequential stop monitor; it "
                "requires adaptive=True"
            )
        if backend != "jax" or not store_nulls:
            raise ValueError(
                "adaptive_priors requires the default backend='jax' with "
                "store_nulls=True (the materialized adaptive path)"
            )
        if len(pairs) != 1:
            raise ValueError(
                "adaptive_priors carries ONE cell's prior tallies; got "
                f"{len(pairs)} (discovery, test) pairs — warm-start each "
                "pair separately (grid_preservation does this per cell)"
            )
    disc_names = sorted({d for d, _ in pairs}, key=list(datasets).index)
    assign = ds.normalize_module_assignments(
        module_assignments, datasets, disc_names
    )

    by_disc: dict[str, list[str]] = {}
    for d_name, t_name in pairs:
        by_disc.setdefault(d_name, []).append(t_name)

    def auto_n_perm(labels, with_data):
        # Bonferroni across all module×statistic tests (SURVEY.md §3.4):
        # 7 statistics with data, 3 topology-only without; floor of 1000.
        n_stats_eff = 7 if with_data else 3
        return max(1000, pv.required_perms(0.05, n_tests=len(labels) * n_stats_eff))

    trace_dir = resolve_profile_dir(profile)
    profiling = profile is not None and profile is not False

    tel, tel_owned = tm.resolve_arg(telemetry)

    results: dict[str, dict[str, PreservationResult]] = {}
    interrupted = False
    trace_cm = device_trace(trace_dir)
    trace_cm.__enter__()  # covers every pair's device work; closed below
    tel_cm = tel.activate() if tel is not None else None
    run_sid = None
    if tel_cm is not None:
        tel_cm.__enter__()  # ambient for every layer below (engine loops,
        # checkpoints, autotune, backend) — closed below
        # the run span is the root of the trace tree (ISSUE 5): pairs,
        # observed passes, and null runs all nest under it
        run_sid = tel.begin_span(
            "run_start", pairs=sum(len(v) for v in by_disc.values()),
            null=null, alternative=alternative, adaptive=bool(adaptive),
            store_nulls=bool(store_nulls), backend=backend, seed=int(seed),
            fault_policy=ft is not None,
        )
    try:
        out = _run_pairs(
            by_disc, datasets, assign, modules, background_label, null,
            alternative, n_perm, auto_n_perm, engine_cls, config, mesh,
            vmap_tests, backend, seed, progress, ckpt_path, checkpoint_every,
            verbose, simplify, results, trace_dir, profiling,
            adaptive, adaptive_rule, store_nulls, tel, ft,
            adaptive_priors=adaptive_priors,
        )
        if tel is not None:
            tel.end_span(
                run_sid, "run_end",
                pairs_done=sum(len(v) for v in results.values()),
            )
        return out
    finally:
        if tel_cm is not None:
            tel_cm.__exit__(None, None, None)
            if tel_owned:
                tel.close()
        trace_cm.__exit__(None, None, None)
        if emergency_dir is not None:
            import shutil

            shutil.rmtree(emergency_dir, ignore_errors=True)


def _run_pairs(by_disc, datasets, assign, modules, background_label, null,
               alternative, n_perm, auto_n_perm, engine_cls, config, mesh,
               vmap_tests, backend, seed, progress, ckpt_path,
               checkpoint_every, verbose, simplify, results, trace_dir,
               profiling, adaptive=False, adaptive_rule=None,
               store_nulls=True, tel=None, ft=None, adaptive_priors=None):
    """Pair-loop body of :func:`module_preservation` (split out so the
    profiler trace context can bracket it without deep nesting)."""

    def observed_span(d_name, t_name, n_modules):
        """Telemetry span over one pair's observed pass (no-op when off)."""
        if tel is None:
            return contextlib.nullcontext()
        return tel.span(
            "observed", discovery=str(d_name), test=str(t_name),
            n_modules=int(n_modules),
        )

    def attach_telemetry(prof):
        """``result.profile`` gains a pointer to the telemetry sink, so a
        result object always names the event log that explains its run."""
        if tel is None or tel.path is None:
            return prof
        prof = dict(prof or {})
        prof.setdefault("telemetry", tel.path)
        prof.setdefault("telemetry_run", tel.run_id)
        return prof

    def run_pair_null(engine, np_this, observed, prog, ck):
        """One pair's null: fixed (default, bit-identical to previous
        releases) or adaptive sequential early-stopping, each materialized
        (store_nulls=True) or streaming. Returns ``(nulls, stream,
        completed, interrupted)`` — exactly one of ``nulls``/``stream`` is
        set; adaptive runs legitimately complete below ``np_this`` when
        every module retires, so the interrupt signal comes from the loop,
        not the count."""
        if not store_nulls:
            if adaptive:
                sc = engine.run_null_adaptive_streaming(
                    np_this, observed, key=seed, alternative=alternative,
                    rule=adaptive_rule, progress=prog, checkpoint_path=ck,
                    checkpoint_every=checkpoint_every, fault_policy=ft,
                )
                return None, sc, sc.completed, not sc.finished
            sc = engine.run_null_streaming(
                np_this, observed, key=seed, progress=prog,
                checkpoint_path=ck, checkpoint_every=checkpoint_every,
                fault_policy=ft,
            )
            return None, sc, sc.completed, sc.completed < np_this
        if adaptive:
            nulls, completed, finished = engine.run_null_adaptive(
                np_this, observed, key=seed, alternative=alternative,
                rule=adaptive_rule, progress=prog, checkpoint_path=ck,
                checkpoint_every=checkpoint_every, fault_policy=ft,
                priors=adaptive_priors,
            )
            return nulls, None, completed, not finished
        nulls, completed = engine.run_null(
            np_this, key=seed, progress=prog, checkpoint_path=ck,
            checkpoint_every=checkpoint_every, fault_policy=ft,
            observed=observed,
        )
        return nulls, None, completed, completed < np_this

    def run_pair_null_guarded(build_engine, engine, np_this, observed, prog,
                              ck, d_name, t_name):
        """:func:`run_pair_null` under the elastic recovery ladder
        (ISSUE 4 + ISSUE 6). On a device-loss-class failure — whose loop
        already failure-saved every completed permutation to ``ck`` —
        the ladder climbs down one rung at a time:

        1. *shrink*: survivors remain
           (:func:`netrep_tpu.utils.backend.enumerate_survivors`) —
           rebuild a smaller mesh over them
           (:func:`netrep_tpu.parallel.mesh.shrink_mesh` preserves as
           much row sharding as still divides), release the superseded
           engine's device arrays *before* the replacement allocates,
           and resume from the checkpoint;
        2. *grow back*: the loop raised
           :class:`~netrep_tpu.utils.faults.CapacityRestoredError` at a
           chunk boundary (committed + checkpointed) — rebuild the
           ORIGINAL mesh and resume;
        3. *CPU*, the final rung, only when zero devices survive (or the
           elastic rebuild budget is spent): force the CPU platform and
           resume replicated.

        Every resume is bit-identical to an unfaulted run: per-permutation
        keys depend only on (seed, index), the checkpoint fingerprint is
        mesh-shape-independent (host-input digest), and the shared
        injector on ``ft`` never re-fires a consumed fault on resumed
        dispatches. A device loss after the CPU rung propagates — CPU
        cannot be lost, so it means something else is wrong."""
        from ..parallel import mesh as meshmod
        from ..parallel.distributed import filter_addressable
        from ..utils import backend as be
        from ..utils import checkpoint as ckpt_mod

        cur_mesh = mesh
        full_spec = meshmod.mesh_spec(mesh)

        def rebuild(new_mesh):
            nonlocal engine, cur_mesh
            # free the superseded engine's HBM before the replacement
            # allocates (ISSUE 6 satellite: GC-timing must not decide
            # whether both device footprints coexist)
            rel = getattr(engine, "release", None)
            if rel is not None:
                rel()
            engine = build_engine(new_mesh)
            cur_mesh = new_mesh
            if ft is not None:
                ft.mesh_rebuilds += 1

        while True:
            try:
                return run_pair_null(engine, np_this, observed, prog, ck)
            except CapacityRestoredError:
                # rung 4 (grow back): committed work is checkpointed; the
                # original capacity is available again
                have = (
                    set(cur_mesh.devices.flat) if cur_mesh is not None
                    else set()
                )
                restored = [d for d in full_spec[0] if d not in have]
                grown = meshmod.mesh_from_spec(full_spec)
                be.announce_mesh_grown(
                    list(grown.devices.flat), restored,
                    discovery=str(d_name), test=str(t_name),
                )
                rebuild(grown)
                if ft is not None:
                    ft.mesh_shrunk = False
            except DeviceLostError as e:
                if ck is None:  # no checkpoint, nothing to resume from
                    raise
                reason = getattr(e, "reason", "device_lost")
                cause = e.__cause__ if e.__cause__ is not None else e
                survivors, lost = be.enumerate_survivors(cur_mesh, e)
                survivors = filter_addressable(survivors)
                budget_ok = ft is None or (
                    ft.mesh_rebuilds < ft.policy.max_mesh_rebuilds
                )
                if survivors and budget_ok:
                    # rung 3 (shrink): resume on the survivor mesh instead
                    # of falling off the CPU cliff
                    be.announce_mesh_shrunk(
                        reason, survivors, lost,
                        discovery=str(d_name), test=str(t_name),
                        error=type(cause).__name__,
                    )
                    rebuild(meshmod.shrink_mesh(survivors, like=cur_mesh))
                    if ft is not None:
                        ft.mesh_shrunk = True
                    continue
                # rung 5 (final): zero accelerators survive — CPU
                freed = lost if lost else (
                    list(cur_mesh.devices.flat) if cur_mesh is not None
                    else []
                )
                be.degrade_to_cpu(
                    reason,
                    discovery=str(d_name), test=str(t_name),
                    error=type(cause).__name__,
                    freed=be.device_inventory(freed),
                )
                rel = getattr(engine, "release", None)
                if rel is not None:
                    rel()
                # the mesh-shape-independent fingerprint makes this resume
                # validate cleanly; the acceptance scope stays as a belt
                # for engines whose fingerprint is still layout-sensitive
                # (key/seed mismatches always refuse either way)
                with ckpt_mod.accept_degraded_fingerprint(reason):
                    return run_pair_null(build_engine(None), np_this,
                                         observed, prog, ck)

    def pair_progress():
        # verbose=True with no user callback gets the reference-style
        # textual progress bar, fresh per pair so rate/ETA restart
        from ..utils.progress import resolve_progress

        return resolve_progress(progress, verbose)

    interrupted = False
    for d_name, t_names in by_disc.items():
        if interrupted:
            break
        disc_ds = datasets[d_name]

        can_vmap = (
            vmap_tests
            and backend == "jax"
            and len(t_names) > 1
            # data-only pairs (ISSUE 9) run sequentially: the multi-test
            # engine stacks the T cohorts' matrices, which data-only
            # datasets do not materialize
            and disc_ds.correlation is not None
            and all(
                datasets[t].correlation is not None for t in t_names
            )
            and all(
                datasets[t].node_names == datasets[t_names[0]].node_names
                for t in t_names
            )
            and len({datasets[t].data is not None for t in t_names}) == 1
        )
        if vmap_tests and not can_vmap and len(t_names) > 1:
            logger.warning(
                "vmap_tests requested but unavailable (requires the default "
                "backend='jax' and materialized matrices; test datasets %s "
                "must share a node universe and agree on data presence); "
                "falling back to sequential pairs (any matrix sharding is "
                "retained per pair)", t_names,
            )

        if can_vmap:
            from ..parallel.multitest import MultiTestEngine

            t0 = datasets[t_names[0]]
            labels, mod_specs, counts, pool = _overlap_setup(
                disc_ds, t0, assign[d_name], modules, background_label, null
            )
            with_data = disc_ds.data is not None and t0.data is not None
            np_this = n_perm if n_perm is not None else auto_n_perm(labels, with_data)
            if verbose:
                logger.info(
                    "discovery %r → tests %s (vmapped): %d modules, %d "
                    "permutations", d_name, t_names, len(labels), np_this,
                )
            t_pair0 = time.perf_counter()
            pair_sid = None
            if tel is not None:
                pair_sid = tel.begin_span(
                    "pair_start", discovery=str(d_name),
                    test="+".join(map(str, t_names)), vmapped=True,
                    n_modules=len(labels), n_perm=int(np_this),
                )
            def build_engine(m=mesh, _t_names=t_names, _specs=mod_specs,
                             _pool=pool, _with_data=with_data):
                cfg = config
                if m is None and cfg.matrix_sharding == "row":
                    # degraded CPU rebuild: no mesh left to row-shard over
                    cfg = dataclasses.replace(
                        cfg, matrix_sharding="replicated"
                    )
                return MultiTestEngine(
                    disc_ds.correlation, disc_ds.network, disc_ds.data,
                    np.stack([datasets[t].correlation for t in _t_names]),
                    np.stack([datasets[t].network for t in _t_names]),
                    [datasets[t].data for t in _t_names]
                    if _with_data else None,
                    _specs, _pool, config=cfg, mesh=m,
                )

            engine = build_engine()
            timer = PairTimer(trace_dir) if profiling else None
            with observed_span(d_name, "+".join(map(str, t_names)),
                               len(labels)):
                observed = (
                    timer.time_observed(engine.observed) if timer
                    else engine.observed()
                )
            nulls, stream, completed, interrupted = run_pair_null_guarded(
                build_engine, engine, np_this, observed,
                (timer.wrap_progress(pair_progress())
                 if timer else pair_progress()),
                ckpt_path(d_name, "+".join(t_names)),
                d_name, "+".join(map(str, t_names)),
            )
            prof_dict = attach_telemetry(
                timer.finish_null(completed) if timer else None
            )
            if tel is not None:
                tel.end_span(
                    pair_sid, "pair_end", discovery=str(d_name),
                    test="+".join(map(str, t_names)),
                    s=time.perf_counter() - t_pair0,
                    completed=int(completed),
                    interrupted=bool(interrupted),
                )
            if interrupted:
                logger.warning(
                    "interrupted after %d/%d permutations; p-values use the "
                    "completed subset; stopping remaining pairs",
                    completed, np_this,
                )
            total_space = pv.total_permutations(pool.size, [m.size for m in mod_specs])
            for ti, t_name in enumerate(t_names):
                results.setdefault(d_name, {})[t_name] = _make_result(
                    d_name, t_name, labels, counts, observed[ti],
                    None if nulls is None else nulls[ti],
                    completed, np_this, alternative, total_space,
                    profile=prof_dict,  # one vmapped run → shared timings
                    p_type="sequential" if adaptive else "fixed",
                    # streamed tallies carry the T axis; each pair's result
                    # gets its own (n_modules, 7) slice
                    stream=(
                        None if stream is None
                        else dataclasses.replace(
                            stream, hi=stream.hi[ti], lo=stream.lo[ti],
                            eff=stream.eff[ti],
                        )
                    ),
                    nulls_exact=_nulls_exact(engine, observed, nulls),
                )
            continue

        for t_name in t_names:
            test_ds = datasets[t_name]
            labels, mod_specs, counts, pool = _overlap_setup(
                disc_ds, test_ds, assign[d_name], modules, background_label, null
            )
            with_data = disc_ds.data is not None and test_ds.data is not None
            np_this = n_perm if n_perm is not None else auto_n_perm(labels, with_data)
            if verbose:
                logger.info(
                    "discovery %r → test %r: %d modules, %d permutations, "
                    "null=%r", d_name, t_name, len(labels), np_this, null,
                )
            t_pair0 = time.perf_counter()
            pair_sid = None
            if tel is not None:
                pair_sid = tel.begin_span(
                    "pair_start", discovery=str(d_name), test=str(t_name),
                    vmapped=False, n_modules=len(labels),
                    n_perm=int(np_this),
                )
            def build_engine(m=mesh, _test_ds=test_ds, _specs=mod_specs,
                             _pool=pool):
                cfg = config
                if m is None and cfg.matrix_sharding == "row":
                    # degraded CPU rebuild: no mesh left to row-shard over
                    cfg = dataclasses.replace(
                        cfg, matrix_sharding="replicated"
                    )
                return engine_cls(
                    disc_ds.correlation, disc_ds.network, disc_ds.data,
                    _test_ds.correlation, _test_ds.network, _test_ds.data,
                    _specs, _pool, config=cfg, mesh=m,
                )

            engine = build_engine()
            timer = PairTimer(trace_dir) if profiling else None
            with observed_span(d_name, t_name, len(labels)):
                observed = (
                    timer.time_observed(engine.observed) if timer
                    else engine.observed()
                )
            nulls, stream, completed, was_interrupted = run_pair_null_guarded(
                build_engine, engine, np_this, observed,
                (timer.wrap_progress(pair_progress())
                 if timer else pair_progress()),
                ckpt_path(d_name, t_name), d_name, t_name,
            )
            if tel is not None:
                tel.end_span(
                    pair_sid, "pair_end", discovery=str(d_name),
                    test=str(t_name),
                    s=time.perf_counter() - t_pair0,
                    completed=int(completed),
                    interrupted=bool(was_interrupted),
                )
            total_space = pv.total_permutations(pool.size, [m.size for m in mod_specs])
            results.setdefault(d_name, {})[t_name] = _make_result(
                d_name, t_name, labels, counts, observed, nulls, completed,
                np_this, alternative, total_space,
                profile=attach_telemetry(
                    timer.finish_null(completed) if timer else None
                ),
                p_type="sequential" if adaptive else "fixed",
                stream=stream,
                nulls_exact=_nulls_exact(engine, observed, nulls),
            )
            if was_interrupted:
                # Ctrl-C aborts the whole multi-pair run, not just the
                # current pair (the reference's clean user-interrupt,
                # SURVEY.md §5); pairs finished so far are returned.
                interrupted = True
                logger.warning(
                    "interrupted after %d/%d permutations; p-values use the "
                    "completed subset; stopping remaining pairs",
                    completed, np_this,
                )
                break

    return shape_results(results, simplify)
