"""`module_preservation` — the framework's main entry point, the rebuild of
the reference's top-level orchestrator (SURVEY.md §2.1, call stack §3.1):
validate inputs, loop over (discovery, test) dataset pairs, run the
permutation engine (the TPU-native ``PermutationProcedure``), aggregate exact
permutation p-values, and shape results.

Argument names follow the reference's documented surface
(``modulePreservation(network, data, correlation, moduleAssignments,
modules, backgroundLabel, discovery, test, selfPreservation, nThreads,
nPerm, null, alternative, simplify, verbose)`` — SURVEY.md §2.1) in
snake_case; ``n_threads`` is accepted for familiarity but ignored (XLA owns
device parallelism — SURVEY.md §2.3 intra-op row).
"""

from __future__ import annotations

import logging
from typing import Callable

import numpy as np

from ..ops import pvalues as pv
from ..parallel.engine import ModuleSpec, PermutationEngine
from ..utils.config import EngineConfig
from . import dataset as ds
from .results import PreservationResult, shape_results

logger = logging.getLogger("netrep_tpu")


def module_preservation(
    network,
    data=None,
    correlation=None,
    module_assignments=None,
    modules=None,
    background_label: str = "0",
    discovery=None,
    test=None,
    self_preservation: bool = False,
    n_threads: int | None = None,  # accepted, unused (XLA owns parallelism)
    n_perm: int | None = None,
    null: str = "overlap",
    alternative: str = "greater",
    simplify: bool = True,
    verbose: bool = False,
    seed: int = 0,
    config: EngineConfig | None = None,
    mesh=None,
    progress: Callable[[int, int], None] | None = None,
):
    """Permutation test of network module preservation across datasets.

    Parameters mirror the reference (SURVEY.md §2.1); TPU-specific additions:

    - ``seed`` — PRNG seed; same seed ⇒ identical nulls regardless of chunk
      size or device mesh (SURVEY.md §7 "RNG semantics").
    - ``config`` — :class:`~netrep_tpu.utils.config.EngineConfig` TPU knobs.
    - ``mesh`` — optional :class:`jax.sharding.Mesh`; permutation chunks are
      sharded across its ``config.mesh_axis`` axis (SURVEY.md §2.3).
    - ``progress`` — callback ``(done, total)`` per chunk.

    Returns
    -------
    ``{discovery: {test: PreservationResult}}``, collapsed by ``simplify``.
    """
    if null not in ("overlap", "all"):
        raise ValueError(f"null must be 'overlap' or 'all', got {null!r}")
    if alternative not in ("greater", "less", "two.sided"):
        raise ValueError(
            "alternative must be one of 'greater', 'less', 'two.sided', "
            f"got {alternative!r}"
        )
    config = config or EngineConfig()

    datasets = ds.build_datasets(network, data=data, correlation=correlation)
    pairs = ds.resolve_pairs(datasets, discovery, test, self_preservation)
    disc_names = sorted({d for d, _ in pairs}, key=list(datasets).index)
    assign = ds.normalize_module_assignments(
        module_assignments, datasets, disc_names
    )

    if n_perm is None:
        # reference default: enough permutations for Bonferroni-corrected
        # significance at 0.05 across modules (SURVEY.md §3.1 requiredPerms-
        # style default), with a floor of 1000.
        n_perm_auto = True
    else:
        n_perm_auto = False

    results: dict[str, dict[str, PreservationResult]] = {}
    for d_name, t_name in pairs:
        disc_ds, test_ds = datasets[d_name], datasets[t_name]
        labels, specs, counts = ds.module_overlap(
            disc_ds, test_ds, assign[d_name], modules, background_label
        )
        dropped = [lab for lab, di, ti in specs if len(ti) < 2]
        if dropped:
            logger.warning(
                "discovery %r → test %r: dropping module(s) %s with <2 "
                "nodes present in the test dataset", d_name, t_name, dropped,
            )
        kept = [(lab, di, ti) for lab, di, ti in specs if len(ti) >= 2]
        if not kept:
            raise ValueError(
                f"no module of discovery {d_name!r} has ≥2 nodes present in "
                f"test {t_name!r}; nothing to test"
            )
        labels = [lab for lab, _, _ in kept]
        mod_specs = [ModuleSpec(lab, di, ti) for lab, di, ti in kept]

        tpos = test_ds.index_of()
        if null == "overlap":
            pool = np.asarray(
                [tpos[nm] for nm in disc_ds.node_names if nm in tpos],
                dtype=np.int32,
            )
        else:
            pool = np.arange(test_ds.n_nodes, dtype=np.int32)

        # Bonferroni across all module×statistic tests (SURVEY.md §3.4):
        # 7 statistics with data, 3 topology-only without.
        n_stats_eff = 7 if (disc_ds.data is not None and test_ds.data is not None) else 3
        np_this = (
            max(1000, pv.required_perms(0.05, n_tests=len(labels) * n_stats_eff))
            if n_perm_auto
            else n_perm
        )
        if verbose:
            logger.info(
                "discovery %r → test %r: %d modules, %d permutations, "
                "null=%r", d_name, t_name, len(labels), np_this, null,
            )

        engine = PermutationEngine(
            disc_ds.correlation, disc_ds.network, disc_ds.data,
            test_ds.correlation, test_ds.network, test_ds.data,
            mod_specs, pool, config=config, mesh=mesh,
        )
        observed = engine.observed()
        nulls, completed = engine.run_null(
            np_this, key=seed, progress=progress
        )
        interrupted = completed < np_this
        if interrupted:
            logger.warning(
                "interrupted after %d/%d permutations; p-values use the "
                "completed subset", completed, np_this,
            )

        total_space = pv.total_permutations(
            pool.size, [m.size for m in mod_specs]
        )
        p_values = pv.permutation_pvalues(
            observed, nulls[:completed], alternative, total_nperm=total_space
        )

        n_present = np.array([counts[lab][0] for lab in labels])
        tot = np.array([counts[lab][1] for lab in labels])
        res = PreservationResult(
            discovery=d_name,
            test=t_name,
            module_labels=labels,
            observed=observed,
            nulls=nulls,
            p_values=p_values,
            n_vars_present=n_present,
            prop_vars_present=n_present / tot,
            total_size=tot,
            alternative=alternative,
            n_perm=np_this,
            completed=completed,
        )
        results.setdefault(d_name, {})[t_name] = res
        if interrupted:
            # Ctrl-C aborts the whole multi-pair run, not just the current
            # pair (the reference's clean user-interrupt, SURVEY.md §5);
            # pairs finished so far are returned.
            logger.warning("stopping remaining dataset pairs after interrupt")
            break

    return shape_results(results, simplify)
