"""`network_properties` — observed per-module topological properties, the
rebuild of the reference's ``networkProperties()`` / NetProps C++ entry
(SURVEY.md §2.1, §3.2): per dataset and module, the summary profile
(eigengene), weighted degree, node contribution, coherence, and average edge
weight; the data-less variant skips the data-dependent properties.

These are one-shot observed computations (once per module, not the hot
loop), so they run through the NumPy oracle kernels — the framework's
semantic source of truth (netrep_tpu/ops/oracle.py), against which the JAX
hot-path kernels are parity-tested. Device dispatch would add latency, not
throughput, here.
"""

from __future__ import annotations

import numpy as np

from ..ops import oracle
from . import dataset as ds


def network_properties(
    network,
    data=None,
    correlation=None,
    module_assignments=None,
    modules=None,
    background_label: str = "0",
    discovery=None,
    test=None,
    self_preservation: bool = True,
    simplify: bool = True,
):
    """Observed per-module network properties (SURVEY.md §3.2).

    Returns ``{discovery: {test: {module: props}}}`` where ``props`` has:

    - ``summary`` : (n_samples,) summary profile (None when data-less)
    - ``degree`` : (m,) within-module weighted degree, normalized to the
      module maximum
    - ``contribution`` : (m,) node contributions (None when data-less)
    - ``coherence`` : float (NaN when data-less)
    - ``avg_weight`` : float
    - ``node_names`` : module node labels present in the dataset

    ``simplify=True`` collapses single-level nesting (reference semantics,
    SURVEY.md §2.1).
    """
    datasets = ds.build_datasets(network, data=data, correlation=correlation)
    # networkProperties defaults to computing properties in every dataset,
    # including the discovery itself (self pairs allowed).
    pairs = ds.resolve_pairs(datasets, discovery, test, self_preservation)
    disc_names = sorted({d for d, _ in pairs}, key=list(datasets).index)
    assign = ds.normalize_module_assignments(
        module_assignments, datasets, disc_names
    )

    out: dict[str, dict[str, dict[str, dict]]] = {}
    for d_name, t_name in pairs:
        disc_ds, tgt = datasets[d_name], datasets[t_name]
        labels, specs, _counts = ds.module_overlap(
            disc_ds, tgt, assign[d_name], modules, background_label
        )
        per_mod = {}
        for lab, _di, ti in specs:
            if len(ti) == 0:
                per_mod[lab] = None
                continue
            sub = np.ix_(ti, ti)
            net_sub = tgt.network[sub]
            deg = oracle.weighted_degree(net_sub)
            dmax = np.max(np.abs(deg))
            props = {
                "node_names": [tgt.node_names[i] for i in ti],
                "degree": deg / dmax if dmax > 0 else deg,
                "avg_weight": oracle.avg_edge_weight(net_sub),
                "summary": None,
                "contribution": None,
                "coherence": float("nan"),
            }
            if tgt.data is not None:
                dat = tgt.data[:, ti]
                prof = oracle.summary_profile(dat)
                nc = oracle.node_contribution(dat, prof)
                props.update(
                    summary=prof,
                    contribution=nc,
                    coherence=float(np.mean(nc**2)),
                )
            per_mod[lab] = props
        out.setdefault(d_name, {})[t_name] = per_mod

    if simplify:
        if len(out) == 1:
            inner = next(iter(out.values()))
            return next(iter(inner.values())) if len(inner) == 1 else inner
    return out


def properties_table(
    network,
    data=None,
    correlation=None,
    module_assignments=None,
    modules=None,
    background_label: str = "0",
    discovery=None,
    test=None,
    self_preservation: bool = True,
):
    """Tidy node-level export of observed network properties: one row per
    (discovery, test, module, node) with that node's ``degree`` and
    ``contribution`` plus the module-level ``avg_weight``/``coherence``
    repeated on each row — the long-format frame users of the reference
    assemble by hand from ``networkProperties()``'s nested lists (the
    preservation-side analogue is :func:`netrep_tpu.results_table`).
    Arguments are :func:`network_properties`'s; requires pandas."""
    try:
        import pandas as pd
    except ImportError as e:
        raise ImportError(
            "properties_table requires pandas — install the frames extra: "
            "pip install netrep-tpu[frames]"
        ) from e

    full = network_properties(
        network, data=data, correlation=correlation,
        module_assignments=module_assignments, modules=modules,
        background_label=background_label, discovery=discovery, test=test,
        self_preservation=self_preservation, simplify=False,
    )
    rows = []
    for d_name, tests in full.items():
        for t_name, mods in tests.items():
            for lab, props in mods.items():
                if props is None:  # module absent from this dataset
                    continue
                contrib = props["contribution"]
                for i, nm in enumerate(props["node_names"]):
                    rows.append({
                        "discovery": d_name,
                        "test": t_name,
                        "module": lab,
                        "node": nm,
                        "degree": float(props["degree"][i]),
                        "contribution": (
                            float(contrib[i]) if contrib is not None
                            else float("nan")
                        ),
                        "avg_weight": float(props["avg_weight"]),
                        "coherence": float(props["coherence"]),
                    })
    return pd.DataFrame(
        rows, columns=["discovery", "test", "module", "node", "degree",
                       "contribution", "avg_weight", "coherence"],
    )
