"""``module_preservation(data_only=…)`` — the atlas-plane user surface
(ISSUE 9 tentpole).

The dense entry point requires materialized n×n correlation/network
matrices per dataset; at atlas scale (100k+ genes) those are exactly what
cannot exist. This surface takes ONLY data + the WGCNA soft-threshold
spec and runs the same orchestrator (pair resolution, overlap handling,
permutation null, exact p-values, result shaping) with every k×k
submatrix derived on device from gathered data columns
(:mod:`netrep_tpu.atlas.modules`) — the dense
:class:`~netrep_tpu.parallel.engine.PermutationEngine` with
``correlation=None, network=None``.

Composes with everything the dense surface composes with: streaming
tallies (``store_nulls=False``), adaptive early stopping, checkpoints,
telemetry, fault policies, and permutation-axis meshes. For the
*construction* side of the atlas plane (thresholded
:class:`~netrep_tpu.ops.sparse.SparseAdjacency` networks out of the tile
grid) see :func:`netrep_tpu.atlas.build_sparse_network`.
"""

from __future__ import annotations

from . import preservation as _pres


def module_preservation(
    data,
    module_assignments=None,
    data_only=2.0,
    **kwargs,
):
    """Data-only permutation test of module preservation.

    Parameters
    ----------
    data : (n_samples, n) matrix, list, or dict of them — one per
        dataset, exactly like the dense surface's ``data`` argument.
        Zero-variance columns are rejected with the same posture as the
        dense path's non-finite-correlation check (their derived
        correlations are NaN — ``np.corrcoef`` semantics).
    module_assignments, **kwargs : as for
        :func:`netrep_tpu.models.preservation.module_preservation`
        (``discovery``/``test``/``n_perm``/``adaptive``/``store_nulls``/
        ``config``/``mesh``/…).
    data_only : the derivation spec — soft-threshold power β for the
        unsigned WGCNA adjacency ``|corr|**β`` (default 2.0), or a
        ``(β, kind)`` pair with ``kind`` in ``('unsigned', 'signed',
        'signed-hybrid')``.

    Returns the usual ``PreservationResult`` shape.
    """
    return _pres.module_preservation(
        network=None,
        data=data,
        correlation=None,
        module_assignments=module_assignments,
        data_only=data_only,
        **kwargs,
    )
