"""All-pairs preservation atlas (ISSUE 17): the D×D grid as ONE job.

The paper's unit of work is one (discovery, test) pair; real consortia
ask the D·(D−1) question — is every module of every cohort preserved in
every other cohort? — and re-ask it every time one cohort grows.
:func:`grid_preservation` runs that grid with every amortization the
serving stack already proved out, while keeping each cell's numbers
BIT-IDENTICAL to a solo :func:`~netrep_tpu.models.preservation
.module_preservation` call with the same seed (pinned in
tests/test_grid.py):

- **cross-pair packing** — cells sharing a test dataset (a grid COLUMN)
  and a byte-equal permutation pool ride one
  :class:`~netrep_tpu.serve.packer.GridPackedEngine`: shared
  module-size-bucket dispatch streams, per-request discovery props,
  request-local slice offsets, per-request RNG key groups;
- **discovery-side dedup** — one
  :class:`~netrep_tpu.parallel.engine.ObservedCache` spans the whole
  grid, so cells sharing a discovery dataset (a grid ROW) compute their
  per-bucket discovery property arrays once (digest-keyed; hits emit
  ``grid_dedup_hit``);
- **grid checkpoint** — with ``grid_dir`` set, the grid persists as a
  digest-keyed JSON manifest of per-cell results (each a
  :class:`~netrep_tpu.models.results.PreservationResult` ``.npz``) plus
  per-pack count-space chunk checkpoints, so an interrupted grid resumes
  across tunnel windows and a FINISHED cell is never recomputed;
- **fleet spread** — ``fleet=`` routes each cell through a
  :class:`~netrep_tpu.serve.fleet.FleetCoordinator`: rows land on
  replicas by the PR 14 content-digest hash ring (locality: one
  replica's warm engines keep serving the same cohort pair);
- **incremental re-analysis** — when one dataset's ``content_digest``
  changes, only its row and column recompute; each recomputed adaptive
  cell's :class:`~netrep_tpu.ops.sequential.StopMonitor` is seeded with
  the prior run's per-module count-space tallies
  (:meth:`~netrep_tpu.ops.sequential.StopMonitor.seed_priors`, emitted
  as ``grid_warmstart_seeded``), so a stable cell retires in hundreds of
  fresh permutations while every REPORTED number stays fresh-draw-only.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import time

import numpy as np

from ..ops import pvalues as pv
from ..parallel.engine import ObservedCache
from ..utils import telemetry as tm
from ..utils.checkpoint import content_digest
from ..utils.config import EngineConfig
from . import dataset as ds
from .preservation import _overlap_setup
from .results import PreservationResult

logger = logging.getLogger("netrep_tpu")

MANIFEST_NAME = "grid_manifest.json"
_MANIFEST_VERSION = 1


@dataclasses.dataclass
class GridResult:
    """The grid's results plus its execution accounting.

    ``results[discovery][test]`` is the cell's
    :class:`~netrep_tpu.models.results.PreservationResult` — bit-identical
    to the solo call. ``stats`` records how the grid earned its speed:
    ``cells_total``/``cells_computed``/``cells_reused``/
    ``cells_warmstarted``, ``perms_evaluated`` (fresh permutations ×
    modules actually folded, the bench's <25%-delta meter), and the
    observed-cache ``dedup`` counters."""

    results: dict
    stats: dict
    manifest_path: str | None = None

    def cell(self, discovery, test) -> PreservationResult:
        return self.results[str(discovery)][str(test)]

    def __getitem__(self, key):
        return self.results[str(key)]

    def cells(self):
        for d, row in self.results.items():
            for t, res in row.items():
                yield d, t, res


def _cfg_id(config: EngineConfig) -> str:
    return hashlib.blake2b(repr(config).encode(),
                           digest_size=8).hexdigest()


def _pool_sig(pool: np.ndarray) -> str:
    return hashlib.blake2b(
        np.ascontiguousarray(pool, dtype=np.int64), digest_size=8
    ).hexdigest()


def _cell_key(d: str, t: str) -> str:
    return f"{d}→{t}"


def _safe(name: str) -> str:
    import re

    return re.sub(r"[^A-Za-z0-9_.-]", "_", str(name))


def _auto_n_perm(labels, with_data: bool) -> int:
    # the library's Bonferroni auto rule (models/preservation.py),
    # mirrored per cell so a grid cell defaults exactly like a solo call
    n_stats_eff = 7 if with_data else 3
    return max(1000, pv.required_perms(0.05, n_tests=len(labels) * n_stats_eff))


def _result_from_pack(res: dict, d: str, t: str) -> PreservationResult:
    """One run_pack / serve result dict → the PreservationResult the solo
    call would shape (count-space: the grid never materializes nulls)."""
    n_used = res.get("n_perm_used")
    return PreservationResult(
        n_perm_used=None if n_used is None else np.asarray(n_used),
        p_type=str(res["p_type"]),
        discovery=d,
        test=t,
        module_labels=[str(l) for l in res["module_labels"]],
        observed=np.asarray(res["observed"]),
        nulls=None,
        counts_hi=np.asarray(res["counts_hi"]),
        counts_lo=np.asarray(res["counts_lo"]),
        counts_eff=np.asarray(res["counts_eff"]),
        p_values=np.asarray(res["p_values"]),
        n_vars_present=np.asarray(res["n_vars_present"]),
        prop_vars_present=np.asarray(res["prop_vars_present"]),
        total_size=np.asarray(res["total_size"]),
        alternative=str(res["alternative"]),
        n_perm=int(res["n_perm"]),
        completed=int(res["completed"]),
        total_space=res["total_space"],
    )


def _cell_perms(res: PreservationResult) -> int:
    """Fresh permutation-work meter for one cell: per-module counts for
    adaptive runs, completed × modules for fixed ones."""
    if res.n_perm_used is not None:
        return int(np.asarray(res.n_perm_used, dtype=np.int64).sum())
    return int(res.completed) * len(res.module_labels)


def _priors_from(prev: PreservationResult, labels) -> tuple | None:
    """Warm-start tallies from a prior run of the same cell — None when
    the stored result cannot seed this run's monitor (module set changed,
    non-adaptive prior, or counts missing)."""
    if prev.p_type != "sequential" or prev.n_perm_used is None:
        return None
    if prev.counts_hi is None or prev.counts_lo is None:
        return None
    if [str(l) for l in prev.module_labels] != [str(l) for l in labels]:
        return None
    return (
        np.asarray(prev.counts_hi, dtype=np.int64),
        np.asarray(prev.counts_lo, dtype=np.int64),
        np.asarray(prev.n_perm_used, dtype=np.int64),
    )


def _load_manifest(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    if m.get("version") != _MANIFEST_VERSION:
        return None
    return m


def _write_manifest(path: str, manifest: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def grid_preservation(
    network=None,
    data=None,
    correlation=None,
    module_assignments=None,
    modules=None,
    background_label: str = "0",
    datasets=None,
    n_perm: int | None = None,
    null: str = "overlap",
    alternative: str = "greater",
    seed: int = 0,
    config: EngineConfig | None = None,
    adaptive: bool = False,
    adaptive_rule=None,
    grid_dir: str | None = None,
    fleet=None,
    fleet_tenant: str = "grid",
    telemetry=None,
    verbose: bool = False,
    checkpoint_every: int = 8192,
) -> GridResult:
    """Run the all-pairs preservation grid (module docstring).

    Inputs follow :func:`~netrep_tpu.models.preservation
    .module_preservation`'s dict-keyed surface: ``network`` /
    ``correlation`` / ``data`` map dataset names to matrices, and
    ``module_assignments`` maps each DISCOVERY dataset name to its
    node→module mapping — every assigned dataset is a grid row, every
    dataset is a grid column, and the cells are all ordered pairs
    (row, column) with row ≠ column. ``datasets`` optionally narrows
    the grid to a subset of names (rows and columns).

    - ``grid_dir`` — persistence root: the digest-keyed manifest, one
      ``cell_<d>__<t>.npz`` result per finished cell, and ``ckpt/`` pack
      checkpoints. Re-running with the same directory resumes: finished
      cells whose dataset digests (and analysis parameters) still match
      load from disk with ZERO permutations; a changed dataset
      invalidates exactly its row + column, and (``adaptive=True``) each
      invalidated cell's fresh monitor is seeded with the stored run's
      tallies as priors.
    - ``fleet`` — a :class:`~netrep_tpu.serve.fleet.FleetCoordinator`
      (e.g. :func:`~netrep_tpu.serve.fleet.build_inprocess_fleet`):
      cells route to replicas via the content-digest hash ring instead
      of running in-process. The coordinator's serve config must carry
      the same ``EngineConfig`` for bit-parity (the serve contract).
      Grid-side manifest reuse still applies; warm-start priors and the
      cross-grid observed cache are local-execution features.
    - ``adaptive`` / ``adaptive_rule`` / ``n_perm`` / ``null`` /
      ``alternative`` / ``seed`` — per-cell analysis knobs, exactly as
      the solo call interprets them (every cell shares the one seed,
      like ``module_preservation`` across pairs).
    """
    if null not in ("overlap", "all"):
        raise ValueError(f"null must be 'overlap' or 'all', got {null!r}")
    if alternative not in ("greater", "less", "two.sided"):
        raise ValueError(
            "alternative must be one of 'greater', 'less', 'two.sided', "
            f"got {alternative!r}"
        )
    config = config or EngineConfig()
    if config.network_from_correlation is not None:
        raise ValueError(
            "grid_preservation v1 runs on materialized matrices; "
            "data-only (derived-network) grids run cell-by-cell via "
            "module_preservation"
        )
    built = ds.build_datasets(network, data=data, correlation=correlation)
    names = (
        list(built) if datasets is None else [str(n) for n in datasets]
    )
    for n in names:
        if n not in built:
            raise ValueError(f"datasets names unknown dataset {n!r}")
    if not isinstance(module_assignments, dict) or not module_assignments:
        raise ValueError(
            "grid_preservation needs module_assignments as a dict keyed "
            "by discovery dataset name (each value the node→module "
            "mapping)"
        )
    rows = [n for n in names if n in module_assignments]
    if not rows:
        raise ValueError(
            "no grid dataset carries module assignments; nothing to test"
        )
    assign = ds.normalize_module_assignments(
        {k: module_assignments[k] for k in rows}, built, rows
    )
    cells = [(d, t) for d in rows for t in names if t != d]
    if not cells:
        raise ValueError("the grid needs at least two datasets")

    digests = {
        n: content_digest(
            [built[n].correlation, built[n].network, built[n].data]
        )
        for n in names
    }
    cfg_id = _cfg_id(config)
    params = {
        "null": null, "alternative": alternative, "seed": int(seed),
        "adaptive": bool(adaptive), "cfg": cfg_id,
        "n_perm": None if n_perm is None else int(n_perm),
        "rule": repr(adaptive_rule) if adaptive_rule is not None else None,
    }

    manifest_path = None
    manifest = None
    prior_cells: dict[str, dict] = {}
    if grid_dir is not None:
        os.makedirs(os.path.join(grid_dir, "ckpt"), exist_ok=True)
        manifest_path = os.path.join(grid_dir, MANIFEST_NAME)
        manifest = _load_manifest(manifest_path)
        if manifest is not None and manifest.get("params") == params:
            prior_cells = dict(manifest.get("cells", {}))
        manifest = {
            "version": _MANIFEST_VERSION, "params": params,
            "datasets": dict(digests), "cells": {},
        }

    tel, tel_owned = tm.resolve_arg(telemetry)
    tel_cm = tel.activate() if tel is not None else None
    grid_sid = None
    if tel_cm is not None:
        tel_cm.__enter__()
        grid_sid = tel.begin_span(
            "grid_start", datasets=len(names), rows=len(rows),
            cells=len(cells), adaptive=bool(adaptive),
            fleet=fleet is not None, resumable=grid_dir is not None,
        )
    t0 = time.perf_counter()
    cache = ObservedCache()
    stats = {
        "cells_total": len(cells), "cells_computed": 0,
        "cells_reused": 0, "cells_warmstarted": 0,
        "perms_evaluated": 0, "packs": 0,
    }
    try:
        results, computed = _run_grid(
            built, names, rows, cells, assign, modules, background_label,
            null, alternative, n_perm, seed, config, adaptive,
            adaptive_rule, grid_dir, manifest, prior_cells, digests,
            fleet, fleet_tenant, tel, cache, stats, verbose,
            checkpoint_every,
        )
        if manifest_path is not None:
            _write_manifest(manifest_path, manifest)
        stats["dedup"] = cache.stats()
        stats["wall_s"] = time.perf_counter() - t0
        if tel is not None:
            tel.end_span(
                grid_sid, "grid_end",
                cells_computed=stats["cells_computed"],
                cells_reused=stats["cells_reused"],
                cells_warmstarted=stats["cells_warmstarted"],
                perms_evaluated=stats["perms_evaluated"],
                s=stats["wall_s"],
            )
        return GridResult(results=results, stats=stats,
                          manifest_path=manifest_path)
    finally:
        if tel_cm is not None:
            tel_cm.__exit__(None, None, None)
            if tel_owned:
                tel.close()


def _run_grid(built, names, rows, cells, assign, modules, background_label,
              null, alternative, n_perm, seed, config, adaptive,
              adaptive_rule, grid_dir, manifest, prior_cells, digests,
              fleet, fleet_tenant, tel, cache, stats, verbose,
              checkpoint_every):
    """Grid execution body: resolve every cell's plan, reuse finished
    cells from the manifest, then run the remaining cells column-packed
    (or fleet-routed) and persist."""
    from ..serve.packer import (
        GridPackedEngine, RequestPlan, assign_bases, run_pack,
    )

    def cell_path(d, t):
        if grid_dir is None:
            return None
        return os.path.join(grid_dir, f"cell_{_safe(d)}__{_safe(t)}.npz")

    # -- resolve plans -----------------------------------------------------
    plans: dict[tuple[str, str], dict] = {}
    for d, t in cells:
        labels, specs, counts, pool = _overlap_setup(
            built[d], built[t], assign[d], modules, background_label, null
        )
        with_data = built[d].data is not None and built[t].data is not None
        np_this = (
            int(n_perm) if n_perm is not None
            else _auto_n_perm(labels, with_data)
        )
        plans[(d, t)] = {
            "labels": labels, "specs": specs, "counts": counts,
            "pool": pool, "n_perm": np_this, "with_data": with_data,
        }

    # -- manifest reuse + warm-start priors --------------------------------
    results: dict[str, dict] = {d: {} for d in rows}
    todo: list[tuple[str, str]] = []
    priors: dict[tuple[str, str], tuple] = {}
    for d, t in cells:
        key = _cell_key(d, t)
        ent = prior_cells.get(key)
        path = cell_path(d, t)
        fresh = (
            ent is not None and path is not None
            and ent.get("disc_digest") == digests[d]
            and ent.get("test_digest") == digests[t]
            and int(ent.get("n_perm", -1)) == plans[(d, t)]["n_perm"]
            and os.path.exists(ent.get("path") or path)
        )
        if fresh:
            try:
                res = PreservationResult.load(ent.get("path") or path)
            except (OSError, ValueError):
                fresh = False
            else:
                results[d][t] = res
                stats["cells_reused"] += 1
                if manifest is not None:
                    manifest["cells"][key] = dict(ent)
                if tel is not None:
                    tel.emit("grid_cell_done", discovery=str(d),
                             test=str(t), source="manifest", perms=0)
        if not fresh:
            todo.append((d, t))
            if adaptive and ent is not None:
                stored = ent.get("path") or path
                if stored and os.path.exists(stored):
                    try:
                        prev = PreservationResult.load(stored)
                    except (OSError, ValueError):
                        prev = None
                    p = (None if prev is None
                         else _priors_from(prev, plans[(d, t)]["labels"]))
                    if p is not None:
                        priors[(d, t)] = p

    def finish_cell(d, t, res: PreservationResult):
        results[d][t] = res
        stats["cells_computed"] += 1
        perms = _cell_perms(res)
        stats["perms_evaluated"] += perms
        path = cell_path(d, t)
        if path is not None:
            res.save(path)
            manifest["cells"][_cell_key(d, t)] = {
                "discovery": str(d), "test": str(t),
                "disc_digest": digests[d], "test_digest": digests[t],
                "n_perm": int(plans[(d, t)]["n_perm"]),
                "completed": int(res.completed),
                "p_type": res.p_type, "path": path,
                "warmstarted": (d, t) in priors,
            }
        if tel is not None:
            tel.emit("grid_cell_done", discovery=str(d), test=str(t),
                     source="computed", perms=int(perms),
                     warmstarted=(d, t) in priors)

    # -- fleet spread ------------------------------------------------------
    if fleet is not None and todo:
        _run_fleet(fleet, fleet_tenant, built, assign, todo, plans, null,
                   alternative, seed, adaptive, adaptive_rule, tel,
                   finish_cell, verbose)
        return results, todo

    # -- column-packed local execution -------------------------------------
    # group the remaining cells by (test dataset, pool signature, data
    # presence): the GridPackedEngine compatibility identity. Cells of a
    # group share one engine; groups of one run as single-request packs
    # through the same code path.
    groups: dict[tuple, list[tuple[str, str]]] = {}
    for t in names:
        for d, tt in todo:
            if tt != t:
                continue
            p = plans[(d, t)]
            gkey = (t, _pool_sig(p["pool"]), p["with_data"])
            groups.setdefault(gkey, []).append((d, t))
    for (t, psig, with_data), members in groups.items():
        req_plans = []
        sources = []
        for d, _t in members:
            p = plans[(d, t)]
            req_plans.append(RequestPlan(
                labels=p["labels"], specs=p["specs"], counts=p["counts"],
                pool=p["pool"], n_perm=p["n_perm"], seed=int(seed),
                alternative=alternative, adaptive=bool(adaptive),
                rule=adaptive_rule, priors=priors.get((d, t)),
            ))
            dd = built[d]
            sources.append((
                dd.correlation, dd.network,
                dd.data if with_data else None,
            ))
        assign_bases(req_plans)
        tds = built[t]
        engine = GridPackedEngine(
            sources, tds.correlation, tds.network,
            tds.data if with_data else None,
            [p.specs for p in req_plans], req_plans[0].pool,
            config=config, observed_cache=cache,
        )
        ck = None
        if grid_dir is not None:
            h = hashlib.blake2b(digest_size=8)
            for (d, _t), p in zip(members, req_plans):
                h.update(f"{d}|{t}|{p.seed}|{p.n_perm}|".encode())
                h.update(p.signature().encode())
            ck = os.path.join(grid_dir, "ckpt",
                              f"pack_{_safe(t)}_{h.hexdigest()}.npz")
        if verbose:
            logger.info(
                "grid column %r: %d cell(s) packed (%s)", t, len(members),
                ", ".join(d for d, _ in members),
            )
        for d, _t in members:
            if tel is not None:
                tel.emit("grid_cell_start", discovery=str(d), test=str(t),
                         pack_size=len(members),
                         n_modules=len(plans[(d, t)]["labels"]),
                         warmstarted=(d, t) in priors)
            if (d, t) in priors:
                stats["cells_warmstarted"] += 1
                if tel is not None:
                    tel.emit(
                        "grid_warmstart_seeded", discovery=str(d),
                        test=str(t),
                        prior_perms=int(priors[(d, t)][2].sum()),
                    )
        stats["packs"] += 1
        pack_res = run_pack(
            engine, req_plans, telemetry=tel, checkpoint_path=ck,
            checkpoint_every=checkpoint_every,
        )
        if ck is not None:
            # the pack finished: its chunk checkpoint is spent
            try:
                os.unlink(ck)
            except OSError:
                pass
        for (d, _t), res in zip(members, pack_res):
            finish_cell(d, t, _result_from_pack(res, d, t))
    return results, todo


def _run_fleet(fleet, tenant, built, assign, todo, plans, null,
               alternative, seed, adaptive, adaptive_rule, tel,
               finish_cell, verbose):
    """Fleet-spread execution: register every grid dataset once (the
    coordinator broadcasts and records content digests for ring
    routing), then route each cell to the replica the hash ring owns it
    on. The serve path's own pack/bit-parity contract applies on each
    replica; cells sharing a replica and test dataset pack there."""
    needed = sorted({d for d, _ in todo} | {t for _, t in todo})
    for n in needed:
        dset = built[n]
        fleet.register_dataset(
            tenant, n, network=dset.network, correlation=dset.correlation,
            data=dset.data, assignments=assign.get(n),
        )
    for d, t in todo:
        if tel is not None:
            tel.emit("grid_cell_start", discovery=str(d), test=str(t),
                     pack_size=1, fleet=True,
                     n_modules=len(plans[(d, t)]["labels"]))
        if verbose:
            rep = fleet.route(tenant, d, t)
            logger.info("grid cell %r→%r routed to replica %s", d, t,
                        getattr(rep, "rid", "?"))
        res = fleet.analyze(
            tenant, d, t, n_perm=plans[(d, t)]["n_perm"], seed=int(seed),
            alternative=alternative, adaptive=bool(adaptive),
            rule=adaptive_rule,
        )
        finish_cell(d, t, _result_from_pack(res, d, t))
