"""``sparse_module_preservation`` — the Config E user surface
(BASELINE.json:11: 50k-cell kNN graph, sparse adjacency, Leiden-cluster
modules). Mirrors :func:`~netrep_tpu.models.preservation.module_preservation`
semantics (overlap resolution, permutation null, exact p-values, result
shaping) on :class:`~netrep_tpu.ops.sparse.SparseAdjacency` networks, where
the dense ``n × n`` network/correlation matrices the reference's surface
requires (SURVEY.md §2.1) are exactly what cannot exist at this scale."""

from __future__ import annotations

import logging
from typing import Callable, Sequence

import numpy as np

from ..ops import pvalues as pv
from ..ops.sparse import SparseAdjacency
from ..parallel.engine import ModuleSpec
from ..parallel.sparse import SparsePermutationEngine
from ..utils.config import EngineConfig
from .results import PreservationResult

logger = logging.getLogger("netrep_tpu")


def _normalize_names(names, n: int) -> list[str]:
    """Node-name normalization shared by the sparse surfaces: positional
    ``node_{i}`` defaults, stringify, length check."""
    if names is None:
        return [f"node_{i}" for i in range(n)]
    names = [str(nm) for nm in names]
    if len(names) != n:
        raise ValueError("names length != network size")
    return names


def _normalize_assignments(
    labels: dict[str, str] | Sequence,
    names: list[str],
    what: str = "network",
) -> dict[str, str]:
    """Dict/positional-array module-assignment normalization shared by the
    sparse surfaces: node name → str label, every node covered."""
    if labels is None:
        raise ValueError(
            "module_assignments must be provided (node name → label dict or "
            "per-position label array)"
        )
    if isinstance(labels, dict):
        missing = [nm for nm in names if nm not in labels]
        if missing:
            raise ValueError(
                f"module_assignments is missing {len(missing)} {what} "
                f"node(s), e.g. {missing[:3]}"
            )
        return {nm: str(labels[nm]) for nm in names}
    labels = np.asarray(labels)
    if labels.shape[0] != len(names):
        raise ValueError(
            f"module_assignments has {labels.shape[0]} entries but the "
            f"{what} network has {len(names)} nodes"
        )
    return {nm: str(l) for nm, l in zip(names, labels)}


def _resolve_modules(
    labels: dict[str, str] | Sequence,
    disc_names: list[str],
    test_names: list[str],
    modules,
    background_label: str,
):
    """Name-aligned overlap resolution via the shared
    :func:`~netrep_tpu.models.dataset.module_overlap_names` core (same
    semantics as the dense path, SURVEY.md §3.1), preceded by the
    dict/positional-array normalization the sparse surface accepts."""
    from .dataset import module_overlap_names

    assignments = _normalize_assignments(labels, disc_names, "discovery")

    all_labels, raw_specs, counts = module_overlap_names(
        disc_names, test_names, assignments, modules, background_label,
    )
    kept, specs = [], []
    for lab, disc_idx, test_idx in raw_specs:
        if len(test_idx) < 2:
            logger.warning(
                "dropping module %r: %d node(s) present in the test dataset",
                lab, len(test_idx),
            )
            continue
        kept.append(lab)
        specs.append(ModuleSpec(lab, disc_idx, test_idx))
    if not kept:
        raise ValueError(
            "no module has ≥2 nodes present in the test dataset; nothing to test"
        )
    return kept, specs, counts


def sparse_module_preservation(
    discovery_network: SparseAdjacency,
    test_network: SparseAdjacency,
    module_assignments,
    discovery_data=None,
    test_data=None,
    discovery_correlation: SparseAdjacency | None = None,
    test_correlation: SparseAdjacency | None = None,
    discovery_names: Sequence[str] | None = None,
    test_names: Sequence[str] | None = None,
    modules=None,
    background_label: str = "0",
    discovery: str = "discovery",
    test: str = "test",
    n_perm: int | None = None,
    null: str = "overlap",
    alternative: str = "greater",
    seed: int = 0,
    config: EngineConfig | None = None,
    mesh=None,
    verbose: bool = False,
    progress: Callable[[int, int], None] | None = None,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 8192,
) -> PreservationResult:
    """Permutation test of module preservation on sparse networks.

    Parameters follow :func:`module_preservation` where they apply;
    differences forced by the sparse representation:

    - ``discovery_network`` / ``test_network`` are
      :class:`SparseAdjacency` objects (build with ``from_coo`` /
      ``from_dense``); no *dense* ``correlation`` argument exists. The
      correlation statistics come from ``discovery_correlation`` /
      ``test_correlation`` — optional PRECOMPUTED sparse correlations in
      the same neighbor-list format, authoritative when given (as the
      dense surface's ``correlation`` argument is) — or else are computed
      from ``*_data`` on the fly (``zᵀz/(s-1)`` per module slice).
      Without data, a precomputed correlation restores four finite
      statistics (``avg.weight``, ``cor.cor``, ``cor.degree``,
      ``avg.cor``); with neither, only ``avg.weight`` and ``cor.degree``
      are defined (:mod:`netrep_tpu.ops.sparse`). Absent correlation
      pairs count as 0, the same convention as absent edges.
    - ``discovery_names`` / ``test_names`` align nodes across datasets by
      name; omitted, both graphs must have the same node count and
      position ``i`` is the same node in both.
    - ``module_assignments`` maps discovery node name → label (dict) or is
      a per-position label array.
    - ``discovery`` / ``test`` are dataset *names* recorded on the result
      (plot labels, multi-result bookkeeping) — the matrices themselves ride
      in the positional arguments, so unlike the dense surface these are
      purely labels, defaulting to ``"discovery"`` / ``"test"``.

    Returns a single :class:`PreservationResult` (one dataset pair).
    """
    if null not in ("overlap", "all"):
        raise ValueError(f"null must be 'overlap' or 'all', got {null!r}")
    if alternative not in ("greater", "less", "two.sided"):
        raise ValueError(
            "alternative must be one of 'greater', 'less', 'two.sided', "
            f"got {alternative!r}"
        )
    if not isinstance(discovery_network, SparseAdjacency) or not isinstance(
        test_network, SparseAdjacency
    ):
        raise TypeError(
            "discovery_network/test_network must be SparseAdjacency (use "
            "SparseAdjacency.from_coo / from_dense; for dense matrices use "
            "module_preservation)"
        )
    for what, d, adj in (
        ("discovery", discovery_data, discovery_network),
        ("test", test_data, test_network),
    ):
        if d is not None:
            d = np.asarray(d)
            if d.ndim != 2 or d.shape[1] != adj.n:
                raise ValueError(
                    f"{what}_data must be (n_samples, {adj.n}), got "
                    f"{d.shape}"
                )

    if discovery_names is None or test_names is None:
        if discovery_names is not None or test_names is not None:
            raise ValueError(
                "provide both discovery_names and test_names, or neither"
            )
        if discovery_network.n != test_network.n:
            raise ValueError(
                "without node names the two networks must have the same "
                f"node count (got {discovery_network.n} vs "
                f"{test_network.n}); pass discovery_names/test_names"
            )
        discovery_names = [f"node_{i}" for i in range(discovery_network.n)]
        test_names = list(discovery_names)
    discovery_names = [str(n) for n in discovery_names]
    test_names = [str(n) for n in test_names]
    if len(discovery_names) != discovery_network.n:
        raise ValueError("discovery_names length != discovery network size")
    if len(test_names) != test_network.n:
        raise ValueError("test_names length != test network size")

    labels, specs, counts = _resolve_modules(
        module_assignments, discovery_names, test_names, modules,
        background_label,
    )

    tpos = {nm: i for i, nm in enumerate(test_names)}
    if null == "overlap":
        pool = np.asarray(
            [tpos[nm] for nm in discovery_names if nm in tpos],
            dtype=np.int32,
        )
    else:
        pool = np.arange(test_network.n, dtype=np.int32)

    with_data = discovery_data is not None and test_data is not None
    with_corr = (
        discovery_correlation is not None and test_correlation is not None
    )
    if n_perm is None:
        # finite statistics: 7 with data; 4 with a precomputed correlation
        # only (avg.weight, cor.cor, cor.degree, avg.cor); 2 with neither
        n_stats_eff = 7 if with_data else (4 if with_corr else 2)
        n_perm = max(1000, pv.required_perms(0.05, n_tests=len(labels) * n_stats_eff))

    engine = SparsePermutationEngine(
        discovery_network, discovery_data if with_data else None,
        test_network, test_data if with_data else None,
        specs, pool, config=config or EngineConfig(), mesh=mesh,
        disc_corr=discovery_correlation, test_corr=test_correlation,
    )
    if verbose:
        logger.info(
            "sparse %r → %r: %d modules, %d permutations",
            discovery, test, len(labels), n_perm,
        )
    from ..utils.progress import resolve_progress

    progress = resolve_progress(progress, verbose)
    observed = engine.observed()
    nulls, completed = engine.run_null(
        n_perm, key=seed, progress=progress,
        checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
    )
    if completed < n_perm:
        logger.warning(
            "interrupted after %d/%d permutations; p-values use the "
            "completed subset", completed, n_perm,
        )
    total_space = pv.total_permutations(pool.size, [m.size for m in specs])
    p_values = pv.permutation_pvalues(
        observed, nulls[:completed], alternative, total_nperm=total_space
    )
    n_present = np.array([counts[lab][0] for lab in labels])
    tot = np.array([counts[lab][1] for lab in labels])
    return PreservationResult(
        discovery=discovery,
        test=test,
        module_labels=labels,
        observed=observed,
        nulls=nulls,
        p_values=p_values,
        n_vars_present=n_present,
        prop_vars_present=n_present / tot,
        total_size=tot,
        alternative=alternative,
        n_perm=n_perm,
        completed=completed,
        total_space=total_space,
    )


def sparse_network_properties(
    network: SparseAdjacency,
    data=None,
    module_assignments=None,
    names: Sequence[str] | None = None,
    modules=None,
    background_label: str = "0",
) -> dict:
    """Observed per-module network properties on a sparse network — the
    Config E twin of :func:`~netrep_tpu.models.properties.network_properties`
    (the reference's ``networkProperties()``, SURVEY.md §3.2), for one
    dataset whose modules are defined over its own nodes.

    Returns ``{module: props}`` with the dense surface's keys
    (``node_names``, ``degree`` normalized to the module max,
    ``avg_weight``, and — when ``data`` is given — ``summary``,
    ``contribution``, ``coherence``; None/NaN otherwise). Degree and average
    edge weight come from the padded neighbor lists, never a dense matrix;
    the denominator counts all ordered pairs ``m·(m-1)``, matching the
    dense kernels (absent edges are zeros).
    """
    from ..ops import oracle

    if not isinstance(network, SparseAdjacency):
        raise TypeError("network must be a SparseAdjacency")
    if data is not None:
        data = np.asarray(data)
        if data.ndim != 2 or data.shape[1] != network.n:
            raise ValueError(
                f"data must be (n_samples, {network.n}), got "
                f"{getattr(data, 'shape', None)}"
            )
    names = _normalize_names(names, network.n)
    # Observation surface: unlike the preservation path (_resolve_modules),
    # singleton modules are KEPT — there is no test-overlap requirement; the
    # dense network_properties twin reports them too (avg_weight NaN).
    assignments = _normalize_assignments(module_assignments, names)
    per_node = [assignments[nm] for nm in names]
    by_label: dict[str, list[int]] = {}
    for i, lab in enumerate(per_node):
        if lab != str(background_label):
            by_label.setdefault(lab, []).append(i)
    if modules is not None:
        wanted = [str(m) for m in modules]
        unknown = [m for m in wanted if m not in by_label]
        if unknown:
            raise ValueError(
                f"modules {unknown} do not exist in the module assignments"
            )
        by_label = {m: by_label[m] for m in wanted}
    if not by_label:
        raise ValueError("all nodes carry the background label; no modules")

    out = {}
    for lab, node_pos in by_label.items():
        idx = np.asarray(node_pos, dtype=np.int64)
        m = idx.size
        nbr_rows = network.nbr[idx]                   # (m, k)
        wgt_rows = network.wgt[idx].astype(np.float64)
        member = np.isin(nbr_rows, idx) & (nbr_rows != idx[:, None])
        deg = (wgt_rows * member).sum(axis=1)
        dmax = np.max(np.abs(deg))
        props = {
            "node_names": [names[i] for i in idx],
            "degree": deg / dmax if dmax > 0 else deg,
            # m<2: no pairs — NaN, matching oracle.avg_edge_weight
            "avg_weight": (
                float(deg.sum() / (m * (m - 1))) if m > 1 else float("nan")
            ),
            "summary": None,
            "contribution": None,
            "coherence": float("nan"),
        }
        if data is not None:
            dat = data[:, idx]
            prof = oracle.summary_profile(dat)
            nc = oracle.node_contribution(dat, prof)
            props.update(
                summary=prof, contribution=nc,
                coherence=float(np.mean(nc**2)),
            )
        out[lab] = props
    return out
