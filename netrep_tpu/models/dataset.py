"""Dataset containers and input normalization — the rebuild of the
reference's input-processing layer (SURVEY.md §2.1 "Input processing",
§3.1 L4): normalizes the ``network`` / ``data`` / ``correlation`` arguments
(single matrix, list, or dict over datasets) into aligned internal
structures, and validates symmetry, finiteness, and cross-dataset name
matching with informative errors (error-message parity is an explicit goal,
SURVEY.md §7 step 6).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Sequence

import numpy as np

try:  # pandas is optional at runtime but used when given
    import pandas as pd
# netrep: allow(exception-taxonomy) — optional-dependency probe: ANY import-time failure (broken install included) means "run without pandas"
except Exception:  # pragma: no cover
    pd = None

_SYM_TOL = 1e-8


@dataclasses.dataclass
class Dataset:
    """One dataset's aligned matrices.

    Attributes
    ----------
    name : dataset label.
    correlation : (n, n) correlation matrix — or None for a DATA-ONLY
        dataset (ISSUE 9, the atlas plane: correlation/network derive
        from ``data`` on demand and are never materialized).
    network : (n, n) network (edge weight / adjacency) matrix, or None
        (data-only).
    data : (n_samples, n) data matrix or None (data-less variant).
    node_names : length-n node labels (column names).
    sample_names : sample labels for ``data`` (or None).
    """

    name: str
    correlation: np.ndarray | None
    network: np.ndarray | None
    data: np.ndarray | None
    node_names: list[str]
    sample_names: list[str] | None = None

    @property
    def n_nodes(self) -> int:
        return len(self.node_names)

    def index_of(self) -> dict[str, int]:
        return {nm: i for i, nm in enumerate(self.node_names)}


def _as_matrix(x, what: str, dataset: str):
    """Extract (array, row_names, col_names) from ndarray / DataFrame."""
    if pd is not None and isinstance(x, pd.DataFrame):
        return (
            x.to_numpy(dtype=np.float64),
            [str(r) for r in x.index],
            [str(c) for c in x.columns],
        )
    arr = np.asarray(x, dtype=np.float64)
    if arr.ndim != 2:
        raise ValueError(
            f"{what} for dataset {dataset!r} must be a 2-dimensional matrix, "
            f"got {arr.ndim} dimension(s)"
        )
    return arr, None, None


def _check_square_symmetric(arr: np.ndarray, what: str, dataset: str):
    if arr.shape[0] != arr.shape[1]:
        raise ValueError(
            f"{what} for dataset {dataset!r} must be square, got shape {arr.shape}"
        )
    if not np.isfinite(arr).all():
        raise ValueError(
            f"{what} for dataset {dataset!r} contains non-finite values "
            "(NA/NaN/Inf are not allowed)"
        )
    if not np.allclose(arr, arr.T, atol=_SYM_TOL):
        raise ValueError(f"{what} for dataset {dataset!r} is not symmetric")


def _normalize_collection(x, what: str) -> dict[str, object]:
    """Turn a single matrix / sequence / mapping into {dataset_name: matrix}."""
    if x is None:
        return {}
    if isinstance(x, Mapping):
        return {str(k): v for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return {str(i + 1): v for i, v in enumerate(x)}
    return {"1": x}


def build_data_only_datasets(data) -> dict[str, Dataset]:
    """Normalize DATA-ONLY inputs (ISSUE 9, the atlas plane): each dataset
    is just a (n_samples, n) data matrix — its correlation and network
    derive on demand and are never materialized, so the dense surface's
    square/symmetric/[-1, 1] checks have no object to run on. What CAN be
    validated is validated with the same informative-error posture:
    2-D shape, finiteness, ≥2 samples, duplicate names — and
    zero-variance (constant) columns are rejected up front, because their
    derived correlations are NaN (``np.corrcoef`` semantics, pinned in
    tests/test_degenerate_inputs.py) exactly as the dense path's
    non-finite-correlation check would reject the materialized matrix.
    """
    datas = _normalize_collection(data, "data")
    if not datas:
        raise ValueError(
            "data_only runs need data (matrix, list, or dict): the "
            "correlation and network are derived from it"
        )
    out: dict[str, Dataset] = {}
    for name, raw in datas.items():
        dat, samp_names, names = _as_matrix(raw, "data", name)
        if dat.shape[0] < 2:
            raise ValueError(
                f"data for dataset {name!r} needs at least 2 samples to "
                f"correlate, got {dat.shape[0]}"
            )
        if not np.isfinite(dat).all():
            raise ValueError(
                f"data for dataset {name!r} contains non-finite values"
            )
        sd = np.std(dat, axis=0)
        if (sd == 0).any():
            bad = np.flatnonzero(sd == 0)
            raise ValueError(
                f"data for dataset {name!r} has {bad.size} zero-variance "
                f"(constant) column(s), e.g. positions {bad[:3].tolist()}: "
                "their derived correlations are NaN (np.corrcoef "
                "semantics) — drop or jitter these nodes, exactly as the "
                "dense surface's non-finite-correlation check would demand"
            )
        if names is None:
            names = [f"node_{i}" for i in range(dat.shape[1])]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate node names in dataset {name!r}")
        out[name] = Dataset(
            name=name, correlation=None, network=None, data=dat,
            node_names=list(names), sample_names=samp_names,
        )
    return out


def build_datasets(
    network,
    data=None,
    correlation=None,
) -> dict[str, Dataset]:
    """Normalize user inputs into named, validated :class:`Dataset` objects.

    Mirrors the reference's input-processing semantics (SURVEY.md §2.1):
    ``network`` is required; ``correlation`` is required for the correlation
    statistics; ``data`` is optional (data-less variant drops the
    data-dependent statistics, SURVEY.md §2.2). Checks performed per dataset:
    square + symmetric + finite correlation/network, correlation entries in
    [-1, 1], data/correlation/network node-name agreement and equal node
    counts. (Data-only datasets — no matrices at all — go through
    :func:`build_data_only_datasets` instead.)
    """
    nets = _normalize_collection(network, "network")
    if not nets:
        raise ValueError("network must be provided (matrix, list, or dict)")
    datas = _normalize_collection(data, "data")
    corrs = _normalize_collection(correlation, "correlation")
    if not corrs:
        raise ValueError(
            "correlation must be provided: the preservation statistics "
            "cor.cor and avg.cor are defined on the correlation structure"
        )
    if set(corrs) != set(nets):
        raise ValueError(
            f"correlation datasets {sorted(corrs)} do not match network "
            f"datasets {sorted(nets)}"
        )
    if datas and not set(datas) <= set(nets):
        raise ValueError(
            f"data datasets {sorted(datas)} are not a subset of network "
            f"datasets {sorted(nets)}"
        )

    out: dict[str, Dataset] = {}
    for name, net_raw in nets.items():
        net, _nr, net_names = _as_matrix(net_raw, "network", name)
        _check_square_symmetric(net, "network", name)
        corr, _cr, corr_names = _as_matrix(corrs[name], "correlation", name)
        _check_square_symmetric(corr, "correlation", name)
        if np.nanmax(np.abs(corr)) > 1 + 1e-6:
            raise ValueError(
                f"correlation for dataset {name!r} has entries outside [-1, 1]"
            )
        if corr.shape != net.shape:
            raise ValueError(
                f"correlation and network for dataset {name!r} disagree in "
                f"size: {corr.shape} vs {net.shape}"
            )

        dat = samp_names = dat_names = None
        if name in datas:
            dat, samp_names, dat_names = _as_matrix(datas[name], "data", name)
            if not np.isfinite(dat).all():
                raise ValueError(
                    f"data for dataset {name!r} contains non-finite values"
                )
            if dat.shape[1] != net.shape[0]:
                raise ValueError(
                    f"data for dataset {name!r} has {dat.shape[1]} nodes "
                    f"(columns) but the network has {net.shape[0]}"
                )

        names = net_names or corr_names or dat_names
        if names is None:
            names = [f"node_{i}" for i in range(net.shape[0])]
        for label, other in (("correlation", corr_names), ("data", dat_names)):
            if other is not None and other != names:
                raise ValueError(
                    f"node names of {label} and network disagree for dataset "
                    f"{name!r}"
                )
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate node names in dataset {name!r}")

        out[name] = Dataset(
            name=name,
            correlation=corr,
            network=net,
            data=dat,
            node_names=list(names),
            sample_names=samp_names,
        )
    return out


def normalize_module_assignments(
    module_assignments,
    datasets: dict[str, Dataset],
    discovery: Sequence[str],
) -> dict[str, dict[str, str]]:
    """Normalize ``module_assignments`` into {discovery_dataset: {node: label}}.

    Accepts a mapping node→label, a sequence aligned with the discovery
    dataset's node order, a pandas Series, or a mapping
    discovery_dataset→(any of the above) for multiple discovery datasets
    (SURVEY.md §2.1).
    """
    if module_assignments is None:
        raise ValueError("module_assignments must be provided")

    def one(x, dname: str) -> dict[str, str]:
        ds = datasets[dname]
        if pd is not None and isinstance(x, pd.Series):
            x = {str(k): v for k, v in x.items()}
        if isinstance(x, Mapping):
            by_name = {str(k): v for k, v in x.items()}  # tolerate int keys
            miss = set(ds.node_names) - set(by_name)
            if miss:
                raise ValueError(
                    f"module_assignments is missing {len(miss)} node(s) of "
                    f"discovery dataset {dname!r} (e.g. {sorted(miss)[:3]})"
                )
            return {nm: str(by_name[nm]) for nm in ds.node_names}
        seq = list(x)
        if len(seq) != ds.n_nodes:
            raise ValueError(
                f"module_assignments has length {len(seq)} but discovery "
                f"dataset {dname!r} has {ds.n_nodes} nodes"
            )
        return {nm: str(l) for nm, l in zip(ds.node_names, seq)}

    if isinstance(module_assignments, Mapping):
        # A mapping keyed entirely by dataset names is a per-discovery dict;
        # anything else is a node→label mapping for the single discovery.
        keys = {str(k) for k in module_assignments}
        if keys and keys <= set(datasets):
            missing = set(discovery) - keys
            if missing:
                raise ValueError(
                    f"module_assignments has no entry for discovery "
                    f"dataset(s) {sorted(missing)}"
                )
            return {
                str(k): one(v, str(k))
                for k, v in module_assignments.items()
                if str(k) in set(discovery)
            }
    if len(discovery) > 1:
        raise ValueError(
            "with multiple discovery datasets, module_assignments must be a "
            "dict {discovery_dataset: assignments}"
        )
    return {discovery[0]: one(module_assignments, discovery[0])}


def resolve_pairs(
    datasets: dict[str, Dataset],
    discovery,
    test,
    self_preservation: bool,
) -> list[tuple[str, str]]:
    """Resolve the (discovery, test) dataset pairs to analyse (SURVEY.md
    §3.1: loop over pairs; self-pairs skipped unless ``self_preservation``)."""
    names = list(datasets)

    def pick(x, what):
        if x is None:
            return None
        if isinstance(x, (str, int)):
            x = [x]
        out = []
        for item in x:
            key = str(item)
            if key not in datasets:
                raise ValueError(
                    f"{what} dataset {item!r} not found; available datasets: "
                    f"{names}"
                )
            out.append(key)
        return out

    disc = pick(discovery, "discovery")
    tst = pick(test, "test")
    if disc is None:
        disc = [names[0]]
    if tst is None:
        tst = [n for n in names if n not in disc] or list(disc)

    pairs = [
        (d, t)
        for d in disc
        for t in tst
        if self_preservation or d != t
    ]
    if not pairs:
        raise ValueError(
            "no (discovery, test) pairs to analyse: discovery == test and "
            "self_preservation=False"
        )
    return pairs


def module_overlap_names(
    disc_names: Sequence[str],
    test_names: Sequence[str],
    assignments: dict[str, str],
    modules: Sequence[str] | None,
    background_label: str | None = "0",
    disc_label: str = "discovery",
):
    """Per-module aligned (discovery, test) index vectors over the nodes
    present in both datasets, plus overlap bookkeeping (nVarsPresent /
    propVarsPresent / totalSize, SURVEY.md §2.1 "Result shaping") — the
    name-list core shared by the dense (:func:`module_overlap`) and sparse
    (:mod:`netrep_tpu.models.sparse_api`) surfaces.

    Returns (module_labels, specs, counts) where ``specs`` is a list of
    ``(label, disc_idx, test_idx)`` and ``counts`` maps label →
    (n_present, total_size).
    """
    tpos = {nm: i for i, nm in enumerate(test_names)}
    all_labels = sorted(
        {v for v in assignments.values() if v != str(background_label)},
        key=lambda s: (len(s), s),
    )
    if modules is not None:
        modules = [str(m) for m in modules]
        unknown = [m for m in modules if m not in set(assignments.values())]
        if unknown:
            raise ValueError(
                f"requested module(s) {unknown} do not exist in the "
                f"module assignments for discovery dataset {disc_label}"
            )
        labels = modules
    else:
        labels = all_labels

    specs, counts = [], {}
    for lab in labels:
        disc_idx, test_idx = [], []
        total = 0
        for i, nm in enumerate(disc_names):
            if assignments[nm] != lab:
                continue
            total += 1
            j = tpos.get(nm)
            if j is not None:
                disc_idx.append(i)
                test_idx.append(j)
        counts[lab] = (len(disc_idx), total)
        specs.append((lab, np.asarray(disc_idx, np.int32), np.asarray(test_idx, np.int32)))
    return labels, specs, counts


def module_overlap(
    disc_ds: Dataset,
    test_ds: Dataset,
    assignments: dict[str, str],
    modules: Sequence[str] | None,
    background_label: str | None = "0",
):
    """Dataset-object wrapper over :func:`module_overlap_names`."""
    return module_overlap_names(
        disc_ds.node_names, test_ds.node_names, assignments, modules,
        background_label, disc_label=repr(disc_ds.name),
    )
