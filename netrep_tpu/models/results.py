"""Result objects for `module_preservation` — the rebuild of the reference's
nested-list result shaping (SURVEY.md §2.1 "Result shaping"):
``result[discovery][test]`` with elements ``observed`` (modules × 7),
``nulls`` (nPerm × modules × 7), ``p_values``, ``nVarsPresent``,
``propVarsPresent``, ``totalSize``; ``simplify=True`` collapses a
single-pair result to the inner object.
"""

from __future__ import annotations

import dataclasses

import numpy as np

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None

from ..ops.oracle import STAT_NAMES


@dataclasses.dataclass
class PreservationResult:
    """Result for one (discovery, test) dataset pair.

    ``p_values`` are Phipson–Smyth exact permutation p-values
    (:func:`netrep_tpu.ops.pvalues.permp`; never zero). Conventions, pinned
    by tests and documented as re-verification debt against the unobservable
    reference (SURVEY.md §7 "Exact p-values"): ``alternative='two.sided'``
    uses min-tail × 2 capped at 1, and the exact finite-space method applies
    automatically when the permutation space has ≤ 10,000 elements
    (statmod's documented auto rule).
    """

    discovery: str
    test: str
    module_labels: list[str]
    observed: np.ndarray          # (n_modules, 7)
    nulls: np.ndarray             # (n_perm, n_modules, 7)
    p_values: np.ndarray          # (n_modules, 7)
    n_vars_present: np.ndarray    # (n_modules,)
    prop_vars_present: np.ndarray
    total_size: np.ndarray
    alternative: str
    n_perm: int                   # permutations requested
    completed: int                # permutations actually completed
    profile: dict | None = None   # per-pair timings when profile= was set
                                  # (SURVEY.md §5 "Tracing / profiling"):
                                  # trace_dir, observed_s, null_s,
                                  # perms_per_sec, chunk_ms,
                                  # compile_chunk_ms, steady_chunk_ms

    @property
    def stat_names(self) -> tuple[str, ...]:
        return STAT_NAMES

    def observed_frame(self):
        return pd.DataFrame(self.observed, index=self.module_labels, columns=STAT_NAMES)

    def p_frame(self):
        return pd.DataFrame(self.p_values, index=self.module_labels, columns=STAT_NAMES)

    def __repr__(self) -> str:  # S3 print-method analogue (SURVEY.md §1 L5)
        lines = [
            f"Module preservation: discovery={self.discovery!r} "
            f"test={self.test!r} ({self.completed}/{self.n_perm} permutations,"
            f" alternative={self.alternative!r})"
        ]
        if pd is not None:
            lines.append("p-values:")
            lines.append(self.p_frame().to_string(float_format=lambda v: f"{v:.4g}"))
        return "\n".join(lines)

    def max_pvalue(self) -> np.ndarray:
        """Per-module worst-case p-value across the seven statistics — the
        reference's conventional module-level preservation call (a module is
        preserved when *all* statistics are significant)."""
        with np.errstate(invalid="ignore"):
            return np.nanmax(self.p_values, axis=1)

    _SAVE_VERSION = 1

    def save(self, path: str) -> None:
        """Persist the result as a single ``.npz`` (atomic write) — the
        analogue of saving the reference's result object as .rds. ``profile``
        timings are not persisted (session-local diagnostics)."""
        import json

        from ..utils.checkpoint import atomic_savez

        meta = {
            "discovery": self.discovery,
            "test": self.test,
            "module_labels": list(self.module_labels),
            "alternative": self.alternative,
            "n_perm": int(self.n_perm),
            "completed": int(self.completed),
        }
        atomic_savez(
            path,
            # top-level format marker checked FIRST on load, so a foreign
            # .npz (e.g. a null checkpoint) gets an informative error even
            # if a future format changes the meta encoding
            result_version=np.int64(self._SAVE_VERSION),
            meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
            observed=self.observed,
            nulls=self.nulls,
            p_values=self.p_values,
            n_vars_present=self.n_vars_present,
            prop_vars_present=self.prop_vars_present,
            total_size=self.total_size,
        )

    @classmethod
    def load(cls, path: str) -> "PreservationResult":
        """Load a result saved by :meth:`save`."""
        import json

        with np.load(path) as z:
            if "result_version" not in z.files:
                raise ValueError(
                    f"{path} is not a PreservationResult file (no "
                    "result_version marker — null checkpoints and other "
                    ".npz files are not loadable here)"
                )
            version = int(z["result_version"])
            if version != cls._SAVE_VERSION:
                raise ValueError(
                    f"unsupported result-file version {version!r} "
                    f"in {path} (this build reads version {cls._SAVE_VERSION})"
                )
            meta = json.loads(bytes(z["meta"]).decode())
            return cls(
                discovery=meta["discovery"],
                test=meta["test"],
                module_labels=[str(l) for l in meta["module_labels"]],
                observed=z["observed"],
                nulls=z["nulls"],
                p_values=z["p_values"],
                n_vars_present=z["n_vars_present"],
                prop_vars_present=z["prop_vars_present"],
                total_size=z["total_size"],
                alternative=meta["alternative"],
                n_perm=meta["n_perm"],
                completed=meta["completed"],
            )


def shape_results(
    results: dict[str, dict[str, PreservationResult]], simplify: bool
):
    """``simplify=True`` collapses single-discovery/single-test nesting,
    mirroring the reference (SURVEY.md §2.1)."""
    if not simplify:
        return results
    if len(results) == 1:
        inner = next(iter(results.values()))
        if len(inner) == 1:
            return next(iter(inner.values()))
        return inner
    return results
