"""Result objects for `module_preservation` — the rebuild of the reference's
nested-list result shaping (SURVEY.md §2.1 "Result shaping"):
``result[discovery][test]`` with elements ``observed`` (modules × 7),
``nulls`` (nPerm × modules × 7), ``p_values``, ``nVarsPresent``,
``propVarsPresent``, ``totalSize``; ``simplify=True`` collapses a
single-pair result to the inner object.
"""

from __future__ import annotations

import dataclasses

import numpy as np

try:
    import pandas as pd
# netrep: allow(exception-taxonomy) — optional-dependency probe: ANY import-time failure (broken install included) means "run without pandas"
except Exception:  # pragma: no cover
    pd = None

from ..ops.oracle import STAT_NAMES
from ..utils import telemetry as tm


@dataclasses.dataclass
class PreservationResult:
    """Result for one (discovery, test) dataset pair.

    ``p_values`` are Phipson–Smyth exact permutation p-values
    (:func:`netrep_tpu.ops.pvalues.permp`; never zero). Conventions, pinned
    by tests and documented as re-verification debt against the unobservable
    reference (SURVEY.md §7 "Exact p-values"): ``alternative='two.sided'``
    uses min-tail × 2 capped at 1, and the exact finite-space method applies
    automatically when the permutation space has ≤ 10,000 elements
    (statmod's documented auto rule).
    """

    discovery: str
    test: str
    module_labels: list[str]
    observed: np.ndarray          # (n_modules, 7)
    nulls: np.ndarray | None      # (n_perm, n_modules, 7); None for
                                  # streaming (store_nulls=False) runs —
                                  # the exceedance tallies below replace it
    p_values: np.ndarray          # (n_modules, 7)
    n_vars_present: np.ndarray    # (n_modules,)
    prop_vars_present: np.ndarray
    total_size: np.ndarray
    alternative: str
    n_perm: int                   # permutations requested
    completed: int                # permutations actually completed
    profile: dict | None = None   # per-pair timings when profile= was set
                                  # (SURVEY.md §5 "Tracing / profiling"):
                                  # trace_dir, observed_s, null_s,
                                  # perms_per_sec, chunk_ms,
                                  # compile_chunk_ms, steady_chunk_ms
    total_space: float | None = None  # size of the full permutation space
                                  # (may be inf); kept so p-values can be
                                  # recomputed exactly when results are
                                  # merged by combine_analyses()
    n_perm_used: np.ndarray | None = None  # (n_modules,) permutations each
                                  # module actually drew — differs across
                                  # modules only for adaptive runs (retired
                                  # modules stop early; their null rows are
                                  # NaN past retirement). None = fixed run,
                                  # every module saw `completed`.
    p_type: str = "fixed"         # 'fixed' (every module at n_perm) or
                                  # 'sequential' (Besag–Clifford early
                                  # stopping; p-values are Phipson–Smyth at
                                  # each module's own n_perm_used)
    counts_hi: np.ndarray | None = None  # (n_modules, 7) null draws >= observed
    counts_lo: np.ndarray | None = None  # (n_modules, 7) null draws <= observed
    counts_eff: np.ndarray | None = None  # (n_modules, 7) valid draws per cell
                                  # — the streaming (store_nulls=False)
                                  # run's sufficient statistics: p-values
                                  # are ops.pvalues.counts_pvalues of
                                  # these, and combine_analyses pools them
                                  # when no null array exists. None on
                                  # store_nulls=True runs (the null array
                                  # carries strictly more information).
    p_tail: np.ndarray | None = None  # (n_modules, 7) generalized-Pareto
                                  # tail p-values (Knijnenburg et al. 2009)
                                  # beside the exact estimator — NaN where
                                  # the fit was not attempted or refused;
                                  # see tail_pvalues(). None until computed.
    tail_ok: np.ndarray | None = None  # (n_modules, 7) bool: True only
                                  # where p_tail came from a fit that
                                  # passed the Anderson–Darling gate.
    nulls_exact: bool = True      # False when the stored null VALUES went
                                  # through the bf16 screened fast-pass
                                  # (ISSUE 16): decided permutations keep
                                  # their bf16-rounded statistics — counts
                                  # and p-values are exact by construction,
                                  # the value array is not. Gates the GPD
                                  # tail fit, which reads the extreme
                                  # values themselves (see tail_pvalues()).

    @property
    def stat_names(self) -> tuple[str, ...]:
        return STAT_NAMES

    def observed_frame(self):
        return pd.DataFrame(self.observed, index=self.module_labels, columns=STAT_NAMES)

    def p_frame(self):
        return pd.DataFrame(self.p_values, index=self.module_labels, columns=STAT_NAMES)

    def __repr__(self) -> str:  # S3 print-method analogue (SURVEY.md §1 L5)
        lines = [
            f"Module preservation: discovery={self.discovery!r} "
            f"test={self.test!r} ({self.completed}/{self.n_perm} permutations,"
            f" alternative={self.alternative!r})"
        ]
        if pd is not None:
            lines.append("p-values:")
            lines.append(self.p_frame().to_string(float_format=lambda v: f"{v:.4g}"))
        return "\n".join(lines)

    def max_pvalue(self) -> np.ndarray:
        """Per-module worst-case p-value across the seven statistics — the
        reference's conventional module-level preservation call (a module is
        preserved when *all* statistics are significant)."""
        import warnings

        with warnings.catch_warnings():
            # an all-NaN row (data-less run: no computable statistics) is a
            # legitimate input; nanmax's RuntimeWarning for it is noise here
            warnings.simplefilter("ignore", category=RuntimeWarning)
            return np.nanmax(self.p_values, axis=1)

    def preserved_modules(
        self, alpha: float = 0.05, adjust: str = "bonferroni"
    ) -> list[str]:
        """Module labels meeting the conventional preservation call (the
        reference vignette's interpretation rule, done by hand there): every
        computed statistic significant at ``alpha``, Bonferroni-adjusted for
        the number of modules tested (``adjust='none'`` skips adjustment).
        Modules with no computable statistics (all-NaN row) never qualify."""
        if adjust == "bonferroni":
            thresh = alpha / max(len(self.module_labels), 1)
        elif adjust == "none":
            thresh = alpha
        else:
            raise ValueError(
                f"adjust must be 'bonferroni' or 'none', got {adjust!r}"
            )
        mx = self.max_pvalue()
        return [
            lab
            for lab, p in zip(self.module_labels, mx)
            if np.isfinite(p) and p < thresh
        ]

    def to_frame(self):
        """Long-format (tidy) table of this pair's results: one row per
        module × statistic with observed value, p-value, and the overlap
        bookkeeping — the shape downstream analyses (grouping, filtering,
        ggplot-style plotting) want, complementing the reference-shaped
        wide frames (:meth:`observed_frame` / :meth:`p_frame`)."""
        if pd is None:  # pragma: no cover - pandas is an extra
            raise ImportError("to_frame requires pandas")
        k, t = len(self.module_labels), len(STAT_NAMES)
        tail_cols = {} if self.p_tail is None else {
            "p_tail": self.p_tail.reshape(-1),
            "tail_ok": self.tail_ok.reshape(-1),
        }
        return pd.DataFrame({
            "discovery": self.discovery,
            "test": self.test,
            "module": np.repeat(self.module_labels, t),
            "statistic": list(STAT_NAMES) * k,
            "observed": self.observed.reshape(-1),
            "p_value": self.p_values.reshape(-1),
            "n_vars_present": np.repeat(self.n_vars_present, t),
            "prop_vars_present": np.repeat(self.prop_vars_present, t),
            "total_size": np.repeat(self.total_size, t),
            "n_perm_used": np.repeat(self.module_n_perm(), t),
            **tail_cols,
        })

    def tail_pvalues(
        self, refresh: bool = False
    ) -> tuple[np.ndarray, np.ndarray]:
        """Generalized-Pareto tail p-values beside the exact estimator
        (:func:`netrep_tpu.ops.pvalues.gpd_tail_pvalues`): for cells whose
        observed statistic lands beyond nearly every null draw, a gated GPD
        fit over the null tail resolves p-values far below the exact
        estimator's 1/(completed+1) floor. Computed lazily from the stored
        null array (requires ``store_nulls=True``) and cached on the result
        as ``p_tail``/``tail_ok`` so they persist through :meth:`save`.
        Returns ``(p_tail, tail_ok)``; ``p_tail`` is NaN wherever
        ``tail_ok`` is False — fall back to ``p_values`` there.

        Raises when the stored null values came through the bf16 screened
        fast-pass (``nulls_exact=False``, ISSUE 16): decided permutations
        keep their bf16-rounded statistics, and a GPD fit over that
        quantized tail is meaningless even though the counts-based
        ``p_values`` remain exact. Rerun with
        ``EngineConfig(null_precision='f32')`` for a fittable array."""
        if self.p_tail is not None and not refresh:
            return self.p_tail, self.tail_ok
        if self.nulls is None:
            raise ValueError(
                "tail_pvalues needs the null array; this result carries "
                "exceedance counts only (store_nulls=False) — the GPD tail "
                "fit reads the extreme null draws themselves"
            )
        from ..ops import pvalues as pv

        self.p_tail, self.tail_ok = pv.gpd_tail_pvalues(
            self.observed,
            np.asarray(self.nulls)[: self.completed],
            self.alternative,
            nulls_exact=self.nulls_exact,
        )
        tel = tm.current()
        if tel is not None:
            tel.emit(
                "tail_fit",
                cells=int(self.p_tail.size),
                fitted=int(np.sum(self.tail_ok)),
                n_perm=int(self.completed),
            )
        return self.p_tail, self.tail_ok

    def module_n_perm(self) -> np.ndarray:
        """(n_modules,) permutations backing each module's p-values:
        ``n_perm_used`` for adaptive runs, ``completed`` broadcast for
        fixed runs — one accessor so downstream code never branches."""
        if self.n_perm_used is not None:
            return np.asarray(self.n_perm_used, dtype=np.int64)
        return np.full(len(self.module_labels), int(self.completed),
                       dtype=np.int64)

    _SAVE_VERSION = 1

    def save(self, path: str) -> None:
        """Persist the result as a single ``.npz`` (atomic write) — the
        analogue of saving the reference's result object as .rds. ``profile``
        timings are not persisted (session-local diagnostics)."""
        import json

        from ..utils.checkpoint import atomic_savez

        meta = {
            "discovery": self.discovery,
            "test": self.test,
            "module_labels": list(self.module_labels),
            "alternative": self.alternative,
            "n_perm": int(self.n_perm),
            "completed": int(self.completed),
            # inf is stored as the string "inf": json.dumps would emit the
            # non-standard token Infinity, which Python reads back but
            # strict JSON parsers (jq, other languages) reject
            "total_space": (
                None if self.total_space is None
                else "inf" if np.isinf(self.total_space)
                else float(self.total_space)
            ),
            "p_type": self.p_type,
            # streaming (store_nulls=False) results have no null array —
            # the flag (additive key, same format version) tells load() to
            # restore nulls=None instead of the empty placeholder below
            "store_nulls": self.nulls is not None,
            # additive key: files written before the bf16 screen existed
            # always carried exact f32 null values
            "nulls_exact": bool(self.nulls_exact),
        }
        extra = (
            {} if self.n_perm_used is None
            else {"n_perm_used": np.asarray(self.n_perm_used)}
        )
        for name in ("counts_hi", "counts_lo", "counts_eff",
                     "p_tail", "tail_ok"):
            val = getattr(self, name)
            if val is not None:
                extra[name] = np.asarray(val)
        atomic_savez(
            path,
            **extra,
            # top-level format marker checked FIRST on load, so a foreign
            # .npz (e.g. a null checkpoint) gets an informative error even
            # if a future format changes the meta encoding
            result_version=np.int64(self._SAVE_VERSION),
            meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
            observed=self.observed,
            nulls=(
                self.nulls if self.nulls is not None
                else np.zeros((0,) + self.observed.shape)
            ),
            p_values=self.p_values,
            n_vars_present=self.n_vars_present,
            prop_vars_present=self.prop_vars_present,
            total_size=self.total_size,
        )

    @classmethod
    def load(cls, path: str) -> "PreservationResult":
        """Load a result saved by :meth:`save`."""
        import json

        with np.load(path) as z:
            if "result_version" not in z.files:
                raise ValueError(
                    f"{path} is not a PreservationResult file (no "
                    "result_version marker — null checkpoints and other "
                    ".npz files are not loadable here)"
                )
            version = int(z["result_version"])
            if version != cls._SAVE_VERSION:
                raise ValueError(
                    f"unsupported result-file version {version!r} "
                    f"in {path} (this build reads version {cls._SAVE_VERSION})"
                )
            meta = json.loads(bytes(z["meta"]).decode())
            return cls(
                discovery=meta["discovery"],
                test=meta["test"],
                module_labels=[str(l) for l in meta["module_labels"]],
                observed=z["observed"],
                # store_nulls=False results persist an empty placeholder;
                # files from before the flag existed always carried nulls
                nulls=(
                    z["nulls"] if meta.get("store_nulls", True) else None
                ),
                counts_hi=z["counts_hi"] if "counts_hi" in z.files else None,
                counts_lo=z["counts_lo"] if "counts_lo" in z.files else None,
                counts_eff=(
                    z["counts_eff"] if "counts_eff" in z.files else None
                ),
                p_tail=z["p_tail"] if "p_tail" in z.files else None,
                tail_ok=z["tail_ok"] if "tail_ok" in z.files else None,
                nulls_exact=bool(meta.get("nulls_exact", True)),
                p_values=z["p_values"],
                n_vars_present=z["n_vars_present"],
                prop_vars_present=z["prop_vars_present"],
                total_size=z["total_size"],
                alternative=meta["alternative"],
                n_perm=meta["n_perm"],
                completed=meta["completed"],
                total_space=(
                    # "inf" string per save(); plain float Infinity accepted
                    # too for files written before the strict-JSON encoding
                    float(ts) if (ts := meta.get("total_space")) is not None
                    else None
                ),
                # optional adaptive-run fields (absent in pre-adaptive
                # files — same version, additive keys)
                n_perm_used=(
                    z["n_perm_used"] if "n_perm_used" in z.files else None
                ),
                p_type=meta.get("p_type", "fixed"),
            )


def combine_analyses(*analyses, allow_duplicate_nulls: bool = False):
    """Merge ``module_preservation`` results whose permutations were computed
    separately — the rebuild of the reference's ``combineAnalyses()``
    (upstream ``R/combineAnalyses.R``, SURVEY.md §2.1 user API): split a large
    ``n_perm`` across machines/sessions (different seeds), then pool the null
    distributions and recompute the exact Phipson–Smyth p-values over the
    combined permutation count.

    Accepts two or more :class:`PreservationResult` objects for the same
    (discovery, test) pair, or two or more nested ``{discovery: {test:
    result}}`` dicts (as returned by ``simplify=False``), which are merged
    key-by-key.

    Each input contributes its *completed* permutations only. The runs must
    agree on everything except the nulls: module labels, alternative,
    dataset names, observed statistics, and node counts — disagreement means
    the inputs came from different analyses and is an error.

    Identical null blocks across inputs (the same seed run twice) would
    silently double-count correlated permutations, biasing p-values; this is
    detected via a content hash and raises unless ``allow_duplicate_nulls``.

    Streaming results (``store_nulls=False``) combine too: when any input
    lacks a null array, every input is lifted into count space
    (:func:`netrep_tpu.ops.pvalues.tail_counts` for materialized inputs),
    the per-cell tallies and draw counts are summed, and the exact
    Phipson–Smyth p-values recompute from the pooled counts — the same
    numbers pooling the null arrays would give. The combined result then
    carries counts but no nulls. Caveat: without null rows there is
    nothing to content-hash, so the duplicate-seed check above cannot run
    on count-only merges — splitting a run across seeds remains the
    caller's responsibility there.
    """
    if len(analyses) < 2:
        raise ValueError("combine_analyses needs at least two results")
    if all(isinstance(a, dict) for a in analyses):
        keysets = [set(a) for a in analyses]
        if any(ks != keysets[0] for ks in keysets[1:]):
            level = "discovery" if isinstance(
                next(iter(analyses[0].values()), None), dict
            ) else "test"
            raise ValueError(
                f"nested results disagree on {level} datasets: "
                f"{sorted(map(sorted, keysets))}"
            )
        return {
            d: combine_analyses(
                *(a[d] for a in analyses),
                allow_duplicate_nulls=allow_duplicate_nulls,
            )
            for d in analyses[0]
        }
    if all(isinstance(a, PreservationResult) for a in analyses):
        return _combine_pair_results(analyses, allow_duplicate_nulls)
    raise TypeError(
        "combine_analyses takes all PreservationResult objects or all "
        f"nested dicts, got {[type(a).__name__ for a in analyses]}"
    )


def _combine_pair_results(results, allow_duplicate_nulls):
    import hashlib

    from ..ops import pvalues as pv

    first = results[0]
    for r in results[1:]:
        if (r.discovery, r.test) != (first.discovery, first.test):
            raise ValueError(
                f"results are for different dataset pairs: "
                f"({first.discovery!r}, {first.test!r}) vs "
                f"({r.discovery!r}, {r.test!r})"
            )
        if list(r.module_labels) != list(first.module_labels):
            raise ValueError("results have different module labels")
        if r.alternative != first.alternative:
            raise ValueError(
                f"results use different alternatives: "
                f"{first.alternative!r} vs {r.alternative!r}"
            )
        if not np.array_equal(r.n_vars_present, first.n_vars_present) or \
           not np.array_equal(r.total_size, first.total_size):
            raise ValueError("results have different node-overlap counts")
        # observed is deterministic given the inputs, so any drift beyond
        # numeric noise means the analyses ran on different data
        if not np.allclose(
            r.observed, first.observed, rtol=1e-4, atol=1e-5, equal_nan=True
        ):
            raise ValueError(
                "observed statistics differ between results — these are not "
                "runs of the same analysis"
            )

    spaces = [r.total_space for r in results if r.total_space is not None]
    total_space = spaces[0] if spaces else None
    for s in spaces[1:]:
        same = (s == total_space) or (
            np.isfinite(s) and np.isfinite(total_space)
            and np.isclose(s, total_space, rtol=1e-9)
        )
        if not same:
            raise ValueError(
                f"results record different permutation-space sizes "
                f"({total_space!r} vs {s!r})"
            )

    if any(r.nulls is None for r in results):
        return _combine_count_results(results, total_space)

    blocks = [np.asarray(r.nulls[: r.completed]) for r in results]
    if not allow_duplicate_nulls:
        # Detect the same seed run twice at per-permutation granularity:
        # a byte-identical null row in two inputs means they drew the same
        # node assignment (even when one run was interrupted and is only a
        # prefix of the other's stream). In a SMALL finite space, though,
        # independent with-replacement runs legitimately collide — so only
        # raise when the cross-input duplicate count exceeds what
        # independent uniform sampling from `total_space` predicts.
        from collections import Counter

        # All-NaN rows carry no draw identity (defensive: adaptive runs NaN
        # retired modules' rows, and a fully-NaN row would hash identically
        # across unrelated inputs) — exclude them from the collision count.
        # Known limitation: an adaptive and a fixed run of the SAME seed
        # NaN-mask the same draw differently, so their rows hash apart and
        # that duplication goes undetected here.
        per_block = [
            Counter(
                hashlib.sha256(np.ascontiguousarray(row)).digest()
                for row in block
                if not np.isnan(row).all()
            )
            for block in blocks
        ]
        total = Counter()
        for c in per_block:
            total.update(c)
        # Colliding PAIRS across different inputs — the same units as the
        # birthday-style expectation below (the old row-count approximation
        # under-counted multi-way collisions): all identical pairs minus the
        # within-block ones.
        cross_pairs = sum(t * (t - 1) // 2 for t in total.values()) - sum(
            v * (v - 1) // 2 for c in per_block for v in c.values()
        )
        if cross_pairs:
            sizes = [b.shape[0] for b in blocks]
            n_pairs = (sum(sizes) ** 2 - sum(s * s for s in sizes)) / 2
            if (total_space is not None and np.isfinite(total_space)
                    and total_space > 0):
                expected = n_pairs / total_space
                threshold = expected + 4.0 * np.sqrt(expected) + 0.5
            else:
                # Space size unknown (results saved by an older release) or
                # infinite. A duplicated seed replicates ~100% of the smaller
                # block, so tolerate up to 5% of it as possible small-space
                # chance collisions rather than rejecting on the first match.
                expected = 0.0
                threshold = 0.05 * min(s for s in sizes if s) + 0.5
            if (cross_pairs > threshold and cross_pairs == 1
                    and min(s for s in sizes if s) > 1):
                # A single colliding pair in a large space is far more often
                # one legitimate chance collision than a duplicated seed (a
                # duplicated seed replicates the whole smaller block): warn,
                # keep the merge. Requires every block to have >1 row —
                # with a 1-row block, one collision IS its full duplication
                # (the interrupted same-seed prefix case) and must raise.
                import warnings

                warnings.warn(
                    "one byte-identical null row shared between inputs "
                    f"(~{expected:.2g} expected by chance); keeping the "
                    "merge — a duplicated seed would replicate many rows",
                    stacklevel=3,
                )
            elif cross_pairs > threshold:
                raise ValueError(
                    f"{cross_pairs} byte-identical null row pair(s) shared "
                    f"between inputs (~{expected:.2f} expected by chance "
                    "for this permutation space) — the same seed run "
                    "twice?; pooling correlated permutations biases "
                    "p-values. Pass allow_duplicate_nulls=True to "
                    "override."
                )

    nulls = np.concatenate(blocks, axis=0)
    completed = int(nulls.shape[0])
    p_values = pv.permutation_pvalues(
        first.observed, nulls, first.alternative, total_nperm=total_space
    )
    # pooling with any sequential input keeps per-module permutation counts
    # ragged (each block contributes its own NaN-tailed rows); the counts
    # are recomputed from the pooled array, which permutation_pvalues
    # already groups by — the Phipson–Smyth estimator composes unchanged
    any_seq = any(
        r.p_type == "sequential" or r.n_perm_used is not None
        for r in results
    )
    # tail p-values do not pool additively — refit the GPD over the pooled
    # null tail whenever any input had computed them. Exactness is a
    # conjunction: one screened block quantizes part of the pooled tail,
    # so the refit is dropped rather than fitted over quantized draws
    # (tail_pvalues() on the combined result raises with the guidance).
    nulls_exact = all(r.nulls_exact for r in results)
    p_tail = tail_ok = None
    if nulls_exact and any(r.p_tail is not None for r in results):
        p_tail, tail_ok = pv.gpd_tail_pvalues(
            first.observed, nulls, first.alternative
        )
    return PreservationResult(
        p_tail=p_tail,
        tail_ok=tail_ok,
        nulls_exact=nulls_exact,
        n_perm_used=pv.effective_nperm(nulls) if any_seq else None,
        p_type="sequential" if any_seq else "fixed",
        discovery=first.discovery,
        test=first.test,
        module_labels=list(first.module_labels),
        observed=first.observed,
        nulls=nulls,
        p_values=p_values,
        n_vars_present=first.n_vars_present,
        prop_vars_present=first.prop_vars_present,
        total_size=first.total_size,
        alternative=first.alternative,
        n_perm=int(sum(r.n_perm for r in results)),
        completed=completed,
        total_space=total_space,
    )


def _combine_count_results(results, total_space):
    """Pool results in count space — the merge path when any input is a
    streaming (``store_nulls=False``) result: per-cell exceedance tallies
    and valid-draw counts are additive across independent runs, and the
    Phipson–Smyth estimator over the pooled counts equals the estimator
    over the pooled null arrays (it only ever reads counts)."""
    from ..ops import pvalues as pv

    first = results[0]
    parts = []
    for r in results:
        if r.counts_hi is not None:
            parts.append((
                np.asarray(r.counts_hi, dtype=np.int64),
                np.asarray(r.counts_lo, dtype=np.int64),
                np.asarray(r.counts_eff, dtype=np.int64),
            ))
        elif r.nulls is not None:
            parts.append(pv.tail_counts(r.observed, r.nulls[: r.completed]))
        else:
            raise ValueError(
                f"result ({r.discovery!r}, {r.test!r}) carries neither a "
                "null array nor exceedance counts; it cannot be combined"
            )
    hi = sum(p[0] for p in parts)
    lo = sum(p[1] for p in parts)
    eff = sum(p[2] for p in parts)
    p_values = pv.counts_pvalues(
        first.observed, hi, lo, eff, first.alternative,
        total_nperm=total_space,
    )
    completed = int(sum(r.completed for r in results))
    any_seq = any(
        r.p_type == "sequential" or r.n_perm_used is not None
        for r in results
    )
    return PreservationResult(
        n_perm_used=(
            sum(r.module_n_perm() for r in results) if any_seq else None
        ),
        p_type="sequential" if any_seq else "fixed",
        discovery=first.discovery,
        test=first.test,
        module_labels=list(first.module_labels),
        observed=first.observed,
        nulls=None,
        counts_hi=hi,
        counts_lo=lo,
        counts_eff=eff,
        p_values=p_values,
        n_vars_present=first.n_vars_present,
        prop_vars_present=first.prop_vars_present,
        total_size=first.total_size,
        alternative=first.alternative,
        n_perm=int(sum(r.n_perm for r in results)),
        completed=completed,
        total_space=total_space,
    )


def results_table(results):
    """One tidy table across every (discovery, test) pair — accepts a single
    :class:`PreservationResult`, a ``{test: result}`` dict, or the full
    ``{discovery: {test: result}}`` nesting from ``simplify=False``.
    Concatenates each pair's :meth:`PreservationResult.to_frame`."""
    if isinstance(results, PreservationResult):
        return results.to_frame()
    if isinstance(results, dict):
        frames = []
        for v in results.values():
            inner = v.values() if isinstance(v, dict) else [v]
            for r in inner:
                if not isinstance(r, PreservationResult):
                    raise TypeError(
                        f"expected PreservationResult values, got {type(r).__name__}"
                    )
                frames.append(r.to_frame())
        if not frames:
            raise ValueError("no results to tabulate")
        return pd.concat(frames, ignore_index=True)
    raise TypeError(
        "results_table takes a PreservationResult or the nested dict "
        f"module_preservation returns, got {type(results).__name__}"
    )


def shape_results(
    results: dict[str, dict[str, PreservationResult]], simplify: bool
):
    """``simplify=True`` collapses single-discovery/single-test nesting,
    mirroring the reference (SURVEY.md §2.1)."""
    if not simplify:
        return results
    if len(results) == 1:
        inner = next(iter(results.values()))
        if len(inner) == 1:
            return next(iter(inner.values()))
        return inner
    return results
