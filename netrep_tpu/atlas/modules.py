"""Atlas module plane — data-only k×k submatrices (ISSUE 9 tentpole).

At atlas scale the dense n×n correlation/network pair cannot exist, but
the seven preservation statistics only ever consume k×k module
submatrices — and with standardized data columns in hand, the observed
and every per-permutation correlation submatrix is ONE MXU matmul of the
gathered ``(s, m)`` data slice (``zᵀz/(s-1)``, exact Pearson — the same
identity the sparse engine's on-the-fly correlation uses), with the
network submatrix derived elementwise on device
(:func:`netrep_tpu.ops.stats.derived_net`, the PR 8 in-register mode
extended into a full pipeline). The dense
:class:`~netrep_tpu.parallel.engine.PermutationEngine` then runs with
``correlation=None, network=None``: these kernels are its data-only
chunk/observed unit of work.

Degenerate-input semantics: inside the ENGINE hot path a zero-variance
column standardizes to all-zero (the documented zero-variance guard of
:func:`netrep_tpu.ops.stats.standardize_masked` — statistics stay
finite, ``tests/test_degenerate_inputs.py``). The atlas *construction*
plane (:mod:`netrep_tpu.atlas.tiles`) instead propagates NaN exactly
like ``np.corrcoef`` and its validated spec rejects such columns up
front, mirroring the dense surface's non-finite-correlation rejection.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..ops import stats as jstats
from ..ops.sparse import corr_from_zdata


def data_only_gather_and_stats(
    disc: jstats.DiscProps,
    idx: jnp.ndarray,              # (..., m) int32 test-node ids (padded)
    test_dataT: jnp.ndarray,       # (n, n_samples) TRANSPOSED test data
    net_beta,
    n_iter: int = 60,
    summary_method: str = "power",
) -> jnp.ndarray:
    """Per-permutation unit of work of the data-only pipeline: gather the
    module's data columns (a contiguous row gather of the transposed
    layout), standardize, and derive BOTH test submatrices from the slice
    — correlation as ``zᵀz/(s-1)`` (one MXU matmul) and network as the
    soft-threshold construction ``net_beta`` names. Nothing ``O(n²)`` is
    ever touched; the working set is ``O(m·s + m²)`` per instance.
    Batching over permutations/modules is ``vmap`` of this function —
    the same contract as :func:`netrep_tpu.ops.stats.gather_and_stats`.
    """
    w = disc.mask
    zdata = jstats.gather_zdata(test_dataT, idx, w)        # (..., s, m)
    corr = corr_from_zdata(zdata, test_dataT.shape[-1], w)
    net = jstats.derived_net(corr, net_beta)
    return jstats.module_stats_masked(
        disc, corr, net, zdata, n_iter=n_iter,
        summary_method=summary_method,
    )


@partial(jax.jit, static_argnames=("net_beta", "summary_method"))
def make_disc_props_data_only(
    dataT: jnp.ndarray,            # (n, n_samples) TRANSPOSED discovery data
    idx_pad: jnp.ndarray,          # (K, cap) padded discovery ids
    mask: jnp.ndarray,             # (K, cap)
    net_beta,
    summary_method: str = "eigh",
) -> jstats.DiscProps:
    """Discovery-side fixed properties for a bucket of modules with NO
    stored matrices: the correlation submatrix comes from the gathered
    data slice (``zᵀz/(s-1)``), the network derives elementwise
    (``net_beta``), and the data statistics ride the same slice. Runs
    once per pair, outside the hot loop — exact ``eigh`` summary by
    default, like every discovery pass."""
    w = jstats._f32(mask)
    safe = jnp.where(mask > 0, idx_pad, 0)
    sub = jnp.swapaxes(jnp.take(dataT, safe, axis=0), -1, -2)  # (K, s, cap)
    z = jstats.standardize_masked(sub, w)
    corr = corr_from_zdata(z, dataT.shape[-1], w)
    net = jstats.derived_net(corr, net_beta)
    return jstats.make_disc_props(corr, net, sub, mask,
                                  summary_method=summary_method)


def normalize_beta_static(net_beta):
    """Normalize a ``β`` / ``(β, kind)`` spec into the hashable tuple the
    jit-static threading needs (lists arrive from JSON payloads)."""
    beta, kind = jstats.normalize_net_beta(
        tuple(net_beta) if isinstance(net_beta, list) else net_beta
    )
    return (beta, kind)


def dense_reference_stats(data_disc, data_test, specs, net_beta):
    """Small-n oracle of the data-only plane (tests/bench parity rows):
    materialize the n×n correlation the tile plane refuses to, derive the
    network, and hand back the (correlation, network) pair per dataset —
    the inputs a dense ``module_preservation`` reference run takes.
    Float32 end to end so the parity comparison prices only the gather
    path, not a precision mismatch."""
    beta, kind = normalize_beta_static(net_beta)
    out = []
    for d in (data_disc, data_test):
        d = np.asarray(d, np.float32)
        z = np.asarray(jstats.standardize_masked(
            jnp.asarray(d), jnp.ones(d.shape[1], jnp.float32)
        ))
        corr = np.array(jnp.clip(
            jnp.matmul(z.T, z, preferred_element_type=jnp.float32)
            / max(d.shape[0] - 1, 1), -1.0, 1.0,
        ))
        np.fill_diagonal(corr, 1.0)
        net = np.array(jstats.derived_net(jnp.asarray(corr), (beta, kind)))
        np.fill_diagonal(net, 0.0)
        out.append((corr, net))
    return out
