"""Atlas-scale tiled network plane (ISSUE 9): data-only preservation at
100k+ genes without ever materializing n×n.

- :mod:`~netrep_tpu.atlas.tiles` — :class:`TiledNetwork`, the data +
  soft-threshold-β spec whose correlation/adjacency exist only as
  on-demand tiles;
- :mod:`~netrep_tpu.atlas.builder` — the streaming construction pass
  (tile grid → :class:`~netrep_tpu.ops.sparse.SparseAdjacency` edges +
  global degree vectors; checkpointable, fault-covered, traced,
  mesh-shardable, autotuned tile edge) with exact tile screening
  (ISSUE 11: ``screen=True`` dispatches only tiles whose column-moment
  bound clears the τ cut / running top-k floor — work proportional to
  signal, output bit-identical to the unscreened scan);
- :mod:`~netrep_tpu.atlas.modules` — the data-only k×k module plane the
  dense permutation engine runs on with ``correlation=None,
  network=None`` (user surface:
  :func:`netrep_tpu.models.atlas_api.module_preservation`).
"""

from .builder import AtlasBuild, build_sparse_network
from .tiles import (
    TiledNetwork, derived_net_np, supertile_maxima, tile_norm_maxima,
)

__all__ = [
    "AtlasBuild",
    "TiledNetwork",
    "build_sparse_network",
    "derived_net_np",
    "supertile_maxima",
    "tile_norm_maxima",
]
