"""Atlas-scale tiled network plane (ISSUE 9): data-only preservation at
100k+ genes without ever materializing n×n.

- :mod:`~netrep_tpu.atlas.tiles` — :class:`TiledNetwork`, the data +
  soft-threshold-β spec whose correlation/adjacency exist only as
  on-demand tiles;
- :mod:`~netrep_tpu.atlas.builder` — the streaming construction pass
  (tile grid → :class:`~netrep_tpu.ops.sparse.SparseAdjacency` edges +
  global degree vectors; checkpointable, fault-covered, traced,
  mesh-shardable, autotuned tile edge);
- :mod:`~netrep_tpu.atlas.modules` — the data-only k×k module plane the
  dense permutation engine runs on with ``correlation=None,
  network=None`` (user surface:
  :func:`netrep_tpu.models.atlas_api.module_preservation`).
"""

from .builder import AtlasBuild, build_sparse_network
from .tiles import TiledNetwork, derived_net_np

__all__ = [
    "AtlasBuild",
    "TiledNetwork",
    "build_sparse_network",
    "derived_net_np",
]
