"""`TiledNetwork` — the data-only network spec whose correlation/adjacency
values exist ONLY as on-demand tiles (ISSUE 9 tentpole).

At atlas scale (100k+ genes) a dense n×n float32 correlation/adjacency
pair is ~80 GB — unrepresentable on any single device — while the data it
derives from is O(n·samples) (a few tens of MB). This module holds that
derivation as a *spec*: standardized data columns plus the soft-threshold
``beta`` (the WGCNA construction, :func:`netrep_tpu.ops.stats
.derived_net`), and computes any (I, J) tile of the correlation
(``zᵀ[:, I] z[:, J]/(s-1)``) or adjacency (``|r|**β`` et al.) on demand —
a single MXU matmul per tile, never anything O(n²).

Two value planes, one spec:

- **host reference plane** (:meth:`TiledNetwork.corr_tile`): float64, in
  ``np.corrcoef``'s exact operation order (centered variables-as-rows
  layout, GEMM, multiply by the reciprocal of ``s-1``, divide by the
  GEMM-diagonal stddevs, clip) — including its degenerate-input
  semantics: a zero-variance column yields 0/0 = **NaN across its whole
  row and column, exactly where ``np.corrcoef`` puts them** (pinned
  bit-for-bit on the NaN mask in ``tests/test_degenerate_inputs.py``;
  finite values agree to float64 rounding — GEMM sub-blocking makes
  full-bitwise value equality unattainable on ragged tail tiles).
- **device plane** (:meth:`TiledNetwork.z32` + the builder's jitted tile
  kernel): float32 standardized columns whose tile matmul feeds the
  streaming construction pass (:mod:`netrep_tpu.atlas.builder`) and the
  data-only permutation engine.

Validation mirrors the dense surface's degenerate-input contract
(``models/dataset.py`` rejects non-finite correlations): a zero-variance
column would make every tile touching it NaN, so
:meth:`TiledNetwork.from_data` rejects such columns up front with an
informative error — ``allow_degenerate=True`` keeps them for callers
pinning the NaN-propagation parity itself.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from ..ops import stats as jstats


def _normalize_beta(beta) -> tuple[float, str]:
    beta_t = tuple(beta) if isinstance(beta, list) else beta
    b, kind = jstats.normalize_net_beta(beta_t)
    if not b > 0:
        raise ValueError(f"soft-threshold power must be > 0, got {b!r}")
    return b, kind


def derived_net_np(r: np.ndarray, beta) -> np.ndarray:
    """Host (numpy) twin of :func:`netrep_tpu.ops.stats.derived_net` — the
    soft-threshold adjacency of a correlation tile. One formula site per
    plane; parity between the two is pinned by tests/test_atlas.py."""
    b, kind = _normalize_beta(beta)
    if kind == "signed":
        return np.clip((1.0 + r) * 0.5, 0.0, None) ** b
    if kind == "signed-hybrid":
        return np.clip(r, 0.0, None) ** b
    return np.abs(r) ** b


@dataclasses.dataclass(frozen=True)
class TiledNetwork:
    """Data-only network spec: centered data columns + soft-threshold β.

    ``xc`` is the (n, s) float64 CENTERED data in ``np.cov``'s
    variables-as-rows layout (the op-order anchor of the corrcoef-parity
    contract); ``stddev`` the per-column ddof-1 standard deviations taken
    from tile-GEMM diagonals. Build with :meth:`from_data`.
    """

    xc: np.ndarray                 # (n, s) float64 centered columns
    stddev: np.ndarray             # (n,) float64 ddof-1 sd (0 = degenerate)
    beta: tuple                    # normalized (β, kind)
    node_names: list[str] | None = None

    @property
    def n(self) -> int:
        return self.xc.shape[0]

    @property
    def n_samples(self) -> int:
        return self.xc.shape[1]

    @classmethod
    def from_data(cls, data, beta, names: Sequence[str] | None = None,
                  allow_degenerate: bool = False) -> "TiledNetwork":
        """Validate and standardize a (n_samples, n) data matrix into a
        tile spec. Rejections mirror the dense input layer's informative
        errors: non-2-D / non-finite data, fewer than 2 samples, and —
        unless ``allow_degenerate`` — zero-variance columns, whose
        correlations are NaN (``np.corrcoef`` semantics; the dense
        surface rejects the resulting non-finite correlation matrix the
        same way)."""
        arr = np.asarray(data, dtype=np.float64)
        if arr.ndim != 2:
            raise ValueError(
                f"data must be a 2-dimensional (n_samples, n_nodes) "
                f"matrix, got {arr.ndim} dimension(s)"
            )
        if arr.shape[0] < 2:
            raise ValueError(
                f"data needs at least 2 samples to correlate, got "
                f"{arr.shape[0]}"
            )
        if not np.isfinite(arr).all():
            raise ValueError(
                "data contains non-finite values (NA/NaN/Inf are not "
                "allowed)"
            )
        beta_n = _normalize_beta(beta)
        s, n = arr.shape
        X = np.ascontiguousarray(arr.T)              # (n, s) cov layout
        X = X - np.average(X, axis=1)[:, None]
        rcp = np.true_divide(1, s - 1)
        # stddev from tile-GEMM diagonals — the same dot products the
        # corrcoef path's diag(cov) takes, block by block
        d = np.empty(n)
        edge = 4096
        for j0 in range(0, n, edge):
            blk = X[j0: j0 + edge]
            d[j0: j0 + edge] = np.einsum("is,is->i", blk, blk) * rcp
        stddev = np.sqrt(d)
        if not allow_degenerate and (stddev == 0).any():
            bad = np.flatnonzero(stddev == 0)
            raise ValueError(
                f"data has {bad.size} zero-variance (constant) column(s), "
                f"e.g. positions {bad[:3].tolist()}: their correlations "
                "are NaN (np.corrcoef semantics) and the preservation "
                "statistics are undefined — drop or jitter these nodes, "
                "exactly as the dense surface's non-finite-correlation "
                "check would demand"
            )
        if names is not None:
            names = [str(nm) for nm in names]
            if len(names) != n:
                raise ValueError(
                    f"names has {len(names)} entries but data has {n} "
                    "columns"
                )
            if len(set(names)) != n:
                raise ValueError("duplicate node names")
        return cls(xc=X, stddev=stddev, beta=beta_n,
                   node_names=list(names) if names is not None else None)

    # -- host reference plane (float64, corrcoef op order) -----------------

    def corr_tile(self, I, J) -> np.ndarray:
        """The (I, J) correlation tile in ``np.corrcoef``'s exact op
        order — NaN propagation from zero-variance columns included
        (module docstring). ``I``/``J`` are index arrays or slices."""
        rcp = np.true_divide(1, self.n_samples - 1)
        with np.errstate(divide="ignore", invalid="ignore"):
            c = (self.xc[I] @ self.xc[J].T) * rcp
            c /= self.stddev[I][:, None]
            c /= self.stddev[J][None, :]
        np.clip(c, -1, 1, out=c)
        return c

    def adjacency_tile(self, I, J) -> np.ndarray:
        """The (I, J) soft-threshold adjacency tile ``derived_net(r, β)``
        (diagonal untouched — consumers mask self-pairs, as every
        statistic kernel does)."""
        return derived_net_np(self.corr_tile(I, J), self.beta)

    # -- device plane ------------------------------------------------------

    def z32(self) -> np.ndarray:
        """(n, s) float32 standardized columns for the device tile kernel:
        ``z[i]·z[j] = r_ij`` exactly (each column scaled by
        ``1/(sd·√(s-1))``). Degenerate columns (sd 0) become all-zero —
        the engine-side zero-variance guard — so build specs through
        :meth:`from_data`'s validation when NaN semantics are wanted."""
        with np.errstate(divide="ignore", invalid="ignore"):
            z = self.xc / (self.stddev * np.sqrt(self.n_samples - 1))[:, None]
        return np.nan_to_num(z, nan=0.0, posinf=0.0, neginf=0.0).astype(
            np.float32
        )

    def spec_digest(self) -> str:
        """Content identity of this spec — data sample digest + the
        derivation parameters (β, kind), so checkpoints (and serve pack
        keys) can never mix two different derivations of the same data."""
        from ..utils.checkpoint import content_digest

        b, kind = self.beta
        return f"{content_digest([self.xc])}|beta:{b:g}|{kind}"

    # -- column-moment cache (exact tile screening, ISSUE 11) --------------

    def column_moments(self, segments: int = 8) -> np.ndarray:
        """Per-column sample-segment norms of the device plane — the
        ``(n, P)`` float64 matrix ``A`` with ``A[j, p] = ‖z_j over sample
        segment p‖`` of the :meth:`z32` standardized columns (so
        ``Σ_p A[j, p]² = 1`` for non-degenerate columns).

        This is the moment table every screening bound derives from: by
        Cauchy–Schwarz applied per segment,

            ``|r_ij| = |Σ_p z_i⁽ᵖ⁾·z_j⁽ᵖ⁾| ≤ Σ_p A[i, p]·A[j, p]``

        — an upper bound on any correlation from O(n·P) numbers, tight
        exactly when two columns' sample support overlaps (the sparse,
        modular structure of real co-expression data) and ≤ 1 always.
        Computed once per (spec, P) and memoized on the instance; row
        blocks are processed in bounded chunks so the transient float64
        working set never scales with n.
        """
        P = max(1, min(int(segments), self.n_samples))
        cache = self.__dict__.get("_moment_cache")
        if cache is None:
            cache = {}
            object.__setattr__(self, "_moment_cache", cache)
        if P not in cache:
            s = self.n_samples
            starts = (np.arange(P, dtype=np.int64) * s) // P
            z = self.z32()
            A = np.empty((self.n, P), dtype=np.float64)
            blk = 1 << 16
            for i0 in range(0, self.n, blk):
                zz = z[i0: i0 + blk].astype(np.float64)
                A[i0: i0 + blk] = np.add.reduceat(zz * zz, starts, axis=1)
            np.sqrt(A, out=A)
            cache[P] = A
        return cache[P]


# -- screening bound kernels (ISSUE 11) -------------------------------------


def tile_norm_maxima(A: np.ndarray, edge: int, n_tiles: int) -> np.ndarray:
    """Per-tile segment-norm maxima: ``M[t, p] = max_{j in tile t} A[j, p]``
    for ``n_tiles`` column tiles of ``edge`` genes (padding tiles past the
    real columns are all-zero, so their bounds are 0 and they can never
    survive a screen). With ``M`` for a row block ``I`` and a column tile
    ``J``, ``min(1, M_I · M_J)`` bounds every ``|r_ij|`` in the (I, J)
    tile: ``Σ_p A[i,p]A[j,p] ≤ Σ_p (max_I A[·,p])(max_J A[·,p])``."""
    n, P = A.shape
    M = np.zeros((n_tiles, P), dtype=np.float64)
    full = min(n // edge, n_tiles)
    if full:
        M[:full] = A[: full * edge].reshape(full, edge, P).max(axis=1)
    if full < n_tiles and full * edge < n:
        M[full] = A[full * edge:].max(axis=0)
    return M


def supertile_maxima(M: np.ndarray, factor: int) -> np.ndarray:
    """Coarse-level maxima over groups of ``factor`` consecutive tiles:
    ``MS[g] = max over tiles g·S..(g+1)·S of M`` — the super-tile bound
    table of the two-resolution screen. A super-tile bound dominates every
    member tile's bound, so pruning at the coarse level is exact."""
    T, P = M.shape
    G = -(-T // factor)
    MS = np.zeros((G, P), dtype=np.float64)
    for g in range(G):
        MS[g] = M[g * factor: (g + 1) * factor].max(axis=0)
    return MS
