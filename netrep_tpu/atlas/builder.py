"""Streaming construction pass over the tile grid (ISSUE 9 tentpole).

One scan of the tile grid of a :class:`~netrep_tpu.atlas.tiles
.TiledNetwork` produces, without ever materializing n×n:

- **thresholded edges** — per-row top-k (device ``lax.top_k`` over the
  row strip, O(edge·k) transferred) or ``|r| ≥ τ`` (host-selected) —
  emitted directly into the existing
  :class:`~netrep_tpu.ops.sparse.SparseAdjacency` neighbor-list format,
  symmetrized by union: the bridge that puts atlas-scale data-only
  inputs onto the Config E sparse engine
  (``sparse_module_preservation``) unchanged;
- **per-node degree vectors** over the FULL derived network (every
  column, not just the kept edges) — the global topology the seven
  statistics' dense-path contracts are defined against, accumulated one
  row strip at a time.

Operational contract (the PR 2/4/5/6 machinery, applied to a new loop):

- **chunk-checkpointable**: after every ``checkpoint_every`` row blocks
  the pass persists its accumulators through the null-checkpoint format
  (``x_atlas_*`` extras; interrupt → resume is exact, and a checkpoint
  from a different spec/edge/threshold refuses with the usual
  informative error);
- **fault-policy-covered**: each strip dispatch runs under the PR 4/6
  recovery ladder (transient retry with deterministic backoff, hang
  abandon, device-loss failure-save before the error propagates);
- **traced**: a ``tile_pass_start``/``tile_pass_end`` span with one
  ``tile`` event per row block (duration, edges kept, device-memory
  gauges) on the PR 5 trace tree;
- **autotuned**: the tile edge resolves from the persistent cache
  (:func:`netrep_tpu.utils.autotune.resolve_tile_edge`, recorded beside
  the superchunk entry) and the measured columns/s feed back per edge;
- **mesh-shardable**: with a mesh, the strip's column tiles spread over
  ``config.mesh_axis`` under ``shard_map`` — each device runs the SAME
  fixed-shape per-tile program on its tile subset, so the sharded pass
  is bit-identical to the single-device pass (pinned in
  tests/test_atlas.py).

Device memory stays bounded by the tile working set (O(edge·n) strip +
O(n·s) data columns); host memory is O(n·k) selected edges.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops import stats as jstats
from ..ops.sparse import SparseAdjacency
from ..utils import faults as flt
from ..utils import telemetry as tm
from ..utils.autotune import make_key, resolve_tile_edge
from ..utils.checkpoint import (
    load_null_checkpoint, save_null_checkpoint, validate_identity,
)
from ..utils.config import EngineConfig
from .tiles import TiledNetwork, derived_net_np


@dataclasses.dataclass
class AtlasBuild:
    """Result of one construction pass.

    ``adjacency`` carries the derived-net weights at the selected edges,
    ``correlation`` the raw r values on the SAME neighbor structure —
    together they are the (network, sparse-correlation) pair the Config E
    engine consumes; ``degree`` is the full (unthresholded) derived-net
    weighted degree per node."""

    adjacency: SparseAdjacency
    correlation: SparseAdjacency
    degree: np.ndarray             # (n,) float64
    n: int
    tile_edge: int
    n_blocks: int
    selected_edges: int            # directed selections before symmetrize


def _fingerprint(net: TiledNetwork, edge: int, top_k, tau) -> np.ndarray:
    spec = (
        f"atlas-pass|{net.spec_digest()}|n:{net.n}|edge:{int(edge)}"
        f"|topk:{top_k}|tau:{tau}"
    )
    return np.frombuffer(spec.encode(), dtype=np.uint8)


#: the pass draws no random numbers; the checkpoint key slot carries this
#: constant so the shared identity validation (seed splice refusal) is a
#: tautology here rather than a special case
_KEY_DATA = np.zeros(2, dtype=np.uint32)


def _build_strip_fn(edge: int, T: int, n: int, s: int, beta, top_k,
                    mesh, mesh_axis: str) -> Callable:
    """Jitted row-strip program: ``(zI, z_tiles, row0) -> parts``.

    ``z_tiles`` is the full standardized matrix reshaped to (T, edge, s);
    each tile is one fixed-shape (edge, s)×(s, edge) matmul, and EVERY
    arithmetic step — correlation, pair mask, derived-net values, and the
    per-tile partial degree — happens inside that fixed-shape per-tile
    body. A shard_map over the tile axis therefore runs the identical
    per-tile program on a subset: bitwise equality with the single-device
    pass by construction (the cross-tile degree accumulation happens on
    the HOST in float64, where summation order is fixed). Returns
    ``(deg_parts (T, edge), idxs, r_sel, score_sel)`` in top-k mode or
    ``(deg_parts, masked r strip)`` in threshold mode (host selects)."""
    tile_ids = jnp.arange(T, dtype=jnp.int32)

    def one_tile(zI, zj, tile_id, row0):
        r = jnp.clip(
            jnp.matmul(zI, zj.T, preferred_element_type=jnp.float32),
            -1.0, 1.0,
        )                                              # (edge, edge)
        cols = tile_id * edge + jnp.arange(edge, dtype=jnp.int32)
        rows = row0 + jnp.arange(edge, dtype=jnp.int32)
        # pair validity: real column, real row, not the self pair
        mask = (
            (cols[None, :] < n)
            & (rows[:, None] < n)
            & (cols[None, :] != rows[:, None])
        )
        net_vals = jnp.where(mask, jstats.derived_net(r, beta), 0.0)
        deg_part = jnp.sum(net_vals, axis=-1)          # (edge,)
        score = jnp.where(mask, jnp.abs(r), -1.0)
        return r, score, deg_part

    def tiles_body(zI, z_tiles, tids, row0):
        return jax.vmap(one_tile, in_axes=(None, 0, 0, None))(
            zI, z_tiles, tids, row0
        )

    if mesh is not None:
        from ..parallel.sharded import _NO_CHECK_KW, _shard_map

        sharded_tiles = _shard_map(
            tiles_body, mesh=mesh,
            in_specs=(P(), P(mesh_axis), P(mesh_axis), P()),
            out_specs=P(mesh_axis),
            **_NO_CHECK_KW,
        )
    else:
        sharded_tiles = tiles_body

    def strip(zI, z_tiles, row0):
        r, score, deg_parts = sharded_tiles(zI, z_tiles, tile_ids, row0)
        # strip layout (edge, T*edge): flattened index IS the global col
        r_flat = jnp.swapaxes(r, 0, 1).reshape(edge, T * edge)
        s_flat = jnp.swapaxes(score, 0, 1).reshape(edge, T * edge)
        if top_k is None:
            return deg_parts, jnp.where(s_flat >= 0, r_flat, 0.0)
        vals, idxs = jax.lax.top_k(s_flat, top_k)
        r_sel = jnp.take_along_axis(r_flat, idxs, axis=1)
        return deg_parts, idxs, r_sel, vals

    return jax.jit(strip)


def build_sparse_network(
    net: TiledNetwork,
    top_k: int | None = None,
    tau: float | None = None,
    *,
    tile_edge: int | None = None,
    config: EngineConfig | None = None,
    mesh=None,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 1,
    progress: Callable[[int, int], None] | None = None,
    telemetry=None,
    fault_policy=None,
) -> AtlasBuild:
    """One streaming scan of the tile grid (module docstring). Exactly one
    of ``top_k`` (per-row strongest |r| edges, device-selected) / ``tau``
    (``|r| ≥ τ``, τ > 0, host-selected) picks the threshold rule.
    ``checkpoint_every`` counts ROW BLOCKS; an interrupted pass resumes
    exactly from ``checkpoint_path``."""
    if (top_k is None) == (tau is None):
        raise ValueError("pass exactly one of top_k (int) or tau (float)")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if tau is not None and not tau > 0:
        raise ValueError(
            f"tau must be > 0 (τ=0 would keep every pair — the dense "
            f"matrix the tile plane exists to avoid), got {tau}"
        )
    config = config or EngineConfig()
    n, s = net.n, net.n_samples

    at_key = make_key(
        jax.default_backend(), "atlas-tiles", f"n{n}s{s}", 0,
        "topk" if top_k is not None else "tau",
    )
    edge, at_cache = resolve_tile_edge(config, at_key, explicit=tile_edge)
    edge = int(min(edge, max(8, -(-n // 8) * 8)))
    T = -(-n // edge)                      # column tiles
    if mesh is not None:
        ax = mesh.shape[config.mesh_axis]
        T = -(-T // ax) * ax               # pad tile count to the mesh
    n_pad = T * edge
    B = -(-n // edge)                      # row blocks (real rows only)
    k_eff = None if top_k is None else int(min(top_k, max(1, n - 1)))

    tel, tel_owned = tm.resolve_arg(telemetry)
    if tel is None:
        tel = tm.current()
        tel_owned = False
    ft = flt.resolve_runtime(fault_policy)

    # accumulators (+ resume)
    deg = np.zeros(n, dtype=np.float64)
    rows_l: list[np.ndarray] = []
    cols_l: list[np.ndarray] = []
    corr_l: list[np.ndarray] = []
    start_block = 0
    fp = _fingerprint(net, edge, k_eff, tau)
    if checkpoint_path is not None:
        ckpt = load_null_checkpoint(checkpoint_path)
        if ckpt is not None:
            validate_identity(ckpt, _KEY_DATA, fp, checkpoint_path)
            deg = np.asarray(ckpt["nulls"], dtype=np.float64).copy()
            start_block = int(ckpt["completed"])
            ex = ckpt["extras"]
            if ex.get("atlas_rows") is not None and ex["atlas_rows"].size:
                rows_l = [ex["atlas_rows"].astype(np.int64)]
                cols_l = [ex["atlas_cols"].astype(np.int64)]
                corr_l = [ex["atlas_corr"].astype(np.float64)]

    def save(done: int) -> None:
        if checkpoint_path is None:
            return
        save_null_checkpoint(
            checkpoint_path, deg, done, _KEY_DATA, fp,
            extra={
                "atlas_rows": (
                    np.concatenate(rows_l) if rows_l
                    else np.empty(0, np.int64)
                ),
                "atlas_cols": (
                    np.concatenate(cols_l) if cols_l
                    else np.empty(0, np.int64)
                ),
                "atlas_corr": (
                    np.concatenate(corr_l) if corr_l
                    else np.empty(0, np.float64)
                ),
            },
        )

    z = net.z32()
    if n_pad != n:
        z = np.concatenate(
            [z, np.zeros((n_pad - n, s), dtype=np.float32)]
        )
    z_dev = jnp.asarray(z)
    z_tiles = z_dev.reshape(T, edge, s)
    strip_fn = _build_strip_fn(
        edge, T, n, s, net.beta, k_eff, mesh, config.mesh_axis
    )

    mem = None
    sid = None
    if tel is not None:
        sid = tel.begin_span(
            "tile_pass_start", n=int(n), edge=int(edge), blocks=int(B),
            start_block=int(start_block), samples=int(s),
            mode="topk" if k_eff is not None else "tau",
        )
        from ..utils.profiling import make_memory_probe

        mem = make_memory_probe()

    done = start_block
    last_saved = start_block
    t_marks: list[tuple[int, float]] = []
    t0 = time.perf_counter()
    try:
        for b in range(start_block, B):
            row0 = b * edge
            zI = jax.lax.dynamic_slice_in_dim(z_dev, row0, edge, axis=0)

            def _dispatch(_zI=zI, _row0=row0):
                out = strip_fn(_zI, z_tiles, jnp.int32(_row0))
                return jax.block_until_ready(out)

            t_b0 = time.perf_counter()
            if ft is None:
                out = _dispatch()
            else:
                out = ft.run_dispatch(
                    _dispatch, start=b, take=1, telemetry=tel,
                    rescue=lambda: save(done), label="tile_strip",
                )
            lo = row0
            hi = min(row0 + edge, n)
            m = hi - lo
            kept = 0
            if k_eff is not None:
                deg_b, idxs, r_sel, score = (np.asarray(a) for a in out)
                # cross-tile fold on the host in f64: summation order is
                # then fixed regardless of how the tile axis was sharded
                deg[lo:hi] += deg_b.astype(np.float64).sum(axis=0)[:m]
                keep = score[:m] >= 0          # rows short of k candidates
                ii, jj = np.nonzero(keep)
                rows_l.append((lo + ii).astype(np.int64))
                cols_l.append(idxs[:m][keep].astype(np.int64))
                corr_l.append(r_sel[:m][keep].astype(np.float64))
                kept = int(keep.sum())
            else:
                deg_b, r_strip = (np.asarray(a) for a in out)
                deg[lo:hi] += deg_b.astype(np.float64).sum(axis=0)[:m]
                sel = np.abs(r_strip[:m]) >= tau
                ii, jj = np.nonzero(sel)
                rows_l.append((lo + ii).astype(np.int64))
                cols_l.append(jj.astype(np.int64))
                corr_l.append(r_strip[:m][sel].astype(np.float64))
                kept = int(sel.sum())
            done = b + 1
            t_marks.append((done, time.perf_counter()))
            if tel is not None:
                tel.emit(
                    "tile", parent=sid, block=int(b), blocks=int(B),
                    s=t_marks[-1][1] - t_b0, edges_kept=kept,
                    **(mem() if mem is not None else {}),
                )
            if progress is not None:
                progress(done, B)
            if checkpoint_path is not None and done - last_saved >= checkpoint_every:
                save(done)
                last_saved = done
    except BaseException:
        # failure-save (KeyboardInterrupt and the fault ladder's terminal
        # errors alike): completed row blocks must never be re-scanned
        if done > last_saved:
            save(done)
        if tel is not None:
            tel.end_span(
                sid, "tile_pass_end", blocks_done=int(done),
                blocks=int(B), interrupted=True,
                s=time.perf_counter() - t0,
            )
            if tel_owned:
                tel.close()
        raise
    if checkpoint_path is not None and done > last_saved:
        save(done)

    rows = np.concatenate(rows_l) if rows_l else np.empty(0, np.int64)
    cols = np.concatenate(cols_l) if cols_l else np.empty(0, np.int64)
    corr = np.concatenate(corr_l) if corr_l else np.empty(0, np.float64)
    wgt = derived_net_np(corr, net.beta)
    adjacency = SparseAdjacency.from_coo(rows, cols, wgt, n, symmetrize=True)
    correlation = SparseAdjacency.from_coo(
        rows, cols, corr, n, symmetrize=True
    )
    if tel is not None:
        tel.end_span(
            sid, "tile_pass_end", blocks_done=int(done), blocks=int(B),
            interrupted=False, edges=int(rows.size),
            nnz=int(adjacency.nnz), s=time.perf_counter() - t0,
        )
        if tel_owned:
            tel.close()
    if at_cache is not None and len(t_marks) >= 2:
        # steady-state gene rows/s (first block's interval absorbs the jit
        # compile, same convention as the null loops)
        (b0, tm0), (b1, tm1) = t_marks[0], t_marks[-1]
        if tm1 > tm0 and b1 > b0:
            at_cache.record(at_key, edge, (b1 - b0) * edge / (tm1 - tm0))
    return AtlasBuild(
        adjacency=adjacency, correlation=correlation, degree=deg, n=n,
        tile_edge=edge, n_blocks=B, selected_edges=int(rows.size),
    )
