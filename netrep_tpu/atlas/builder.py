"""Streaming construction pass over the tile grid (ISSUE 9 tentpole,
ISSUE 11 exact tile screening).

One scan of the tile grid of a :class:`~netrep_tpu.atlas.tiles
.TiledNetwork` produces, without ever materializing n×n:

- **thresholded edges** — per-row top-k (device ``lax.top_k`` over the
  row strip, O(edge·k) transferred) or ``|r| ≥ τ`` (device-masked: only
  surviving entries + flat indices cross the wire, ISSUE 11 satellite) —
  emitted directly into the existing
  :class:`~netrep_tpu.ops.sparse.SparseAdjacency` neighbor-list format,
  symmetrized by union: the bridge that puts atlas-scale data-only
  inputs onto the Config E sparse engine
  (``sparse_module_preservation``) unchanged;
- **per-node degree vectors** over the FULL derived network (every
  column, not just the kept edges) — optional (``degree=``): the global
  topology is a sum over every tile, so it is only available on an
  unscreened pass.

**Exact tile screening** (ISSUE 11 tentpole, ``screen=True``): at 1M
genes the grid has 100× the tiles of the 100k ceiling and — in the
sparse, modular structure real co-expression data has — almost every
tile is noise that provably cannot contribute an edge. The screened pass
makes work proportional to signal while staying bit-identical to the
unscreened scan by construction:

1. **column moments**: per-column sample-segment norms of the
   standardized data (:meth:`TiledNetwork.column_moments`) give, by
   Cauchy–Schwarz applied per segment, an upper bound on any
   correlation a tile can contain from O(n·P) numbers
   (:func:`~netrep_tpu.atlas.tiles.tile_norm_maxima`);
2. **two-resolution scan**: coarse super-tile bounds over groups of
   ``supertile`` tiles (:func:`~netrep_tpu.atlas.tiles
   .supertile_maxima`; τ mode prunes whole S×S blocks of the grid from
   one precomputed super-bound table) → surviving groups refine into
   per-tile bounds → only surviving tiles are dispatched, as a
   fixed-shape worklist program (power-of-two bucketed, mesh-shardable
   over the worklist axis exactly like the unscreened tile axis);
3. **threshold floors**: a tile is skipped when its bound (plus a
   float32 forward-error margin) falls below the active threshold — the
   τ cut, or the **running per-row top-k floor**: the k-th best |r| each
   row has accumulated so far, which tightens monotonically as the
   block's groups are processed in descending-bound order. Skipping is
   exact: every value in a skipped tile is strictly below anything that
   could enter the output, so the screened pass emits bit-identical
   edges (same values, same order) as the unscreened pass.

Operational contract (the PR 2/4/5/6 machinery, applied to a new loop):

- **chunk-checkpointable**: after every ``checkpoint_every`` row blocks
  the pass persists its accumulators through the null-checkpoint format
  (``x_atlas_*`` extras — COO so-far plus the screening tally so
  interrupt → resume replays the same tightened floors and keeps the
  skip counters exact; a checkpoint from a different spec/edge/
  threshold/degree refuses with the usual informative error, while the
  **screening toggle deliberately shares the fingerprint**: screened and
  unscreened passes produce bit-identical output, so a checkpoint from
  either resumes under the other);
- **fault-policy-covered**: each dispatch (full strip or screened
  worklist group) runs under the PR 4/6 recovery ladder;
- **traced**: a ``tile_pass_start``/``tile_pass_end`` span with one
  ``tile`` event per row block plus, when screening, one ``tile_screen``
  event per row block (bound-math duration, tiles skipped/dispatched,
  active floor) on the PR 5 trace tree; the pass-end event carries
  ``tiles_skipped``/``nxn_bytes_avoided`` (correlation bytes never
  computed) and the strip-transfer byte split;
- **autotuned**: the tile edge resolves from the persistent cache
  (:func:`netrep_tpu.utils.autotune.resolve_tile_edge`) and, when
  screening, the super-tile factor beside it
  (:func:`netrep_tpu.utils.autotune.resolve_supertile`);
- **mesh-shardable**: strips and screened worklists spread over
  ``config.mesh_axis`` under ``shard_map`` — each device runs the SAME
  fixed-shape per-tile program on its subset, and cross-tile folds
  happen on the host in float64, so sharded passes (screened or not)
  are bit-identical to the single-device pass.

Device memory stays bounded by the tile working set; host memory is
O(n·k) selected edges plus the O(n·P) moment table.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops import stats as jstats
from ..ops.sparse import SparseAdjacency
from ..utils import faults as flt
from ..utils import telemetry as tm
from ..utils.autotune import make_key, resolve_supertile, resolve_tile_edge
from ..utils.checkpoint import (
    load_null_checkpoint, save_null_checkpoint, validate_identity,
)
from ..utils.config import EngineConfig
from .tiles import (
    TiledNetwork, derived_net_np, supertile_maxima, tile_norm_maxima,
)


@dataclasses.dataclass
class AtlasBuild:
    """Result of one construction pass.

    ``adjacency`` carries the derived-net weights at the selected edges,
    ``correlation`` the raw r values on the SAME neighbor structure —
    together they are the (network, sparse-correlation) pair the Config E
    engine consumes; ``degree`` is the full (unthresholded) derived-net
    weighted degree per node, or None when the pass ran with
    ``degree=False`` (always the case under screening: the full degree is
    a sum over every tile, including the ones screening exists to skip).
    The screening tally (``tiles_*``, ``strip_bytes_*``) mirrors what the
    ``tile_pass_end`` telemetry span reports."""

    adjacency: SparseAdjacency
    correlation: SparseAdjacency
    degree: np.ndarray | None
    n: int
    tile_edge: int
    n_blocks: int
    selected_edges: int            # directed selections before symmetrize
    supertile: int = 0             # coarse group factor (0 = unscreened)
    tiles_total: int = 0           # real tiles in the scanned grid
    tiles_dispatched: int = 0
    tiles_skipped: int = 0
    strip_bytes_full: int = 0      # what full-strip transfers would move
    strip_bytes_moved: int = 0     # what actually crossed the wire


def _fingerprint(net: TiledNetwork, edge: int, top_k, tau,
                 degree: bool) -> np.ndarray:
    """Checkpoint identity of one pass. DELIBERATELY excludes the
    screening knobs (``screen``/``supertile``/``screen_segments``):
    screened and unscreened passes produce bit-identical output, so they
    share a fingerprint and a checkpoint written by either resumes under
    the other (pinned in tests/test_atlas_screen.py). The threshold rule
    (top_k/τ), tile edge, and the degree flag each change the output, so
    they refuse."""
    spec = (
        f"atlas-pass|{net.spec_digest()}|n:{net.n}|edge:{int(edge)}"
        f"|topk:{top_k}|tau:{tau}|deg:{int(bool(degree))}"
    )
    return np.frombuffer(spec.encode(), dtype=np.uint8)


#: the pass draws no random numbers; the checkpoint key slot carries this
#: constant so the shared identity validation (seed splice refusal) is a
#: tautology here rather than a special case
_KEY_DATA = np.zeros(2, dtype=np.uint32)

#: column sentinel for empty top-k candidate slots: sorts after every real
#: column index, so tie-breaking against real candidates is never affected
_COL_SENTINEL = np.int64(1) << 62


def _bound_margin(s: int) -> float:
    """Safety margin added to every screening bound before comparing it to
    a threshold: the bounds are exact for the real-valued correlations,
    but the device computes ``r`` in float32 — a length-``s`` f32 dot
    product of unit vectors carries forward error ≤ ~s·2⁻²⁴, so the
    margin (16× that, plus an absolute floor) guarantees even the rounded
    |r| of a skipped tile stays strictly below the active threshold."""
    return 16.0 * s * 2.0 ** -24 + 1e-7


def _bucket_width(n_work: int, ax: int) -> int:
    """Fixed-shape worklist width for ``n_work`` surviving tiles: next
    power of two (few distinct widths → few compiles), then rounded up to
    a multiple of the mesh axis so a sharded dispatch splits evenly."""
    w = 1
    while w < n_work:
        w <<= 1
    if ax > 1:
        w = -(-w // ax) * ax
    return w


def _tau_ceil32(tau: float) -> np.float32:
    """Smallest float32 ≥ τ. Comparing a float32 |r| against it is
    EXACTLY the float64 comparison ``|r| ≥ τ`` (every f32 is exact in
    f64), so device-side selection reproduces the host-f64 criterion bit
    for bit — including knife-edge values."""
    t = np.float32(tau)
    if float(t) < tau:
        t = np.nextafter(t, np.float32(np.inf), dtype=np.float32)
    return t


def _tile_body(edge: int, n: int, beta, with_deg: bool) -> Callable:
    """The fixed-shape per-tile program every dispatch composes: one
    (edge, s)×(s, edge) MXU matmul, clip, pair-validity mask (worklist
    padding slots carry ``tile_id = -1`` and mask out entirely), |r|
    score, and — degree passes only — the derived-net partial degree.
    Identical between the full-strip and worklist paths, so screened and
    unscreened dispatches produce bit-identical tiles."""

    def one_tile(zI, zj, tile_id, row0):
        r = jnp.clip(
            jnp.matmul(zI, zj.T, preferred_element_type=jnp.float32),
            -1.0, 1.0,
        )                                              # (edge, edge)
        cols = tile_id * edge + jnp.arange(edge, dtype=jnp.int32)
        rows = row0 + jnp.arange(edge, dtype=jnp.int32)
        # pair validity: real tile, real column, real row, not self
        mask = (
            (tile_id >= 0)
            & (cols[None, :] < n)
            & (rows[:, None] < n)
            & (cols[None, :] != rows[:, None])
        )
        score = jnp.where(mask, jnp.abs(r), -1.0)
        if with_deg:
            net_vals = jnp.where(mask, jstats.derived_net(r, beta), 0.0)
            return r, score, jnp.sum(net_vals, axis=-1)
        return r, score

    return one_tile


def _tau_compact(s_flat, r_flat, tau32, cap: int):
    """Device-side τ selection (ISSUE 11 satellite): instead of shipping
    the full masked (edge, W·edge) f32 strip to the host, keep only the
    survivors. ``top_k`` over ``N - flat_index`` (keyed to the selection
    mask) yields the first ``cap`` surviving flat indices in ascending
    order — exactly ``np.nonzero``'s row-major order on the host — and a
    gather pulls their r values. The survivor count rides along so the
    caller can detect capacity overflow and re-dispatch with a larger
    ``cap`` (exactness is never at stake, only a recompile)."""
    sf = s_flat.reshape(-1)
    n_flat = sf.shape[0]
    sel = sf >= tau32
    cnt = jnp.sum(sel.astype(jnp.int32))
    key = jnp.where(sel, jnp.int32(n_flat) - jnp.arange(n_flat,
                                                        dtype=jnp.int32), 0)
    kv, _ = jax.lax.top_k(key, cap)
    fidx = jnp.int32(n_flat) - kv          # == n_flat at empty slots
    rv = jnp.take(r_flat.reshape(-1), jnp.minimum(fidx, n_flat - 1))
    return cnt, fidx, rv


def _make_sharded_tiles(one_tile, mesh, mesh_axis):
    def tiles_body(zI, z_tiles, tids, row0):
        return jax.vmap(one_tile, in_axes=(None, 0, 0, None))(
            zI, z_tiles, tids, row0
        )

    if mesh is None:
        return tiles_body
    from ..parallel.sharded import _NO_CHECK_KW, _shard_map

    return _shard_map(
        tiles_body, mesh=mesh,
        in_specs=(P(), P(mesh_axis), P(mesh_axis), P()),
        out_specs=P(mesh_axis),
        **_NO_CHECK_KW,
    )


def _build_strip_fn(edge: int, T: int, n: int, s: int, beta, top_k,
                    tau32, cap, with_deg: bool, mesh,
                    mesh_axis: str) -> Callable:
    """Jitted FULL-STRIP program (unscreened path): ``(zI, z_tiles, row0)
    -> parts`` over all T column tiles. Strip layout (edge, T·edge):
    the flattened index IS the global column. Cross-tile folds (degree)
    happen on the HOST in float64 where summation order is fixed, so a
    shard_map over the tile axis is bitwise-equal by construction.
    τ mode returns the device-compacted survivors (``cap`` capacity;
    ``cap=None`` falls back to the full masked strip when the flat index
    would overflow int32)."""
    tile_ids = jnp.arange(T, dtype=jnp.int32)
    one_tile = _tile_body(edge, n, beta, with_deg)
    sharded_tiles = _make_sharded_tiles(one_tile, mesh, mesh_axis)

    def strip(zI, z_tiles, row0):
        out = sharded_tiles(zI, z_tiles, tile_ids, row0)
        if with_deg:
            r, score, deg_parts = out
            head = (deg_parts,)
        else:
            r, score = out
            head = ()
        r_flat = jnp.swapaxes(r, 0, 1).reshape(edge, T * edge)
        s_flat = jnp.swapaxes(score, 0, 1).reshape(edge, T * edge)
        if top_k is not None:
            vals, idxs = jax.lax.top_k(s_flat, top_k)
            r_sel = jnp.take_along_axis(r_flat, idxs, axis=1)
            return head + (idxs, r_sel, vals)
        if cap is None:
            return head + (jnp.where(s_flat >= 0, r_flat, 0.0),)
        return head + _tau_compact(s_flat, r_flat, tau32, cap)

    return jax.jit(strip)


def _build_group_fn(edge: int, w: int, n: int, s: int, beta, top_k,
                    tau32, cap, mesh, mesh_axis: str) -> Callable:
    """Jitted WORKLIST program (screened path): ``(zI, z_tiles, wids,
    row0) -> parts`` over the ``w`` surviving tiles named by ``wids``
    (padded with -1; padding masks out entirely). The per-tile body is
    the SAME fixed-shape program the full strip runs — a worklist
    dispatch computes bit-identical tiles, and a mesh shard_map over the
    worklist axis is bit-identical for the same reason the tile axis is.
    Top-k mode returns the group-local top-k per row (the host merges
    groups under the running floor); τ mode device-compacts survivors."""
    one_tile = _tile_body(edge, n, beta, False)
    sharded_tiles = _make_sharded_tiles(one_tile, mesh, mesh_axis)
    kk = None if top_k is None else int(min(top_k, w * edge))

    def group(zI, z_tiles, wids, row0):
        zw = jnp.take(z_tiles, jnp.maximum(wids, 0), axis=0)
        r, score = sharded_tiles(zI, zw, wids, row0)
        r_flat = jnp.swapaxes(r, 0, 1).reshape(edge, w * edge)
        s_flat = jnp.swapaxes(score, 0, 1).reshape(edge, w * edge)
        if kk is not None:
            vals, idxs = jax.lax.top_k(s_flat, kk)
            r_sel = jnp.take_along_axis(r_flat, idxs, axis=1)
            return idxs, r_sel, vals
        return _tau_compact(s_flat, r_flat, tau32, cap)

    return jax.jit(group)


def _merge_topk(cv, cc, cr, nv, nc, nr, k: int):
    """Fold one group's per-row candidates into the running per-row
    top-k. Two stable sorts — columns ascending, then score descending —
    reproduce ``lax.top_k``'s exact ordering contract (value desc, ties
    by ascending global column), so the merged sequence is bit-identical
    to what a single full-strip top-k would have produced."""
    v = np.concatenate([cv, nv], axis=1)
    c = np.concatenate([cc, nc], axis=1)
    r = np.concatenate([cr, nr], axis=1)
    o1 = np.argsort(c, axis=1, kind="stable")
    v = np.take_along_axis(v, o1, axis=1)
    c = np.take_along_axis(c, o1, axis=1)
    r = np.take_along_axis(r, o1, axis=1)
    o2 = np.argsort(-v, axis=1, kind="stable")
    return (
        np.take_along_axis(v, o2, axis=1)[:, :k],
        np.take_along_axis(c, o2, axis=1)[:, :k],
        np.take_along_axis(r, o2, axis=1)[:, :k],
    )


def build_sparse_network(
    net: TiledNetwork,
    top_k: int | None = None,
    tau: float | None = None,
    *,
    tile_edge: int | None = None,
    config: EngineConfig | None = None,
    mesh=None,
    screen: bool = False,
    supertile: int | None = None,
    screen_segments: int = 8,
    degree: bool | None = None,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 1,
    progress: Callable[[int, int], None] | None = None,
    telemetry=None,
    fault_policy=None,
    _screen_observer: Callable | None = None,
) -> AtlasBuild:
    """One streaming scan of the tile grid (module docstring). Exactly one
    of ``top_k`` (per-row strongest |r| edges, device-selected) / ``tau``
    (``|r| ≥ τ``, τ > 0, device-masked) picks the threshold rule.

    ``screen=True`` turns on the exact screening pass: only tiles whose
    moment bound clears the active threshold (τ, or the running per-row
    top-k floor) are dispatched — output bit-identical to ``screen=False``
    by construction. Screening requires ``degree=False`` (the full-network
    degree is a sum over every tile); ``degree`` defaults to ``not
    screen``. ``supertile`` overrides the autotuned coarse group factor,
    ``screen_segments`` the number of sample segments the moment bounds
    use (more segments = tighter bounds on support-structured data; any
    value is exact). ``checkpoint_every`` counts ROW BLOCKS; an
    interrupted pass resumes exactly from ``checkpoint_path``, including
    across a screening toggle (shared fingerprint).

    ``_screen_observer(block, level, tile_ids, threshold)`` is a test
    hook: called on every skip decision with the tiles skipped and the
    active threshold they were judged against.
    """
    if (top_k is None) == (tau is None):
        raise ValueError("pass exactly one of top_k (int) or tau (float)")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if tau is not None and not tau > 0:
        raise ValueError(
            f"tau must be > 0 (τ=0 would keep every pair — the dense "
            f"matrix the tile plane exists to avoid), got {tau}"
        )
    if degree is None:
        degree = not screen
    with_deg = bool(degree)
    if screen and with_deg:
        raise ValueError(
            "screen=True cannot compute the full-network degree vector — "
            "the degree is a sum over every tile, including the ones "
            "screening skips; pass degree=False (the screened default) "
            "or screen=False"
        )
    config = config or EngineConfig()
    n, s = net.n, net.n_samples

    mode = "topk" if top_k is not None else "tau"
    at_key = make_key(
        jax.default_backend(), "atlas-tiles", f"n{n}s{s}", 0,
        mode + ("+screen" if screen else ""),
    )
    edge, at_cache = resolve_tile_edge(config, at_key, explicit=tile_edge)
    edge = int(min(edge, max(8, -(-n // 8) * 8)))
    T = -(-n // edge)                      # column tiles
    ax = 1
    if mesh is not None:
        ax = mesh.shape[config.mesh_axis]
        T = -(-T // ax) * ax               # pad tile count to the mesh
    n_pad = T * edge
    B = -(-n // edge)                      # row blocks (real rows only)
    T_real = -(-n // edge)                 # real column tiles
    k_eff = None if top_k is None else int(min(top_k, max(1, n - 1)))
    tau32 = None if tau is None else _tau_ceil32(tau)
    tau_cmp = None if tau32 is None else float(tau32)

    # two-resolution screening tables (host float64, deterministic)
    S_res, st_cache, st_key = 0, None, None
    A = M = MS = SB = None
    margin = _bound_margin(s)
    if screen:
        st_key = make_key(
            jax.default_backend(), "atlas-screen", f"n{n}s{s}", 0, mode,
        )
        S_res, st_cache = resolve_supertile(config, st_key,
                                            explicit=supertile)
        S_res = int(max(1, min(S_res, T_real)))
        A = net.column_moments(screen_segments)
        M = tile_norm_maxima(A, edge, T_real)
        MS = supertile_maxima(M, S_res)
        if mode == "tau":
            # super-row × super-col bound grid: one table prunes whole
            # S×S blocks of the tile grid (row groups tile exactly like
            # column groups — same gene axis, same edge)
            SB = np.minimum(MS @ MS.T, 1.0)

    tel, tel_owned = tm.resolve_arg(telemetry)
    if tel is None:
        tel = tm.current()
        tel_owned = False
    ft = flt.resolve_runtime(fault_policy)

    # accumulators (+ resume)
    deg = np.zeros(n, dtype=np.float64)
    rows_l: list[np.ndarray] = []
    cols_l: list[np.ndarray] = []
    corr_l: list[np.ndarray] = []
    start_block = 0
    tiles_dispatched = 0
    tiles_skipped = 0
    bytes_full = 0
    bytes_moved = 0
    fp = _fingerprint(net, edge, k_eff, tau, with_deg)
    if checkpoint_path is not None:
        ckpt = load_null_checkpoint(checkpoint_path)
        if ckpt is not None:
            validate_identity(ckpt, _KEY_DATA, fp, checkpoint_path)
            deg = np.asarray(ckpt["nulls"], dtype=np.float64).copy()
            start_block = int(ckpt["completed"])
            ex = ckpt["extras"]
            if ex.get("atlas_rows") is not None and ex["atlas_rows"].size:
                rows_l = [ex["atlas_rows"].astype(np.int64)]
                cols_l = [ex["atlas_cols"].astype(np.int64)]
                corr_l = [ex["atlas_corr"].astype(np.float64)]
            # screening/transfer tally (ISSUE 11): resume keeps the skip
            # counters exact across interrupts — and across a screening
            # toggle, where a missing tally simply starts at zero
            for name, default in (("atlas_tiles_dispatched", 0),
                                  ("atlas_tiles_skipped", 0),
                                  ("atlas_bytes_full", 0),
                                  ("atlas_bytes_moved", 0)):
                if ex.get(name) is not None:
                    val = int(np.asarray(ex[name]).reshape(-1)[0])
                else:
                    val = default
                if name == "atlas_tiles_dispatched":
                    tiles_dispatched = val
                elif name == "atlas_tiles_skipped":
                    tiles_skipped = val
                elif name == "atlas_bytes_full":
                    bytes_full = val
                else:
                    bytes_moved = val

    def save(done: int) -> None:
        if checkpoint_path is None:
            return
        save_null_checkpoint(
            checkpoint_path, deg, done, _KEY_DATA, fp,
            extra={
                "atlas_rows": (
                    np.concatenate(rows_l) if rows_l
                    else np.empty(0, np.int64)
                ),
                "atlas_cols": (
                    np.concatenate(cols_l) if cols_l
                    else np.empty(0, np.int64)
                ),
                "atlas_corr": (
                    np.concatenate(corr_l) if corr_l
                    else np.empty(0, np.float64)
                ),
                "atlas_tiles_dispatched": np.int64(tiles_dispatched),
                "atlas_tiles_skipped": np.int64(tiles_skipped),
                "atlas_bytes_full": np.int64(bytes_full),
                "atlas_bytes_moved": np.int64(bytes_moved),
            },
        )

    z = net.z32()
    if n_pad != n:
        z = np.concatenate(
            [z, np.zeros((n_pad - n, s), dtype=np.float32)]
        )
    z_dev = jnp.asarray(z)
    z_tiles = z_dev.reshape(T, edge, s)

    # compiled-program memo: full strips keyed by τ capacity, worklist
    # groups by (mode, width, capacity) — few distinct shapes per build
    progs: dict = {}
    strip_flat = edge * T * edge
    # τ survivor capacity: starts small, grows (power-of-two) on overflow
    # — a recompile and re-dispatch, never a wrong answer. The full-strip
    # compaction needs the flat index to fit int32; past that the τ path
    # falls back to the PR 9 full-strip transfer.
    tau_cap = [min(strip_flat, 8192)]
    tau_compact_ok = strip_flat < 2 ** 31 - 1

    def get_strip_fn(cap):
        key = ("strip", cap)
        if key not in progs:
            progs[key] = _build_strip_fn(
                edge, T, n, s, net.beta, k_eff, tau32, cap, with_deg,
                mesh, config.mesh_axis,
            )
        return progs[key]

    def get_group_fn(w, cap):
        key = ("group", w, cap)
        if key not in progs:
            progs[key] = _build_group_fn(
                edge, w, n, s, net.beta, k_eff, tau32, cap, mesh,
                config.mesh_axis,
            )
        return progs[key]

    mem = None
    sid = None
    if tel is not None:
        sid = tel.begin_span(
            "tile_pass_start", n=int(n), edge=int(edge), blocks=int(B),
            start_block=int(start_block), samples=int(s), mode=mode,
            screen=bool(screen), supertile=int(S_res),
            degree=bool(with_deg),
        )
        from ..utils.profiling import make_memory_probe

        mem = make_memory_probe()

    def run_dispatch(thunk, b, label):
        if ft is None:
            return thunk()
        return ft.run_dispatch(
            thunk, start=b, take=1, telemetry=tel,
            rescue=lambda: save(done), label=label,
        )

    def grow_cap(cnt):
        cap = tau_cap[0]
        while cap < cnt:
            cap <<= 1
        tau_cap[0] = min(cap, strip_flat)

    def decode_tau(cnt, fidx, rv, w, wids, lo):
        """Map compacted flat survivors back to (row, global col, r) —
        ascending flat order == the host np.nonzero row-major order."""
        f = fidx[:cnt].astype(np.int64)
        row = f // (w * edge)
        rem = f % (w * edge)
        if wids is None:                   # full strip: flat col IS global
            col = rem
        else:
            col = wids[rem // edge].astype(np.int64) * edge + rem % edge
        return lo + row, col, rv[:cnt].astype(np.float64)

    done = start_block
    last_saved = start_block
    t_marks: list[tuple[int, float]] = []
    t0 = time.perf_counter()
    try:
        for b in range(start_block, B):
            row0 = b * edge
            zI = jax.lax.dynamic_slice_in_dim(z_dev, row0, edge, axis=0)
            lo = row0
            hi = min(row0 + edge, n)
            m = hi - lo
            kept = 0
            disp_b = 0
            skip_b = 0
            moved_b = 0
            screen_s = 0.0
            t_b0 = time.perf_counter()

            if not screen:
                # ---- unscreened: one full-strip dispatch ----------------
                if mode == "tau" and tau_compact_ok:
                    while True:
                        cap = tau_cap[0]
                        fn = get_strip_fn(cap)

                        def _dispatch(_fn=fn, _zI=zI, _row0=row0):
                            return jax.block_until_ready(
                                _fn(_zI, z_tiles, jnp.int32(_row0))
                            )

                        out = run_dispatch(_dispatch, b, "tile_strip")
                        out = [np.asarray(a) for a in out]
                        cnt = int(out[1] if with_deg else out[0])
                        if cnt <= cap:
                            break
                        grow_cap(cnt)      # recompile + re-dispatch, rare
                    if with_deg:
                        deg_b, _cnt, fidx, rv = out
                        deg[lo:hi] += (
                            deg_b.astype(np.float64).sum(axis=0)[:m]
                        )
                    else:
                        _cnt, fidx, rv = out
                    br, bc, bv = decode_tau(cnt, fidx, rv, T, None, lo)
                    rows_l.append(br)
                    cols_l.append(bc)
                    corr_l.append(bv)
                    kept = int(cnt)
                    moved_b = sum(a.nbytes for a in out)
                else:
                    fn = get_strip_fn(None)

                    def _dispatch(_fn=fn, _zI=zI, _row0=row0):
                        return jax.block_until_ready(
                            _fn(_zI, z_tiles, jnp.int32(_row0))
                        )

                    out = run_dispatch(_dispatch, b, "tile_strip")
                    out = [np.asarray(a) for a in out]
                    moved_b = sum(a.nbytes for a in out)
                    if with_deg:
                        deg_b = out.pop(0)
                        # cross-tile fold on the host in f64: summation
                        # order is then fixed regardless of how the tile
                        # axis was sharded
                        deg[lo:hi] += (
                            deg_b.astype(np.float64).sum(axis=0)[:m]
                        )
                    if k_eff is not None:
                        idxs, r_sel, score = out
                        keep = score[:m] >= 0  # rows short of k candidates
                        ii, jj = np.nonzero(keep)
                        rows_l.append((lo + ii).astype(np.int64))
                        cols_l.append(idxs[:m][keep].astype(np.int64))
                        corr_l.append(r_sel[:m][keep].astype(np.float64))
                        kept = int(keep.sum())
                    else:
                        (r_strip,) = out
                        sel = np.abs(r_strip[:m]) >= tau32
                        ii, jj = np.nonzero(sel)
                        rows_l.append((lo + ii).astype(np.int64))
                        cols_l.append(jj.astype(np.int64))
                        corr_l.append(r_strip[:m][sel].astype(np.float64))
                        kept = int(sel.sum())
                disp_b = T_real
            else:
                # ---- screened: coarse → refine → worklist dispatch ------
                t_s0 = time.perf_counter()
                mb = M[b]                          # row-block max norms
                cb = np.minimum(MS @ mb, 1.0)      # coarse (per group)
                G = MS.shape[0]
                screen_s += time.perf_counter() - t_s0
                if k_eff is not None:
                    cand_v = np.full((m, k_eff), -1.0, np.float32)
                    cand_c = np.full((m, k_eff), _COL_SENTINEL, np.int64)
                    cand_r = np.zeros((m, k_eff), np.float32)
                    floor = -1.0
                    t_s0 = time.perf_counter()
                    # descending-bound order: signal groups first, so the
                    # per-row floors tighten before noise groups are judged
                    order = np.argsort(-cb, kind="stable")
                    screen_s += time.perf_counter() - t_s0
                    for g in order:
                        t_s0 = time.perf_counter()
                        t0g = int(g) * S_res
                        t1g = min(t0g + S_res, T_real)
                        n_g = t1g - t0g
                        if cb[g] + margin < floor:
                            skip_b += n_g
                            screen_s += time.perf_counter() - t_s0
                            if _screen_observer is not None:
                                _screen_observer(
                                    b, "coarse",
                                    np.arange(t0g, t1g, dtype=np.int64),
                                    float(floor),
                                )
                            continue
                        tb = np.minimum(M[t0g:t1g] @ mb, 1.0)
                        live = (tb + margin) >= floor
                        screen_s += time.perf_counter() - t_s0
                        if not live.all():
                            dropped = t0g + np.flatnonzero(~live)
                            skip_b += int(dropped.size)
                            if _screen_observer is not None:
                                _screen_observer(b, "refine", dropped,
                                                 float(floor))
                        # pending tiles of this group, strongest bound
                        # first: while no floor exists yet, dispatch only
                        # a bootstrap batch (just enough tiles to fill k
                        # candidates per row), so the floor forms before
                        # the bulk of the group is committed
                        t_s0 = time.perf_counter()
                        o = np.argsort(-tb[live], kind="stable")
                        pending = (t0g + np.flatnonzero(live))[o]
                        pbound = tb[live][o]
                        boot = max(1, -(-2 * k_eff // edge))
                        screen_s += time.perf_counter() - t_s0
                        while pending.size:
                            if floor < 0:
                                take = pending[:boot]
                                pending = pending[boot:]
                                pbound = pbound[boot:]
                            else:
                                t_s0 = time.perf_counter()
                                ok = (pbound + margin) >= floor
                                screen_s += time.perf_counter() - t_s0
                                if not ok.all():
                                    dropped = pending[~ok]
                                    skip_b += int(dropped.size)
                                    if _screen_observer is not None:
                                        _screen_observer(
                                            b, "refine", np.sort(dropped),
                                            float(floor),
                                        )
                                take = pending[ok]
                                pending = pending[:0]
                                if take.size == 0:
                                    break
                            # ascending within the dispatch: the group's
                            # top-k tie-breaking (by worklist position)
                            # must match the global column order
                            take = np.sort(take)
                            w = _bucket_width(take.size, ax)
                            wids = np.full(w, -1, np.int32)
                            wids[:take.size] = take
                            fn = get_group_fn(w, None)

                            def _dispatch(_fn=fn, _zI=zI, _w=wids,
                                          _row0=row0):
                                return jax.block_until_ready(
                                    _fn(_zI, z_tiles, jnp.asarray(_w),
                                        jnp.int32(_row0))
                                )

                            out = run_dispatch(_dispatch, b, "tile_group")
                            idxs, r_sel, vals = (np.asarray(a)
                                                 for a in out)
                            moved_b += (idxs.nbytes + r_sel.nbytes
                                        + vals.nbytes)
                            idxs = idxs[:m].astype(np.int64)
                            vals = vals[:m]
                            r_sel = r_sel[:m]
                            cols = (
                                wids[idxs // edge].astype(np.int64) * edge
                                + idxs % edge
                            )
                            bad = vals < 0
                            cols[bad] = _COL_SENTINEL
                            r_sel = np.where(bad, np.float32(0.0), r_sel)
                            cand_v, cand_c, cand_r = _merge_topk(
                                cand_v, cand_c, cand_r, vals, cols, r_sel,
                                k_eff,
                            )
                            # the running floor: weakest k-th-best across
                            # the block's rows (-1 until every row holds k
                            # real candidates — no skipping before that)
                            floor = float(cand_v[:, -1].min())
                            disp_b += int(take.size)
                    keep = cand_v >= 0
                    ii, jj = np.nonzero(keep)
                    rows_l.append((lo + ii).astype(np.int64))
                    cols_l.append(cand_c[keep])
                    corr_l.append(cand_r[keep].astype(np.float64))
                    kept = int(keep.sum())
                else:
                    gr = b // S_res                # row super-group
                    parts_r: list[np.ndarray] = []
                    parts_c: list[np.ndarray] = []
                    parts_v: list[np.ndarray] = []
                    for g in range(G):
                        t_s0 = time.perf_counter()
                        t0g = g * S_res
                        t1g = min(t0g + S_res, T_real)
                        n_g = t1g - t0g
                        # S×S coarse level: the super-row × super-col
                        # bound prunes this whole group for every block
                        # in the row group from one precomputed table
                        if (SB[gr, g] + margin < tau_cmp
                                or cb[g] + margin < tau_cmp):
                            skip_b += n_g
                            screen_s += time.perf_counter() - t_s0
                            if _screen_observer is not None:
                                _screen_observer(
                                    b, "coarse",
                                    np.arange(t0g, t1g, dtype=np.int64),
                                    tau_cmp,
                                )
                            continue
                        tb = np.minimum(M[t0g:t1g] @ mb, 1.0)
                        live = (tb + margin) >= tau_cmp
                        work = t0g + np.flatnonzero(live)
                        screen_s += time.perf_counter() - t_s0
                        if work.size < n_g:
                            dropped = t0g + np.flatnonzero(~live)
                            skip_b += int(dropped.size)
                            if _screen_observer is not None:
                                _screen_observer(b, "refine", dropped,
                                                 tau_cmp)
                        if work.size == 0:
                            continue
                        w = _bucket_width(work.size, ax)
                        wids = np.full(w, -1, np.int32)
                        wids[:work.size] = work
                        while True:
                            cap = min(tau_cap[0], edge * w * edge)
                            fn = get_group_fn(w, cap)

                            def _dispatch(_fn=fn, _zI=zI, _w=wids,
                                          _row0=row0):
                                return jax.block_until_ready(
                                    _fn(_zI, z_tiles, jnp.asarray(_w),
                                        jnp.int32(_row0))
                                )

                            out = run_dispatch(_dispatch, b, "tile_group")
                            cnt_a, fidx, rv = (np.asarray(a) for a in out)
                            cnt = int(cnt_a)
                            if cnt <= cap:
                                break
                            grow_cap(cnt)
                        moved_b += cnt_a.nbytes + fidx.nbytes + rv.nbytes
                        br, bc, bv = decode_tau(cnt, fidx, rv, w, wids, lo)
                        parts_r.append(br)
                        parts_c.append(bc)
                        parts_v.append(bv)
                        disp_b += int(work.size)
                    if parts_r:
                        br = np.concatenate(parts_r)
                        bc = np.concatenate(parts_c)
                        bv = np.concatenate(parts_v)
                        # groups dispatched out of column order reassemble
                        # into the unscreened (row-major) emit order
                        o = np.lexsort((bc, br))
                        rows_l.append(br[o])
                        cols_l.append(bc[o])
                        corr_l.append(bv[o])
                        kept = int(br.size)
                if tel is not None:
                    tel.emit(
                        "tile_screen", parent=sid, block=int(b),
                        s=screen_s, tiles_skipped=int(skip_b),
                        tiles_dispatched=int(disp_b),
                        floor=(float(floor) if k_eff is not None
                               else tau_cmp),
                    )

            tiles_dispatched += disp_b
            tiles_skipped += skip_b
            bytes_full += m * T_real * edge * 4
            bytes_moved += moved_b
            done = b + 1
            t_marks.append((done, time.perf_counter()))
            if tel is not None:
                tel.emit(
                    "tile", parent=sid, block=int(b), blocks=int(B),
                    s=t_marks[-1][1] - t_b0, edges_kept=kept,
                    tiles_dispatched=int(disp_b),
                    tiles_skipped=int(skip_b),
                    **(mem() if mem is not None else {}),
                )
            if progress is not None:
                progress(done, B)
            if checkpoint_path is not None and done - last_saved >= checkpoint_every:
                save(done)
                last_saved = done
    except BaseException:
        # failure-save (KeyboardInterrupt and the fault ladder's terminal
        # errors alike): completed row blocks must never be re-scanned
        if done > last_saved:
            save(done)
        if tel is not None:
            tel.end_span(
                sid, "tile_pass_end", blocks_done=int(done),
                blocks=int(B), interrupted=True,
                s=time.perf_counter() - t0,
            )
            if tel_owned:
                tel.close()
        raise
    if checkpoint_path is not None and done > last_saved:
        save(done)

    rows = np.concatenate(rows_l) if rows_l else np.empty(0, np.int64)
    cols = np.concatenate(cols_l) if cols_l else np.empty(0, np.int64)
    corr = np.concatenate(corr_l) if corr_l else np.empty(0, np.float64)
    wgt = derived_net_np(corr, net.beta)
    adjacency = SparseAdjacency.from_coo(rows, cols, wgt, n, symmetrize=True)
    correlation = SparseAdjacency.from_coo(
        rows, cols, corr, n, symmetrize=True
    )
    tiles_total = B * T_real
    if tel is not None:
        tel.end_span(
            sid, "tile_pass_end", blocks_done=int(done), blocks=int(B),
            interrupted=False, edges=int(rows.size),
            nnz=int(adjacency.nnz), s=time.perf_counter() - t0,
            tiles_total=int(tiles_total),
            tiles_dispatched=int(tiles_dispatched),
            tiles_skipped=int(tiles_skipped),
            skip_fraction=round(tiles_skipped / max(1, tiles_total), 6),
            nxn_bytes_avoided=int(tiles_skipped) * edge * edge * 4,
            strip_bytes_full=int(bytes_full),
            strip_bytes_moved=int(bytes_moved),
        )
        if tel_owned:
            tel.close()
    if len(t_marks) >= 2:
        # steady-state gene rows/s (first block's interval absorbs the jit
        # compile, same convention as the null loops)
        (b0, tm0), (b1, tm1) = t_marks[0], t_marks[-1]
        if tm1 > tm0 and b1 > b0:
            cps = (b1 - b0) * edge / (tm1 - tm0)
            if at_cache is not None:
                at_cache.record(at_key, edge, cps)
            if st_cache is not None:
                st_cache.record(st_key, S_res, cps)
    return AtlasBuild(
        adjacency=adjacency, correlation=correlation,
        degree=deg if with_deg else None, n=n,
        tile_edge=edge, n_blocks=B, selected_edges=int(rows.size),
        supertile=int(S_res), tiles_total=int(tiles_total),
        tiles_dispatched=int(tiles_dispatched),
        tiles_skipped=int(tiles_skipped),
        strip_bytes_full=int(bytes_full),
        strip_bytes_moved=int(bytes_moved),
    )
