"""Repo-native static analysis (ISSUE 12): the invariant linter.

The contracts that make exact-reproducibility hold at fleet scale — the
``fold_in(key, i)`` RNG discipline, no buffer donation into Pallas call
paths, the fault-taxonomy line that bugs never silently retry, the pinned
telemetry event schema, the ``x_`` checkpoint-extras namespace, and lock
discipline across thread seams — are encoded as AST rules
(:mod:`netrep_tpu.analysis.rules`) and enforced by a walker with inline,
reasoned, counted suppressions (:mod:`netrep_tpu.analysis.linter`).

Run it: ``python -m netrep_tpu lint [--json] [--rule NAME] [paths...]``
(exit 2 on findings). The tier-1 gate ``tests/test_lint.py`` asserts the
package itself lints clean, so every new violation must be fixed or
justified in the same commit that introduces it.
"""

from .linter import (  # noqa: F401
    LINT_SCHEMA, LintReport, lint_paths, lint_source,
)
from .rules import Finding, Module, default_rules  # noqa: F401

__all__ = [
    "LINT_SCHEMA",
    "LintReport",
    "Finding",
    "Module",
    "default_rules",
    "lint_paths",
    "lint_source",
]
