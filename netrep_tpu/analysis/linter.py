"""The invariant-linter walker and CLI (ISSUE 12).

Walks a tree of Python sources, runs every rule in
:mod:`netrep_tpu.analysis.rules` over each parsed module, applies inline
suppressions, and renders a human report or one machine JSON line.

Suppression grammar (one comment, same line as the finding or the line
directly above it)::

    # netrep: allow(<rule>[, <rule>...]) — <reason>

The separator may be an em dash, ``--``, or ``:``; the reason is
REQUIRED — a suppression without one is itself a finding
(``suppression-syntax``, not suppressible) because an unexplained
exception is indistinguishable from a silenced bug. Honored suppressions
are counted and reported; suppressions that match no finding are listed
as stale (informational — they do not fail the lint, so a fixed
violation does not force a lockstep comment removal, but the report
keeps them visible until someone does).

Exit codes: 0 clean, 2 unsuppressed findings — the shape ``perf --check``
already uses, so CI and ``tpu_watch.sh`` treat both gates alike.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import re
import sys
import tokenize

from .rules import Finding, Module, default_rules

#: version of the ``--json`` output shape (``summarize_watch.py`` keys on
#: ``lint_v`` to classify the line)
LINT_SCHEMA = 1

#: the meta-rule name for malformed suppressions; never suppressible
SYNTAX_RULE = "suppression-syntax"

_ALLOW_RE = re.compile(
    r"#\s*netrep:\s*allow\(\s*([A-Za-z0-9_,\s-]*?)\s*\)\s*"
    r"(?:—|--|:)?\s*(.*?)\s*$"
)


@dataclasses.dataclass
class Suppression:
    """One parsed ``# netrep: allow(...)`` comment."""

    path: str
    line: int
    rules: tuple[str, ...]
    reason: str
    used: int = 0


def _comments(source: str) -> list[tuple[int, str]]:
    """(line, text) of every COMMENT token — tokenize, not line-scanning,
    so a docstring DESCRIBING the suppression grammar is not parsed as a
    suppression (the linter's own docs would otherwise self-flag)."""
    out: list[tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenizeError, IndentationError, SyntaxError):
        pass  # the AST parse already reported the file as broken
    return out


def parse_suppressions(path: str,
                       source: str) -> tuple[list[Suppression],
                                             list[Finding]]:
    """Scan comment tokens for allow-comments; malformed ones (no reason,
    or an empty rule list) come back as ``suppression-syntax`` findings."""
    sups: list[Suppression] = []
    bad: list[Finding] = []
    for i, text in _comments(source):
        m = _ALLOW_RE.search(text)
        if not m:
            if "netrep: allow" in text:
                bad.append(Finding(
                    SYNTAX_RULE, path, i,
                    "unparseable suppression — the grammar is "
                    "'# netrep: allow(<rule>) — <reason>'",
                ))
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = m.group(2)
        if not rules:
            bad.append(Finding(
                SYNTAX_RULE, path, i,
                "suppression names no rule — use "
                "'# netrep: allow(<rule>) — <reason>'",
            ))
            continue
        if not reason:
            bad.append(Finding(
                SYNTAX_RULE, path, i,
                f"suppression for {', '.join(rules)} carries no reason — "
                "an unexplained exception is indistinguishable from a "
                "silenced bug",
            ))
            continue
        sups.append(Suppression(path, i, rules, reason))
    return sups, bad


def _apply_suppressions(findings: list[Finding],
                        sups: list[Suppression]
                        ) -> tuple[list[Finding], list[Finding]]:
    """Split findings into (kept, suppressed); same line or line above."""
    by_pos: dict[tuple[int, str], Suppression] = {}
    for s in sups:
        for r in s.rules:
            by_pos[(s.line, r)] = s
    kept, suppressed = [], []
    for f in findings:
        if f.rule == SYNTAX_RULE:
            kept.append(f)
            continue
        s = by_pos.get((f.line, f.rule)) or by_pos.get((f.line - 1, f.rule))
        if s is not None:
            s.used += 1
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed


@dataclasses.dataclass
class LintReport:
    """Everything one lint run produced, pre-rendering."""

    findings: list[Finding]
    suppressed: list[Finding]
    suppressions: list[Suppression]
    files: int
    rules: tuple[str, ...]
    parse_errors: list[Finding]

    @property
    def ok(self) -> bool:
        return not (self.findings or self.parse_errors)

    @property
    def stale(self) -> list[Suppression]:
        """Unused suppressions whose rules were all ACTIVE this run — a
        ``--rule``-filtered run must not report the other rules'
        suppressions as stale."""
        active = set(self.rules)
        return [s for s in self.suppressions
                if s.used == 0 and set(s.rules) <= active]

    def to_json(self) -> dict:
        return {
            "lint_v": LINT_SCHEMA,
            "ok": self.ok,
            "files": self.files,
            "rules": list(self.rules),
            "findings": [dataclasses.asdict(f)
                         for f in self.findings + self.parse_errors],
            "suppressed": [dataclasses.asdict(f) for f in self.suppressed],
            "suppressions": [dataclasses.asdict(s)
                             for s in self.suppressions],
            "stale_suppressions": [dataclasses.asdict(s)
                                   for s in self.stale],
        }

    def render(self) -> str:
        lines = []
        for f in sorted(self.findings + self.parse_errors,
                        key=lambda f: (f.path, f.line)):
            lines.append(f.render())
        per_rule: dict[str, int] = {}
        for f in self.suppressed:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        tally = ", ".join(f"{r}: {n}" for r, n in sorted(per_rule.items()))
        lines.append(
            f"{len(self.findings) + len(self.parse_errors)} finding(s) "
            f"over {self.files} file(s), {len(self.suppressed)} "
            f"suppressed ({tally or 'none'})"
        )
        for s in self.stale:
            lines.append(
                f"{s.path}:{s.line}: stale suppression for "
                f"{', '.join(s.rules)} (matched no finding)"
            )
        return "\n".join(lines)


def _iter_sources(paths: list[str]):
    for root in paths:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def package_root() -> str:
    """The installed ``netrep_tpu`` package directory — the default (and
    tier-1-gated) lint target."""
    import netrep_tpu

    return os.path.dirname(os.path.abspath(netrep_tpu.__file__))


def lint_paths(paths: list[str] | None = None,
               rules=None,
               rule_names: list[str] | None = None) -> LintReport:
    """Lint files/trees and return the :class:`LintReport`.

    ``paths`` defaults to the package itself. ``rule_names`` filters the
    active set (the CLI's ``--rule``)."""
    if rules is None:
        rules = default_rules()
    if rule_names:
        known = {r.name for r in rules}
        unknown = set(rule_names) - known
        if unknown:
            raise ValueError(
                f"unknown rule(s) {sorted(unknown)}; "
                f"available: {sorted(known)}"
            )
        rules = [r for r in rules if r.name in rule_names]
    pkg = package_root()
    roots = [pkg] if paths is None else paths
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    suppressions: list[Suppression] = []
    parse_errors: list[Finding] = []
    files = 0
    for path in _iter_sources(roots):
        files += 1
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            parse_errors.append(Finding(
                "parse-error", path, 0, f"unreadable: {e}"))
            continue
        rel = os.path.relpath(os.path.abspath(path), pkg)
        pkg_rel = None if rel.startswith("..") else rel
        try:
            mod = Module(path, source, pkg_rel=pkg_rel)
        except SyntaxError as e:
            parse_errors.append(Finding(
                "parse-error", path, e.lineno or 0, f"syntax error: {e.msg}"))
            continue
        sups, bad = parse_suppressions(path, source)
        raw: list[Finding] = list(bad)
        for rule in rules:
            raw.extend(rule.check(mod))
        kept, supd = _apply_suppressions(raw, sups)
        findings.extend(kept)
        suppressed.extend(supd)
        suppressions.extend(sups)
    return LintReport(
        findings=findings, suppressed=suppressed,
        suppressions=suppressions, files=files,
        rules=tuple(r.name for r in rules), parse_errors=parse_errors,
    )


def lint_source(source: str, path: str = "<fixture>.py",
                rules=None, rule_names: list[str] | None = None
                ) -> LintReport:
    """Lint one in-memory source string — the fixture entry point
    ``tests/test_lint.py`` drives every rule through."""
    if rules is None:
        rules = default_rules()
    if rule_names:
        rules = [r for r in rules if r.name in rule_names]
    mod = Module(path, source, pkg_rel=None)
    sups, bad = parse_suppressions(path, source)
    raw: list[Finding] = list(bad)
    for rule in rules:
        raw.extend(rule.check(mod))
    kept, supd = _apply_suppressions(raw, sups)
    return LintReport(
        findings=kept, suppressed=supd, suppressions=sups, files=1,
        rules=tuple(r.name for r in rules), parse_errors=[],
    )


def main_lint(args) -> int:
    """The ``python -m netrep_tpu lint`` entry point (argparse namespace
    with ``json``, ``rule``, ``paths``)."""
    try:
        report = lint_paths(
            paths=args.paths or None,
            rule_names=args.rule or None,
        )
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report.to_json()))
    else:
        print(report.render())
    return 0 if report.ok else 2
