"""The invariant rule catalogue (ISSUE 12).

Every contract that makes the repo's bit-identity story hold — the
``fold_in(key, i)`` RNG discipline, no buffer donation into Pallas call
paths, the fault-taxonomy rule that bugs never silently retry, the pinned
telemetry schema, the ``x_`` checkpoint-extras namespace, and lock
discipline around cross-thread state — lived in CHANGES.md prose and
whichever test happened to exercise it. This module encodes each as an
AST-level :class:`Rule` so ``python -m netrep_tpu lint`` machine-checks
them on every commit (the PR 8 alias-unsafe donation bug and the ADVICE r5
tolerance-tier hole are both instances a rule here would have caught).

A rule is any object with ``name``, ``description``, and
``check(module) -> list[Finding]``; :data:`RULES` is the active set the
walker (:mod:`netrep_tpu.analysis.linter`) runs. Rules must be pure
functions of the parsed source — no imports of the module under analysis,
no execution — so the linter is safe on broken/unimportable files and
fast enough for every watch cycle.

Suppressions: a finding on line *L* is silenced by a comment on *L* or
*L-1* of the form ``# netrep: allow(<rule>) — <reason>`` (see
:mod:`netrep_tpu.analysis.linter` for the grammar). Suppressions are
counted and reported — a justified exception is still an exception.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterable, Iterator, Protocol


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a file/line."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Module:
    """A parsed source file plus the cheap derived views rules share:
    the import alias map, the source lines, and the path's position
    relative to the package root (``pkg_rel`` is ``None`` for files
    outside ``netrep_tpu/`` — rule scoping treats those as always in
    scope, so test fixtures exercise every rule without path games)."""

    def __init__(self, path: str, source: str, pkg_rel: str | None = None):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.pkg_rel = pkg_rel
        self.aliases = _import_aliases(self.tree)

    def in_scope(self, top_dirs: tuple[str, ...]) -> bool:
        """True when this module falls under one of the package's
        ``top_dirs`` subpackages — or is not a package file at all
        (fixtures are always in scope)."""
        if self.pkg_rel is None:
            return True
        head = self.pkg_rel.replace("\\", "/").split("/", 1)[0]
        return head in top_dirs

    def dotted(self, node: ast.AST) -> str | None:
        """Resolve an attribute chain (``np.random.default_rng``) to its
        canonical dotted name (``numpy.random.default_rng``) using the
        module's import aliases; ``None`` for non-name expressions."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        root = self.aliases.get(parts[0])
        if root is not None:
            parts[0:1] = root.split(".")
        return ".".join(parts)


def _import_aliases(tree: ast.Module) -> dict[str, str]:
    """Name → dotted-module map from every import statement in the file
    (function-level imports included — the repo defers heavy imports)."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _imported_modules(tree: ast.Module) -> set[str]:
    """Every module path named by an import statement (including relative
    ``from ..ops import fused_stats`` → ``..ops.fused_stats``)."""
    mods: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            mods.update(a.name for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            base = ("." * node.level) + (node.module or "")
            mods.add(base)
            mods.update(f"{base}.{a.name}" for a in node.names)
    return mods


def _body_calls(body: Iterable[ast.AST]) -> Iterator[ast.AST]:
    """Walk statement bodies without descending into nested function or
    class definitions (their contracts are checked at their own site)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class Rule(Protocol):
    """The rule protocol: a name (the suppression/selection key), a
    one-line description for the catalogue, and a pure AST check."""

    name: str
    description: str

    def check(self, mod: Module) -> list[Finding]:  # pragma: no cover
        ...


# ---------------------------------------------------------------------------
# 1. rng-discipline
# ---------------------------------------------------------------------------

class RngDiscipline:
    """Inside the null-path subpackages (``parallel/``, ``ops/``,
    ``atlas/``) the ONLY legal randomness is a stream derived from the
    run key via ``jax.random.fold_in(key, i)`` — that contract is what
    makes results independent of chunk size, mesh shape, resume point,
    and serve packing. Creating fresh keys (``jax.random.key`` /
    ``PRNGKey`` / ``split``), host RNGs (``np.random.*`` /  stdlib
    ``random.*``), or wall-clock entropy (``time.time``) on a null path
    silently breaks bit-identity; sanctioned sites (the root-key
    constructor, cache-busting index draws) carry a reasoned
    suppression."""

    name = "rng-discipline"
    description = ("null-path modules may only use fold_in-derived RNG "
                   "streams (no key creation/split, np.random, stdlib "
                   "random, or time.time)")

    SCOPE = ("parallel", "ops", "atlas")
    #: jax.random members that CONSUME an existing key (legal) rather
    #: than create or fork one (illegal on null paths)
    ALLOWED_JAX_RANDOM = frozenset(
        {"fold_in", "permutation", "key_data", "wrap_key_data"}
    )

    def check(self, mod: Module) -> list[Finding]:
        if not mod.in_scope(self.SCOPE):
            return []
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = mod.dotted(node.func)
            if d is None:
                continue
            msg = None
            if d.startswith("jax.random."):
                tail = d.rsplit(".", 1)[1]
                if tail not in self.ALLOWED_JAX_RANDOM:
                    msg = (f"{d}() creates/forks a PRNG stream on a null "
                           "path — only fold_in-derived streams keep "
                           "results chunk/mesh/resume-independent")
            elif d.startswith("numpy.random."):
                msg = (f"{d}() is host randomness on a null path — "
                       "results must derive from fold_in(key, i) only")
            elif d == "time.time":
                msg = ("time.time() is wall-clock entropy on a null path "
                       "— use deterministic inputs (perf_counter/"
                       "monotonic are fine for telemetry durations)")
            elif d.startswith("random.") and mod.aliases.get(
                    "random") == "random":
                msg = (f"stdlib {d}() on a null path — only "
                       "fold_in-derived jax.random streams are legal")
            if msg is not None:
                out.append(Finding(self.name, mod.path, node.lineno, msg))
        return out


# ---------------------------------------------------------------------------
# 2. donation-alias
# ---------------------------------------------------------------------------

class DonationAlias:
    """The PR 8 bug class: donating a buffer (``donate_argnums``) into a
    jitted program whose call path reaches a Pallas kernel aliases input
    and output under interpret mode — the kernel reads rows its own
    output already overwrote. The repo's convention is a mode-gated
    variable (``donate = () if stat_mode == 'fused' else (0,)``); an
    UNCONDITIONAL literal donation in any module that imports Pallas or
    the fused kernels is exactly the latent form of that bug."""

    name = "donation-alias"
    description = ("no unconditional literal donate_argnums in modules "
                   "that reach Pallas kernels — donation must be "
                   "mode-gated off the fused/interpret path")

    PALLAS_MARKERS = ("pallas", "fused_stats", "fused_gather")

    def _touches_pallas(self, mod: Module) -> bool:
        return any(
            marker in imported
            for imported in _imported_modules(mod.tree)
            for marker in self.PALLAS_MARKERS
        )

    def check(self, mod: Module) -> list[Finding]:
        if not self._touches_pallas(mod):
            return []
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg not in ("donate_argnums", "donate_argnames"):
                    continue
                v = kw.value
                nonempty_literal = (
                    (isinstance(v, ast.Constant)
                     and not (v.value in ((), None) or v.value == ()))
                    or (isinstance(v, (ast.Tuple, ast.List)) and v.elts)
                )
                if nonempty_literal:
                    out.append(Finding(
                        self.name, mod.path, kw.value.lineno,
                        f"literal {kw.arg} in a Pallas-reaching module "
                        "donates unconditionally — interpret-mode "
                        "kernels alias donated buffers (PR 8 bug class); "
                        "gate it off the fused path via a variable",
                    ))
        return out


# ---------------------------------------------------------------------------
# 3. exception-taxonomy
# ---------------------------------------------------------------------------

class ExceptionTaxonomy:
    """The fault taxonomy (``utils/faults.py``) draws one line: transient
    device faults retry, BUGS NEVER SILENTLY RETRY (or vanish). A bare
    ``except:`` / ``except Exception`` / ``except BaseException`` that
    swallows is where a bug becomes a silent wrong answer. Every broad
    handler must re-raise (any ``raise`` in the handler), route through
    ``faults.classify_error``, or carry a reasoned suppression naming why
    swallowing is the contract at that site (observer code that must
    never kill the run, import-time optional dependencies)."""

    name = "exception-taxonomy"
    description = ("broad except handlers must re-raise, classify via "
                   "faults.classify_error, or carry a justification "
                   "suppression")

    BROAD = frozenset({"Exception", "BaseException"})

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        names = t.elts if isinstance(t, ast.Tuple) else [t]
        return any(isinstance(n, ast.Name) and n.id in self.BROAD
                   for n in names)

    def check(self, mod: Module) -> list[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node):
                continue
            handled = False
            for sub in _body_calls(node.body):
                if isinstance(sub, ast.Raise):
                    handled = True
                    break
                if (isinstance(sub, ast.Call)
                        and (mod.dotted(sub.func) or "").rsplit(
                            ".", 1)[-1] == "classify_error"):
                    handled = True
                    break
            if not handled:
                caught = ("bare except" if node.type is None
                          else f"except {ast.unparse(node.type)}")
                out.append(Finding(
                    self.name, mod.path, node.lineno,
                    f"{caught} swallows without re-raising or "
                    "classify_error — bugs must never silently retry or "
                    "vanish (faults.py taxonomy); narrow the type, "
                    "re-raise, or justify with a suppression",
                ))
        return out


# ---------------------------------------------------------------------------
# 4. telemetry-registry
# ---------------------------------------------------------------------------

class TelemetryRegistry:
    """Every literal event name passed to ``emit()`` / ``begin_span()`` /
    ``span()`` / ``end_span()`` must belong to the pinned registries in
    ``utils/telemetry.py`` (``ENGINE_EVENTS`` / ``RECOVERY_EVENTS`` /
    ``SERVE_EVENTS`` / ``SPAN_EVENTS``). Dashboards, ``summarize_watch``
    and the CLI report key on these names — an unregistered emit is
    schema drift that no test notices until a dashboard goes dark
    (``request_requeued`` shipped exactly that way in PR 10)."""

    name = "telemetry-registry"
    description = ("literal event names in emit()/begin_span()/span()/"
                   "end_span() must be members of the pinned telemetry "
                   "registries")

    def __init__(self, known: frozenset[str] | None = None):
        if known is None:
            from ..utils.telemetry import KNOWN_EVENTS

            known = KNOWN_EVENTS
        self.known = known

    def check(self, mod: Module) -> list[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in ("emit", "begin_span", "span"):
                pos = 0
            elif attr == "end_span":
                pos = 1  # end_span(span_id, ev, ...)
            else:
                continue
            if len(node.args) <= pos:
                continue
            arg = node.args[pos]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue  # dynamic names are the caller's responsibility
            if arg.value not in self.known:
                out.append(Finding(
                    self.name, mod.path, arg.lineno,
                    f"event name {arg.value!r} is not in any pinned "
                    "telemetry registry (ENGINE/RECOVERY/SERVE/"
                    "SPAN_EVENTS) — register it or the schema drifts "
                    "silently under every dashboard keyed on it",
                ))
        return out


# ---------------------------------------------------------------------------
# 4b. span-pairing
# ---------------------------------------------------------------------------

class SpanPairing:
    """Loop-shaped spans (``begin_span``/``end_span``) are the one place
    the trace tree can leak: a ``begin_span`` whose ``end_span`` never
    ships renders every later event under a span that never closes, and
    the time-split/Perfetto exports mis-nest silently (context-manager
    ``span()`` cannot leak — the ``with`` closes it). The contract: a
    function that calls ``begin_span`` must also contain the matching
    ``end_span``; when the span id is handed off through a ``self``
    attribute (the server-lifetime ``serve_start`` span, opened in
    ``__init__`` and closed in ``close()``), the ``end_span`` may live in
    any method of the same class. This is deliberately presence-based,
    not full path analysis: the crash path MAY skip the end (begin-only
    spans render zero-width by design — a crashed run must still
    export); what it catches is the end never being written at all."""

    name = "span-pairing"
    description = ("every begin_span() needs its end_span in the same "
                   "function (or, for self-attribute span ids, in the "
                   "same class)")

    @staticmethod
    def _calls(fn: ast.FunctionDef, attr: str) -> list[ast.Call]:
        out = []
        for node in _body_calls(fn.body):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == attr):
                out.append(node)
        return out

    @staticmethod
    def _assigns_to_self(fn: ast.FunctionDef, call: ast.Call) -> bool:
        """Whether the begin_span result is stored on ``self`` (the
        cross-method handoff shape: ``self._sid = tel.begin_span(...)``,
        possibly behind a conditional)."""
        for node in _body_calls(fn.body):
            if not isinstance(node, ast.Assign):
                continue
            if node.value is not call:
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    return True
        return False

    def check(self, mod: Module) -> list[Finding]:
        out = []
        # class context per function: a self-attribute handoff may close
        # in any sibling method
        class_of: dict[ast.FunctionDef, ast.ClassDef | None] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        class_of[item] = node
        for fn in [n for n in ast.walk(mod.tree)
                   if isinstance(n, ast.FunctionDef)]:
            begins = self._calls(fn, "begin_span")
            if not begins:
                continue
            if self._calls(fn, "end_span"):
                continue
            cls = class_of.get(fn)
            for call in begins:
                if cls is not None and self._assigns_to_self(fn, call):
                    closed = any(
                        isinstance(m, ast.FunctionDef)
                        and self._calls(m, "end_span")
                        for m in cls.body
                    )
                    if closed:
                        continue
                ev = ""
                if call.args and isinstance(call.args[0], ast.Constant):
                    ev = f" ({call.args[0].value!r})"
                out.append(Finding(
                    self.name, mod.path, call.lineno,
                    f"begin_span{ev} has no matching end_span in "
                    f"{fn.name}()"
                    + (" or its class" if cls is not None else "")
                    + " — the span never closes and every later event "
                      "mis-nests under it; emit the end (crash paths may "
                      "skip it at runtime) or justify with a suppression",
                ))
        return out


# ---------------------------------------------------------------------------
# 5. checkpoint-extras-namespace
# ---------------------------------------------------------------------------

class CheckpointExtrasNamespace:
    """Checkpoint auxiliary state rides ``save_null_checkpoint(...,
    extra={...})`` and is serialized under an ``x_`` prefix so plain
    resumes ignore it. Caller-side literal keys must therefore be bare
    (the writer prefixes; an ``x_``-prefixed key would double-prefix and
    orphan the state on resume) and must not shadow the reserved
    top-level npz names. The second half of the contract: compiled-
    program identity — any ``autotune_key()`` method must consult every
    field that changes the compiled program (gather mode, stat mode,
    effective chunk, bucket signature, data-only derivation), otherwise
    two different programs share one autotune/perf-ledger fingerprint
    and the regression gate compares apples to oranges."""

    name = "checkpoint-extras-namespace"
    description = ("checkpoint extra= keys must be bare identifiers "
                   "(writer adds the x_ prefix) outside the reserved "
                   "set; autotune_key() must consult every compiled-"
                   "program-identity field")

    RESERVED = frozenset(
        {"version", "nulls", "completed", "key_data", "fingerprint"}
    )
    #: EngineConfig/engine fields that select a distinct compiled program
    FINGERPRINT_FIELDS = ("gather_mode", "stat_mode", "effective_chunk",
                          "buckets", "data_only")

    def check(self, mod: Module) -> list[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                d = mod.dotted(node.func) or ""
                if d.rsplit(".", 1)[-1] != "save_null_checkpoint":
                    continue
                for kw in node.keywords:
                    if kw.arg != "extra" or not isinstance(kw.value,
                                                          ast.Dict):
                        continue
                    for k in kw.value.keys:
                        if not (isinstance(k, ast.Constant)
                                and isinstance(k.value, str)):
                            continue
                        key = k.value
                        bad = None
                        if key.startswith("x_"):
                            bad = ("already x_-prefixed — the writer "
                                   "prefixes again and the resume path "
                                   "never finds it")
                        elif key in self.RESERVED:
                            bad = ("shadows a reserved checkpoint field "
                                   "after prefixing conventions change")
                        elif not key.isidentifier():
                            bad = "not a bare identifier"
                        if bad:
                            out.append(Finding(
                                self.name, mod.path, k.lineno,
                                f"checkpoint extra key {key!r} {bad}",
                            ))
            elif (isinstance(node, ast.ClassDef)):
                for item in node.body:
                    if (isinstance(item, ast.FunctionDef)
                            and item.name == "autotune_key"):
                        out.extend(self._check_autotune_key(mod, item))
        return out

    def _check_autotune_key(self, mod: Module,
                            fn: ast.FunctionDef) -> list[Finding]:
        seen: set[str] = set()
        for node in ast.walk(fn):
            # delegation (super().autotune_key(...) / base.autotune_key)
            # inherits the delegate's field coverage — checked at ITS site
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "autotune_key"):
                return []
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                seen.add(node.attr)
            # getattr(self, "field", default) counts as consulting it
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "getattr"
                    and len(node.args) >= 2
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id == "self"
                    and isinstance(node.args[1], ast.Constant)):
                seen.add(str(node.args[1].value))
        missing = [f for f in self.FINGERPRINT_FIELDS if f not in seen]
        if not missing:
            return []
        return [Finding(
            self.name, mod.path, fn.lineno,
            "autotune_key() does not consult compiled-program-identity "
            f"field(s) {missing} — distinct programs would share one "
            "autotune/perf-ledger fingerprint",
        )]


# ---------------------------------------------------------------------------
# 6. thread-shared-state
# ---------------------------------------------------------------------------

class ThreadSharedState:
    """Lock discipline over the scheduler/journal/pool/telemetry/
    checkpoint-writer thread seams: in any class that launches a
    ``threading.Thread`` at one of its own methods, a ``self._*``
    attribute written on one side of the thread boundary and touched on
    the other must only ever be accessed under that class's lock or
    condition (``with self._lock:`` / ``with self._cond:``), inside a
    ``*_locked``-suffixed method (the repo's caller-holds-the-lock
    convention), or carry a reasoned suppression. Synchronization
    primitives themselves (locks, conditions, events, thread handles)
    are exempt — they are their own synchronization. ``__init__`` is
    pre-thread and exempt."""

    name = "thread-shared-state"
    description = ("cross-thread self._* state in thread-launching "
                   "classes must be accessed under the class lock/"
                   "condition (or in *_locked methods)")

    SYNC_CTORS = frozenset({"Lock", "RLock", "Condition", "Event",
                            "Semaphore", "BoundedSemaphore", "Barrier",
                            "Thread", "local"})
    _GUARD_NAME = re.compile(r"lock|cond|mutex")

    def check(self, mod: Module) -> list[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(mod, node))
        return out

    # -- helpers ----------------------------------------------------------

    def _sync_attrs(self, cls: ast.ClassDef) -> set[str]:
        """Attributes holding synchronization primitives / thread
        handles — exempt from the guard requirement, and (for locks and
        conditions) the guards themselves."""
        sync: set[str] = set()
        for node in ast.walk(cls):
            target = None
            value: ast.AST | None = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                target, value = node.target, node.annotation
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                # dataclass field declaration: name: threading.Event = ...
                if (isinstance(node, ast.AnnAssign)
                        and isinstance(node.target, ast.Name)
                        and self._is_sync_expr(node.annotation)):
                    sync.add(node.target.id)
                continue
            if value is not None and self._is_sync_expr(value):
                sync.add(target.attr)
        return sync

    def _is_sync_expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            node = node.func
        if isinstance(node, ast.Attribute):
            return node.attr in self.SYNC_CTORS
        if isinstance(node, ast.Name):
            return node.id in self.SYNC_CTORS
        if isinstance(node, (ast.BinOp, ast.Subscript, ast.Constant)):
            # annotations like "threading.Thread | None"
            return any(self._is_sync_expr(c)
                       for c in ast.iter_child_nodes(node))
        return False

    def _thread_targets(self, cls: ast.ClassDef) -> set[str]:
        """Names of methods that RUN on a spawned thread: the methods
        launched as Thread targets from within the class
        (``threading.Thread(target=self._loop, ...)``) plus the
        transitive closure of ``self.method()`` calls from them — a
        helper invoked by the worker loop executes on the worker thread
        even though no Thread names it."""
        roots: set[str] = set()
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Call)
                    and self._is_thread_ctor(node.func)):
                continue
            for kw in node.keywords:
                if (kw.arg == "target"
                        and isinstance(kw.value, ast.Attribute)
                        and isinstance(kw.value.value, ast.Name)
                        and kw.value.value.id == "self"):
                    roots.add(kw.value.attr)
        if not roots:
            return roots
        calls: dict[str, set[str]] = {}
        for m in cls.body:
            if not isinstance(m, ast.FunctionDef):
                continue
            callees: set[str] = set()
            for node in ast.walk(m):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"):
                    callees.add(node.func.attr)
            calls[m.name] = callees
        closed, frontier = set(roots), list(roots)
        while frontier:
            for callee in calls.get(frontier.pop(), ()):
                if callee in calls and callee not in closed:
                    closed.add(callee)
                    frontier.append(callee)
        return closed

    @staticmethod
    def _is_thread_ctor(func: ast.AST) -> bool:
        return ((isinstance(func, ast.Attribute) and func.attr == "Thread")
                or (isinstance(func, ast.Name) and func.id == "Thread"))

    def _is_guard(self, expr: ast.AST, sync: set[str]) -> bool:
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self":
            return (expr.attr in sync
                    or bool(self._GUARD_NAME.search(expr.attr)))
        return False

    def _accesses(self, method: ast.FunctionDef, sync: set[str]):
        """Yield ``(attr, line, is_write, guarded)`` for every
        ``self._*`` access in the method, tracking ``with self._lock:``
        nesting (no descent into nested functions — closures run on
        whatever thread calls them, checked at their own site if they
        are methods)."""
        guarded_always = method.name.endswith("_locked")

        def walk(node: ast.AST, depth: int):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return
            inner = depth
            if isinstance(node, ast.With):
                if any(self._is_guard(item.context_expr, sync)
                       for item in node.items):
                    inner += 1
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr.startswith("_")
                    and node.attr not in sync):
                yield (node.attr, node.lineno,
                       isinstance(node.ctx, (ast.Store, ast.Del)),
                       guarded_always or inner > 0)
            for child in ast.iter_child_nodes(node):
                yield from walk(child, inner)

        for stmt in method.body:
            yield from walk(stmt, 0)

    def _check_class(self, mod: Module,
                     cls: ast.ClassDef) -> list[Finding]:
        targets = self._thread_targets(cls)
        if not targets:
            return []
        sync = self._sync_attrs(cls)
        methods = [n for n in cls.body if isinstance(n, ast.FunctionDef)]
        # attr -> {"target": [...accesses...], "other": [...]}
        by_attr: dict[str, dict[str, list]] = {}
        for m in methods:
            if m.name == "__init__":
                continue  # pre-thread construction
            side = "target" if m.name in targets else "other"
            for attr, line, is_write, guarded in self._accesses(m, sync):
                rec = by_attr.setdefault(attr, {"target": [], "other": []})
                rec[side].append((m.name, line, is_write, guarded))
        out = []
        for attr, rec in sorted(by_attr.items()):
            crosses = (
                (any(w for _, _, w, _ in rec["target"])
                 and rec["other"])
                or (any(w for _, _, w, _ in rec["other"])
                    and rec["target"])
            )
            if not crosses:
                continue
            for side in ("target", "other"):
                for meth, line, is_write, guarded in rec[side]:
                    if guarded:
                        continue
                    kind = "written" if is_write else "read"
                    out.append(Finding(
                        self.name, mod.path, line,
                        f"self.{attr} is shared across the "
                        f"{cls.name} thread boundary but {kind} in "
                        f"{meth}() outside the class lock/condition — "
                        "guard it (with self._lock / *_locked method) "
                        "or justify with a suppression",
                    ))
        # deterministic order for stable reports
        out.sort(key=lambda f: f.line)
        return out


def default_rules() -> tuple:
    """The active rule set, constructed fresh (the telemetry rule loads
    the pinned registries at construction)."""
    return (
        RngDiscipline(),
        DonationAlias(),
        ExceptionTaxonomy(),
        TelemetryRegistry(),
        SpanPairing(),
        CheckpointExtrasNamespace(),
        ThreadSharedState(),
    )
