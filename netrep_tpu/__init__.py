"""netrep-tpu — a TPU-native (JAX/XLA) framework with the capabilities of the
NetRep R package: permutation testing of network module preservation across
datasets (SURVEY.md; BASELINE.json:5).

Public API (mirrors the reference's exported surface, SURVEY.md §2.1):

- :func:`module_preservation`   — the main entry point (permutation test).
- :func:`grid_preservation`     — the all-pairs D×D atlas over datasets.
- :func:`network_properties`    — observed per-module topological properties.
- :func:`required_perms`        — permutations needed for a significance level.
"""

from .ops.oracle import STAT_NAMES, TOPOLOGY_STATS
from .utils import flightrec as _flightrec

# always-on black-box flight recorder (ISSUE 20): a bounded in-memory
# ring of recent telemetry events plus the ambient flight bus feeding it,
# installed once per process. Stdlib-only and host-side only — import
# stays light, numerics stay bit-identical, NETREP_FLIGHTREC=0 opts out.
_flightrec.install()

__version__ = "0.1.0"

__all__ = [
    "STAT_NAMES",
    "TOPOLOGY_STATS",
    "module_preservation",
    "grid_preservation",
    "GridResult",
    "network_properties",
    "required_perms",
    "permp",
    "load_example",
    "make_example_pair",
    "PreservationResult",
    "combine_analyses",
    "results_table",
    "SparseAdjacency",
    "sparse_module_preservation",
    "sparse_network_properties",
    "TiledNetwork",
    "build_sparse_network",
    "atlas_module_preservation",
    "summarize_trace",
    "make_mesh",
    "selftest",
    "properties_table",
    "FaultPolicy",
]

#: the plot suite (reference exports plotModule + per-panel functions at
#: package level, SURVEY.md §2.1 — a NetRep user expects them here, not
#: behind a submodule import). Lazy like everything else, and deliberately
#: NOT in ``__all__``: matplotlib is the optional ``plot`` extra, so a
#: ``from netrep_tpu import *`` on a base install must not import it (and
#: crash) just by iterating the export list. Attribute access still works.
_PLOT_EXPORTS = frozenset({
    "plot_module", "plot_data", "plot_correlation", "plot_network",
    "plot_summary", "plot_contribution", "plot_degree",
    "plot_module_sparse",
})


def __getattr__(name):
    # Lazy imports keep `import netrep_tpu` light (no jax trace-time cost)
    # until an API that needs it is touched.
    if name in ("module_preservation", "network_properties",
                "properties_table"):
        from .models import preservation, properties

        return {
            "module_preservation": preservation.module_preservation,
            "network_properties": properties.network_properties,
            "properties_table": properties.properties_table,
        }[name]
    if name in ("grid_preservation", "GridResult"):
        from .models import grid

        return getattr(grid, name)
    if name in ("required_perms", "permp"):
        from .ops import pvalues

        return getattr(pvalues, name)
    if name in ("load_example", "make_example_pair"):
        from . import data

        return getattr(data, name)
    if name == "SparseAdjacency":
        from .ops.sparse import SparseAdjacency

        return SparseAdjacency
    if name in ("sparse_module_preservation", "sparse_network_properties"):
        from .models import sparse_api

        return getattr(sparse_api, name)
    if name in ("TiledNetwork", "build_sparse_network"):
        from . import atlas

        return getattr(atlas, name)
    if name == "atlas_module_preservation":
        from .models.atlas_api import module_preservation

        return module_preservation
    if name == "summarize_trace":
        from .utils.profiling import summarize_trace

        return summarize_trace
    if name == "make_mesh":
        from .parallel.mesh import make_mesh

        return make_mesh
    if name == "selftest":
        from .utils.selftest import selftest

        return selftest
    if name == "FaultPolicy":
        from .utils.config import FaultPolicy

        return FaultPolicy
    if name in _PLOT_EXPORTS:
        try:
            from . import plot
        except ImportError as e:
            raise ImportError(
                f"netrep_tpu.{name} needs matplotlib — install the plot "
                "extra: pip install netrep-tpu[plot]"
            ) from e

        return getattr(plot, name)
    if name in ("PreservationResult", "combine_analyses", "results_table"):
        from .models import results

        return getattr(results, name)
    raise AttributeError(name)
