"""Deployment CLI: ``python -m netrep_tpu <command>``.

The reference's install-validation story is ``R CMD check``; a JAX
framework deployed onto unfamiliar hardware (new TPU generation, tunneled
backend) needs the equivalent one-liner. Commands:

- ``selftest`` (default) — run :func:`netrep_tpu.selftest` on the current
  default backend and exit nonzero on any device-vs-oracle disagreement
  (tolerances are backend-conditional; see utils/selftest.py).
- ``version`` — print the package version.
- ``telemetry <run.jsonl>`` — aggregate a telemetry event log (ISSUE 3;
  written by ``module_preservation(telemetry=...)`` or ``bench.py
  --telemetry``) into the human summary table offline; the table leads
  with a "recovery" section whenever the run retried, abandoned,
  degraded, or had faults injected (ISSUE 4). ``--prom`` emits the
  Prometheus text exposition instead, ``--json`` the raw registry, and
  ``--recovery`` a chronological timeline of the recovery events alone
  (what did this run survive, in what order).
  Runs without touching any backend — safe on a box whose tunnel is dead.
"""

from __future__ import annotations

import argparse
import json
import sys


def _positive(value: str) -> int:
    n = int(value)
    if n < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {n}")
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m netrep_tpu")
    sub = ap.add_subparsers(dest="cmd")
    st = sub.add_parser("selftest", help="on-device numerical self-check")
    # argparse-level validation: a usage error must fail instantly, before
    # the backend resolution below (which can spend its probe budget on a
    # dead tunnel)
    st.add_argument("--n-perm", type=_positive, default=32)
    st.add_argument("--seed", type=int, default=0)
    st.add_argument("--max-shapes", type=_positive, default=None)
    st.add_argument("--json", action="store_true",
                    help="print the summary dict as one JSON line")
    sub.add_parser("version", help="print the package version")
    tl = sub.add_parser(
        "telemetry", help="aggregate a telemetry JSONL into a summary report"
    )
    tl.add_argument("path", help="telemetry event log (JSONL)")
    tl.add_argument("--prom", action="store_true",
                    help="Prometheus text exposition instead of the table")
    tl.add_argument("--json", action="store_true",
                    help="aggregated registry as one JSON line")
    tl.add_argument("--recovery", action="store_true",
                    help="chronological timeline of recovery events "
                         "(retries, abandoned chunks, CPU degradation, "
                         "injected faults)")
    args = ap.parse_args(argv)
    if args.cmd is None:
        # bare invocation = selftest with its own argparse defaults (ONE
        # source of defaults; bare flags are not supported — subcommand
        # flags belong after `selftest`)
        args = ap.parse_args(["selftest", *(argv or [])])

    if args.cmd == "telemetry":
        # pure-offline aggregation: must not resolve a backend (this is
        # the report you run precisely when the tunnel is dead)
        from netrep_tpu.utils.telemetry import aggregate_file, render_recovery

        if args.recovery:
            try:
                timeline = render_recovery(args.path)
            except OSError as e:
                print(f"cannot read {args.path!r}: {e}", file=sys.stderr)
                return 1
            if not timeline:
                print(f"no recovery events in {args.path!r}")
                return 0
            print(timeline)
            return 0
        try:
            reg = aggregate_file(args.path)
        except OSError as e:
            print(f"cannot read {args.path!r}: {e}", file=sys.stderr)
            return 1
        if reg.n_events == 0:
            print(f"no telemetry events in {args.path!r}", file=sys.stderr)
            return 1
        if args.prom:
            sys.stdout.write(reg.render_prometheus())
        elif args.json:
            print(json.dumps(reg.as_dict()))
        else:
            print(reg.render_summary())
        return 0

    import netrep_tpu

    if args.cmd == "version":
        print(netrep_tpu.__version__)
        return 0
    # Hang-safe backend resolution BEFORE any jax.devices() call: this
    # image's sitecustomize re-pins the axon (tunneled TPU) plugin at
    # interpreter startup, and a dead tunnel HANGS the dial instead of
    # erroring — the exact failure the driver entries guard against
    # (utils/backend.py). An explicit non-axon platform is honored; an
    # unresponsive tunnel drops to CPU.
    from netrep_tpu.utils.backend import resolve_backend_or_cpu

    resolve_backend_or_cpu()
    try:
        out = netrep_tpu.selftest(
            n_perm=args.n_perm, seed=args.seed, verbose=not args.json,
            max_shapes=args.max_shapes,
        )
    except (RuntimeError, ValueError) as e:
        print(f"selftest FAILED: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
