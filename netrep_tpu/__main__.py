"""Deployment CLI: ``python -m netrep_tpu <command>``.

The reference's install-validation story is ``R CMD check``; a JAX
framework deployed onto unfamiliar hardware (new TPU generation, tunneled
backend) needs the equivalent one-liner. Commands:

- ``selftest`` (default) — run :func:`netrep_tpu.selftest` on the current
  default backend and exit nonzero on any device-vs-oracle disagreement
  (tolerances are backend-conditional; see utils/selftest.py).
- ``version`` — print the package version.
- ``telemetry <run.jsonl>`` — aggregate a telemetry event log (ISSUE 3;
  written by ``module_preservation(telemetry=...)`` or ``bench.py
  --telemetry``) into the human summary table offline; the table leads
  with a "recovery" section whenever the run retried, abandoned,
  degraded, or had faults injected (ISSUE 4), and ends with the
  compile/dispatch/transfer/host time split of any null runs in the log
  (ISSUE 5). ``--prom`` emits the Prometheus text exposition instead,
  ``--json`` the raw registry, ``--recovery`` a chronological timeline
  of the recovery events alone (what did this run survive, in what
  order), and ``--trace out.json`` exports the span tree as
  Chrome/Perfetto trace-event JSON (open in Perfetto/chrome://tracing).
  Runs without touching any backend — safe on a box whose tunnel is dead.
- ``perf [<ledger>]`` — the throughput-regression ledger (ISSUE 5;
  :mod:`netrep_tpu.utils.perfledger`): prints the per-fingerprint trend,
  ``--check`` compares the newest entry against the robust median of its
  matching history and exits 2 on regression (the ``tpu_watch.sh``
  per-step gate), ``--ingest BENCH_r0*.json`` seeds the ledger from the
  driver-bench trajectory files. The ledger path defaults from
  ``NETREP_PERF_LEDGER``. Also backend-free.
"""

from __future__ import annotations

import argparse
import json
import sys


def _positive(value: str) -> int:
    n = int(value)
    if n < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {n}")
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m netrep_tpu")
    sub = ap.add_subparsers(dest="cmd")
    st = sub.add_parser("selftest", help="on-device numerical self-check")
    # argparse-level validation: a usage error must fail instantly, before
    # the backend resolution below (which can spend its probe budget on a
    # dead tunnel)
    st.add_argument("--n-perm", type=_positive, default=32)
    st.add_argument("--seed", type=int, default=0)
    st.add_argument("--max-shapes", type=_positive, default=None)
    st.add_argument("--json", action="store_true",
                    help="print the summary dict as one JSON line")
    sub.add_parser("version", help="print the package version")
    tl = sub.add_parser(
        "telemetry", help="aggregate a telemetry JSONL into a summary report"
    )
    tl.add_argument("path", help="telemetry event log (JSONL)")
    tl.add_argument("--prom", action="store_true",
                    help="Prometheus text exposition instead of the table")
    tl.add_argument("--json", action="store_true",
                    help="aggregated registry as one JSON line")
    tl.add_argument("--recovery", action="store_true",
                    help="chronological timeline of recovery events "
                         "(retries, abandoned chunks, CPU degradation, "
                         "injected faults)")
    tl.add_argument("--trace", metavar="OUT",
                    help="export the span tree as Chrome/Perfetto "
                         "trace-event JSON to OUT")
    pf = sub.add_parser(
        "perf", help="per-run throughput ledger: trend / regression check"
    )
    pf.add_argument("ledger", nargs="?", default=None,
                    help="ledger JSONL (default: $NETREP_PERF_LEDGER or "
                         "./netrep_perf_ledger.jsonl)")
    pf.add_argument("--check", action="store_true",
                    help="compare the newest entry against the robust "
                         "median of matching prior entries; exit 2 on "
                         "regression beyond --threshold")
    pf.add_argument("--threshold", type=float, default=None,
                    help="fail when newest/median < 1 - THRESHOLD "
                         "(default 0.4)")
    pf.add_argument("--window", type=int, default=None,
                    help="median over at most this many most-recent "
                         "matching entries (default 8)")
    pf.add_argument("--ingest", nargs="+", metavar="BENCH_JSON",
                    help="append entries converted from driver "
                         "BENCH_r0*.json files before any other action")
    args = ap.parse_args(argv)
    if args.cmd is None:
        # bare invocation = selftest with its own argparse defaults (ONE
        # source of defaults; bare flags are not supported — subcommand
        # flags belong after `selftest`)
        args = ap.parse_args(["selftest", *(argv or [])])

    if args.cmd == "perf":
        # backend-free like the telemetry report: the regression gate must
        # run on a box whose tunnel is dead
        from netrep_tpu.utils import perfledger

        ledger = args.ledger or perfledger.default_path()
        if args.ingest:
            n = perfledger.ingest_bench_files(args.ingest, ledger)
            print(f"ingested {n} entr{'y' if n == 1 else 'ies'} into "
                  f"{ledger}")
        if args.check:
            try:
                ok, report = perfledger.check(
                    ledger,
                    threshold=(
                        args.threshold if args.threshold is not None
                        else perfledger.DEFAULT_THRESHOLD
                    ),
                    window=(
                        args.window if args.window is not None
                        else perfledger.DEFAULT_WINDOW
                    ),
                )
            except OSError as e:
                print(f"cannot read {ledger!r}: {e}", file=sys.stderr)
                return 1
            print(report)
            return 0 if ok else 2
        if not args.ingest:
            try:
                print(perfledger.trend(ledger))
            except OSError as e:
                print(f"cannot read {ledger!r}: {e}", file=sys.stderr)
                return 1
        return 0

    if args.cmd == "telemetry":
        # pure-offline aggregation: must not resolve a backend (this is
        # the report you run precisely when the tunnel is dead)
        from netrep_tpu.utils.telemetry import aggregate_file, render_recovery

        if args.trace:
            from netrep_tpu.utils.trace import write_perfetto

            try:
                n = write_perfetto(args.path, args.trace)
            except OSError as e:
                print(f"cannot read {args.path!r}: {e}", file=sys.stderr)
                return 1
            print(f"wrote {n} trace events to {args.trace}")
            return 0
        if args.recovery:
            try:
                timeline = render_recovery(args.path)
            except OSError as e:
                print(f"cannot read {args.path!r}: {e}", file=sys.stderr)
                return 1
            if not timeline:
                print(f"no recovery events in {args.path!r}")
                return 0
            print(timeline)
            return 0
        try:
            reg = aggregate_file(args.path)
        except OSError as e:
            print(f"cannot read {args.path!r}: {e}", file=sys.stderr)
            return 1
        if reg.n_events == 0:
            print(f"no telemetry events in {args.path!r}", file=sys.stderr)
            return 1
        if args.prom:
            sys.stdout.write(reg.render_prometheus())
        elif args.json:
            print(json.dumps(reg.as_dict()))
        else:
            print(reg.render_summary())
            from netrep_tpu.utils.trace import render_time_split

            split = render_time_split(args.path)
            if split:
                print()
                print(split)
        return 0

    import netrep_tpu

    if args.cmd == "version":
        print(netrep_tpu.__version__)
        return 0
    # Hang-safe backend resolution BEFORE any jax.devices() call: this
    # image's sitecustomize re-pins the axon (tunneled TPU) plugin at
    # interpreter startup, and a dead tunnel HANGS the dial instead of
    # erroring — the exact failure the driver entries guard against
    # (utils/backend.py). An explicit non-axon platform is honored; an
    # unresponsive tunnel drops to CPU.
    from netrep_tpu.utils.backend import resolve_backend_or_cpu

    resolve_backend_or_cpu()
    try:
        out = netrep_tpu.selftest(
            n_perm=args.n_perm, seed=args.seed, verbose=not args.json,
            max_shapes=args.max_shapes,
        )
    except (RuntimeError, ValueError) as e:
        print(f"selftest FAILED: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
