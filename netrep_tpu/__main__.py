"""Deployment CLI: ``python -m netrep_tpu <command>``.

The reference's install-validation story is ``R CMD check``; a JAX
framework deployed onto unfamiliar hardware (new TPU generation, tunneled
backend) needs the equivalent one-liner. Commands:

- ``selftest`` (default) — run :func:`netrep_tpu.selftest` on the current
  default backend and exit nonzero on any device-vs-oracle disagreement
  (tolerances are backend-conditional; see utils/selftest.py).
- ``version`` — print the package version.
- ``telemetry <run.jsonl>`` — aggregate a telemetry event log (ISSUE 3;
  written by ``module_preservation(telemetry=...)`` or ``bench.py
  --telemetry``) into the human summary table offline; the table leads
  with a "recovery" section whenever the run retried, abandoned,
  degraded, or had faults injected (ISSUE 4), and ends with the
  compile/dispatch/transfer/host time split of any null runs in the log
  (ISSUE 5). ``--prom`` emits the Prometheus text exposition instead,
  ``--json`` the raw registry, ``--recovery`` a chronological timeline
  of the recovery events alone (what did this run survive, in what
  order), and ``--trace out.json`` exports the span tree as
  Chrome/Perfetto trace-event JSON (open in Perfetto/chrome://tracing).
  Runs without touching any backend — safe on a box whose tunnel is dead.
- ``serve`` — the always-on multi-tenant preservation service (ISSUE 7;
  :mod:`netrep_tpu.serve`): a unix-socket (or stdio) daemon with a job
  queue that packs concurrent requests into shared device dispatches on
  warm compiled-engine pools, per-tenant fairness and admission control,
  Prometheus metrics via the ``metrics`` op, and graceful SIGTERM drain.
  The ``telemetry`` report gains a per-tenant section for its logs.
- ``perf [<ledger>]`` — the throughput-regression ledger (ISSUE 5;
  :mod:`netrep_tpu.utils.perfledger`): prints the per-fingerprint trend,
  ``--check`` compares the newest entry against the robust median of its
  matching history and exits 2 on regression (the ``tpu_watch.sh``
  per-step gate), ``--ingest BENCH_r0*.json`` seeds the ledger from the
  driver-bench trajectory files. The ledger path defaults from
  ``NETREP_PERF_LEDGER``. Also backend-free.
- ``roofline [<run.jsonl>] [--ledger L --check]`` — the speed-of-light
  view (ISSUE 18; :mod:`netrep_tpu.utils.costmodel`): folds a telemetry
  run's per-chunk cost fields into a per-family achieved-vs-roofline
  table sorted by headroom (with the span-sum vs ``null_run_end``
  reconciliation verdict), and ``--check`` gates the newest
  roofline-bearing ledger entry's utilisation against the robust median
  of its matching history, exit 2 on drift. Also backend-free.
"""

from __future__ import annotations

import argparse
import json
import sys


def _positive(value: str) -> int:
    n = int(value)
    if n < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {n}")
    return n


def _telemetry_follow(path: str, poll_s: float = 0.25,
                      max_polls: int | None = None) -> int:
    """``telemetry --follow`` (ISSUE 13): tail -f the live JSONL,
    rendering each event line as it lands (the shared
    :func:`netrep_tpu.utils.telemetry.format_event` renderer) — the
    poor-man's live view for non-serve runs. Ctrl-C exits cleanly and,
    when the log carried serve events, prints the same per-tenant table
    ``top`` renders (:mod:`netrep_tpu.serve.top` — one renderer, two
    feeds). ``max_polls`` bounds the loop for tests."""
    import time

    from netrep_tpu.utils.telemetry import (
        format_event, is_event, tenant_summary,
    )

    events = []
    t0 = None
    polls = 0
    try:
        with open(path, encoding="utf-8") as f:
            while True:
                line = f.readline()
                if not line:
                    polls += 1
                    if max_polls is not None and polls >= max_polls:
                        break
                    time.sleep(poll_s)
                    continue
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn in-flight line: re-read never helps
                if not is_event(e):
                    continue
                if t0 is None:
                    t0 = e["t"]
                events.append(e)
                print(format_event(e, t0), flush=True)
    except KeyboardInterrupt:
        pass
    except OSError as e:
        print(f"cannot follow {path!r}: {e}", file=sys.stderr)
        return 1
    rows = tenant_summary(events)
    if rows:
        from netrep_tpu.serve.top import render_tenant_table

        table_rows = []
        for t in sorted(rows):
            r = rows[t]
            table_rows.append({
                "tenant": t, "done": r["done"], "failed": r["failed"],
                "expired": r["expired"], "device_s": r["device_s"],
            })
        print()
        print(render_tenant_table(table_rows))
    return 0


def _chaos(args) -> int:
    """The ``chaos`` subcommand: a deterministic elastic-recovery drill
    (ISSUE 6). Injects the fault plan into a toy preservation run on a
    small permutation mesh, verifies the recovered result is BIT-IDENTICAL
    to the unfaulted baseline, and prints the recovery timeline — the
    one-liner ``tpu_watch.sh`` runs every cycle and CI can gate on. Exit
    codes: 0 drill passed, 1 parity failed or the run did not recover."""
    import os
    import tempfile

    plan = args.plan or os.environ.get("NETREP_FAULT_PLAN") or (
        "device_lost_partial@24;capacity_restored@40"
    )
    # the baseline below must run UNFAULTED: the env var would otherwise
    # activate injection for it too (resolve_runtime's env activation)
    os.environ.pop("NETREP_FAULT_PLAN", None)

    from netrep_tpu.utils.backend import (
        enable_persistent_cache, resolve_backend_or_cpu,
    )

    resolve_backend_or_cpu()
    if os.environ.get("NETREP_PERSISTENT_CACHE", "1") != "0":
        # drills share the repo-local compile cache (ISSUE 15): the
        # baseline and recovered runs compile identical programs
        enable_persistent_cache()
    import numpy as np

    import jax

    from netrep_tpu import module_preservation
    from netrep_tpu.data import make_mixed_pair
    from netrep_tpu.parallel.mesh import make_mesh
    from netrep_tpu.utils.config import EngineConfig, FaultPolicy
    from netrep_tpu.utils.telemetry import render_recovery

    n_dev = args.devices or min(4, len(jax.devices()))
    mixed = make_mixed_pair(120, 3, n_samples=16, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    kw = dict(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td}, module_assignments=assign,
        discovery="d", test="t", n_perm=args.n_perm, seed=0,
        config=EngineConfig(chunk_size=16, superchunk=2, autotune=False),
    )
    base = module_preservation(**kw)
    tel_path = args.telemetry
    tmp = None
    if tel_path is None:
        fd, tmp = tempfile.mkstemp(suffix=".jsonl", prefix="netrep_chaos_")
        os.close(fd)
        tel_path = tmp
    try:
        res = module_preservation(
            **kw, telemetry=tel_path,
            mesh=make_mesh(n_perm_shards=n_dev, n_row_shards=1)
            if n_dev > 1 else None,
            fault_policy=FaultPolicy(plan=plan, backoff_base_s=0.0,
                                     backoff_jitter=0.0),
        )
        recovered = int(res.completed) == int(args.n_perm)
        identical = (
            np.array_equal(np.asarray(base.p_values),
                           np.asarray(res.p_values))
            and (base.nulls is None
                 or np.array_equal(base.nulls, res.nulls))
        )
        timeline = render_recovery(tel_path)
        summary = {
            "plan": plan, "devices": n_dev, "n_perm": int(args.n_perm),
            "recovered": recovered, "bit_identical": identical,
            "ok": recovered and identical,
        }
        if args.json:
            print(json.dumps(summary))
        else:
            print(f"chaos drill: plan={plan!r} on {n_dev} device(s)")
            if timeline:
                print(timeline)
            print(
                "chaos drill "
                + ("PASSED" if summary["ok"] else "FAILED")
                + f": recovered={recovered} bit_identical={identical}"
            )
        return 0 if summary["ok"] else 1
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _chaos_serve(args) -> int:
    """``chaos --serve`` (ISSUE 10): the serving analogue of the elastic
    drill. Boots the REAL daemon with a plan-injected ``SIGKILL``
    mid-pack, lets concurrent journaled requests die with it, restarts
    the daemon with ``--recover``, and asserts every request completes
    with p-values BIT-IDENTICAL to direct (unkilled) calls — clients
    retry under their original idempotency keys, so nothing recomputes
    twice and nothing is lost. Exit 0 = drill passed."""
    import os
    import signal
    import subprocess
    import tempfile
    import threading
    import time

    plan = args.plan or os.environ.get("NETREP_FAULT_PLAN") or "sigkill@24"
    # the baseline below must run unkilled/unfaulted
    os.environ.pop("NETREP_FAULT_PLAN", None)

    from netrep_tpu.utils.backend import (
        enable_persistent_cache, resolve_backend_or_cpu,
    )

    resolve_backend_or_cpu()
    if os.environ.get("NETREP_PERSISTENT_CACHE", "1") != "0":
        # drills share the repo-local compile cache (ISSUE 15): the
        # baseline and recovered runs compile identical programs
        enable_persistent_cache()
    import numpy as np

    from netrep_tpu import module_preservation
    from netrep_tpu.data import make_mixed_pair
    from netrep_tpu.utils.config import EngineConfig

    genes, modules, n_samples, fseed = 100, 3, 16, 7
    reqs = [{"seed": 100 + i, "n_perm": int(args.n_perm)}
            for i in range(args.requests)]

    # unkilled baseline: the PR 7 parity contract pins served == direct,
    # so the direct call IS the uninterrupted server's answer
    mixed = make_mixed_pair(genes, modules, n_samples=n_samples, seed=fseed)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    cfg = EngineConfig(chunk_size=args.chunk, autotune=False)
    baseline = {}
    for r in reqs:
        res = module_preservation(
            network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
            data={"d": dd, "t": td}, module_assignments=assign,
            discovery="d", test="t", n_perm=r["n_perm"], seed=r["seed"],
            config=cfg,
        )
        baseline[r["seed"]] = np.asarray(res.p_values)

    tmp = tempfile.mkdtemp(prefix="netrep_chaos_serve_")
    sock = os.path.join(tmp, "serve.sock")
    journal = os.path.join(tmp, "journal.jsonl")
    env_base = {**os.environ, "JAX_PLATFORMS":
                os.environ.get("JAX_PLATFORMS", "cpu") or "cpu"}

    def boot(extra_env, recover):
        cmd = [sys.executable, "-m", "netrep_tpu", "serve",
               "--socket", sock, "--journal", journal,
               "--chunk", str(args.chunk), "--checkpoint-every",
               str(args.chunk)]
        if recover:
            cmd.append("--recover")
        return subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env={**env_base, **extra_env},
        )

    def wait_socket(proc, budget=180.0):
        deadline = time.monotonic() + budget
        while not os.path.exists(sock):
            if time.monotonic() > deadline or proc.poll() is not None:
                return False
            time.sleep(0.2)
        return True

    from netrep_tpu.serve.client import SocketClient

    def drive(client_results):
        """One thread per request, pinned idempotency keys — the sockets
        die with the daemon; the retry happens against the recovered one."""
        def worker(r):
            c = None
            try:
                c = SocketClient(sock, timeout=600)
                client_results[r["seed"]] = np.asarray(c.analyze(
                    "drill", "fx_d", "fx_t", n_perm=r["n_perm"],
                    seed=r["seed"], idempotency_key=f"drill-{r['seed']}",
                )["p_values"])
            # netrep: allow(exception-taxonomy) — drill clients: sockets die with the SIGKILLed daemon; the retry against the recovered daemon is the assertion
            except Exception:
                pass  # expected for requests in flight at the kill
            finally:
                if c is not None:
                    try:
                        c.close()
                    except OSError:
                        pass

        threads = [threading.Thread(target=worker, args=(r,), daemon=True)
                   for r in reqs]
        for t in threads:
            t.start()
        return threads

    summary = {"plan": plan, "requests": len(reqs),
               "n_perm": int(args.n_perm)}
    proc = proc2 = None
    try:
        proc = boot({"NETREP_FAULT_PLAN": plan}, recover=False)
        if not wait_socket(proc):
            print("chaos --serve: daemon never opened its socket",
                  file=sys.stderr)
            return 1
        reg = SocketClient(sock, timeout=600)
        reg.register_fixture("drill", genes=genes, modules=modules,
                             n_samples=n_samples, seed=fseed)
        reg.close()
        threads = drive(results_a := {})
        proc.wait(timeout=600)      # the injected SIGKILL fires mid-pack
        for t in threads:
            t.join(timeout=60)
        summary["killed"] = proc.returncode == -signal.SIGKILL
        summary["done_before_kill"] = len(results_a)

        try:
            os.unlink(sock)         # SIGKILL skipped the daemon's cleanup
        except OSError:
            pass
        proc2 = boot({}, recover=True)
        if not wait_socket(proc2):
            print("chaos --serve: recovered daemon never opened its "
                  "socket", file=sys.stderr)
            return 1
        threads = drive(results_b := {})
        for t in threads:
            t.join(timeout=600)

        identical = all(
            s in results_b and np.array_equal(results_b[s], baseline[s])
            for s in baseline
        ) and all(np.array_equal(results_a[s], baseline[s])
                  for s in results_a)
        summary["recovered"] = len(results_b) == len(reqs)
        summary["bit_identical"] = bool(identical)
        summary["ok"] = bool(summary["killed"] and summary["recovered"]
                             and identical)
        if args.json:
            print(json.dumps(summary))
        else:
            print(f"serve chaos drill: plan={plan!r}, "
                  f"{len(reqs)} requests @ {args.n_perm} perms")
            print("serve chaos drill "
                  + ("PASSED" if summary["ok"] else "FAILED")
                  + f": killed={summary['killed']} "
                    f"recovered={summary['recovered']} "
                    f"bit_identical={summary['bit_identical']}")
        return 0 if summary["ok"] else 1
    finally:
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()


def _chaos_fleet(args) -> int:
    """``chaos --fleet`` (ISSUE 14): the fleet analogue of the serving
    drill, and the one-command proof of the whole replication story.
    Boots the REAL fleet daemon (coordinator + N replica daemons),
    drives concurrent journaled requests through the coordinator's one
    socket, SIGKILLs a replica MID-PACK (picked live: the first replica
    whose stats show inflight work), lets the coordinator's failover
    move the shipped journal to the peer, and asserts every request
    completes with p-values BIT-IDENTICAL to direct (unkilled) calls.
    Prints the coordinator's ``--recovery`` timeline — replica_lost →
    failover_start → failover_done (with the measured failover time) →
    ring_rebalanced. Exit 0 = drill passed."""
    import os
    import signal
    import subprocess
    import tempfile
    import threading
    import time

    os.environ.pop("NETREP_FAULT_PLAN", None)   # the drill kills by pid

    from netrep_tpu.utils.backend import (
        enable_persistent_cache, resolve_backend_or_cpu,
    )

    resolve_backend_or_cpu()
    if os.environ.get("NETREP_PERSISTENT_CACHE", "1") != "0":
        # drills share the repo-local compile cache (ISSUE 15): the
        # baseline and recovered runs compile identical programs
        enable_persistent_cache()
    import numpy as np

    from netrep_tpu import module_preservation
    from netrep_tpu.data import make_mixed_pair
    from netrep_tpu.utils.config import EngineConfig

    genes, modules, n_samples, fseed = 100, 3, 16, 7
    reqs = [{"seed": 100 + i, "n_perm": int(args.n_perm)}
            for i in range(args.requests)]

    # unkilled baseline: served == direct is the PR 7 parity pin, so the
    # direct call IS the undisturbed single-replica fleet's answer
    mixed = make_mixed_pair(genes, modules, n_samples=n_samples, seed=fseed)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    cfg = EngineConfig(chunk_size=args.chunk, autotune=False)
    baseline = {}
    for r in reqs:
        res = module_preservation(
            network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
            data={"d": dd, "t": td}, module_assignments=assign,
            discovery="d", test="t", n_perm=r["n_perm"], seed=r["seed"],
            config=cfg,
        )
        baseline[r["seed"]] = np.asarray(res.p_values)

    tmp = tempfile.mkdtemp(prefix="netrep_chaos_fleet_")
    sock = os.path.join(tmp, "fleet.sock")
    tel = os.path.join(tmp, "fleet_tel.jsonl")
    env = {**os.environ, "JAX_PLATFORMS":
           os.environ.get("JAX_PLATFORMS", "cpu") or "cpu"}
    env.pop("NETREP_FAULT_PLAN", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "netrep_tpu", "serve",
         "--fleet", str(args.replicas), "--socket", sock,
         "--fleet-dir", os.path.join(tmp, "fleet"),
         "--telemetry", tel, "--chunk", str(args.chunk),
         "--checkpoint-every", str(args.chunk),
         "--heartbeat-s", "0.2"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env,
    )
    summary = {"replicas": int(args.replicas), "requests": len(reqs),
               "n_perm": int(args.n_perm)}
    try:
        deadline = time.monotonic() + 300
        while not os.path.exists(sock):
            if time.monotonic() > deadline or proc.poll() is not None:
                print("chaos --fleet: coordinator never opened its "
                      "socket", file=sys.stderr)
                return 1
            time.sleep(0.2)

        from netrep_tpu.serve.client import SocketClient

        reg = SocketClient(sock, timeout=600)
        reg.register_fixture("drill", genes=genes, modules=modules,
                             n_samples=n_samples, seed=fseed)
        reg.close()

        results = {}
        lock = threading.Lock()

        def worker(r):
            c = None
            try:
                c = SocketClient(sock, timeout=900)
                out = c.analyze("drill", "fx_d", "fx_t",
                                n_perm=r["n_perm"], seed=r["seed"],
                                idempotency_key=f"drill-{r['seed']}",
                                retries=8)
                with lock:
                    results[r["seed"]] = np.asarray(out["p_values"])
            # netrep: allow(exception-taxonomy) — drill clients: a request that dies with the killed replica is re-served via the journal; the parity gate below is the assertion
            except Exception:
                pass
            finally:
                if c is not None:
                    try:
                        c.close()
                    except OSError:
                        pass

        threads = [threading.Thread(target=worker, args=(r,), daemon=True)
                   for r in reqs]
        for t in threads:
            t.start()

        # pick the victim live: the first replica whose stats show
        # in-flight work — "mid-pack" by construction, not by timing
        victim = None
        stat_c = SocketClient(sock, timeout=60)
        kill_deadline = time.monotonic() + 240
        while victim is None and time.monotonic() < kill_deadline:
            st = stat_c.stats()
            for rid, row in sorted(st.get("replicas", {}).items()):
                if (row.get("alive") and row.get("inflight")
                        and row.get("pid")):
                    victim = (rid, int(row["pid"]))
                    break
            if victim is None:
                time.sleep(0.02)
        stat_c.close()
        if getattr(args, "evict", False):
            # noticed eviction (ISSUE 19): the notice runs the FULL
            # handoff — ring removal, bounded drain, journal-tail ship,
            # peer adoption — before the process dies, so nothing is
            # lost and nothing recomputes; the receipt proves it
            summary["evicted_replica"] = victim[0] if victim else None
            if victim is not None:
                ec = SocketClient(sock, timeout=900)
                receipt = ec.request(
                    "evict_notice", replica=victim[0],
                    grace_s=float(getattr(args, "grace", 60.0)),
                )
                ec.close()
                summary["handoff"] = {
                    k: receipt.get(k)
                    for k in ("ok", "peer", "s", "requeued", "results")
                }
        else:
            # unnoticed eviction: SIGKILL mid-pack, the failover path
            if victim is not None:
                os.kill(victim[1], signal.SIGKILL)
            summary["killed_replica"] = victim[0] if victim else None

        for t in threads:
            t.join(timeout=600)
        identical = all(
            s in results and np.array_equal(results[s], baseline[s])
            for s in baseline
        )
        summary["recovered"] = len(results) == len(reqs)
        summary["bit_identical"] = bool(identical)
        summary["ok"] = bool(victim and summary["recovered"]
                             and identical)
        c = SocketClient(sock, timeout=120)
        c.shutdown()
        c.close()
        proc.wait(timeout=300)
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()

    from netrep_tpu.utils.telemetry import render_recovery

    timeline = ""
    try:
        timeline = render_recovery(tel)
    except OSError:
        pass
    evict = bool(getattr(args, "evict", False))
    if evict:
        # the zero-recompute pin: a NOTICED eviction must complete as a
        # handoff (evict_handoff_done on the timeline) without ever
        # entering the failover path (no failover_start anywhere)
        summary["zero_recompute"] = bool(
            "evict_handoff_done" in timeline
            and "failover_start" not in timeline
        )
        summary["ok"] = bool(summary["ok"] and summary["zero_recompute"])
    fo = [l for l in timeline.splitlines() if "failover_done" in l]
    if args.json:
        print(json.dumps(summary))
    else:
        kind = "noticed eviction" if evict else "replica-kill"
        print(f"fleet chaos drill ({kind}): {args.replicas} replicas, "
              f"{len(reqs)} requests @ {args.n_perm} perms")
        if timeline:
            print(timeline)
        tail = (f": evicted={summary.get('evicted_replica')} "
                f"zero_recompute={summary.get('zero_recompute')} "
                if evict else
                f": killed={summary.get('killed_replica')} ")
        print("fleet chaos drill "
              + ("PASSED" if summary["ok"] else "FAILED") + tail
              + f"recovered={summary['recovered']} "
                f"bit_identical={summary['bit_identical']}"
              + (f" ({fo[-1].strip()})" if fo else ""))
    return 0 if summary["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m netrep_tpu")
    sub = ap.add_subparsers(dest="cmd")
    st = sub.add_parser("selftest", help="on-device numerical self-check")
    # argparse-level validation: a usage error must fail instantly, before
    # the backend resolution below (which can spend its probe budget on a
    # dead tunnel)
    st.add_argument("--n-perm", type=_positive, default=32)
    st.add_argument("--seed", type=int, default=0)
    st.add_argument("--max-shapes", type=_positive, default=None)
    st.add_argument("--json", action="store_true",
                    help="print the summary dict as one JSON line")
    sub.add_parser("version", help="print the package version")
    tl = sub.add_parser(
        "telemetry", help="aggregate a telemetry JSONL into a summary report"
    )
    tl.add_argument("path", nargs="+",
                    help="telemetry event log(s) (JSONL); several files "
                         "merge in the --trace export (client log + N "
                         "server generations → one trace, ISSUE 13)")
    tl.add_argument("--prom", action="store_true",
                    help="Prometheus text exposition instead of the table")
    tl.add_argument("--json", action="store_true",
                    help="aggregated registry as one JSON line")
    tl.add_argument("--recovery", action="store_true",
                    help="chronological timeline of recovery events "
                         "(retries, abandoned chunks, CPU degradation, "
                         "injected faults)")
    tl.add_argument("--trace", metavar="OUT",
                    help="export the span tree as Chrome/Perfetto "
                         "trace-event JSON to OUT; with several input "
                         "files, spans sharing a trace id (a request "
                         "across a SIGKILL + --recover restart) render "
                         "as one continuous trace")
    tl.add_argument("--follow", action="store_true",
                    help="tail the log live (ISSUE 13): render events/"
                         "spans as they land — the poor-man's live view "
                         "for non-serve runs; exits on Ctrl-C with a "
                         "per-tenant table when the log has serve events")
    pf = sub.add_parser(
        "perf", help="per-run throughput ledger: trend / regression check"
    )
    pf.add_argument("ledger", nargs="?", default=None,
                    help="ledger JSONL (default: $NETREP_PERF_LEDGER or "
                         "./netrep_perf_ledger.jsonl)")
    pf.add_argument("--check", action="store_true",
                    help="compare the newest entry against the robust "
                         "median of matching prior entries; exit 2 on "
                         "regression beyond --threshold")
    pf.add_argument("--threshold", type=float, default=None,
                    help="fail when newest/median < 1 - THRESHOLD "
                         "(default 0.4)")
    pf.add_argument("--window", type=int, default=None,
                    help="median over at most this many most-recent "
                         "matching entries (default 8)")
    pf.add_argument("--ingest", nargs="+", metavar="BENCH_JSON",
                    help="append entries converted from driver "
                         "BENCH_r0*.json files before any other action")
    rf = sub.add_parser(
        "roofline",
        help="per-family achieved-vs-speed-of-light table from a "
             "telemetry run + utilisation drift gate over the perf "
             "ledger (ISSUE 18)",
    )
    rf.add_argument("path", nargs="?", default=None, metavar="RUN_JSONL",
                    help="telemetry run JSONL: fold its chunk/superchunk "
                         "cost fields into the per-family headroom table "
                         "(sorted by headroom, reconciliation verdict "
                         "appended)")
    rf.add_argument("--ledger", default=None, metavar="LEDGER",
                    help="perf ledger for --check (default: "
                         "$NETREP_PERF_LEDGER or "
                         "./netrep_perf_ledger.jsonl)")
    rf.add_argument("--check", action="store_true",
                    help="compare the newest roofline-bearing ledger "
                         "entry's utilisation (achieved perms/s when the "
                         "device kind has no peak entry) against the "
                         "robust median of matching priors; exit 2 on "
                         "regression beyond --threshold")
    rf.add_argument("--threshold", type=float, default=None,
                    help="fail when newest/median < 1 - THRESHOLD "
                         "(default 0.4)")
    rf.add_argument("--window", type=int, default=None,
                    help="median over at most this many most-recent "
                         "matching entries (default 8)")
    sv = sub.add_parser(
        "serve",
        help="always-on multi-tenant preservation service (ISSUE 7): "
             "tenants register datasets once, then submit many analyses; "
             "concurrent requests are packed into shared device "
             "dispatches on warm compiled-engine pools. SIGTERM drains "
             "gracefully.",
    )
    sv.add_argument("--socket", default=None, metavar="PATH",
                    help="serve line-delimited JSON ops on this unix "
                         "socket (default: stdin/stdout)")
    sv.add_argument("--telemetry", default=None, metavar="PATH",
                    help="append serving telemetry (request spans, pack "
                         "events, engine runs) to this JSONL (default: "
                         "$NETREP_TELEMETRY)")
    sv.add_argument("--max-queue", type=_positive, default=64,
                    help="per-tenant queue bound (admission control)")
    sv.add_argument("--max-pack", type=_positive, default=4,
                    help="max requests per shared dispatch pack")
    sv.add_argument("--pool-size", type=int, default=8,
                    help="warm compiled-engine pool size (LRU)")
    sv.add_argument("--chunk", type=_positive, default=64,
                    help="EngineConfig.chunk_size for served runs")
    sv.add_argument("--n-perm", type=_positive, default=None,
                    help="default permutation budget for requests that "
                         "omit n_perm (default: the library's Bonferroni "
                         "auto rule)")
    sv.add_argument("--drain-timeout", "--drain-timeout-s", type=float,
                    default=120.0, dest="drain_timeout",
                    help="max seconds to finish queued work on "
                         "SIGTERM/shutdown; past the bound the remainder "
                         "is journaled as requeued-on-restart and the "
                         "process exits cleanly (ISSUE 10)")
    sv.add_argument("--journal", default="netrep_serve_journal.jsonl",
                    metavar="PATH",
                    help="write-ahead request journal (fsynced accepted/"
                         "done records; the crash-recovery source). "
                         "Default: ./netrep_serve_journal.jsonl")
    sv.add_argument("--no-journal", action="store_true",
                    help="disable the journal entirely (PR 7 behavior: "
                         "no durability, no idempotency persistence)")
    sv.add_argument("--recover", nargs="?", const=True, default=None,
                    metavar="JOURNAL",
                    help="replay the journal on boot: re-register "
                         "datasets, answer duplicates from journaled "
                         "results, re-queue unfinished requests in "
                         "original order, resume partial packs from "
                         "their checkpoints (bit-identical to an "
                         "uninterrupted server)")
    sv.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                    help="per-pack checkpoint directory (default: "
                         "<journal>.ckpt when journaling)")
    sv.add_argument("--checkpoint-every", type=_positive, default=4096,
                    help="pack checkpoint cadence in permutations (how "
                         "much re-compute a SIGKILL can cost)")
    sv.add_argument("--brownout-enter-s", type=float, default=None,
                    help="enter brownout load shedding when the "
                         "estimated backlog drain time exceeds this "
                         "(default: disabled)")
    sv.add_argument("--brownout-exit-s", type=float, default=None,
                    help="exit brownout below this estimate (default: "
                         "half of --brownout-enter-s)")
    sv.add_argument("--brownout-rate", type=float, default=None,
                    help="assumed steady-state perms/s before the server "
                         "has measured its own (else the perf ledger's "
                         "serve history is consulted)")
    # -- fleet serving (ISSUE 14) ---------------------------------------
    sv.add_argument("--fleet", type=_positive, default=None, metavar="N",
                    help="run N replica daemons behind a coordinator on "
                         "--socket: consistent-hash routing by dataset "
                         "digest (warm-pool locality), continuous "
                         "journal shipping to a designated peer, "
                         "heartbeat failover (the peer replays the "
                         "shipped journal bit-identically), fleet-wide "
                         "brownout admission, and respawn-on-death")
    sv.add_argument("--fleet-dir", default=None, metavar="DIR",
                    help="fleet state directory (replica journals, "
                         "shipped copies, the SHARED pack-checkpoint "
                         "dir); default: <socket>.fleet")
    sv.add_argument("--fleet-route", default="proxy",
                    choices=["proxy", "redirect"],
                    help="proxy: the coordinator forwards analyze ops "
                         "verbatim (clients keep one socket); redirect: "
                         "it answers with the home replica's socket and "
                         "the client re-sends there directly")
    sv.add_argument("--heartbeat-s", type=float, default=0.25,
                    help="fleet health-loop poll interval")
    sv.add_argument("--ship-interval-s", type=float, default=0.2,
                    help="journal-ship tail interval per replica")
    sv.add_argument("--fleet-brownout-enter-s", type=float, default=None,
                    help="fleet-wide brownout: shed new admissions when "
                         "the AGGREGATE backlog drain estimate across "
                         "replicas exceeds this")
    sv.add_argument("--no-respawn", action="store_true",
                    help="do not respawn a failed replica after its "
                         "failover completes (the fleet shrinks)")
    # -- autoscaling (ISSUE 19) ------------------------------------------
    sv.add_argument("--autoscale", action="store_true",
                    help="[--fleet] run the autoscaler control loop: "
                         "spawn replicas when the aggregate backlog-"
                         "drain estimate exceeds --scale-up-drain-s, "
                         "drain-and-retire idle replicas, and (with "
                         "--autoscale-min 0) scale to zero — the "
                         "journal + AOT store are the fleet state, and "
                         "a submission against the empty fleet spawns "
                         "on demand and queues behind the boot")
    sv.add_argument("--autoscale-min", type=int, default=0,
                    metavar="N",
                    help="[--autoscale] fleet-size floor (0 = allow "
                         "scale-to-zero)")
    sv.add_argument("--autoscale-max", type=_positive, default=None,
                    metavar="N",
                    help="[--autoscale] fleet-size ceiling (default: "
                         "max(4, --fleet N))")
    sv.add_argument("--scale-up-drain-s", type=float, default=10.0,
                    help="[--autoscale] spawn when the aggregate "
                         "backlog-drain estimate exceeds this "
                         "(hysteresis: retire only below half)")
    sv.add_argument("--scale-down-idle-s", type=float, default=30.0,
                    help="[--autoscale] retire a replica idle this long")
    sv.add_argument("--aot-export", action="store_true",
                    help="export programs this server had to jit-compile "
                         "into the AOT warm-start store (fleet replicas "
                         "do this automatically; see `warmup`)")
    sv.add_argument("--fleet-label", default=None, metavar="RID",
                    help="replica identity inside a fleet (set by the "
                         "coordinator when spawning replicas): the first "
                         "completed pack records its cold-start compile "
                         "span under a fleet-labeled perf-ledger "
                         "fingerprint")
    ch = sub.add_parser(
        "chaos",
        help="deterministic elastic-recovery drill (ISSUE 6): run a toy "
             "preservation null on a small mesh with an injected fault "
             "plan, verify the recovered result is bit-identical to the "
             "unfaulted run, and print the recovery timeline",
    )
    ch.add_argument("--plan", default=None,
                    help="fault plan (default: $NETREP_FAULT_PLAN, else "
                         "'device_lost_partial@24;capacity_restored@40')")
    ch.add_argument("--devices", type=_positive, default=None,
                    help="mesh size for the drill (default: min(4, "
                         "available devices))")
    ch.add_argument("--n-perm", type=_positive, default=64)
    ch.add_argument("--telemetry", default=None, metavar="PATH",
                    help="write the drill's event log here (default: a "
                         "temp file, removed after the run)")
    ch.add_argument("--json", action="store_true",
                    help="print the summary dict as one JSON line")
    ch.add_argument("--serve", action="store_true",
                    help="serving chaos drill (ISSUE 10): boot the real "
                         "daemon with a plan-injected SIGKILL mid-pack, "
                         "restart it with --recover, and assert every "
                         "journaled request completes bit-identically "
                         "vs an unkilled baseline")
    ch.add_argument("--fleet", action="store_true",
                    help="fleet chaos drill (ISSUE 14): boot the real "
                         "fleet daemon, SIGKILL a replica MID-PACK, let "
                         "the coordinator fail its shipped journal over "
                         "to the peer, and assert every request "
                         "completes bit-identically vs unkilled direct "
                         "calls; prints the failover timeline")
    ch.add_argument("--replicas", type=_positive, default=2,
                    help="[--fleet] replica daemons in the drill")
    ch.add_argument("--evict", action="store_true",
                    help="[--fleet] noticed-eviction drill (ISSUE 19): "
                         "instead of SIGKILL, send an eviction notice "
                         "for a mid-pack replica and assert the handoff "
                         "(ring removal → drain → journal-tail ship → "
                         "peer adoption) completes with ZERO recompute "
                         "— no failover events — and bit-parity")
    ch.add_argument("--grace", type=float, default=60.0,
                    help="[--fleet --evict] eviction notice grace "
                         "period in seconds")
    ch.add_argument("--requests", type=_positive, default=3,
                    help="[--serve/--fleet] concurrent requests in the "
                         "drill")
    ch.add_argument("--chunk", type=_positive, default=16,
                    help="[--serve/--fleet] served EngineConfig"
                         ".chunk_size")
    tp = sub.add_parser(
        "top",
        help="live ops dashboard over a running serve daemon (ISSUE 13): "
             "per-tenant queue depth, p50/p99 latency, attributed "
             "device-seconds, brownout state, and SLO burn rate, "
             "refreshed from the daemon's stats op",
    )
    tp.add_argument("--socket", required=True, metavar="PATH",
                    help="the daemon's unix socket")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="refresh interval in seconds (default 2)")
    tp.add_argument("--once", action="store_true",
                    help="print one frame and exit (scripts/CI)")
    tp.add_argument("--json", action="store_true",
                    help="emit the snapshot as one JSON line instead of "
                         "the table")
    tp.add_argument("--timeout", type=float, default=30.0,
                    help="socket timeout seconds")
    wu = sub.add_parser(
        "warmup",
        help="pre-export the engine program grid into the AOT store "
             "(ISSUE 15): trace + serialize + compile the bucketed null "
             "programs for given fixture shapes once, so a fresh "
             "process (or a respawned fleet replica) sharing the store "
             "answers its first request at steady-state speed "
             "(compile_span ~0, source: aot)",
    )
    wu.add_argument("--genes", type=_positive, default=120)
    wu.add_argument("--modules", type=_positive, default=3)
    wu.add_argument("--samples", type=_positive, default=16)
    wu.add_argument("--fixture-seed", type=int, default=7)
    wu.add_argument("--chunk", type=_positive, default=64,
                    help="EngineConfig.chunk_size (must match the "
                         "serving/run config for the entries to hit)")
    wu.add_argument("--n-perm", type=_positive, default=None,
                    help="request budget the serve-path plan assumes "
                         "(program identity is n_perm-independent; this "
                         "only sizes the plan)")
    wu.add_argument("--grid", default=None, metavar="G:M:S[,G:M:S...]",
                    help="warm several genes:modules:samples shapes in "
                         "one run instead of the single-shape flags")
    wu.add_argument("--target", default="both",
                    choices=["serve", "direct", "both"],
                    help="which engine construction to warm: the packed "
                         "serve path, the direct module_preservation "
                         "path, or both (default)")
    wu.add_argument("--measure", action="store_true",
                    help="measure instead of export: build the serve-"
                         "path engine fresh in THIS process, run one "
                         "null, and report its compile_span + source — "
                         "run it in a fresh process against a populated "
                         "store for the warm-start proof")
    wu.add_argument("--store", default=None, metavar="DIR",
                    help="AOT store directory (default: $NETREP_AOT_STORE "
                         "or .jax_cache/<cpu-fp>/aot)")
    wu.add_argument("--telemetry", default=None, metavar="PATH",
                    help="append warmup_start/end spans + aot_export "
                         "events to this JSONL")
    wu.add_argument("--json", action="store_true",
                    help="print the report as one JSON line")
    ln = sub.add_parser(
        "lint",
        help="invariant linter (ISSUE 12): statically enforce the "
             "repo's determinism/RNG/exception/telemetry/thread "
             "contracts over netrep_tpu/ (exit 2 on findings; "
             "suppressions are counted, reasons required)",
    )
    ln.add_argument("paths", nargs="*", default=None,
                    help="files or directories to lint (default: the "
                         "installed netrep_tpu package)")
    ln.add_argument("--json", action="store_true",
                    help="one machine-readable JSON line (lint_v schema; "
                         "summarize_watch.py classifies it)")
    ln.add_argument("--rule", action="append", metavar="NAME",
                    help="run only this rule (repeatable)")
    bu = sub.add_parser(
        "bundle",
        help="diagnostic bundles (ISSUE 20): render a collected "
             "bundle's triage report (timeline, detector verdicts, time "
             "split), or --collect one from this process",
    )
    bu.add_argument("path", nargs="?", default=None,
                    help="bundle directory to render (or the destination "
                         "with --collect)")
    bu.add_argument("--collect", action="store_true",
                    help="collect a bundle now instead of rendering "
                         "(dest = path, else netrep-bundle-<reason> in "
                         "the CWD)")
    bu.add_argument("--reason", default="manual",
                    help="reason slug stamped on a --collect bundle")
    args = ap.parse_args(argv)
    if args.cmd is None:
        # bare invocation = selftest with its own argparse defaults (ONE
        # source of defaults; bare flags are not supported — subcommand
        # flags belong after `selftest`)
        args = ap.parse_args(["selftest", *(argv or [])])

    if args.cmd == "lint":
        # backend-free: pure AST analysis, runnable on a box whose
        # tunnel is dead (and in every tpu_watch.sh cycle)
        from netrep_tpu.analysis.linter import main_lint

        return main_lint(args)

    if args.cmd == "bundle":
        # backend-free forensics (ISSUE 20): rendering — and host-side
        # collection — must work on a box whose tunnel is dead
        from netrep_tpu.utils import bundle as fbundle

        if args.collect:
            path = fbundle.collect(dest=args.path, reason=args.reason)
            print(path)
            return 0
        if args.path is None:
            print("bundle: pass a bundle directory to render, or "
                  "--collect", file=sys.stderr)
            return 1
        try:
            print(fbundle.render_report(args.path))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"cannot render {args.path!r}: {e}", file=sys.stderr)
            return 1
        return 0

    if args.cmd == "perf":
        # backend-free like the telemetry report: the regression gate must
        # run on a box whose tunnel is dead
        from netrep_tpu.utils import perfledger

        ledger = args.ledger or perfledger.default_path()
        if args.ingest:
            n = perfledger.ingest_bench_files(args.ingest, ledger)
            print(f"ingested {n} entr{'y' if n == 1 else 'ies'} into "
                  f"{ledger}")
        if args.check:
            try:
                ok, report = perfledger.check(
                    ledger,
                    threshold=(
                        args.threshold if args.threshold is not None
                        else perfledger.DEFAULT_THRESHOLD
                    ),
                    window=(
                        args.window if args.window is not None
                        else perfledger.DEFAULT_WINDOW
                    ),
                )
            except OSError as e:
                print(f"cannot read {ledger!r}: {e}", file=sys.stderr)
                return 1
            print(report)
            if not ok:
                # the drift verdict is a pinned anomaly (ISSUE 20): emit
                # it through the detector registry so the flight ring /
                # an auto-bundle records WHY a watch cycle flagged
                from netrep_tpu.utils import detectors

                detectors.fire("perf_drift", ledger=ledger)
            return 0 if ok else 2
        if not args.ingest:
            try:
                print(perfledger.trend(ledger))
            except OSError as e:
                print(f"cannot read {ledger!r}: {e}", file=sys.stderr)
                return 1
        return 0

    if args.cmd == "roofline":
        # backend-free like `perf`: the headroom table and drift gate
        # must run on a box whose tunnel is dead
        from netrep_tpu.utils import costmodel, perfledger
        from netrep_tpu.utils.telemetry import read_events

        if args.path is None and not args.check:
            print("roofline: nothing to do — pass a telemetry run JSONL "
                  "and/or --check", file=sys.stderr)
            return 1
        if args.path is not None:
            try:
                folded = costmodel.fold_roofline_events(
                    read_events(args.path)
                )
            except OSError as e:
                print(f"cannot read {args.path!r}: {e}", file=sys.stderr)
                return 1
            print(costmodel.render_roofline(folded))
        if args.check:
            ledger = args.ledger or perfledger.default_path()
            try:
                ok, report = perfledger.check_roofline(
                    ledger,
                    threshold=(
                        args.threshold if args.threshold is not None
                        else perfledger.DEFAULT_THRESHOLD
                    ),
                    window=(
                        args.window if args.window is not None
                        else perfledger.DEFAULT_WINDOW
                    ),
                )
            except OSError as e:
                print(f"cannot read {ledger!r}: {e}", file=sys.stderr)
                return 1
            print(report)
            if not ok:
                # same pinned-anomaly routing as `perf --check` above
                from netrep_tpu.utils import detectors

                detectors.fire("roofline_drift", ledger=ledger)
            return 0 if ok else 2
        return 0

    if args.cmd == "telemetry":
        # pure-offline aggregation: must not resolve a backend (this is
        # the report you run precisely when the tunnel is dead)
        from netrep_tpu.utils.telemetry import aggregate_file, render_recovery

        paths = args.path
        path0 = paths[0]
        if args.trace:
            from netrep_tpu.utils.trace import write_perfetto

            try:
                n = write_perfetto(paths, args.trace)
            except OSError as e:
                print(f"cannot read {paths!r}: {e}", file=sys.stderr)
                return 1
            print(f"wrote {n} trace events to {args.trace}"
                  + (f" (merged from {len(paths)} files)"
                     if len(paths) > 1 else ""))
            return 0
        if len(paths) > 1:
            print("multiple input files are only merged by --trace; "
                  "reporting on the first", file=sys.stderr)
        if args.follow:
            return _telemetry_follow(path0)
        if args.recovery:
            try:
                timeline = render_recovery(path0)
            except OSError as e:
                print(f"cannot read {path0!r}: {e}", file=sys.stderr)
                return 1
            if not timeline:
                print(f"no recovery events in {path0!r}")
                return 0
            print(timeline)
            return 0
        try:
            reg = aggregate_file(path0)
        except OSError as e:
            print(f"cannot read {path0!r}: {e}", file=sys.stderr)
            return 1
        if reg.n_events == 0:
            print(f"no telemetry events in {path0!r}", file=sys.stderr)
            return 1
        if args.prom:
            sys.stdout.write(reg.render_prometheus())
        elif args.json:
            print(json.dumps(reg.as_dict()))
        else:
            print(reg.render_summary())
            from netrep_tpu.utils.telemetry import render_tenants
            from netrep_tpu.utils.trace import render_time_split

            split = render_time_split(path0)
            if split:
                print()
                print(split)
            # per-tenant serving section (ISSUE 7): present only for logs
            # written by `netrep serve` / the load generator
            tenants = render_tenants(path0)
            if tenants:
                print()
                print(tenants)
            # per-replica fleet section (ISSUE 14): present only for
            # logs written by a fleet coordinator
            from netrep_tpu.utils.telemetry import render_replicas

            replicas = render_replicas(path0)
            if replicas:
                print()
                print(replicas)
            # all-pairs grid section (ISSUE 17): present only for logs
            # written by `grid_preservation`
            from netrep_tpu.utils.telemetry import render_grid

            grid = render_grid(path0)
            if grid:
                print()
                print(grid)
        return 0

    if args.cmd == "top":
        # backend-free: `top` only speaks the daemon's wire ops
        from netrep_tpu.serve.top import run_top

        return run_top(args)

    if args.cmd == "warmup":
        # warm start is the whole point: the persistent XLA compile
        # cache must be on so exported programs' executables land beside
        # the store (and the backend must resolve hang-safely first).
        # NETREP_PERSISTENT_CACHE=0 opts out — the warmstart bench's
        # honest cold reference measures with both layers off.
        import os

        from netrep_tpu.utils.backend import (
            enable_persistent_cache, resolve_backend_or_cpu,
        )

        resolve_backend_or_cpu()
        if os.environ.get("NETREP_PERSISTENT_CACHE", "1") != "0":
            enable_persistent_cache()
        from netrep_tpu.warmup import main_warmup

        return main_warmup(args)

    if args.cmd == "serve":
        import os

        if args.telemetry is None:
            args.telemetry = os.environ.get("NETREP_TELEMETRY") or None
        if os.environ.get("NETREP_PERSISTENT_CACHE", "1") != "0":
            # warm start (ISSUE 15): serving processes share the
            # persistent XLA compile cache beside the AOT store, so a
            # replica boot's compiles are cache reads when any earlier
            # process (warmup, a peer, a previous generation) did them
            from netrep_tpu.utils.backend import enable_persistent_cache

            enable_persistent_cache()
        if args.fleet and (args.fleet > 1
                           or getattr(args, "autoscale", False)):
            # the fleet coordinator itself is backend-free (it only
            # routes and ships journals); the replica daemons it spawns
            # each resolve their own backend. A fleet of ONE under
            # --autoscale still gets the coordinator: the autoscaler is
            # what grows it (ISSUE 19)
            from netrep_tpu.serve.fleet import fleet_daemon

            return fleet_daemon(args)
        # the daemon resolves its backend hang-safely like selftest below
        # (a dead tunnel must drop the service to CPU, not hang the boot)
        from netrep_tpu.utils.backend import resolve_backend_or_cpu

        resolve_backend_or_cpu()
        from netrep_tpu.serve.server import serve_daemon

        return serve_daemon(args)

    if args.cmd == "chaos":
        if getattr(args, "fleet", False):
            return _chaos_fleet(args)
        if getattr(args, "evict", False):
            print("chaos --evict is a fleet drill; add --fleet",
                  file=sys.stderr)
            return 2
        if args.serve:
            return _chaos_serve(args)
        return _chaos(args)

    import netrep_tpu

    if args.cmd == "version":
        print(netrep_tpu.__version__)
        return 0
    # Hang-safe backend resolution BEFORE any jax.devices() call: this
    # image's sitecustomize re-pins the axon (tunneled TPU) plugin at
    # interpreter startup, and a dead tunnel HANGS the dial instead of
    # erroring — the exact failure the driver entries guard against
    # (utils/backend.py). An explicit non-axon platform is honored; an
    # unresponsive tunnel drops to CPU.
    import os

    from netrep_tpu.utils.backend import resolve_backend_or_cpu

    resolve_backend_or_cpu()
    if os.environ.get("NETREP_PERSISTENT_CACHE", "1") != "0":
        # selftest subprocesses (CI, tpu_watch, the tier-1 CLI tests)
        # share the repo-local compile cache instead of each paying the
        # full cold compile (ISSUE 15 tier-1 wall-clock satellite)
        from netrep_tpu.utils.backend import enable_persistent_cache

        enable_persistent_cache()
    try:
        out = netrep_tpu.selftest(
            n_perm=args.n_perm, seed=args.seed, verbose=not args.json,
            max_shapes=args.max_shapes,
        )
    except (RuntimeError, ValueError) as e:
        print(f"selftest FAILED: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
