"""Module visualization suite — the rebuild of the reference's plot layer
(SURVEY.md §2.1 "Plot suite", §3.3): ``plot_module`` renders the stacked
composite (data heatmap + summary-profile bars, correlation heatmap,
edge-weight heatmap, node-contribution bars, weighted-degree bars) and the
per-panel functions ``plot_data`` / ``plot_correlation`` / ``plot_network`` /
``plot_summary`` / ``plot_contribution`` / ``plot_degree`` render each panel
alone — matplotlib instead of R base graphics, same semantics:

- nodes are grouped by module and ordered by weighted degree (descending)
  computed in ``order_nodes_by`` (default: the discovery dataset — the
  reference's ``orderNodesBy`` behavior, SURVEY.md §3.3);
- samples are ordered by the summary profile of ``order_samples_by``
  (default: the plotted dataset);
- the data/correlation panels use a diverging two-hue map around a neutral
  midpoint (values have polarity), the network panel a single-hue sequential
  map (edge weight is magnitude), bars a single neutral hue.

Pure host-side code: it only crosses into the compute layer through
:mod:`netrep_tpu.ops.oracle` (one-shot observed properties — SURVEY.md §3.3:
"never crosses into C++ except via networkProperties").
"""

from __future__ import annotations

import dataclasses

import numpy as np

import os
import sys

import matplotlib

# Headless-safe default: force Agg only on a display-less Linux box, and only
# when neither pyplot nor an explicit MPLBACKEND has had a say. macOS/Windows
# always have a GUI toolkit; Wayland sessions may have WAYLAND_DISPLAY but no
# DISPLAY; switching an interactive session to Agg would silently break
# plt.show().
if (
    "matplotlib.pyplot" not in sys.modules
    and not os.environ.get("MPLBACKEND")
    and sys.platform.startswith("linux")
    and not os.environ.get("DISPLAY")
    and not os.environ.get("WAYLAND_DISPLAY")
):
    matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
from matplotlib.gridspec import GridSpec  # noqa: E402

from .models import dataset as dsmod  # noqa: E402
from .ops import oracle  # noqa: E402

__all__ = [
    "plot_module",
    "plot_module_sparse",
    "plot_data",
    "plot_correlation",
    "plot_network",
    "plot_summary",
    "plot_contribution",
    "plot_degree",
    "node_order",
    "sample_order",
]

#: Diverging map (two hues + neutral midpoint) for signed quantities
#: (correlation, standardized expression).
DIVERGING_CMAP = "RdBu_r"
#: Single-hue sequential map for magnitudes (edge weights).
SEQUENTIAL_CMAP = "Purples"
#: Single neutral bar hue (one series per bar panel — no legend needed).
BAR_COLOR = "#5E7CA6"
#: Module separator / annotation ink.
_EDGE_INK = "#444444"


@dataclasses.dataclass
class ModuleLayout:
    """Resolved plotting layout for one (discovery → target) dataset view.

    Node order is the concatenation of per-module blocks (each internally
    ordered); ``boundaries`` are cumulative block edges for separator lines.
    """

    target: dsmod.Dataset
    modules: list[str]
    node_idx: np.ndarray          # target-dataset indices, plot order
    node_names: list[str]
    module_of: list[str]          # per plotted node
    boundaries: np.ndarray        # cumulative sizes, len = n_modules + 1
    degree: np.ndarray            # per plotted node (within its module)
    contribution: np.ndarray | None
    summary: np.ndarray | None    # (n_samples,) of the summary-order dataset
    sample_order: np.ndarray | None


def _prepare(
    network,
    data=None,
    correlation=None,
    module_assignments=None,
    modules=None,
    background_label: str = "0",
    discovery=None,
    test=None,
    order_nodes_by="discovery",
    order_samples_by="test",
    stats: str = "full",
) -> ModuleLayout:
    """Shared input processing for all plot functions (SURVEY.md §3.3: same
    L4 input layer, then networkProperties-style observed properties).

    ``stats`` bounds the data statistics computed: ``'full'`` (contribution +
    summary + sample order — the composite plot), ``'summary'`` (summary and
    sample order only), ``'none'`` (pure ordering; the per-module SVDs are
    skipped).
    """
    datasets = dsmod.build_datasets(network, data=data, correlation=correlation)
    names = list(datasets)
    d_name = str(discovery) if discovery is not None else names[0]
    t_name = (
        str(test)
        if test is not None
        else (names[1] if len(names) > 1 and names[1] != d_name else d_name)
    )
    for nm in (d_name, t_name):
        if nm not in datasets:
            raise ValueError(f"dataset {nm!r} not found; available: {names}")
    assign = dsmod.normalize_module_assignments(
        module_assignments, datasets, [d_name]
    )[d_name]

    disc_ds, tgt = datasets[d_name], datasets[t_name]
    labels, specs, _counts = dsmod.module_overlap(
        disc_ds, tgt, assign, modules, background_label
    )
    specs = [(lab, di, ti) for lab, di, ti in specs if len(ti) >= 1]
    if not specs:
        raise ValueError(
            f"no nodes of the requested module(s) are present in dataset "
            f"{t_name!r}"
        )

    if order_nodes_by == "discovery":
        order_ds, order_side = disc_ds, 0
    elif order_nodes_by == "test":
        order_ds, order_side = tgt, 1
    elif order_nodes_by is None:
        order_ds = order_side = None
    else:
        key = str(order_nodes_by)
        if key not in datasets:
            raise ValueError(
                f"order_nodes_by must be a dataset name, 'discovery', "
                f"'test', or None; got {order_nodes_by!r}"
            )
        order_ds = datasets[key]
        order_side = None

    node_idx, node_mods, degree = [], [], []
    for lab, di, ti in specs:
        if order_ds is None:
            order = np.arange(len(ti))
            deg_here = oracle.weighted_degree(tgt.network[np.ix_(ti, ti)])
        else:
            if order_side == 0:
                oidx = di
            elif order_side == 1:
                oidx = ti
            else:  # arbitrary dataset: map by node name, require presence
                opos = order_ds.index_of()
                oidx = np.asarray(
                    [opos.get(tgt.node_names[i], -1) for i in ti], dtype=np.int64
                )
                if (oidx < 0).any():
                    raise ValueError(
                        f"order_nodes_by dataset {order_ds.name!r} is missing "
                        f"nodes of module {lab!r}"
                    )
            deg_order = oracle.weighted_degree(order_ds.network[np.ix_(oidx, oidx)])
            order = np.argsort(-deg_order, kind="stable")
            deg_here = oracle.weighted_degree(tgt.network[np.ix_(ti, ti)])
        ti = np.asarray(ti)
        node_idx.extend(ti[order])
        node_mods.extend([lab] * len(ti))
        degree.extend(np.asarray(deg_here)[order])

    node_idx = np.asarray(node_idx, dtype=np.int64)
    sizes = [len(ti) for _lab, _di, ti in specs]
    boundaries = np.concatenate([[0], np.cumsum(sizes)])

    contribution = summary = sample_order = None
    if tgt.data is not None and stats != "none":
        if stats == "full":
            # per-module contribution/summary in the target dataset
            contribution = np.empty(node_idx.size)
            pos = 0
            for _lab, _di, ti in specs:
                block = node_idx[pos: pos + len(ti)]
                sub = tgt.data[:, block]
                contribution[pos: pos + len(ti)] = oracle.node_contribution(sub)
                pos += len(ti)
        # summary profile of the *first* plotted module orders the samples
        # (the reference's orderSamplesBy semantics: one profile, one order)
        # Sample ordering: samples belong to the plotted dataset, so only its
        # own summary profile (or input order) is meaningful — sample
        # universes are not comparable across datasets.
        summary = oracle.summary_profile(tgt.data[:, node_idx[: sizes[0]]])
        if order_samples_by is None:
            sample_order = np.arange(tgt.data.shape[0])
        elif order_samples_by == "test" or str(order_samples_by) == t_name:
            sample_order = np.argsort(summary, kind="stable")
        else:
            raise ValueError(
                f"order_samples_by must be the plotted dataset ({t_name!r} / "
                f"'test') or None (input order); got {order_samples_by!r} — "
                "samples are not shared across datasets, so another "
                "dataset's summary profile cannot order them"
            )

    return ModuleLayout(
        target=tgt,
        modules=[lab for lab, _di, _ti in specs],
        node_idx=node_idx,
        node_names=[tgt.node_names[i] for i in node_idx],
        module_of=node_mods,
        boundaries=boundaries,
        degree=np.asarray(degree),
        contribution=contribution,
        summary=summary,
        sample_order=sample_order,
    )


def node_order(
    network,
    data=None,
    correlation=None,
    module_assignments=None,
    modules=None,
    background_label: str = "0",
    discovery=None,
    test=None,
    order_nodes_by="discovery",
) -> list[str]:
    """Node names in module-preservation plotting order — the reference's
    exported ``nodeOrder()`` (upstream ``R/plotFunctions.R`` surface,
    SURVEY.md §3.3): per-module blocks, each ordered by weighted degree
    (descending) in the ``order_nodes_by`` dataset ('discovery' — the
    default and the reference's convention — 'test', a dataset name, or
    None for input order). Use it to build custom figures with the same
    layout as :func:`plot_module`."""
    layout = _prepare(
        network, data=data, correlation=correlation,
        module_assignments=module_assignments, modules=modules,
        background_label=background_label, discovery=discovery, test=test,
        order_nodes_by=order_nodes_by, order_samples_by=None,
        stats="none",
    )
    return list(layout.node_names)


def sample_order(
    network,
    data,
    correlation=None,
    module_assignments=None,
    modules=None,
    background_label: str = "0",
    discovery=None,
    test=None,
    order_samples_by="test",
):
    """Sample labels (or indices, for unnamed data) ordered by the plotted
    module's summary profile — the reference's exported ``sampleOrder()``:
    the row order :func:`plot_module`'s data heatmap uses. ``data`` is
    required (the summary profile is a data statistic); when more than one
    module is selected, the first module's profile defines the order, as in
    :func:`plot_module`."""
    layout = _prepare(
        network, data=data, correlation=correlation,
        module_assignments=module_assignments, modules=modules,
        background_label=background_label, discovery=discovery, test=test,
        # node order cannot affect the sample order (the summary profile is
        # column-permutation-invariant), so skip the degree sorts entirely
        order_nodes_by=None, order_samples_by=order_samples_by,
        stats="summary",
    )
    if layout.sample_order is None:
        raise ValueError(
            "sample_order requires `data` for the plotted (test) dataset — "
            "the summary profile that orders samples is a data statistic"
        )
    names = layout.target.sample_names
    if names is not None:
        return [names[i] for i in layout.sample_order]
    return np.asarray(layout.sample_order)


# ---------------------------------------------------------------------------
# Panel renderers (each draws into a supplied Axes)
# ---------------------------------------------------------------------------

def _module_separators(ax, layout: ModuleLayout, axis="x"):
    for b in layout.boundaries[1:-1]:
        if axis in ("x", "both"):
            ax.axvline(b - 0.5, color="white", lw=1.6)
            ax.axvline(b - 0.5, color=_EDGE_INK, lw=0.6)
        if axis in ("y", "both"):
            ax.axhline(b - 0.5, color="white", lw=1.6)
            ax.axhline(b - 0.5, color=_EDGE_INK, lw=0.6)


def _module_header(ax, layout: ModuleLayout):
    for k, lab in enumerate(layout.modules):
        lo, hi = layout.boundaries[k], layout.boundaries[k + 1]
        ax.text(
            (lo + hi - 1) / 2.0, 1.02, str(lab), ha="center", va="bottom",
            transform=ax.get_xaxis_transform(), fontsize=9, color=_EDGE_INK,
        )


def _node_ticks(ax, layout: ModuleLayout, show: bool):
    n = layout.node_idx.size
    if show and n <= 60:
        ax.set_xticks(np.arange(n))
        ax.set_xticklabels(layout.node_names, rotation=90, fontsize=6)
    else:
        ax.set_xticks([])


def _bar_panel(ax, values, layout: ModuleLayout, title: str, show_names: bool):
    x = np.arange(values.size)
    ax.bar(x, values, width=0.82, color=BAR_COLOR, edgecolor="none")
    ax.axhline(0.0, color=_EDGE_INK, lw=0.6)
    _module_separators(ax, layout, axis="x")
    ax.set_xlim(-0.5, values.size - 0.5)
    ax.set_ylabel(title, fontsize=8)
    ax.tick_params(labelsize=7)
    for spine in ("top", "right"):
        ax.spines[spine].set_visible(False)
    _node_ticks(ax, layout, show_names)


def draw_data(ax, layout: ModuleLayout, cmap=DIVERGING_CMAP, show_names=False):
    """Standardized data heatmap: samples (rows, ordered) × nodes (cols)."""
    if layout.target.data is None:
        raise ValueError(
            f"dataset {layout.target.name!r} has no data matrix; the data "
            "heatmap requires one (data-less variant plots topology panels "
            "only)"
        )
    z = oracle.standardize(layout.target.data[:, layout.node_idx])
    z = z[layout.sample_order]
    lim = np.nanmax(np.abs(z)) if z.size else np.nan
    if not np.isfinite(lim) or lim == 0:
        lim = 1.0
    im = ax.imshow(
        z, aspect="auto", cmap=cmap, vmin=-lim, vmax=lim,
        interpolation="nearest",
    )
    _module_separators(ax, layout, axis="x")
    ax.set_ylabel("samples", fontsize=8)
    ax.set_yticks([])
    _node_ticks(ax, layout, show_names)
    return im


def draw_correlation(ax, layout: ModuleLayout, cmap=DIVERGING_CMAP, show_names=False):
    """Node × node correlation heatmap on the plot order."""
    sub = layout.target.correlation[np.ix_(layout.node_idx, layout.node_idx)]
    im = ax.imshow(
        sub, aspect="auto", cmap=cmap, vmin=-1.0, vmax=1.0,
        interpolation="nearest",
    )
    _module_separators(ax, layout, axis="both")
    ax.set_yticks([])
    ax.set_ylabel("correlation", fontsize=8)
    _node_ticks(ax, layout, show_names)
    return im


def draw_network(ax, layout: ModuleLayout, cmap=SEQUENTIAL_CMAP, show_names=False):
    """Node × node edge-weight heatmap (magnitude → sequential map)."""
    sub = layout.target.network[np.ix_(layout.node_idx, layout.node_idx)].copy()
    np.fill_diagonal(sub, np.nan)  # self-edges carry no information
    with np.errstate(all="ignore"):
        vmax = np.nanmax(sub) if sub.size > 1 else np.nan
    if not np.isfinite(vmax) or vmax == 0:
        vmax = 1.0
    im = ax.imshow(
        sub, aspect="auto", cmap=cmap, vmin=0.0, vmax=vmax,
        interpolation="nearest",
    )
    _module_separators(ax, layout, axis="both")
    ax.set_yticks([])
    ax.set_ylabel("edge weight", fontsize=8)
    _node_ticks(ax, layout, show_names)
    return im


def draw_summary(ax, layout: ModuleLayout):
    """Horizontal summary-profile bars aligned with the data heatmap rows."""
    if layout.summary is None:
        raise ValueError("summary profile requires a data matrix")
    vals = layout.summary[layout.sample_order]
    y = np.arange(vals.size)
    ax.barh(y, vals, height=0.82, color=BAR_COLOR, edgecolor="none")
    ax.axvline(0.0, color=_EDGE_INK, lw=0.6)
    ax.set_ylim(vals.size - 0.5, -0.5)  # match imshow row direction
    ax.set_yticks([])
    ax.set_xlabel("summary", fontsize=8)
    ax.xaxis.set_major_locator(matplotlib.ticker.MaxNLocator(2))
    ax.tick_params(labelsize=7)
    for spine in ("top", "right"):
        ax.spines[spine].set_visible(False)


def draw_contribution(ax, layout: ModuleLayout, show_names=False):
    if layout.contribution is None:
        raise ValueError("node contribution requires a data matrix")
    _bar_panel(ax, layout.contribution, layout, "contribution", show_names)


def draw_degree(ax, layout: ModuleLayout, show_names=False):
    _bar_panel(ax, layout.degree, layout, "weighted degree", show_names)


# ---------------------------------------------------------------------------
# Public per-panel functions (reference: plotData / plotCorrelation /
# plotNetwork / plotContribution / plotDegree — SURVEY.md §2.1)
# ---------------------------------------------------------------------------

def _single_panel(draw, colorbar, ax=None, show_node_names=True,
                  stats="full", **kwargs):
    layout = _prepare(stats=stats, **kwargs)
    if ax is None:
        _fig, ax = plt.subplots(figsize=(8, 4))
    art = draw(ax, layout, show_names=show_node_names)
    _module_header(ax, layout)
    if colorbar and art is not None:
        ax.figure.colorbar(art, ax=ax, fraction=0.04, pad=0.02)
    return ax


# The per-panel functions share the composite's reference-shaped signature
# (SURVEY.md §2.1: the reference's plot suite exposes one argument set
# across plotModule and the panel plots). Explicit parameters — not **kw —
# so the R shim's camelCase->snake_case mapping is machine-checkable
# against a real signature (tests/test_r_shim.py).
def plot_data(network, data=None, correlation=None, module_assignments=None,
              modules=None, background_label: str = "0", discovery=None,
              test=None, order_nodes_by="discovery", order_samples_by="test",
              show_node_names: bool = True, ax=None):
    """Standalone data heatmap panel (reference ``plotData``)."""
    return _single_panel(
        draw_data, True, ax=ax, show_node_names=show_node_names,
        stats="summary",
        network=network, data=data, correlation=correlation,
        module_assignments=module_assignments, modules=modules,
        background_label=background_label, discovery=discovery, test=test,
        order_nodes_by=order_nodes_by, order_samples_by=order_samples_by,
    )


def plot_correlation(network, data=None, correlation=None,
                     module_assignments=None, modules=None,
                     background_label: str = "0", discovery=None, test=None,
                     order_nodes_by="discovery", order_samples_by="test",
                     show_node_names: bool = True, ax=None):
    """Standalone correlation heatmap panel (reference ``plotCorrelation``)."""
    return _single_panel(
        draw_correlation, True, ax=ax, show_node_names=show_node_names,
        stats="none",
        network=network, data=data, correlation=correlation,
        module_assignments=module_assignments, modules=modules,
        background_label=background_label, discovery=discovery, test=test,
        order_nodes_by=order_nodes_by, order_samples_by=order_samples_by,
    )


def plot_network(network, data=None, correlation=None,
                 module_assignments=None, modules=None,
                 background_label: str = "0", discovery=None, test=None,
                 order_nodes_by="discovery", order_samples_by="test",
                 show_node_names: bool = True, ax=None):
    """Standalone edge-weight heatmap panel (reference ``plotNetwork``)."""
    return _single_panel(
        draw_network, True, ax=ax, show_node_names=show_node_names,
        stats="none",
        network=network, data=data, correlation=correlation,
        module_assignments=module_assignments, modules=modules,
        background_label=background_label, discovery=discovery, test=test,
        order_nodes_by=order_nodes_by, order_samples_by=order_samples_by,
    )


def plot_summary(network, data=None, correlation=None,
                 module_assignments=None, modules=None,
                 background_label: str = "0", discovery=None, test=None,
                 order_nodes_by="discovery", order_samples_by="test",
                 ax=None):
    """Standalone summary-profile bar panel (per sample)."""
    layout = _prepare(
        network=network, data=data, correlation=correlation,
        module_assignments=module_assignments, modules=modules,
        background_label=background_label, discovery=discovery, test=test,
        order_nodes_by=order_nodes_by, order_samples_by=order_samples_by,
        stats="summary",
    )
    if ax is None:
        _fig, ax = plt.subplots(figsize=(3, 5))
    draw_summary(ax, layout)
    return ax


def plot_contribution(network, data=None, correlation=None,
                      module_assignments=None, modules=None,
                      background_label: str = "0", discovery=None, test=None,
                      order_nodes_by="discovery", order_samples_by="test",
                      show_node_names: bool = True, ax=None):
    """Standalone node-contribution bar panel (reference ``plotContribution``)."""
    return _single_panel(
        draw_contribution, False, ax=ax, show_node_names=show_node_names,
        stats="full",
        network=network, data=data, correlation=correlation,
        module_assignments=module_assignments, modules=modules,
        background_label=background_label, discovery=discovery, test=test,
        order_nodes_by=order_nodes_by, order_samples_by=order_samples_by,
    )


def plot_degree(network, data=None, correlation=None,
                module_assignments=None, modules=None,
                background_label: str = "0", discovery=None, test=None,
                order_nodes_by="discovery", order_samples_by="test",
                show_node_names: bool = True, ax=None):
    """Standalone weighted-degree bar panel (reference ``plotDegree``)."""
    return _single_panel(
        draw_degree, False, ax=ax, show_node_names=show_node_names,
        stats="none",
        network=network, data=data, correlation=correlation,
        module_assignments=module_assignments, modules=modules,
        background_label=background_label, discovery=discovery, test=test,
        order_nodes_by=order_nodes_by, order_samples_by=order_samples_by,
    )


# ---------------------------------------------------------------------------
# The composite (reference: plotModule — SURVEY.md §3.3)
# ---------------------------------------------------------------------------

def plot_module(
    network,
    data=None,
    correlation=None,
    module_assignments=None,
    modules=None,
    background_label: str = "0",
    discovery=None,
    test=None,
    order_nodes_by="discovery",
    order_samples_by="test",
    show_node_names: bool | None = None,
    figsize=(9.5, 12),
    fig=None,
):
    """Composite module plot: stacked panels sharing the node axis — data
    heatmap (with summary-profile bars on the left), correlation heatmap,
    edge-weight heatmap, node-contribution bars, weighted-degree bars
    (SURVEY.md §2.1 "Plot suite"). Data panels are dropped in the data-less
    variant.

    Returns ``(fig, axes)`` where ``axes`` is a dict keyed by panel name.
    """
    layout = _prepare(
        network=network, data=data, correlation=correlation,
        module_assignments=module_assignments, modules=modules,
        background_label=background_label, discovery=discovery, test=test,
        order_nodes_by=order_nodes_by, order_samples_by=order_samples_by,
    )
    has_data = layout.target.data is not None
    if show_node_names is None:
        show_node_names = layout.node_idx.size <= 60

    rows = (
        ["data", "correlation", "network", "contribution", "degree"]
        if has_data
        else ["correlation", "network", "degree"]
    )
    heights = {"data": 2.2, "correlation": 3.0, "network": 3.0,
               "contribution": 1.0, "degree": 1.0}
    if fig is None:
        fig = plt.figure(figsize=figsize)
    gs = GridSpec(
        len(rows), 3,
        width_ratios=[0.9, 8.0, 0.25],
        height_ratios=[heights[r] for r in rows],
        hspace=0.28, wspace=0.06, figure=fig,
    )

    axes: dict[str, plt.Axes] = {}
    for i, row in enumerate(rows):
        ax = fig.add_subplot(gs[i, 1])
        axes[row] = ax
        last = i == len(rows) - 1
        names_here = show_node_names and last
        if row == "data":
            im = draw_data(ax, layout, show_names=names_here)
            axs = fig.add_subplot(gs[i, 0], sharey=ax)
            draw_summary(axs, layout)
            axes["summary"] = axs
            cax = fig.add_subplot(gs[i, 2])
            fig.colorbar(im, cax=cax)
            cax.tick_params(labelsize=6)
            _module_header(ax, layout)
        elif row == "correlation":
            im = draw_correlation(ax, layout, show_names=names_here)
            cax = fig.add_subplot(gs[i, 2])
            fig.colorbar(im, cax=cax)
            cax.tick_params(labelsize=6)
            if rows[0] == "correlation":
                _module_header(ax, layout)
        elif row == "network":
            im = draw_network(ax, layout, show_names=names_here)
            cax = fig.add_subplot(gs[i, 2])
            fig.colorbar(im, cax=cax)
            cax.tick_params(labelsize=6)
        elif row == "contribution":
            draw_contribution(ax, layout, show_names=names_here)
        elif row == "degree":
            draw_degree(ax, layout, show_names=names_here)

    fig.align_ylabels(list(axes.values()))
    fig.suptitle(
        f"Module preservation view — dataset {layout.target.name!r}",
        fontsize=11, y=0.995,
    )
    return fig, axes


def plot_module_sparse(
    network,
    data=None,
    correlation=None,
    module_assignments=None,
    names=None,
    modules=None,
    background_label: str = "0",
    max_nodes: int = 4000,
    **kw,
):
    """Composite module plot for SPARSE networks (Config E): densify ONLY
    the requested modules' subgraph — m ≪ n nodes, so the m×m panels are
    cheap even when the full n×n matrix could never exist — and reuse
    :func:`plot_module`'s panel stack.

    Parameters mirror :func:`~netrep_tpu.models.sparse_api.sparse_module_preservation`
    where they apply: ``network`` is a
    :class:`~netrep_tpu.ops.sparse.SparseAdjacency`; ``correlation`` an
    optional sparse correlation in the same format (used for the
    correlation heatmap when given; otherwise it derives from ``data``; one
    of the two is required). ``max_nodes`` guards against accidentally
    densifying a huge node set — pass an explicit ``modules=`` selection
    for large graphs. Remaining keyword arguments forward to
    :func:`plot_module`.
    """
    import pandas as pd

    from .models.sparse_api import _normalize_assignments, _normalize_names
    from .ops.sparse import SparseAdjacency

    if not isinstance(network, SparseAdjacency):
        raise TypeError("network must be a SparseAdjacency")
    if data is None and correlation is None:
        raise ValueError(
            "provide data= and/or correlation= (sparse): the correlation "
            "heatmap panel needs one of them"
        )
    if data is not None:
        data = np.asarray(data)
        if data.ndim != 2 or data.shape[1] != network.n:
            raise ValueError(
                f"data must be (n_samples, {network.n}), got "
                f"{getattr(data, 'shape', None)}"
            )
    if correlation is not None and (
        not isinstance(correlation, SparseAdjacency)
        or correlation.n != network.n
    ):
        raise ValueError(
            "correlation must be a SparseAdjacency over the same "
            f"{network.n} nodes"
        )
    names = _normalize_names(names, network.n)
    assignments = _normalize_assignments(module_assignments, names)

    wanted = (
        [str(m) for m in modules] if modules is not None
        else sorted({l for l in assignments.values()
                     if l != str(background_label)})
    )
    keep = [i for i, nm in enumerate(names) if assignments[nm] in wanted]
    if not keep:
        raise ValueError(f"no nodes carry module label(s) {wanted}")
    if len(keep) > max_nodes:
        raise ValueError(
            f"selected modules cover {len(keep)} nodes (> max_nodes="
            f"{max_nodes}); pass a smaller modules= selection"
        )
    idx = np.asarray(keep, dtype=np.int64)
    sub_names = [names[i] for i in idx]

    # global node id → local position (or -1), shared by both densify calls;
    # width n+1 so sentinel-padded neighbor ids (== n) land on the -1 slot
    local_of = np.full(network.n + 1, -1, dtype=np.int64)
    local_of[idx] = np.arange(idx.size)

    def densify(adj, diag):
        nbr = adj.nbr[idx]                       # (m, k) global neighbor ids
        wgt = adj.wgt[idx].astype(np.float64)
        cols = local_of[nbr]                     # (m, k) local cols or -1
        rows = np.broadcast_to(
            np.arange(idx.size)[:, None], nbr.shape
        )
        keep = cols >= 0
        out = np.zeros((idx.size, idx.size))
        out[rows[keep], cols[keep]] = wgt[keep]
        np.fill_diagonal(out, diag)
        return pd.DataFrame(out, index=sub_names, columns=sub_names)

    net_df = densify(network, 1.0)
    if correlation is not None:
        corr_df = densify(correlation, 1.0)
    else:
        sub = np.asarray(data)[:, idx]
        corr_df = pd.DataFrame(
            np.corrcoef(sub, rowvar=False), index=sub_names, columns=sub_names
        )
    data_df = (
        pd.DataFrame(np.asarray(data)[:, idx], columns=sub_names)
        if data is not None else None
    )
    sub_assign = {nm: assignments[nm] for nm in sub_names}
    return plot_module(
        network=net_df, data=data_df, correlation=corr_df,
        module_assignments=sub_assign, modules=wanted,
        background_label=background_label, **kw,
    )
