"""Benchmarks for the framework's configurations (SURVEY.md §6, §7 step 8;
BASELINE.md).

Default (no ``--config``): the north-star — a 10,000-permutation null on a
20,000-gene / 50-module network (BASELINE.json:5) — on whatever accelerator
JAX finds (the driver runs this on one real TPU chip). Prints ONE JSON line:
    {"metric": ..., "value": <wall-clock seconds>, "unit": "s",
     "vs_baseline": <target_seconds / value>}
``vs_baseline`` > 1 means faster than the 60 s north-star target.

Other configs (each also prints one JSON line; numbers recorded in
BASELINE.md):

    --config A       ~100-node toy, 4 modules, 1000 perms: pure-NumPy oracle
                     (the measurable CPU baseline, SURVEY.md §6) AND the JAX
                     engine on the same problem/backend
    --config B       5,000 genes / 20 modules / 10,000 perms
    --config C       1 discovery x 4 test cohorts: vmapped multi-test path
                     vs sequential pairs (same problem, same seed)
    --config D       20,000 genes / 50 modules / 100,000 perms with
                     checkpointing every 8192
    --config E       sparse 50k-node kNN graph (k=30, ~1.5M edges),
                     30 modules, 10,000 perms
    --config adaptive  sequential early-stopping (Besag–Clifford) null vs
                     fixed n_perm on a mixed half-preserved/half-random
                     fixture: one row with both wall-clocks, permutations
                     evaluated for each, and decision agreement at
                     alpha=0.05 (measurable on CPU; clamped north-star
                     shape)
    --config superchunk  streaming executor (store_nulls=False): scan-fused
                     superchunk dispatch + on-device exceedance tallies vs
                     the fixed-n chunk loop on the same problem/key — one
                     row with both wall-clocks, dispatches issued, and
                     device→host bytes (counts parity asserted first)
    --config serve   `netrep serve` load generator (benchmarks/serve_load.py):
                     closed-/open-loop mixed multi-tenant traffic against the
                     in-process server — p50/p99 latency, aggregate perms/s,
                     cross-request pack statistics, warm-pool compile_span
                     proof, and throughput vs the serial direct-call baseline
    --config oracle  pure-NumPy oracle (the reference-style CPU loop) on the
                     north-star problem shape at a reduced permutation count
                     (default 50) — the per-config "oracle-CPU" baseline row;
                     combine with --genes/--modules for other shapes
    --config mixed   mixed-precision screened null (ISSUE 16,
                     null_precision=bf16_rescue): bf16 fast pass + exact
                     f32 rescue vs the all-f32 loop on the same problem
                     and key — one row with both wall-clocks and the
                     rescued fraction (pinned-equal-counts gate asserted
                     before any number is emitted)
    --config grid    all-pairs preservation atlas (ISSUE 17,
                     grid_preservation): the packed D x D grid vs the
                     D*(D-1) sequential solo loop on the same cohorts,
                     plus a one-cohort incremental delta against the
                     grid manifest — one row with all three wall-clocks
                     (per-cell bit-identity to the solo runs asserted
                     before any number is emitted)
    --config sharded delegates to benchmarks/microbench_sharded_gather.py

Usage: python bench.py [--config X] [--genes N] [--modules K] [--perms P]
                       [--chunk C] [--samples S] [--dtype D] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

TARGET_SECONDS = 60.0  # BASELINE.json:5 north-star
#: set by ensure_backend when a dead TPU tunnel forced the CPU fallback.
#: north/B then measure a reduced permutation count and project; the scale
#: configs (C/D/E) emit an explicit skip row instead of running for hours
#: on CPU (see main()).
TPU_FALLBACK = False
#: telemetry JSONL path when --telemetry / NETREP_TELEMETRY is set: an
#: ambient netrep_tpu.utils.telemetry.Telemetry bus is activated for the
#: whole bench process, so engine runs emit per-chunk/superchunk events
#: beside the metric row — BENCH trajectories then carry per-phase
#: breakdowns, not just wall-clock (ISSUE 3). The metric row names the
#: file so the two stay linked.
TELEMETRY_PATH = None
#: the live ambient-activation context manager (held for the process
#: lifetime; see the --telemetry block in main())
_TEL_CM = None
#: structured backend-probe record (ISSUE 5 satellite): duration + outcome
#: of ensure_backend's tunnel probe, attached to EVERY metric row — the
#: round-5 120 s silent probe hang was visible only in a prose warning
PROBE_INFO = {}
#: why this process is not on the TPU ("probe_timeout" / "forced_env"),
#: attached to every metric row beside the existing tpu_fallback marker
FALLBACK_REASON = None


def ensure_backend(probe_timeout: float | None = None):
    """Resolve a usable JAX backend. The driver environment pins
    JAX_PLATFORMS=axon (the TPU tunnel), whose plugin registration is
    flaky — and whose ``jax.devices()`` HANGS indefinitely (not errors)
    when the tunnel is down. Probe in a killable subprocess first so a dead
    tunnel produces a fast, explicit error line instead of an opaque hang;
    registration errors still fall back to automatic backend selection."""
    import os

    import jax

    from netrep_tpu.utils.backend import (
        honor_explicit_platform, probe_default_backend, tunnel_expected,
    )

    # Persistent compile cache: a tunnel death mid-benchmark no longer
    # wastes the per-bucket compiles — the next window's warmup chunk hits
    # the cache and goes straight to measurement (the 7/29 and 7/31 windows
    # were ~5-7 min; compile-heavy steps must be resumable to fit). No
    # repo_root argument: the helper's own derivation is the single source
    # of the cache dir shared with conftest/dryrun.
    from netrep_tpu.utils.backend import enable_persistent_cache

    enable_persistent_cache()

    global TPU_FALLBACK, FALLBACK_REASON
    t_probe0 = time.perf_counter()

    def _record_probe(outcome: str):
        """Structured probe record (ISSUE 5 satellite): duration + outcome
        land on every metric row via emit(), and the bench path emits its
        own ``backend_probe`` event so a telemetry log shows the probe
        cost even when the resolution path skipped the subprocess dial."""
        PROBE_INFO.clear()
        PROBE_INFO["probe_outcome"] = outcome
        PROBE_INFO["probe_s"] = round(time.perf_counter() - t_probe0, 3)
        from netrep_tpu.utils.telemetry import current as _tel_current

        tel = _tel_current()
        if tel is not None:
            tel.emit("backend_probe", outcome=outcome,
                     s=time.perf_counter() - t_probe0, source="bench")

    if os.environ.get("NETREP_FORCE_TPU_FALLBACK"):
        # set by run_shielded's second attempt after the TPU child hung:
        # behave exactly like a probe-detected dead tunnel (reduced-count
        # projected rows / explicit skip rows, tpu_fallback markers)
        jax.config.update("jax_platforms", "cpu")
        os.environ["JAX_PLATFORMS"] = "cpu"
        TPU_FALLBACK = True
        FALLBACK_REASON = "forced_env"
        _record_probe("forced_fallback")
        return jax.devices()

    if probe_timeout is None:
        try:
            probe_timeout = float(
                os.environ.get("NETREP_BACKEND_PROBE_TIMEOUT", "120")
            )
        except ValueError:
            probe_timeout = 120.0
    # An explicit non-TPU platform (e.g. JAX_PLATFORMS=cpu) is honored via
    # the live config — the env var alone does NOT stop the axon plugin's
    # get_backend hook from dialing the tunnel.
    devs = honor_explicit_platform()
    if devs is not None:
        _record_probe("explicit_platform")
        return devs
    if tunnel_expected():
        # only a TIMEOUT means the tunnel is hung-dead; a fast "error" probe
        # (e.g. plugin registration RuntimeError) falls through to the
        # auto-backend fallback below, as before
        outcome = probe_default_backend(probe_timeout)
        if outcome == "timeout":
            # Round-2 aborted here (rc=1) and the round's driver-visible
            # perf record was an error line. Fall back to CPU instead: the
            # caller reduces the permutation count and the emitted row
            # carries device + tpu_fallback markers, so a dead tunnel now
            # yields a real (honestly-labeled) measurement.
            print(json.dumps({
                "metric": "backend probe",
                "warning": (
                    "TPU tunnel (axon) unreachable: jax.devices() probe "
                    f"did not complete in {probe_timeout:.0f}s; falling "
                    "back to CPU at reduced permutation count."
                ),
            }), file=sys.stderr)
            jax.config.update("jax_platforms", "cpu")
            os.environ["JAX_PLATFORMS"] = "cpu"
            TPU_FALLBACK = True
            FALLBACK_REASON = "probe_timeout"
            _record_probe("timeout")
            return jax.devices()
        _record_probe(outcome)
    else:
        _record_probe("no_tunnel")
    try:
        return jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "")
        FALLBACK_REASON = FALLBACK_REASON or "registration_error"
        return jax.devices()


def host_contention():
    """Box-contention context attached to CPU-fallback rows (VERDICT r5
    weak #4): the round-5 fallback drifted 752→982 s with no code change,
    and nothing recorded whether the box was busy — loadavg plus the
    running/total process counts make contention distinguishable from a
    real regression when comparing rows across rounds."""
    import os

    try:
        la = os.getloadavg()
    except OSError:  # pragma: no cover - /proc-less platforms
        la = (float("nan"),) * 3
    running = total = 0
    try:
        for pid in os.listdir("/proc"):
            if not pid.isdigit():
                continue
            try:
                with open(f"/proc/{pid}/stat") as f:
                    # state is the field after the parenthesized comm
                    # (which may itself contain spaces)
                    state = f.read().rsplit(")", 1)[1].split()[0]
            except (OSError, IndexError):
                continue
            total += 1
            if state == "R":
                running += 1
    except OSError:  # pragma: no cover
        pass
    return {
        "loadavg": [round(x, 2) for x in la],
        "procs_running": running,
        "procs_total": total,
        "cpus": len(os.sched_getaffinity(0)),
    }


def build_problem(n_genes, n_modules, n_samples, seed=0):
    """Synthetic genome-scale co-expression pair, generated on device:
    data → correlation (one big MXU matmul) → soft-threshold adjacency."""
    import jax
    import jax.numpy as jnp

    def one(key):
        x = jax.random.normal(key, (n_samples, n_genes), dtype=jnp.float32)
        # plant module structure on a rolling window so modules are real
        z = x - x.mean(0)
        z = z / jnp.linalg.norm(z, axis=0)
        corr = jnp.clip(z.T @ z, -1.0, 1.0)
        net = jnp.abs(corr) ** 2
        return x, corr, net

    k1, k2 = jax.random.split(jax.random.key(seed))
    return one(k1), one(k2)


def make_specs(n_genes, n_modules, lo=30, hi=200, seed=1):
    from netrep_tpu.parallel.engine import ModuleSpec

    rng = np.random.default_rng(seed)
    sizes = np.exp(rng.uniform(np.log(lo), np.log(hi), size=n_modules)).astype(int)
    specs, pos = [], 0
    for k, sz in enumerate(sizes):
        idx = np.arange(pos, pos + sz, dtype=np.int32)
        specs.append(ModuleSpec(str(k + 1), idx, idx))
        pos += sz
    assert pos <= n_genes, "module sizes exceed gene count"
    return specs


def make_specs_auto(n_genes, n_modules, seed=1):
    """Module-size range for benchmark scripts at arbitrary ``--genes``:
    the north-star 30-200 range when the pool fits it (>= 10k genes), the
    smoke range (8, 24) below — ONE clamp site shared by tune_northstar,
    bf16_drift and microbench_sharded_gather (review r5: the clamp was
    copy-pasted, and one script lacked make_specs' oversubscription
    assert entirely)."""
    lo, hi = (30, 200) if n_genes >= 10_000 else (8, 24)
    return make_specs(n_genes, n_modules, lo, hi, seed)


def timed_null(engine, n_perm, chunk, **kw):
    """Warm up one chunk (compile, excluded — once-per-shape), then time."""
    import jax

    _ = engine.run_null(chunk, key=99)
    if hasattr(engine, "_test_corr") and engine._test_corr is not None:
        jax.block_until_ready(engine._test_corr)
    t0 = time.perf_counter()
    nulls, done = engine.run_null(n_perm, key=0, **kw)
    elapsed = time.perf_counter() - t0
    assert done == n_perm
    assert np.isfinite(np.asarray(nulls)).all()
    return elapsed


def emit(payload):
    import os

    if isinstance(payload, dict):
        if TELEMETRY_PATH:
            payload.setdefault("telemetry", TELEMETRY_PATH)
        # structured probe/fallback provenance on EVERY metric row
        # (ISSUE 5 satellite): the round-5 120 s silent probe hang and
        # the unexplained CPU rows become machine-readable fields
        for k, v in PROBE_INFO.items():
            payload.setdefault(k, v)
        if FALLBACK_REASON is not None:
            payload.setdefault("fallback_reason", FALLBACK_REASON)
        if isinstance(payload.get("perms_per_sec"), (int, float)):
            # roofline provenance on every throughput row (ISSUE 18):
            # the engine's end-of-run accounting leaves its roofline
            # block as a process note; CONSUME it so a stale note from
            # an earlier benchmark never lands on an unrelated row.
            # Telemetry-off runs leave no note — fields are then null,
            # never guessed.
            from netrep_tpu.utils import costmodel

            note = costmodel.last_run_note(consume=True)
            payload.setdefault("flops",
                               note.get("flops") if note else None)
            payload.setdefault("bytes_hbm",
                               note.get("bytes_hbm") if note else None)
            payload.setdefault("utilisation",
                               note.get("utilisation") if note else None)
            if note is not None:
                payload.setdefault("roofline", note)
        if os.environ.get("NETREP_PERF_LEDGER"):
            # feed the perf-regression ledger (best-effort, never fails
            # the bench): one throughput fingerprint per measured row
            from netrep_tpu.utils import perfledger

            entry = perfledger.entry_from_bench_row(payload)
            if entry is not None:
                perfledger.append_entry(
                    entry, os.environ["NETREP_PERF_LEDGER"]
                )
    print(json.dumps(payload))
    return 0


def resolve(args, genes, modules, perms):
    """Fill per-config defaults for flags the user did not pass (None
    default — explicitly passing any value, including a config's own
    default, is honored as given)."""
    args.genes = genes if args.genes is None else args.genes
    args.modules = modules if args.modules is None else args.modules
    args.perms = perms if args.perms is None else args.perms
    return args


def bench_north(args, label=None):
    import jax

    resolve(args, 20_000, 50, 10_000)

    from netrep_tpu.parallel.engine import PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = build_problem(
        args.genes, args.modules, args.samples
    )
    lo, hi = (30, 200) if not args.smoke else (8, 24)
    specs = make_specs(args.genes, args.modules, lo, hi)
    pool = np.arange(args.genes, dtype=np.int32)
    cfg = EngineConfig(
        chunk_size=args.chunk, summary_method="power", power_iters=40,
        dtype=args.dtype, gather_mode=args.gather_mode,
        cap_granularity=args.cap_granularity,
        # the bench problem's network IS |corr|**2 by construction, so
        # derived mode computes the identical statistics while halving the
        # gather traffic (the roofline bottleneck, BASELINE.md)
        network_from_correlation=2.0 if args.derived_net else None,
    )
    engine = PermutationEngine(
        d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool, config=cfg
    )
    measured = args.perms
    if TPU_FALLBACK:
        # dead tunnel → CPU: measure a slice and project (the chunked loop
        # is linear in n_perm); the row stays real and honestly labeled
        measured = min(args.perms, max(2 * cfg.chunk_size, 256))
    elapsed = timed_null(engine, measured, cfg.chunk_size)
    projected = elapsed * args.perms / measured
    if label is None:
        label = "north-star config, BASELINE.json:5"
    if args.derived_net:
        label += "; derived network |corr|^2"
    if args.cap_granularity != 32:
        label += f"; cap_granularity {args.cap_granularity}"
    row = {
        "metric": (
            f"wall-clock for {args.perms}-perm null, {args.genes} genes / "
            f"{args.modules} modules ({label})"
        ),
        "value": round(projected, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_SECONDS / projected, 4),
        "perms_per_sec": round(measured / elapsed, 2),
        "device": str(jax.devices()[0]),
        "dtype": args.dtype,
        "chunk": args.chunk,
        "gather_mode": engine.gather_mode,  # resolved, not the 'auto' alias
    }
    if TPU_FALLBACK:
        row["tpu_fallback"] = True
        row["measured_perms"] = measured
        row["host_load"] = host_contention()
        row["metric"] += " [CPU fallback: TPU tunnel unreachable]"
    return emit(row)


def bench_a(args):
    """Config A (BASELINE.json:7): toy fixture; oracle-NumPy vs JAX engine."""
    import jax

    from netrep_tpu.data import make_example_pair
    from netrep_tpu.ops import oracle
    from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    resolve(args, 0, 0, 1000)
    n_perm = args.perms
    pair = make_example_pair(np.random.default_rng(42))
    d, t = pair["discovery"], pair["test"]
    tpos = {nm: i for i, nm in enumerate(t["names"])}
    specs, disc_props, sizes = [], [], []
    for lab in sorted(pair["module_sizes"]):
        nodes = [nm for nm, l in pair["labels"].items() if l == lab]
        di = np.array([d["names"].index(nm) for nm in nodes if nm in tpos],
                      dtype=np.int32)
        ti = np.array([tpos[nm] for nm in nodes if nm in tpos], dtype=np.int32)
        specs.append(ModuleSpec(lab, di, ti))
        sizes.append(len(ti))
        disc_props.append(oracle.DiscoveryProps(
            d["correlation"][np.ix_(di, di)], d["network"][np.ix_(di, di)],
            d["data"][:, di],
        ))
    pool = np.array([tpos[nm] for nm in d["names"] if nm in tpos],
                    dtype=np.int32)

    t0 = time.perf_counter()
    nulls_o = oracle.permutation_null(
        disc_props, sizes, t["correlation"], t["network"], t["data"],
        pool, n_perm, np.random.default_rng(0),
    )
    oracle_s = time.perf_counter() - t0
    assert np.isfinite(nulls_o).all()

    cfg = EngineConfig(chunk_size=256)
    engine = PermutationEngine(
        d["correlation"], d["network"], d["data"],
        t["correlation"], t["network"], t["data"], specs, pool, config=cfg,
    )
    jax_s = timed_null(engine, n_perm, cfg.chunk_size)
    return emit({
        "metric": f"Config A toy ({len(specs)} modules, {n_perm} perms): "
                  "oracle-NumPy CPU vs JAX engine",
        "value": round(jax_s, 3),
        "unit": "s",
        "vs_baseline": round(oracle_s / jax_s, 2),  # speedup over oracle
        "oracle_cpu_s": round(oracle_s, 3),
        "oracle_perms_per_sec": round(n_perm / oracle_s, 1),
        "jax_perms_per_sec": round(n_perm / jax_s, 1),
        "device": str(jax.devices()[0]),
    })


def bench_oracle(args):
    """Oracle-CPU row for arbitrary problem shapes (BASELINE.md "oracle-CPU
    row per config"): the pure-NumPy reference loop on the same synthetic
    problem the JAX configs use, at a reduced permutation count (wall-clock
    per permutation is what matters; the loop is embarrassingly linear in
    n_perm)."""
    from netrep_tpu.ops import oracle

    resolve(args, 20_000, 50, 50)
    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = [
        tuple(np.asarray(a) for a in side)
        for side in build_problem(args.genes, args.modules, args.samples)
    ]
    # SAME module-size rule as the JAX configs — the oracle row must measure
    # the same problem the accelerated row runs, not an easier one
    lo, hi = (30, 200) if not args.smoke else (8, 24)
    specs = make_specs(args.genes, args.modules, lo, hi)
    pool = np.arange(args.genes, dtype=np.int32)

    disc_props = [
        oracle.DiscoveryProps(
            d_corr[np.ix_(m.disc_idx, m.disc_idx)],
            d_net[np.ix_(m.disc_idx, m.disc_idx)],
            d_data[:, m.disc_idx],
        )
        for m in specs
    ]
    sizes = [m.size for m in specs]
    from threadpoolctl import threadpool_limits

    t0 = time.perf_counter()
    with threadpool_limits(limits=1):  # honest single-thread baseline
        nulls = oracle.permutation_null(
            disc_props, sizes, t_corr, t_net, t_data, pool, args.perms,
            np.random.default_rng(0),
        )
    elapsed = time.perf_counter() - t0
    assert np.isfinite(nulls).all()
    pps = args.perms / elapsed
    return emit({
        "metric": (
            f"oracle-NumPy CPU loop, {args.genes} genes / {args.modules} "
            f"modules ({args.perms} perms measured; reference-style "
            "baseline, BLAS pinned to 1 thread)"
        ),
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(pps * TARGET_SECONDS / 10_000, 4),
        "perms_per_sec": round(pps, 3),
        "projected_10k_perm_s": round(10_000 / pps, 1),
        "device": "CPU (oracle)",
    })


def bench_native(args):
    """Native C++ tier (``backend='native'``) at Config A/B shapes with a
    thread sweep — the closest measurable analogue of the reference's
    OpenMP performance, and the honest CPU denominator for "what does the
    TPU buy over a good threaded CPU implementation" (VERDICT r2 item 5;
    the round-2 52× figure compared against a 1-thread NumPy loop)."""
    import os

    import jax

    # pure-CPU config: must run even when the TPU tunnel is hung
    jax.config.update("jax_platforms", "cpu")
    os.environ["JAX_PLATFORMS"] = "cpu"

    from netrep_tpu.native import NativePermutationEngine, available

    if not available():
        return emit({"metric": "native backend", "error": "no C++ toolchain"})

    resolve(args, 5000, 20, 200)
    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = [
        tuple(np.asarray(a) for a in side)
        for side in build_problem(args.genes, args.modules, args.samples)
    ]
    lo, hi = (30, 200) if not args.smoke else (8, 24)
    specs = make_specs(args.genes, args.modules, lo, hi)
    pool = np.arange(args.genes, dtype=np.int32)

    cores = len(os.sched_getaffinity(0))
    sweep = sorted({1, 2, 4, 8, cores} & set(range(1, cores + 1))) or [1]
    rows = {}
    for nt in sweep:
        engine = NativePermutationEngine(
            d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
            n_threads=nt,
        )
        t0 = time.perf_counter()
        nulls, done = engine.run_null(args.perms, key=0)
        elapsed = time.perf_counter() - t0
        assert done == args.perms and np.isfinite(nulls).all()
        rows[nt] = round(args.perms / elapsed, 2)
    best = max(rows.values())
    return emit({
        "metric": (
            f"native C++ backend, {args.genes} genes / {args.modules} "
            f"modules ({args.perms} perms measured; thread sweep on a "
            f"{cores}-core box)"
        ),
        "value": round(args.perms / best, 3),
        "unit": "s",
        "vs_baseline": round(best * TARGET_SECONDS / 10_000, 4),
        "perms_per_sec_by_threads": rows,
        "projected_10k_perm_s": round(10_000 / best, 1),
        "device": f"CPU native ({cores} cores)",
    })


def bench_b(args):
    resolve(args, 5000, 20, 10_000)
    # vs_baseline stays 60s/elapsed — the only defined budget; the metric
    # names the actual config so the row cannot be mistaken for north-star
    return bench_north(args, label="Config B, BASELINE.json:8")


def bench_c(args):
    """Config C (BASELINE.json:9): vmapped multi-test vs sequential pairs."""
    import jax

    from netrep_tpu.parallel.engine import PermutationEngine
    from netrep_tpu.parallel.multitest import MultiTestEngine
    from netrep_tpu.utils.config import EngineConfig

    resolve(args, 5000, 20 if not args.smoke else 5, 2000)
    genes, n_perm = args.genes, args.perms
    T = 4
    (d_data, d_corr, d_net), _ = build_problem(genes, args.modules, args.samples)
    tests = [build_problem(genes, args.modules, args.samples, seed=s + 1)[1]
             for s in range(T)]
    lo, hi = (30, 200) if not args.smoke else (8, 24)
    specs = make_specs(genes, args.modules, lo, hi)
    pool = np.arange(genes, dtype=np.int32)
    cfg = EngineConfig(chunk_size=args.chunk, power_iters=40,
                       gather_mode=args.gather_mode)

    multi = MultiTestEngine(
        d_corr, d_net, d_data,
        np.stack([np.asarray(tc) for _, tc, _ in tests]),
        np.stack([np.asarray(tn) for _, _, tn in tests]),
        [np.asarray(td) for td, _, _ in tests],
        specs, pool, config=cfg,
    )
    vmap_s = timed_null(multi, n_perm, cfg.chunk_size)

    # compile-fair comparison: each sequential engine is warmed (one chunk)
    # before its timed run, matching the vmapped path's excluded warm-up —
    # both numbers are steady-state throughput
    seq_s = 0.0
    for td, tc, tn in tests:
        eng = PermutationEngine(
            d_corr, d_net, d_data, tc, tn, td, specs, pool, config=cfg
        )
        seq_s += timed_null(eng, n_perm, cfg.chunk_size)
    return emit({
        "metric": f"Config C ({T} cohorts x {genes} genes, "
                  f"{args.modules} modules, {n_perm} perms): vmapped "
                  "multi-test vs sequential pairs (both compile-excluded)",
        "value": round(vmap_s, 3),
        "unit": "s",
        "vs_baseline": round(seq_s / vmap_s, 2),  # speedup over sequential
        "sequential_s": round(seq_s, 3),
        "vmap_perms_per_sec": round(n_perm / vmap_s, 2),
        "device": str(jax.devices()[0]),
        # the multi-test path implements direct-batched and fused gathers
        # only (no mxu branch) — report what each side ACTUALLY ran so a
        # ratio across different gather implementations is visible
        "vmap_gather_mode": (
            "fused" if multi._base.gather_mode == "fused" else "direct-batched"
        ),
        "sequential_gather_mode": eng.gather_mode,
    })


def bench_d(args):
    """Config D (BASELINE.json:10): 100k perms, checkpointing on."""
    import os
    import tempfile

    import jax

    from netrep_tpu.parallel.engine import PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    resolve(args, 20_000, 50, 100_000)
    n_perm = args.perms
    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = build_problem(
        args.genes, args.modules, args.samples
    )
    lo, hi = (30, 200) if not args.smoke else (8, 24)
    specs = make_specs(args.genes, args.modules, lo, hi)
    pool = np.arange(args.genes, dtype=np.int32)
    cfg = EngineConfig(
        chunk_size=args.chunk, power_iters=40, gather_mode=args.gather_mode,
        cap_granularity=args.cap_granularity,
        network_from_correlation=2.0 if args.derived_net else None,
    )
    engine = PermutationEngine(
        d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool, config=cfg
    )
    # Stable checkpoint path so a mid-run tunnel death (common) is resumed by
    # the next invocation instead of starting the 100k-perm run over; removed
    # on success so later invocations time a fresh full run. The name keys
    # every input that shapes the engine fingerprint (genes/modules/samples/
    # perms/derived) so a parameter change cannot hit a mismatched file.
    import contextlib

    ck = os.path.join(
        tempfile.gettempdir(),
        f"netrep_bench_d_{args.genes}x{args.modules}x{args.samples}x{n_perm}"
        + ("_dnet" if args.derived_net else "")
        + (f"_g{args.cap_granularity}" if args.cap_granularity != 32 else "")
        + ".npz",
    )
    resumed_from = 0
    if os.path.exists(ck):
        try:
            with np.load(ck) as z:  # read only the counter, not the nulls
                resumed_from = int(z["completed"]) if "completed" in z.files else 0
        except Exception:
            resumed_from = 0
        if not 0 < resumed_from < n_perm:
            # unreadable/foreign file, or a fully-completed leftover whose
            # resume would time an empty run — start fresh instead
            with contextlib.suppress(FileNotFoundError):
                os.remove(ck)
            resumed_from = 0
    try:
        elapsed = timed_null(engine, n_perm, cfg.chunk_size,
                             checkpoint_path=ck, checkpoint_every=8192)
    except ValueError:
        # incompatible checkpoint (fingerprint/seed mismatch): discard and
        # run fresh rather than aborting the benchmark
        with contextlib.suppress(FileNotFoundError):
            os.remove(ck)
        resumed_from = 0
        elapsed = timed_null(engine, n_perm, cfg.chunk_size,
                             checkpoint_path=ck, checkpoint_every=8192)
    with contextlib.suppress(FileNotFoundError):
        os.remove(ck)
    done_this_run = max(n_perm - resumed_from, 1)
    pps = done_this_run / elapsed
    projected = n_perm / pps  # == elapsed for an unresumed run
    return emit({
        "metric": f"Config D ({args.genes} genes / {args.modules} modules, "
                  f"{n_perm} perms, checkpoint every 8192"
                  + ("; derived network |corr|^2" if args.derived_net else "")
                  + (f"; cap_granularity {args.cap_granularity}"
                     if args.cap_granularity != 32 else "")
                  + (f"; resumed at {resumed_from}, value projected from "
                     f"{done_this_run} timed perms" if resumed_from else "")
                  + ")",
        "value": round(projected, 3),
        "unit": "s",
        "vs_baseline": round((TARGET_SECONDS * n_perm / 10_000) / projected, 4),
        "perms_per_sec": round(pps, 2),
        "device": str(jax.devices()[0]),
    })


def bench_e(args):
    """Config E (BASELINE.json:11): sparse 50k-node kNN graph."""
    import jax

    from netrep_tpu.ops.sparse import SparseAdjacency
    from netrep_tpu.parallel.engine import ModuleSpec
    from netrep_tpu.parallel.sparse import SparsePermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    resolve(args, 50_000, 30, 10_000)
    n = args.genes
    k = 30
    n_mod = args.modules
    rng = np.random.default_rng(0)
    # synthetic kNN-style graph: k random neighbors per node, symmetrized
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    cols = rng.integers(0, n, size=n * k)
    vals = rng.uniform(0.05, 1.0, size=n * k).astype(np.float32)
    adj = SparseAdjacency.from_coo(rows, cols, vals, n)
    data = rng.standard_normal((args.samples, n)).astype(np.float32)
    lo, hi = (50, 500) if not args.smoke else (8, 24)
    sizes = np.exp(rng.uniform(np.log(lo), np.log(hi), size=n_mod)).astype(int)
    specs, pos = [], 0
    for i, sz in enumerate(sizes):
        idx = np.arange(pos, pos + sz, dtype=np.int32)
        specs.append(ModuleSpec(str(i + 1), idx, idx))
        pos += sz
    pool = np.arange(n, dtype=np.int32)
    cfg = EngineConfig(chunk_size=args.chunk, power_iters=40)
    engine = SparsePermutationEngine(
        adj, data, adj, data, specs, pool, config=cfg
    )
    elapsed = timed_null(engine, args.perms, cfg.chunk_size)
    return emit({
        "metric": f"Config E sparse ({n} nodes, k={k}, {adj.nnz} edges, "
                  f"{n_mod} modules, {args.perms} perms)",
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_SECONDS / elapsed, 4),
        "perms_per_sec": round(args.perms / elapsed, 2),
        "device": str(jax.devices()[0]),
    })


def bench_adaptive(args):
    """Adaptive (sequential early-stopping) vs fixed-n null on a seeded
    mixed fixture — half the modules strongly preserved, half random
    (``netrep_tpu.data.make_mixed_pair``), the workload the Besag–Clifford
    stopping rules retire fastest on. Emits ONE row carrying BOTH runs:
    wall-clock and permutations evaluated for the adaptive pass next to the
    fixed pass, the reduction factor, and whether the two reached the same
    per-module accept/reject decisions at alpha=0.05. North-star-shaped but
    clamped (this config is fully measurable on CPU, where the fallback
    box runs it; the scheduling layer is backend-independent)."""
    import jax

    from netrep_tpu.data import make_mixed_pair
    from netrep_tpu.ops import pvalues as pv
    from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    resolve(args, 2000, 16, 4000)
    if args.smoke:
        args.genes, args.modules, args.perms = 400, 6, 600
    mixed = make_mixed_pair(
        args.genes, args.modules, n_samples=args.samples, seed=7
    )
    (d_data, d_corr, d_net) = mixed["discovery"]
    (t_data, t_corr, t_net) = mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    cfg = EngineConfig(chunk_size=args.chunk, power_iters=40,
                       gather_mode=args.gather_mode)

    def make_engine():
        return PermutationEngine(
            d_corr, d_net, d_data, t_corr, t_net, t_data, specs,
            mixed["pool"], config=cfg,
        )

    fixed_eng = make_engine()
    observed = np.asarray(fixed_eng.observed())
    _ = fixed_eng.run_null(cfg.chunk_size, key=99)  # compile warm-up
    t0 = time.perf_counter()
    nulls_f, done_f = fixed_eng.run_null(args.perms, key=0)
    fixed_s = time.perf_counter() - t0
    assert done_f == args.perms
    p_fixed = pv.permutation_pvalues(observed, np.asarray(nulls_f)[:done_f])

    adaptive_eng = make_engine()
    _ = adaptive_eng.run_null(cfg.chunk_size, key=99)  # warm the full-set compile
    t0 = time.perf_counter()
    nulls_a, done_a, finished = adaptive_eng.run_null_adaptive(
        args.perms, observed, key=0
    )
    adaptive_s = time.perf_counter() - t0
    assert finished
    p_adapt, n_used = pv.sequential_pvalues(
        observed, np.asarray(nulls_a)[:done_a]
    )
    # module-level call at alpha=0.05: every computable statistic significant
    dec_f = np.nanmax(p_fixed, axis=1) < 0.05
    dec_a = np.nanmax(p_adapt, axis=1) < 0.05
    evaluated_fixed = args.perms * len(specs)
    evaluated_adaptive = int(n_used.sum())
    return emit({
        "metric": (
            f"adaptive sequential-stopping null vs fixed n_perm, "
            f"{args.genes} genes / {args.modules} modules "
            f"({mixed['n_preserved']} preserved), ceiling {args.perms} perms"
        ),
        "value": round(adaptive_s, 3),
        "unit": "s",
        "vs_baseline": round(fixed_s / adaptive_s, 3),  # speedup over fixed
        "fixed_s": round(fixed_s, 3),
        "perms_evaluated_adaptive": evaluated_adaptive,
        "perms_evaluated_fixed": evaluated_fixed,
        "perm_reduction_x": round(evaluated_fixed / evaluated_adaptive, 2),
        "n_perm_used": [int(v) for v in n_used],
        "decisions_agree_at_alpha05": bool((dec_f == dec_a).all()),
        "device": str(jax.devices()[0]),
        "chunk": args.chunk,
    })


def bench_superchunk(args):
    """Superchunk streaming executor (``store_nulls=False``) vs the fixed-n
    chunk loop on the SAME problem and key: one row carrying both
    wall-clocks plus the dispatch and device→host-byte counters
    (``utils.profiling.NullProfile``) for each side — the measured form of
    the ISSUE-2 acceptance criteria (≥2× fewer dispatches, ≥10× lower
    transfer volume, wall-clock no worse on the CPU fallback). The
    streamed tallies are asserted equal to the materialized null's
    exceedance counts before any number is emitted, so a fast-but-wrong
    row is impossible. Adaptive-row comparability: same mixed fixture as
    ``--config adaptive``."""
    import jax

    from netrep_tpu.data import make_mixed_pair
    from netrep_tpu.ops import pvalues as pv
    from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
    from netrep_tpu.utils.config import EngineConfig
    from netrep_tpu.utils.profiling import NullProfile

    resolve(args, 2000, 16, 4000)
    if args.smoke:
        args.genes, args.modules, args.perms = 400, 6, 600
    superchunk = 8
    mixed = make_mixed_pair(
        args.genes, args.modules, n_samples=args.samples, seed=7
    )
    (d_data, d_corr, d_net) = mixed["discovery"]
    (t_data, t_corr, t_net) = mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    cfg = EngineConfig(chunk_size=args.chunk, power_iters=40,
                       gather_mode=args.gather_mode, superchunk=superchunk)

    def make_engine():
        return PermutationEngine(
            d_corr, d_net, d_data, t_corr, t_net, t_data, specs,
            mixed["pool"], config=cfg,
        )

    fixed_eng = make_engine()
    observed = np.asarray(fixed_eng.observed())
    _ = fixed_eng.run_null(cfg.chunk_size, key=99)  # compile warm-up
    prof_fixed = NullProfile()
    t0 = time.perf_counter()
    nulls_f, done_f = fixed_eng.run_null(args.perms, key=0,
                                         profile=prof_fixed)
    fixed_s = time.perf_counter() - t0
    assert done_f == args.perms

    stream_eng = make_engine()
    _ = stream_eng.run_null_streaming(  # compile warm-up (distinct key)
        superchunk * cfg.chunk_size, observed, key=99
    )
    prof_stream = NullProfile()
    t0 = time.perf_counter()
    sc = stream_eng.run_null_streaming(args.perms, observed, key=0,
                                       profile=prof_stream)
    stream_s = time.perf_counter() - t0
    assert sc.completed == args.perms

    # parity gate: streamed tallies == materialized exceedance counts
    hi, lo, eff = pv.tail_counts(observed, np.asarray(nulls_f)[:done_f])
    assert (sc.hi == hi).all() and (sc.lo == lo).all() and \
        (sc.eff == eff).all(), "streaming/materialized count mismatch"

    return emit({
        "metric": (
            f"superchunk streaming executor (store_nulls=False, "
            f"superchunk={superchunk}) vs fixed-n chunk loop, "
            f"{args.genes} genes / {args.modules} modules, "
            f"{args.perms} perms, chunk {args.chunk}"
        ),
        "value": round(stream_s, 3),
        "unit": "s",
        "vs_baseline": round(fixed_s / stream_s, 3),  # speedup over fixed
        "fixed_s": round(fixed_s, 3),
        "stream_perms_per_sec": round(args.perms / stream_s, 2),
        "fixed_perms_per_sec": round(args.perms / fixed_s, 2),
        "dispatches_stream": prof_stream.dispatches,
        "dispatches_fixed": prof_fixed.dispatches,
        "dispatch_reduction_x": round(
            prof_fixed.dispatches / max(prof_stream.dispatches, 1), 2
        ),
        "host_bytes_stream": prof_stream.host_bytes,
        "host_bytes_fixed": prof_fixed.host_bytes,
        "transfer_reduction_x": round(
            prof_fixed.host_bytes / max(prof_stream.host_bytes, 1), 2
        ),
        "counts_parity": True,  # asserted above
        "device": str(jax.devices()[0]),
        "chunk": args.chunk,
    })


def bench_mixed(args):
    """Mixed-precision screened null row (ISSUE 16,
    ``null_precision='bf16_rescue'``): the bf16 fast pass with exact f32
    rescue vs the all-f32 loop on the SAME problem and key.

    The pinned-equal-counts gate runs BEFORE any row is emitted — on
    every backend, the screened run's exceedance counts must equal the
    all-f32 run's EXACTLY (the screen's by-construction contract; no
    tolerance, unlike the fused-kernel gate), so a fast-but-wrong row is
    impossible. The headline row is the north-star shape at
    ``--config mixed`` on a live TPU, where the MXU consumes bf16
    operands at ~2x the f32 rate; on the CPU fallback the bf16 rounding
    is emulated (the pass costs MORE, not less), so the row is an
    explicit reduced-shape mechanism row with ``vs_baseline`` nulled —
    parity and rescued-fraction mechanics stay honest, the wall-clock is
    not a device measurement. Metric labels carry the ``mixed`` prefix
    so perf-ledger fingerprints never mix precision paths."""
    import json as _json
    import os
    import tempfile

    import jax

    from netrep_tpu.data import make_mixed_pair
    from netrep_tpu.ops import pvalues as pv
    from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
    from netrep_tpu.utils.config import EngineConfig
    from netrep_tpu.utils.telemetry import Telemetry

    resolve(args, 20_000, 50, 10_000)
    on_cpu = jax.default_backend() == "cpu"

    def make_engine(mixed, null_precision, chunk):
        (dd, dc, dn) = mixed["discovery"]
        (td, tc, tn) = mixed["test"]
        specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
        # stat_mode pinned to the XLA composition: 'auto' resolves to the
        # fused mega-kernel on TPU, where the screen degrades to f32
        cfg = EngineConfig(
            chunk_size=chunk, power_iters=40, dtype=args.dtype,
            superchunk=8, autotune=False, stat_mode="xla",
            gather_mode=args.gather_mode, null_precision=null_precision,
        )
        return PermutationEngine(
            dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=cfg
        )

    def rescued_fraction(run):
        """Run a screened null under a scratch telemetry bus and read the
        whole-pass rescued fraction off its ``null_pass_end`` event."""
        with tempfile.TemporaryDirectory() as td_:
            path = os.path.join(td_, "mixed.jsonl")
            tel = Telemetry(path, run_id="bench-mixed")
            out = run(tel)
            tel.close()
            frac = None
            with open(path, encoding="utf-8") as f:
                for line in f:
                    ev = _json.loads(line)
                    if ev.get("ev") == "null_pass_end":
                        frac = float(ev["data"]["fraction"])
        return out, frac

    # ---- pinned-equal-counts gate (every backend, before any row) -------
    gate = make_mixed_pair(320, 6, n_samples=32, seed=7)
    g_perms = 192
    e32 = make_engine(gate, "f32", 32)
    obs_g = np.asarray(e32.observed())
    nulls_g, done_g = e32.run_null(g_perms, key=0)
    hi_m, lo_m, eff_m = pv.tail_counts(obs_g, np.asarray(nulls_g)[:done_g])
    ebf = make_engine(gate, "bf16_rescue", 32)
    nulls_b, done_b = ebf.run_null(g_perms, key=0, observed=obs_g)
    hi_b, lo_b, eff_b = pv.tail_counts(obs_g, np.asarray(nulls_b)[:done_b])
    assert (hi_b == hi_m).all() and (lo_b == lo_m).all() and \
        (eff_b == eff_m).all(), \
        "screened materialized counts != all-f32 counts at the gate"
    sc_b = ebf.run_null_streaming(g_perms, obs_g, key=0)
    assert (sc_b.hi == hi_m).all() and (sc_b.lo == lo_m).all() and \
        (sc_b.eff == eff_m).all(), \
        "screened streaming tallies != all-f32 counts at the gate"

    # ---- timed row ------------------------------------------------------
    if on_cpu:
        # emulated bf16 rounding on CPU: mechanism row, reduced shape
        genes, modules, perms, chunk = 800, 8, 256, 64
        if args.smoke:
            genes, modules, perms, chunk = 400, 6, 96, 32
    else:
        genes, modules, perms, chunk = (
            args.genes, args.modules, args.perms, args.chunk
        )
    mixed = make_mixed_pair(genes, modules, n_samples=args.samples, seed=7)
    eng_f32 = make_engine(mixed, "f32", chunk)
    observed = np.asarray(eng_f32.observed())
    warm = 8 * chunk
    _ = eng_f32.run_null_streaming(warm, observed, key=99)  # compile
    t0 = time.perf_counter()
    sc_ref = eng_f32.run_null_streaming(perms, observed, key=0)
    f32_s = time.perf_counter() - t0
    assert sc_ref.completed == perms

    eng_bf = make_engine(mixed, "bf16_rescue", chunk)
    _ = eng_bf.run_null_streaming(warm, observed, key=99)
    t0 = time.perf_counter()
    sc, frac = rescued_fraction(
        lambda tel: eng_bf.run_null_streaming(
            perms, observed, key=0, telemetry=tel
        )
    )
    mixed_s = time.perf_counter() - t0
    assert sc.completed == perms
    assert (sc.hi == sc_ref.hi).all() and (sc.lo == sc_ref.lo).all() and \
        (sc.eff == sc_ref.eff).all(), \
        "screened streaming tallies != all-f32 at the timed shape"

    row = {
        "metric": (
            f"mixed bf16-screened {perms}-perm null, {genes} genes / "
            f"{modules} modules (null_precision=bf16_rescue streaming vs "
            f"f32, chunk {chunk})"
        ),
        "value": round(mixed_s, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_SECONDS / mixed_s, 4),
        "f32_s": round(f32_s, 3),
        "mixed_vs_f32_x": round(f32_s / mixed_s, 3),
        "perms_per_sec": round(perms / mixed_s, 2),
        "f32_perms_per_sec": round(perms / f32_s, 2),
        "rescued_fraction": None if frac is None else round(frac, 4),
        "counts_parity": True,  # asserted above, both shapes, exact
        "device": str(jax.devices()[0]),
        "dtype": args.dtype,
        "chunk": chunk,
    }
    if on_cpu:
        row["tpu_fallback"] = TPU_FALLBACK
        row["metric"] += (
            " [CPU emulated bf16 rounding: parity/mechanism row, reduced "
            "shape — the screen only pays off on MXU hardware]"
        )
        # an emulated-rounding wall-clock must never be read against the
        # <60 s target (it is not a device measurement)
        row["vs_baseline"] = None
    return emit(row)


def bench_grid(args):
    """All-pairs preservation atlas row (ISSUE 17, ``grid_preservation``):
    the packed D×D grid vs the D·(D−1) sequential ``module_preservation``
    loop on the SAME cohorts, seed, and adaptive rule.

    Three measurements ride one row:

    - **sequential baseline** — every ordered (discovery, test) pair as
      its own solo run (what a user scripts today);
    - **cold grid** — one ``grid_preservation`` call over the same
      cohorts with a fresh ``grid_dir``: cross-pair packing amortizes
      the per-column dispatch streams, the observed-stat cache dedups
      row-shared discovery work;
    - **one-cohort delta** — the last cohort's data is regenerated and
      the grid re-run against the SAME ``grid_dir``: unchanged cells
      answer from the digest-keyed manifest, the changed row+column
      recompute with the prior run's count tallies seeding the stop
      monitors.

    The bit-identity gate runs BEFORE any row is emitted: every cold
    grid cell must equal its solo run exactly (p-values, observed,
    per-module permutation counts — the two-identity packing contract),
    and every delta-run unchanged cell must equal the cold cell. The
    delta's evaluated permutations are asserted under 25%% of the cold
    grid's (the incremental re-analysis acceptance). Metric labels carry
    the ``grid`` prefix so perf-ledger fingerprints keep atlas rows in
    their own history."""
    import shutil
    import tempfile

    import jax

    from netrep_tpu import grid_preservation, module_preservation
    from netrep_tpu.ops.sequential import StopRule
    from netrep_tpu.utils.config import EngineConfig

    resolve(args, 1000, 8, 1000)
    cohorts = 6  # the acceptance shape: the delta recomputes 2/D of the
    #              cells, so the <25% bound needs the full-width grid
    genes, modules, perms = args.genes, args.modules, args.perms
    samples = args.samples
    if args.smoke:
        # keep the 6-cohort width (the bound under test scales with D);
        # shrink everything else
        genes, modules, perms, samples = 300, 4, 96, 32
    rule = StopRule(min_perms=max(8, perms // 32))
    cfg = EngineConfig(chunk_size=args.chunk, power_iters=40,
                       gather_mode=args.gather_mode, autotune=False)

    names = [f"c{i}" for i in range(cohorts)]

    def make_cohort(seed):
        """Independent cohorts: cross-cohort module preservation is then
        null-typical, so the adaptive monitors retire modules early and
        the warm-start priors have decided tallies to seed — the
        workload the incremental re-analysis is built for. (Preserved
        modules run to the ceiling in every arm equally; they would only
        dilute the delta measurement.)"""
        r = np.random.default_rng(seed)
        d = r.normal(size=(samples, genes))
        corr = np.corrcoef(d, rowvar=False)
        return np.abs(corr) ** 2, corr, d

    network, correlation, data = {}, {}, {}
    for i, n in enumerate(names):
        network[n], correlation[n], data[n] = make_cohort(100 + i)
    # every cohort is a row: contiguous equal blocks, same labels per
    # cohort (node names are the default node_<j> of array inputs)
    assign = {
        n: {f"node_{j}": str(1 + j * modules // genes)
            for j in range(genes)}
        for n in names
    }
    n_cells = cohorts * (cohorts - 1)

    def solo(d, t):
        return module_preservation(
            network, data=data, correlation=correlation,
            module_assignments=assign[d], discovery=d, test=t,
            n_perm=perms, null="all", seed=11, config=cfg,
            simplify=False, adaptive=True, adaptive_rule=rule,
        )[d][t]

    # ---- sequential baseline: D·(D−1) solo runs -------------------------
    t0 = time.perf_counter()
    solo_cells = {
        (d, t): solo(d, t) for d in names for t in names if t != d
    }
    seq_s = time.perf_counter() - t0
    seq_perms = int(sum(
        r.module_n_perm().sum() for r in solo_cells.values()
    ))

    gdir = tempfile.mkdtemp(prefix="bench_grid_")
    try:
        # ---- cold grid --------------------------------------------------
        t0 = time.perf_counter()
        g = grid_preservation(
            network, data=data, correlation=correlation,
            module_assignments=assign, n_perm=perms, null="all", seed=11,
            config=cfg, adaptive=True, adaptive_rule=rule, grid_dir=gdir,
        )
        grid_s = time.perf_counter() - t0
        grid_perms = int(g.stats["perms_evaluated"])
        for (d, t), ref in solo_cells.items():
            cell = g.cell(d, t)
            assert (
                np.array_equal(cell.p_values, ref.p_values)
                and np.array_equal(cell.observed, ref.observed)
                and np.array_equal(cell.n_perm_used, ref.n_perm_used)
            ), f"grid cell {d}->{t} != solo run (packing parity broken)"

        # ---- one-cohort delta -------------------------------------------
        changed = names[-1]
        network[changed], correlation[changed], data[changed] = (
            make_cohort(999)
        )
        t0 = time.perf_counter()
        g2 = grid_preservation(
            network, data=data, correlation=correlation,
            module_assignments=assign, n_perm=perms, null="all", seed=11,
            config=cfg, adaptive=True, adaptive_rule=rule, grid_dir=gdir,
        )
        delta_s = time.perf_counter() - t0
        delta_perms = int(g2.stats["perms_evaluated"])
        for d in names:
            for t in names:
                if t == d or changed in (d, t):
                    continue
                assert np.array_equal(
                    g2.cell(d, t).p_values, g.cell(d, t).p_values
                ), f"unchanged cell {d}->{t} changed under the delta run"
        assert delta_perms < 0.25 * grid_perms, (
            f"one-cohort delta evaluated {delta_perms} permutations — "
            f">= 25% of the cold grid's {grid_perms}; the manifest reuse "
            "or warm-start priors are not engaging"
        )
    finally:
        shutil.rmtree(gdir, ignore_errors=True)

    return emit({
        "metric": (
            f"grid all-pairs atlas, {cohorts} cohorts / {genes} genes / "
            f"{modules} modules, ceiling {perms} perms "
            f"({n_cells} cells, adaptive, packed vs sequential)"
        ),
        "value": round(grid_s, 3),
        "unit": "s",
        "vs_baseline": round(seq_s / grid_s, 3),  # speedup over sequential
        "sequential_s": round(seq_s, 3),
        "perms_per_sec": round(grid_perms / grid_s, 2),
        "grid_perms_evaluated": grid_perms,
        "sequential_perms_evaluated": seq_perms,
        "delta_s": round(delta_s, 3),
        "delta_perms_evaluated": delta_perms,
        "delta_perm_fraction": round(delta_perms / grid_perms, 4),
        "cells": n_cells,
        "cells_reused_on_delta": int(g2.stats["cells_reused"]),
        "cells_warmstarted_on_delta": int(g2.stats["cells_warmstarted"]),
        "dedup_hits": int(g.stats["dedup"]["hits"]),
        "packs": int(g.stats["packs"]),
        "bit_identical_to_solo": True,  # asserted above, every cell
        "device": str(jax.devices()[0]),
        "dtype": args.dtype,
        "chunk": args.chunk,
    })


def bench_pallas(args):
    """Fused-statistics mega-kernel row (ISSUE 8, ``stat_mode='fused'``):
    the Pallas gather+stats+tally kernel driving the streaming executor vs
    the XLA composition on the SAME problem and key.

    Counts parity is asserted in-bench BEFORE any row is emitted — at a
    small shape on every backend (exact on CPU interpret; bounded count
    deviation on MXU-truncating backends, where the kernel's one-hot
    selection rounds like every fused/mxu gather), so a fast-but-wrong
    row is impossible. The headline row is the north-star shape
    (10k-perm / 20k-gene / 50-module) — the <60 s target — and only a
    live TPU produces it: on the CPU fallback the kernel runs the Pallas
    interpreter, whose timing says nothing about Mosaic, so the row is an
    explicit parity-only fallback (labeled, ``tpu_fallback`` marker) at a
    reduced shape instead of an hours-long non-measurement. Metric labels
    carry the ``fused-stats`` prefix so perf-ledger fingerprints never
    mix stat_mode paths."""
    import jax

    from netrep_tpu.data import make_mixed_pair
    from netrep_tpu.ops import pvalues as pv
    from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    resolve(args, 20_000, 50, 10_000)
    on_cpu = jax.default_backend() == "cpu"

    def make_engine(mixed, stat_mode, chunk):
        (dd, dc, dn) = mixed["discovery"]
        (td, tc, tn) = mixed["test"]
        specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
        cfg = EngineConfig(
            chunk_size=chunk, summary_method="power", power_iters=40,
            dtype=args.dtype, superchunk=8, autotune=False,
            stat_mode=stat_mode,
        )
        return PermutationEngine(
            dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=cfg
        )

    # ---- parity gate (every backend, before any row) --------------------
    gate = make_mixed_pair(320, 6, n_samples=32, seed=7)
    g_perms = 192
    e_f = make_engine(gate, "fused", 32)
    obs_g = np.asarray(e_f.observed())
    nulls_g, done_g = e_f.run_null(g_perms, key=0)
    sc_f = e_f.run_null_streaming(g_perms, obs_g, key=0)
    hi_m, lo_m, eff_m = pv.tail_counts(obs_g, np.asarray(nulls_g)[:done_g])
    assert (sc_f.hi == hi_m).all() and (sc_f.lo == lo_m).all() and \
        (sc_f.eff == eff_m).all(), \
        "fused streaming tallies != kernel's own materialized counts"
    sc_x = make_engine(gate, "xla", 32).run_null_streaming(
        g_perms, obs_g, key=0
    )
    dev = max(
        int(np.abs(sc_f.hi - sc_x.hi).max()),
        int(np.abs(sc_f.lo - sc_x.lo).max()),
    )
    tol = 0 if on_cpu else max(2, g_perms // 50)
    assert dev <= tol, (
        f"fused vs xla count deviation {dev} exceeds {tol} at the parity "
        "gate — the mega-kernel is not computing the engine's statistics"
    )

    # ---- timed row ------------------------------------------------------
    if on_cpu:
        # interpreter timing is not a Mosaic measurement: a reduced-shape
        # mechanism row keeps the smoke case and the fallback honest
        genes, modules, perms, chunk = 800, 8, 256, 64
        if args.smoke:
            genes, modules, perms, chunk = 400, 6, 96, 32
    else:
        genes, modules, perms, chunk = (
            args.genes, args.modules, args.perms, args.chunk
        )
    mixed = make_mixed_pair(genes, modules, n_samples=args.samples, seed=7)
    stream_f = make_engine(mixed, "fused", chunk)
    observed = np.asarray(stream_f.observed())
    warm = 8 * chunk
    _ = stream_f.run_null_streaming(warm, observed, key=99)  # compile
    t0 = time.perf_counter()
    sc = stream_f.run_null_streaming(perms, observed, key=0)
    fused_s = time.perf_counter() - t0
    assert sc.completed == perms

    stream_x = make_engine(mixed, "xla", chunk)
    _ = stream_x.run_null_streaming(warm, observed, key=99)
    t0 = time.perf_counter()
    sc_ref = stream_x.run_null_streaming(perms, observed, key=0)
    xla_s = time.perf_counter() - t0
    dev2 = max(
        int(np.abs(sc.hi - sc_ref.hi).max()),
        int(np.abs(sc.lo - sc_ref.lo).max()),
    )
    assert dev2 <= (0 if on_cpu else max(2, perms // 50)), (
        f"fused vs xla count deviation {dev2} at the timed shape"
    )

    row = {
        "metric": (
            f"fused-stats mega-kernel {perms}-perm null, {genes} genes / "
            f"{modules} modules (stat_mode=fused streaming vs xla, "
            f"chunk {chunk})"
        ),
        "value": round(fused_s, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_SECONDS / fused_s, 4),
        "xla_s": round(xla_s, 3),
        "fused_vs_xla_x": round(xla_s / fused_s, 3),
        "perms_per_sec": round(perms / fused_s, 2),
        "xla_perms_per_sec": round(perms / xla_s, 2),
        "counts_parity": True,  # asserted above, both shapes
        "count_dev_gate": dev, "count_dev_timed": dev2,
        "device": str(jax.devices()[0]),
        "dtype": args.dtype,
        "chunk": chunk,
    }
    if on_cpu:
        row["tpu_fallback"] = TPU_FALLBACK
        row["metric"] += (
            " [CPU Pallas interpreter: parity/mechanism row, reduced "
            "shape — kernel timing is only decision-grade on TPU]"
        )
        # an interpreter wall-clock must never be read against the <60 s
        # target (it is not a device measurement)
        row["vs_baseline"] = None
    return emit(row)


def _grouped_support_data(genes, samples, groups, seed=0):
    """Cell-type-block synthetic data for the screening rows: each gene
    expressed (zero-mean within its block) in one sample block, genes
    sorted by block, over a small everywhere-noise floor — the sparse,
    modular structure whose segment-norm bounds make exact screening
    effective (ISSUE 11)."""
    rng = np.random.default_rng(seed)
    x = 0.01 * rng.standard_normal((samples, genes)).astype(np.float32)
    gsz, ssz = genes // groups, samples // groups
    for g in range(groups):
        c0, c1 = g * gsz, (g + 1) * gsz if g < groups - 1 else genes
        r0, r1 = g * ssz, (g + 1) * ssz if g < groups - 1 else samples
        blk = rng.standard_normal((r1 - r0, c1 - c0))
        fac = rng.standard_normal(r1 - r0)
        blk += 1.5 * fac[:, None] * (rng.random(c1 - c0) < 0.5)
        x[r0:r1, c0:c1] += (blk - blk.mean(axis=0)).astype(np.float32)
    return x


def bench_atlas_screen(args):
    """Exact tile screening (ISSUE 11): a screened-vs-unscreened pair of
    tile-pass rows on grouped-support synthetic data. On TPU the screened
    row is the synthetic 1M-gene top-k shape (the grid the unscreened
    pass cannot afford to visit) with the pair's shared-shape comparison
    at 100k genes; on the CPU fallback both rows are an explicitly
    labeled reduced-n mechanism pair. Screened/unscreened BIT-PARITY is
    asserted in-bench before any row is emitted. Every row reports the
    ``tiles_skipped`` fraction and ``nxn_bytes_avoided`` (correlation
    bytes never computed); the ``atlas-screen`` metric label splits the
    perf-ledger fingerprints from the PR 9 atlas rows."""
    import jax

    from netrep_tpu.atlas import TiledNetwork, build_sparse_network
    from netrep_tpu.utils.config import EngineConfig

    on_cpu = jax.default_backend() == "cpu"
    top_k = 16
    beta = 2.0
    cfg = EngineConfig(autotune=False)
    if on_cpu:
        genes, samples, groups, edge = 4096, 64, 16, 128
        if args.smoke:
            genes, samples, groups, edge = 1536, 48, 12, 64
        pair_genes = genes                 # pair shares the reduced shape
        big_genes = None
    else:
        genes, samples, groups, edge = 100_000, 64, 16, 1024
        pair_genes = genes                 # shared-shape pair at 100k
        big_genes = 1_000_000              # screened headline row

    def build(x, screen, **kw):
        tn = TiledNetwork.from_data(x, beta)
        t0 = time.perf_counter()
        b = build_sparse_network(
            tn, top_k=top_k, tile_edge=edge, config=cfg, screen=screen,
            screen_segments=groups, degree=False, **kw,
        )
        return b, time.perf_counter() - t0

    # parity gate: screened == unscreened, bit for bit, before any row
    x = _grouped_support_data(pair_genes, samples, groups)
    un, un_s = build(x, screen=False)
    sc, sc_s = build(x, screen=True)
    assert np.array_equal(un.correlation.nbr, sc.correlation.nbr) and \
        np.array_equal(un.correlation.wgt, sc.correlation.wgt) and \
        np.array_equal(un.adjacency.wgt, sc.adjacency.wgt), \
        "screened tile pass diverged from the unscreened reference"

    def row(build_res, wall, n_genes, screened, vs=None):
        r = {
            "metric": (
                f"atlas-screen {'screened' if screened else 'unscreened'}"
                f" tile pass ({n_genes} genes, top_k={top_k}, "
                f"edge={build_res.tile_edge})"
            ),
            "value": round(wall, 3),
            "unit": "s",
            "vs_baseline": None,
            "genes_per_sec": round(n_genes / wall, 1),
            "tile_edge": build_res.tile_edge,
            "supertile": build_res.supertile,
            "tiles_total": build_res.tiles_total,
            "tiles_dispatched": build_res.tiles_dispatched,
            # the acceptance fraction: share of the grid never dispatched
            "tiles_skipped": round(
                build_res.tiles_skipped / max(1, build_res.tiles_total), 4
            ),
            "tiles_skipped_count": build_res.tiles_skipped,
            # correlation bytes whose tiles were never computed (0 on the
            # unscreened row — it visits the whole grid)
            "nxn_bytes_avoided": (
                build_res.tiles_skipped * build_res.tile_edge ** 2 * 4
            ),
            "strip_bytes_full": build_res.strip_bytes_full,
            "strip_bytes_moved": build_res.strip_bytes_moved,
            "edges_selected": build_res.selected_edges,
            "device": str(jax.devices()[0]),
        }
        if vs is not None:
            r["vs_unscreened"] = round(vs, 3)
        if on_cpu:
            r["tpu_fallback"] = TPU_FALLBACK
            r["metric"] += (
                " [CPU mechanism row, reduced n — the 1M-gene screened "
                "shape is only measured on TPU]"
            )
        return emit(r)

    rows = [
        row(un, un_s, pair_genes, screened=False),
        row(sc, sc_s, pair_genes, screened=True, vs=un_s / sc_s),
    ]
    if big_genes is not None:
        xb = _grouped_support_data(big_genes, samples, groups, seed=1)
        scb, scb_s = build(xb, screen=True)
        rows.append(row(scb, scb_s, big_genes, screened=True))
    return rows[-1]


def bench_atlas(args):
    """Atlas tiled network plane (ISSUE 9): the tile-grid construction
    pass (data columns → per-row top-k SparseAdjacency + global degree,
    never materializing n×n) followed by the data-only permutation null
    (``correlation=None, network=None`` — every k×k submatrix derived
    from gathered data columns) on the SAME synthetic data, then the
    ISSUE 11 screened-vs-unscreened pair (:func:`bench_atlas_screen`;
    ``--screen-only`` skips straight to the pair).

    On TPU the row is the synthetic 100k-gene / 50-module atlas shape —
    the workload class the dense path cannot represent (a 100k×100k f32
    pair is ~80 GB). On the CPU fallback it is an explicitly labeled
    mechanism row at reduced n (full-size CPU tile passes are hours of
    non-measurement). The metric label carries the ``atlas`` prefix so
    perf-ledger fingerprints never mix with dense-path rows, and the row
    reports the peak tile-pass device-memory gauge (PR 5 probes) beside
    the n×n bytes the pass avoided allocating."""
    import jax

    from netrep_tpu.atlas import TiledNetwork, build_sparse_network
    from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
    from netrep_tpu.utils.config import EngineConfig
    from netrep_tpu.utils.profiling import make_memory_probe

    if args.screen_only:
        return bench_atlas_screen(args)
    resolve(args, 100_000, 50, 1000)
    on_cpu = jax.default_backend() == "cpu"
    top_k = 16
    beta = 2.0
    if on_cpu:
        genes, modules, perms = 4000, 8, 256
        if args.smoke:
            genes, modules, perms = 600, 4, 64
        samples = min(args.samples, 32)
    else:
        genes, modules, perms, samples = (
            args.genes, args.modules, args.perms, args.samples
        )
    rng = np.random.default_rng(0)
    lo, hi = (30, 200) if genes >= 10_000 else (8, 24)
    sizes = np.exp(
        rng.uniform(np.log(lo), np.log(hi), size=modules)
    ).astype(int)
    specs, pos = [], 0
    for i, sz in enumerate(sizes):
        idx = np.arange(pos, pos + sz, dtype=np.int32)
        specs.append(ModuleSpec(str(i + 1), idx, idx))
        pos += sz
    assert pos <= genes, "module sizes exceed gene count"

    def planted():
        x = rng.standard_normal((samples, genes)).astype(np.float32)
        for m in specs:
            x[:, m.disc_idx] += 1.1 * rng.standard_normal(samples)[:, None]
        return x

    data_d, data_t = planted(), planted()
    probe = make_memory_probe()
    cfg = EngineConfig(autotune=False)

    t0 = time.perf_counter()
    build = build_sparse_network(
        TiledNetwork.from_data(data_d, beta), top_k=top_k, config=cfg
    )
    tile_s = time.perf_counter() - t0
    mem_tile = probe() if probe is not None else {}

    null_cfg = EngineConfig(
        chunk_size=args.chunk, power_iters=40, autotune=False,
        network_from_correlation=beta,
    )
    engine = PermutationEngine(
        None, None, data_d, None, None, data_t, specs,
        np.arange(genes, dtype=np.int32), config=null_cfg,
    )
    null_s = timed_null(engine, perms, null_cfg.chunk_size)
    mem_null = probe() if probe is not None else {}

    nxn_bytes = int(genes) * int(genes) * 4
    peak = mem_tile.get("mem_peak_bytes") or mem_tile.get(
        "mem_live_buffer_bytes"
    )
    row = {
        "metric": (
            f"atlas tile pass + data-only null ({genes} genes, "
            f"{modules} modules, top_k={top_k}, {perms} perms)"
        ),
        "value": round(tile_s + null_s, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_SECONDS / (tile_s + null_s), 4),
        "tile_pass_s": round(tile_s, 3),
        "null_s": round(null_s, 3),
        "perms_per_sec": round(perms / null_s, 2),
        "genes_per_sec": round(genes / tile_s, 1),
        "tile_edge": build.tile_edge,
        "edges_selected": build.selected_edges,
        "adjacency_nnz": build.adjacency.nnz,
        # peak tile-pass device memory (PR 5 gauges) vs the n×n array the
        # plane never allocates — the memory-bound contract, on the row
        "tile_pass_mem": mem_tile,
        "null_mem": mem_null,
        "nxn_bytes_avoided": nxn_bytes,
        "nxn_avoided": bool(peak is not None and peak < nxn_bytes)
        if peak is not None else None,
        "device": str(jax.devices()[0]),
        "chunk": args.chunk,
    }
    if on_cpu:
        row["tpu_fallback"] = TPU_FALLBACK
        row["metric"] += (
            " [CPU mechanism row, reduced n — the 100k-gene atlas shape "
            "is only measured on TPU]"
        )
        row["vs_baseline"] = None
    emit(row)
    return bench_atlas_screen(args)


def bench_multichip_child(args):
    """One multichip scaling point (spawned by :func:`bench_multichip`):
    build an ``--devices``-wide permutation mesh and measure a real null
    on it. On CPU-class backends the devices are the virtual host
    platform (``--xla_force_host_platform_device_count``, set here BEFORE
    jax initializes); on a live accelerator backend the first N real
    devices. The metric label carries the mesh size (``multichip xN``),
    so the perf ledger's bench fingerprint splits per mesh size and
    ``perf --check`` never compares a 1-device history against a 4-device
    one."""
    import os

    n = args.devices
    resolve(args, 1000, 8, 2048)
    use_cpu = (
        "axon" not in os.environ.get("JAX_PLATFORMS", "")
        or os.environ.get("NETREP_MULTICHIP_CPU")
    )
    if use_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        ensure_backend()
        import jax
    devs = jax.devices()[:n]
    if len(devs) < n:
        return emit({
            "metric": f"multichip x{n}",
            "error": f"only {len(devs)} device(s) available",
            "n_devices": n,
        })

    from netrep_tpu.parallel.engine import PermutationEngine
    from netrep_tpu.parallel.mesh import make_mesh
    from netrep_tpu.utils.config import EngineConfig

    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = build_problem(
        args.genes, args.modules, args.samples
    )
    specs = make_specs_auto(args.genes, args.modules)
    pool = np.arange(args.genes, dtype=np.int32)
    # chunk must divide by the perm axis; keep the per-device share equal
    # across mesh sizes so the rows measure scaling, not chunk effects
    chunk = max(args.chunk, n) // n * n
    cfg = EngineConfig(chunk_size=chunk, summary_method="power",
                       power_iters=40, dtype=args.dtype, autotune=False)
    mesh = (
        make_mesh(n_perm_shards=n, n_row_shards=1, devices=devs)
        if n > 1 else None  # the 1-device baseline is the plain engine
    )
    engine = PermutationEngine(
        d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
        config=cfg, mesh=mesh,
    )
    elapsed = timed_null(engine, args.perms, chunk)
    return emit({
        "metric": f"multichip x{n}",
        "value": round(elapsed, 3),
        "unit": "s",
        "n_devices": n,
        "perms_per_sec": round(args.perms / elapsed, 2),
        "genes": args.genes, "modules": args.modules,
        "n_perm": args.perms, "chunk": chunk, "dtype": args.dtype,
        "device": str(devs[0]),
    })


def bench_multichip(args):
    """Real 1→N-device scaling rows (ISSUE 6 satellite — replaces the
    MULTICHIP_r0*.json stub trajectory): one child process per mesh size
    (the device count must be fixed before jax initializes, so every
    point needs a fresh process), each emitting a measured ``multichip
    xN`` row; this parent relays the rows verbatim (children already fed
    the perf ledger — re-emitting would double-append) and closes with
    one ``multichip scaling`` summary row carrying perms/s and parallel
    efficiency vs the 1-device baseline."""
    import os
    import subprocess

    max_n = args.max_devices
    if max_n is None:
        max_n = int(os.environ.get("NETREP_MULTICHIP_MAX", "4"))
    counts = [1]
    while counts[-1] * 2 <= max_n:
        counts.append(counts[-1] * 2)
    rows = []
    for n in counts:
        cmd = [sys.executable, os.path.abspath(__file__),
               "--config", "multichip", "--devices", str(n)]
        for flag, val in (("--genes", args.genes), ("--modules", args.modules),
                          ("--perms", args.perms), ("--samples", args.samples)):
            if val is not None:
                cmd += [flag, str(val)]
        cmd += ["--chunk", str(args.chunk), "--dtype", args.dtype]
        if args.smoke:
            cmd += ["--smoke"]
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=1800,
                env={**os.environ, "NETREP_BENCH_NO_SUBPROC": "1"},
            )
        except subprocess.TimeoutExpired:
            rows.append({"metric": f"multichip x{n}", "n_devices": n,
                         "error": "timed out"})
            print(json.dumps(rows[-1]))
            continue
        row = None
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    cand = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if cand.get("metric", "").startswith("multichip"):
                    row = cand
        if row is None:
            row = {"metric": f"multichip x{n}", "n_devices": n,
                   "error": (proc.stderr or "no row emitted")[-400:]}
        rows.append(row)
        print(json.dumps(row))  # relay, don't re-emit (ledger already fed)
    base_pps = next(
        (r.get("perms_per_sec") for r in rows
         if r.get("n_devices") == 1 and r.get("perms_per_sec")), None
    )
    scaling = []
    for r in rows:
        pps = r.get("perms_per_sec")
        scaling.append({
            "n_devices": r.get("n_devices"),
            "perms_per_sec": pps,
            "efficiency": (
                round(pps / (base_pps * r["n_devices"]), 3)
                if pps and base_pps else None
            ),
            **({"error": r["error"]} if "error" in r else {}),
        })
    # summary row carries no top-level perms_per_sec → no ledger entry
    # (each point already appended under its own per-mesh-size fingerprint)
    return emit({
        "metric": f"multichip scaling 1..{counts[-1]} devices",
        "rows": scaling,
        "device_counts": counts,
    })


def run_shielded(args):
    """Round-2's failure mode, second line of defense: a tunnel death
    MID-RUN leaves device calls blocked in gRPC with no deadline — the
    benchmark hangs and the driver records nothing (ensure_backend's probe
    only protects startup). Run the TPU-touching configs in a killable
    child instead: on timeout the child is killed and re-run once as an
    explicit CPU fallback (NETREP_FORCE_TPU_FALLBACK → reduced-count
    projected rows / skip rows, tpu_fallback markers); if even that times
    out, emit an error row. Every path ends in one parseable JSON line.
    ``NETREP_BENCH_TIMEOUT`` overrides the per-attempt budget."""
    import os
    import subprocess

    import signal

    default_tmo = {"D": 5400.0}.get(args.config, 1800.0)
    try:
        tmo = float(os.environ.get("NETREP_BENCH_TIMEOUT", default_tmo))
    except ValueError:
        tmo = default_tmo
    cmd = [sys.executable, os.path.abspath(__file__), *sys.argv[1:]]

    def _sigterm(signum, frame):
        raise SystemExit(143)

    def attempt(env):
        # Popen + explicit kill (not subprocess.run): if THIS process is
        # SIGTERMed (an outer watchdog), the libtpu-holding child must die
        # with it or it would hold the exclusive chip as an orphan; the
        # handler turns SIGTERM into SystemExit so the finally runs, and is
        # installed BEFORE the fork so no window exists where the default
        # disposition could kill the parent with a live child
        prev = signal.signal(signal.SIGTERM, _sigterm)
        child = None
        try:
            # new session => the child leads a process group, so the kill
            # reaches grandchildren too (--config sharded spawns the
            # microbench as a grandchild that would otherwise orphan alive
            # holding the exclusive chip)
            child = subprocess.Popen(cmd, env=env, start_new_session=True)
            return child.wait(timeout=tmo)
        finally:
            # kill FIRST, restore the handler LAST: restoring first would
            # reopen a window where a SIGTERM kills this parent with the
            # default disposition before the child group dies
            if child is not None and child.poll() is None:
                try:
                    os.killpg(child.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    child.kill()
                child.wait()
            signal.signal(signal.SIGTERM, prev)

    try:
        return attempt({**os.environ, "NETREP_BENCH_NO_SUBPROC": "1"})
    except subprocess.TimeoutExpired:
        if args.config == "sharded":
            # the sharded microbench has no reduced-count CPU path and no
            # tpu_fallback row markers — a fallback retry would burn the
            # full budget again on a meaningless full-size CPU run
            return emit({
                "metric": "Config sharded",
                "error": f"benchmark timed out ({tmo:.0f}s): TPU attempt "
                         "hung (tunnel death mid-run?)",
                "tpu_fallback": True,
            })
        print(json.dumps({
            "metric": "bench shield",
            "warning": f"benchmark child exceeded {tmo:.0f}s (tunnel death "
                       "mid-run?); killed, retrying as explicit CPU fallback",
        }), file=sys.stderr)
    try:
        return attempt({
            **os.environ, "NETREP_BENCH_NO_SUBPROC": "1",
            "NETREP_FORCE_TPU_FALLBACK": "1", "JAX_PLATFORMS": "cpu",
        })
    except subprocess.TimeoutExpired:
        return emit({
            "metric": f"Config {args.config}",
            "error": f"benchmark timed out twice ({tmo:.0f}s each): TPU "
                     "attempt hung and the CPU fallback did not finish",
            "tpu_fallback": True,
        })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="north",
                    choices=["north", "A", "B", "C", "D", "E", "oracle",
                             "native", "sharded", "adaptive", "superchunk",
                             "multichip", "serve", "pallas", "atlas",
                             "mixed", "grid"])
    ap.add_argument("--devices", type=int, default=None,
                    help="multichip child marker: measure ONE scaling "
                         "point on this many devices (the parent spawns "
                         "one child per mesh size)")
    ap.add_argument("--max-devices", type=int, default=None,
                    help="multichip: largest mesh size to measure "
                         "(default $NETREP_MULTICHIP_MAX or 4; points are "
                         "powers of two)")
    ap.add_argument("--genes", type=int, default=None)
    ap.add_argument("--modules", type=int, default=None)
    ap.add_argument("--perms", type=int, default=None)
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--samples", type=int, default=128)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--gather-mode", default="auto",
                    choices=["auto", "direct", "mxu", "fused"],
                    help="EngineConfig.gather_mode for north/B/C/D configs "
                         "(the multi-test side of C implements "
                         "direct-batched and fused only)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny config for a fast correctness pass")
    ap.add_argument("--telemetry", default=None, metavar="PATH",
                    help="append structured run-telemetry events (JSONL) "
                         "here; the metric row gains a 'telemetry' pointer. "
                         "Defaults from NETREP_TELEMETRY (the tpu_watch.sh "
                         "loop sets it). Aggregate with `python -m "
                         "netrep_tpu telemetry PATH`")
    ap.add_argument("--cap-granularity", type=int, default=32,
                    help="EngineConfig.cap_granularity: bucket capacities "
                         "round to multiples of this (8 trims ~11%% of the "
                         "row traffic; north/B/D configs)")
    ap.add_argument("--derived-net", action="store_true",
                    help="EngineConfig(network_from_correlation=2.0): derive "
                         "network submatrices on device instead of storing "
                         "the n x n network (north/B/D configs)")
    ap.add_argument("--screen-only", action="store_true",
                    help="atlas config: emit only the ISSUE 11 "
                         "screened-vs-unscreened tile-pass pair (skip the "
                         "PR 9 tile+null row)")
    args = ap.parse_args()
    if args.smoke:
        args.genes, args.modules, args.perms, args.chunk, args.samples = (
            500, 5, 64, 32, 32
        )

    import os

    from netrep_tpu.utils.backend import tunnel_expected

    if (args.config in ("north", "A", "B", "C", "D", "E", "sharded",
                        "adaptive", "superchunk", "serve", "pallas",
                        "atlas", "mixed", "grid")
            and tunnel_expected()
            and not os.environ.get("NETREP_BENCH_NO_SUBPROC")):
        # every config that may touch the tunnel backend (A runs the JAX
        # engine on the default backend too; sharded's microbench child
        # would otherwise hang unkillably) runs in a killable child (see
        # run_shielded); the env var marks the child so it executes
        # directly. Only when the tunnel could actually be dialed: an
        # explicit JAX_PLATFORMS=cpu run must not be killed at a TPU-sized
        # timeout and mislabeled a dead tunnel. oracle/native force CPU
        # themselves and are exempt either way.
        return run_shielded(args)

    tel_path = args.telemetry or os.environ.get("NETREP_TELEMETRY")
    if tel_path:
        # ambient bus for the whole bench process: engine loops, backend
        # probes, autotune lookups and checkpoint saves all emit to it
        # (activated AFTER the shield dispatch — the shield parent only
        # babysits the child, which activates its own)
        global TELEMETRY_PATH
        TELEMETRY_PATH = tel_path
        import atexit

        from netrep_tpu.utils.telemetry import Telemetry

        _tel = Telemetry(
            tel_path, run_id=f"bench-{args.config}-{os.getpid()}"
        )
        # keep the context-manager object referenced for the process
        # lifetime: a discarded generator-CM is closed on GC, which would
        # silently deactivate the ambient bus
        global _TEL_CM
        _TEL_CM = _tel.activate()
        _TEL_CM.__enter__()
        atexit.register(_tel.close)

    if args.config == "multichip":
        # the child measures; the parent only spawns and relays — device
        # counts must be fixed before jax initializes, so neither path
        # goes through ensure_backend() here (the child decides itself)
        if args.devices is not None:
            return bench_multichip_child(args)
        return bench_multichip(args)
    if args.config == "sharded":
        # dispatch BEFORE ensure_backend(): libtpu is exclusive per process,
        # so the parent must not acquire the chip the child needs
        import subprocess

        return subprocess.call([
            sys.executable,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "benchmarks", "microbench_sharded_gather.py"),
        ])
    if args.config == "serve":
        # the serve load generator (ISSUE 7): closed-/open-loop mixed
        # multi-tenant traffic against the in-process server — p50/p99
        # latency, aggregate perms/s, pack statistics, warm-pool compile
        # proof, and the >= 2x-vs-serial acceptance row. Delegated like
        # `sharded` (it resolves its own backend and owns its shapes).
        import subprocess

        cmd = [
            sys.executable,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "benchmarks", "serve_load.py"),
        ]
        if args.smoke:
            cmd.append("--smoke")
        return subprocess.call(cmd)
    if args.config == "native":
        # self-contained CPU config (forces cpu platform itself)
        return bench_native(args)
    if args.config == "oracle":
        # pure-CPU config: must run even when the TPU tunnel is hung (the
        # exact situation where the CPU baseline is the only runnable bench).
        # Both the live config AND the env var flip: ensure_backend's hang
        # probe triggers off the env var.
        import jax

        jax.config.update("jax_platforms", "cpu")
        os.environ["JAX_PLATFORMS"] = "cpu"
    ensure_backend()
    if TPU_FALLBACK and args.config in ("C", "D", "E"):
        # scale configs exist to measure TPU behavior; running them to
        # completion on fallback CPU takes hours — emit an explicit,
        # parseable skip row instead (north/B project from a reduced count)
        return emit({
            "metric": f"Config {args.config}",
            "error": "skipped: TPU tunnel unreachable (CPU fallback); this "
                     "config is only meaningful on TPU",
            "tpu_fallback": True,
        })
    return {
        "north": bench_north, "A": bench_a, "B": bench_b,
        "C": bench_c, "D": bench_d, "E": bench_e, "oracle": bench_oracle,
        "adaptive": bench_adaptive, "superchunk": bench_superchunk,
        "pallas": bench_pallas, "atlas": bench_atlas,
        "mixed": bench_mixed, "grid": bench_grid,
    }[args.config](args)


if __name__ == "__main__":
    sys.exit(main())
