"""Benchmark: the north-star config (BASELINE.json:5) — a 10,000-permutation
null on a 20,000-gene / 50-module network — on whatever accelerator JAX
finds (the driver runs this on one real TPU chip).

Prints ONE JSON line:
    {"metric": ..., "value": <wall-clock seconds>, "unit": "s",
     "vs_baseline": <target_seconds / value>}

``vs_baseline`` > 1 means faster than the 60 s north-star target (which was
set for a v4-8 slice; this script reports the single-chip number and the
per-chip permutation throughput in auxiliary fields).

Usage: python bench.py [--genes N] [--modules K] [--perms P] [--chunk C]
                       [--samples S] [--dtype float32|bfloat16] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

TARGET_SECONDS = 60.0  # BASELINE.json:5 north-star


def ensure_backend():
    """Resolve a usable JAX backend. The driver environment pins
    JAX_PLATFORMS=axon (the TPU tunnel), whose plugin registration is
    flaky — when it fails, fall back to automatic backend selection (which
    finds the same TPU via libtpu, else CPU)."""
    import jax

    try:
        return jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "")
        return jax.devices()


def build_problem(n_genes, n_modules, n_samples, seed=0):
    """Synthetic genome-scale co-expression pair, generated on device:
    data → correlation (one big MXU matmul) → soft-threshold adjacency."""
    import jax
    import jax.numpy as jnp

    def one(key):
        x = jax.random.normal(key, (n_samples, n_genes), dtype=jnp.float32)
        # plant module structure on a rolling window so modules are real
        z = x - x.mean(0)
        z = z / jnp.linalg.norm(z, axis=0)
        corr = jnp.clip(z.T @ z, -1.0, 1.0)
        net = jnp.abs(corr) ** 2
        return x, corr, net

    k1, k2 = jax.random.split(jax.random.key(seed))
    return one(k1), one(k2)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--genes", type=int, default=20_000)
    ap.add_argument("--modules", type=int, default=50)
    ap.add_argument("--perms", type=int, default=10_000)
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--samples", type=int, default=128)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny config for a fast correctness pass")
    args = ap.parse_args()
    if args.smoke:
        args.genes, args.modules, args.perms, args.chunk, args.samples = (
            500, 5, 64, 32, 32
        )

    import jax
    ensure_backend()
    from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = build_problem(
        args.genes, args.modules, args.samples
    )

    # 50 modules with sizes drawn log-uniform in [30, 200] (smoke: scaled)
    rng = np.random.default_rng(1)
    lo, hi = (30, 200) if not args.smoke else (8, 24)
    sizes = np.exp(
        rng.uniform(np.log(lo), np.log(hi), size=args.modules)
    ).astype(int)
    specs, pos = [], 0
    for k, sz in enumerate(sizes):
        idx = np.arange(pos, pos + sz, dtype=np.int32)
        specs.append(ModuleSpec(str(k + 1), idx, idx))
        pos += sz
    pool = np.arange(args.genes, dtype=np.int32)

    cfg = EngineConfig(chunk_size=args.chunk, summary_method="power",
                       power_iters=40, dtype=args.dtype)
    engine = PermutationEngine(
        d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool, config=cfg
    )

    # compile warm-up (one chunk) — excluded from the timed run, matching
    # "wall-clock for the null" (compile is once-per-shape, BASELINE.json:2)
    _ = engine.run_null(cfg.chunk_size, key=99)
    jax.block_until_ready(engine._test_corr)

    t0 = time.perf_counter()
    nulls, done = engine.run_null(args.perms, key=0)
    elapsed = time.perf_counter() - t0
    assert done == args.perms
    assert np.isfinite(nulls).all()

    perms_per_sec = args.perms / elapsed
    print(json.dumps({
        "metric": (
            f"wall-clock for {args.perms}-perm null, {args.genes} genes / "
            f"{args.modules} modules (north-star config, BASELINE.json:5)"
        ),
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_SECONDS / elapsed, 4),
        "perms_per_sec": round(perms_per_sec, 2),
        "device": str(jax.devices()[0]),
        "dtype": args.dtype,
        "chunk": args.chunk,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
