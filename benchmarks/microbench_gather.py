"""Micro-benchmark of submatrix-gather strategies for the permutation hot
loop (SURVEY.md §7 "Gather bandwidth"; VERDICT round-1 item 1).

The per-permutation unit of work is: gather ``M[idx, idx]`` (m of n rows and
columns) out of the three n×n / n×s test matrices, for each of ~50 modules,
then run the statistic kernels. This script times candidate formulations of
that gather on the real chip at north-star shapes (n=20k, 50 modules, sizes
log-uniform [30, 200]) so the engine's default is chosen from evidence, not
guesswork.

Strategies:
  primitives  raw row-gather / transpose / one-hot costs
  direct      M[idx[:,None], idx[None,:]]               (per-element gather)
  mxu         sorted row gather + one-hot column matmul (round-1 default)
  transpose   sorted row gather -> transpose -> sorted row gather
  twostage    shared per-perm prefix: S = M[sel,:][:,sel] (T,T) once, then
              per-module gathers at T scale (direct / mxu / transpose)

Usage: python benchmarks/microbench_gather.py [--genes N] [--chunk C] [--reps R]
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# bench.ensure_backend: killable-subprocess tunnel probe (a hung-dead axon
# dial becomes a fast CPU fallback) + persistent compile cache.
from bench import ensure_backend  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# the shared variant-aware timing helper (microbench_parts): every timed
# site threads `variants=` — distinct same-shape inputs cycled across reps
# — because the tunnel short-circuits repeated identical executions and
# prints impossible rates (BASELINE.md "microbench-timing caveat"). On
# accelerators bench() enforces this (raises when variants are missing or
# too few), so this script's rates are transcribable evidence now
# (VERDICT r4 item 2).
from microbench_parts import DEFAULT_WARMUP, bench  # noqa: E402


def make_problem(n, n_modules, seed=1):
    rng = np.random.default_rng(seed)
    sizes = np.exp(rng.uniform(np.log(30), np.log(200), size=n_modules)).astype(int)
    key = jax.random.key(0)
    M = jax.random.normal(key, (n, n), dtype=jnp.float32)
    M = (M + M.T) / 2
    return M, sizes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--genes", type=int, default=20_000)
    ap.add_argument("--modules", type=int, default=50)
    ap.add_argument("--chunk", type=int, default=32)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--only", default="", help="substring filter on section names")
    args = ap.parse_args()
    ensure_backend()
    print(f"device={jax.devices()[0]}")

    n, C = args.genes, args.chunk
    M, sizes = make_problem(n, args.modules)
    T = int(sizes.sum())
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    sum_m2 = int((sizes.astype(np.int64) ** 2).sum())
    print(f"n={n} modules={len(sizes)} T={T} sum_m2={sum_m2} chunk={C}")

    # one distinct input draw per timed-or-warmup call (bench() cycles
    # them): the tunnel must never see the same execution twice in any
    # rate that could be transcribed
    V = max(1, args.reps) + DEFAULT_WARMUP

    # bucket sizes to powers of two (same rule as EngineConfig.rounded_cap)
    def cap_of(s):
        c = 8
        while c < s:
            c *= 2
        return c

    caps = sorted({cap_of(s) for s in sizes})
    by_cap = {c: [k for k, s in enumerate(sizes) if cap_of(s) == c] for c in caps}
    print("buckets:", {c: len(v) for c, v in by_cap.items()})

    pool = jnp.arange(n, dtype=jnp.int32)

    def keyset(v):
        # disjoint fold_in ranges per variant — same shapes, different draws
        return jax.vmap(lambda i: jax.random.fold_in(jax.random.key(7), i))(
            jnp.arange(C, dtype=jnp.uint32) + jnp.uint32(v * C)
        )

    keysets = [keyset(v) for v in range(V)]
    keys = keysets[0]

    def run(name, thunk):
        if args.only and args.only not in name:
            return
        try:
            thunk()
        except Exception as e:  # noqa: BLE001
            print(f"{name}: FAILED {type(e).__name__}: {e}")

    # ---------------- primitives -------------------------------------------
    idx_sorted_vs = [
        jnp.sort(jax.random.choice(jax.random.key(1 + v), n, (T,), replace=False))
        for v in range(V)
    ]
    idx_rand_vs = [
        jax.random.permutation(jax.random.key(101 + v), s)
        for v, s in enumerate(idx_sorted_vs)
    ]
    idx_T_sorted = idx_sorted_vs[0]
    sorted_variants = [(M, s) for s in idx_sorted_vs]

    def prims():
        draw_all = jax.jit(
            lambda ks: jax.vmap(lambda k: jax.random.permutation(k, pool))(ks)
        )
        t = bench(draw_all, keys, reps=args.reps,
                  variants=[(ks,) for ks in keysets])
        print(f"prim perm_draw x{C}:              {t*1e3:8.2f} ms  ({t/C*1e3:.3f} ms/perm)")

        rowg = jax.jit(lambda Mx, idx: jnp.take(Mx, idx, axis=0))
        t = bench(rowg, M, idx_T_sorted, reps=args.reps, variants=sorted_variants)
        print(f"prim row_gather (T,n) sorted:     {t*1e3:8.2f} ms  ({T*n*4/t/1e9:.0f} GB/s)")
        t = bench(rowg, M, idx_rand_vs[0], reps=args.reps,
                  variants=[(M, r) for r in idx_rand_vs])
        print(f"prim row_gather (T,n) random:     {t*1e3:8.2f} ms  ({T*n*4/t/1e9:.0f} GB/s)")

        tr = jax.jit(lambda Mx, idx: jnp.take(Mx, idx, axis=0).T)
        t = bench(tr, M, idx_T_sorted, reps=args.reps, variants=sorted_variants)
        print(f"prim gather+transpose (n,T):      {t*1e3:8.2f} ms")

        twog = jax.jit(lambda Mx, idx: jnp.take(jnp.take(Mx, idx, axis=0).T, idx, axis=0))
        t = bench(twog, M, idx_T_sorted, reps=args.reps, variants=sorted_variants)
        print(f"prim gather.T gather (T,T):       {t*1e3:8.2f} ms")

        colsel = jax.jit(
            lambda Mx, idx: jnp.matmul(
                jnp.take(Mx, idx, axis=0),
                (jax.lax.broadcasted_iota(jnp.int32, (n, T), 0) == idx[None, :]).astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
        )
        t = bench(colsel, M, idx_T_sorted, reps=args.reps, variants=sorted_variants)
        print(f"prim gather+onehot (T,T):         {t*1e3:8.2f} ms  ({2*T*T*n/t/1e12:.1f} TFLOP/s)")

        direct2d = jax.jit(lambda Mx, idx: Mx[idx[:, None], idx[None, :]])
        t = bench(direct2d, M, idx_T_sorted, reps=args.reps, variants=sorted_variants)
        print(f"prim direct 2D gather (T,T):      {t*1e3:8.2f} ms  ({T*T/t/1e6:.0f} Melem/s)")

    run("prim", prims)

    # ---------------- full-chunk strategies --------------------------------
    # Each strategy computes, for every perm in the chunk and every module,
    # the (cap, cap) submatrix, and reduces it (sum) so XLA can't DCE the
    # gather but the comparison isn't polluted by the stats kernels.

    def draw(key):
        return jax.random.permutation(key, pool)

    def module_idx(perm, cap, ks):
        """(K, cap) padded per-module indices + (K, cap) masks for bucket."""
        cols, masks = [], []
        for k in ks:
            off, size = int(offsets[k]), int(sizes[k])
            idx = perm[off : off + size]
            cols.append(jnp.pad(idx, (0, cap - size), constant_values=n))
            masks.append((jnp.arange(cap) < size).astype(jnp.float32))
        return jnp.stack(cols), jnp.stack(masks)

    def sub_direct(Mx, idx):           # (cap,) -> (cap, cap)
        i = jnp.minimum(idx, n - 1)
        return Mx[i[:, None], i[None, :]]

    def sub_mxu(Mx, idx):
        order = jnp.argsort(idx)
        srt = jnp.take(idx, order)
        rows = jnp.take(Mx, srt, axis=0, mode="clip")
        cap = idx.shape[0]
        onehot = (
            jax.lax.broadcasted_iota(jnp.int32, (Mx.shape[0], cap), 0) == srt[None, :]
        ).astype(Mx.dtype)
        sub = jnp.matmul(rows, onehot, preferred_element_type=jnp.float32)
        pos = jax.lax.broadcasted_iota(jnp.int32, (cap, cap), 1)
        P = (pos == order[:, None]).astype(Mx.dtype)
        return P.T @ sub @ P

    def sub_transpose(Mx, idx):
        order = jnp.argsort(idx)
        srt = jnp.take(idx, order)
        rows = jnp.take(Mx, srt, axis=0, mode="clip")          # (cap, n)
        sub = jnp.take(rows.T, srt, axis=0, mode="clip")        # (cap, cap)
        cap = idx.shape[0]
        pos = jax.lax.broadcasted_iota(jnp.int32, (cap, cap), 1)
        P = (pos == order[:, None]).astype(Mx.dtype)
        return P.T @ sub.T @ P

    def chunk_of(sub_fn, batch):
        def chunk(ks, Mx):
            def per_perm(key):
                perm = draw(key)
                acc = 0.0
                for cap, ks_ in by_cap.items():
                    idx_b, mask = module_idx(perm, cap, ks_)
                    subs = jax.vmap(partial(sub_fn, Mx))(idx_b)
                    pair = mask[:, :, None] * mask[:, None, :]
                    acc += jnp.sum(subs * pair)
                return acc

            return jax.lax.map(per_perm, ks, batch_size=batch)

        jitted = jax.jit(chunk)
        return lambda ks: jitted(ks, M)

    key_variants = [(ks,) for ks in keysets]

    for name, fn in [("direct", sub_direct), ("mxu", sub_mxu), ("transpose", sub_transpose)]:
        for batch in ([2, 8] if name != "direct" else [2]):
            def go(name=name, fn=fn, batch=batch):
                t = bench(chunk_of(fn, batch), keys, reps=args.reps,
                          variants=key_variants)
                print(f"chunk {name:9s} batch={batch}:         {t*1e3:8.2f} ms  ({t/C*1e3:6.3f} ms/perm)")
            run(f"chunk-{name}-b{batch}", go)

    # two-stage: shared (T,T) prefix submatrix, then per-module at T scale
    def chunk_twostage(inner, batch):
        def chunk(ks, Mx):
            return jax.lax.map(partial(per_perm, Mx), ks, batch_size=batch)

        def per_perm(Mx, key):
            perm = draw(key)
            sel = perm[:T]
            srt = jnp.sort(sel)
            rank = jnp.searchsorted(srt, sel).astype(jnp.int32)  # (T,)
            R = jnp.take(Mx, srt, axis=0)                # (T, n) sorted rows
            S = jnp.take(R.T, srt, axis=0)               # (T, T) sorted basis
            acc = 0.0
            for cap, ks in by_cap.items():
                cols, masks = [], []
                for k in ks:
                    off, size = int(offsets[k]), int(sizes[k])
                    cols.append(jnp.pad(rank[off : off + size], (0, cap - size), constant_values=T))
                    masks.append((jnp.arange(cap) < size).astype(jnp.float32))
                idx_b, mask = jnp.stack(cols), jnp.stack(masks)
                subs = jax.vmap(partial(inner, S))(idx_b)
                pair = mask[:, :, None] * mask[:, None, :]
                acc += jnp.sum(subs * pair)
            return acc

        jitted = jax.jit(chunk)
        return lambda ks: jitted(ks, M)

    def sub_direct_T(S, idx):
        i = jnp.minimum(idx, T - 1)
        return S[i[:, None], i[None, :]]

    def sub_mxu_T(S, idx):
        order = jnp.argsort(idx)
        srt = jnp.take(idx, order)
        rows = jnp.take(S, srt, axis=0, mode="clip")
        cap = idx.shape[0]
        onehot = (
            jax.lax.broadcasted_iota(jnp.int32, (T, cap), 0) == srt[None, :]
        ).astype(S.dtype)
        sub = jnp.matmul(rows, onehot, preferred_element_type=jnp.float32)
        pos = jax.lax.broadcasted_iota(jnp.int32, (cap, cap), 1)
        P = (pos == order[:, None]).astype(S.dtype)
        return P.T @ sub @ P

    for name, inner in [("2stage+direct", sub_direct_T), ("2stage+mxu", sub_mxu_T)]:
        for batch in [2, 8]:
            def go(name=name, inner=inner, batch=batch):
                t = bench(chunk_twostage(inner, batch), keys, reps=args.reps,
                          variants=key_variants)
                print(f"chunk {name:13s} batch={batch}:     {t*1e3:8.2f} ms  ({t/C*1e3:6.3f} ms/perm)")
            run(f"2stage-{name.split('+')[1]}-b{batch}", go)


if __name__ == "__main__":
    main()
