"""Capture a jax.profiler trace of the real bench-engine chunk and print an
op-level time breakdown (VERDICT round-1 items 1-2: "profile first").

Runs the north-star engine (bench.py shapes) for a few chunks under
``jax.profiler.trace``, then parses the xplane with
``jax.profiler.ProfileData`` and aggregates device-op durations by fusion
name so the hot spots are visible without TensorBoard.

Usage: python benchmarks/profile_chunk.py [--genes N] [--chunk C] [--top K]
       [--dtype float32|bfloat16] [--precision default|highest]
"""

from __future__ import annotations

import argparse
import collections
import glob
import os
import re
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--genes", type=int, default=20_000)
    ap.add_argument("--modules", type=int, default=50)
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--samples", type=int, default=128)
    ap.add_argument("--nchunks", type=int, default=2)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--perm-batch", type=int, default=2)
    ap.add_argument("--outdir", default="")
    args = ap.parse_args()

    import jax

    from bench import build_problem, ensure_backend

    ensure_backend()
    from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = build_problem(
        args.genes, args.modules, args.samples
    )
    rng = np.random.default_rng(1)
    sizes = np.exp(rng.uniform(np.log(30), np.log(200), size=args.modules)).astype(int)
    specs, pos = [], 0
    for k, sz in enumerate(sizes):
        idx = np.arange(pos, pos + sz, dtype=np.int32)
        specs.append(ModuleSpec(str(k + 1), idx, idx))
        pos += sz
    pool = np.arange(args.genes, dtype=np.int32)

    cfg = EngineConfig(chunk_size=args.chunk, summary_method="power",
                       power_iters=40, dtype=args.dtype,
                       perm_batch=args.perm_batch)
    engine = PermutationEngine(
        d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool, config=cfg
    )

    # warm up (compile) outside the trace
    _ = engine.run_null(cfg.chunk_size, key=99)

    outdir = args.outdir or tempfile.mkdtemp(prefix="netrep_trace_")
    n_perm = args.nchunks * cfg.chunk_size
    with jax.profiler.trace(outdir):
        t0 = time.perf_counter()
        _nulls, done = engine.run_null(n_perm, key=0)
        elapsed = time.perf_counter() - t0
    print(f"traced {done} perms in {elapsed:.3f}s -> {done/elapsed:.1f} perms/s "
          f"({elapsed/done*1e3:.3f} ms/perm)  trace={outdir}")

    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        print("no xplane captured", file=sys.stderr)
        return 1
    pd = jax.profiler.ProfileData.from_serialized_xspace(
        open(sorted(paths)[-1], "rb").read()
    )
    per_op = collections.Counter()
    total = 0.0
    for plane in pd.planes:
        if "TPU" not in plane.name and "tpu" not in plane.name.lower():
            continue
        for line in plane.lines:
            for ev in line.events:
                dur = ev.duration_ns
                name = ev.name
                # strip fusion suffix digits for aggregation
                base = re.sub(r"[.\d]+$", "", name)
                per_op[base] += dur
                total += dur
    print(f"\ntotal device-op time: {total/1e6:.1f} ms over {args.nchunks} chunks "
          f"({total/1e6/n_perm:.3f} ms/perm)")
    print(f"{'op (aggregated)':60s} {'ms':>10s} {'%':>6s}")
    for name, dur in per_op.most_common(args.top):
        print(f"{name[:60]:60s} {dur/1e6:10.2f} {dur/total*100:6.1f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
