#!/bin/bash
# Resumable TPU measurement watcher (VERDICT r2 item 2: the round-2 watcher
# lived in /tmp and died with the session; this one is committed).
#
# Loops: probe the axon tunnel with a hard timeout; while it is up, work
# through the measurement QUEUE below in order, marking each step done in a
# state file so tunnel deaths / restarts resume instead of redoing. Each
# result line appends to the log as it lands — a mid-run death loses nothing.
#
# Usage:  nohup benchmarks/tpu_watch.sh [logfile] [statefile] &
# Defaults keep both under /tmp (session artifacts); pass repo paths to
# persist across sessions. BASELINE.md rows are filled from the log.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_bench_results.jsonl}
STATE=${2:-/tmp/tpu_watch_state}
# Structured run telemetry (ISSUE 3): every bench step appends events to
# $TELEMETRY (bench.py reads NETREP_TELEMETRY), and after each step the
# aggregate is re-rendered as a Prometheus text exposition at $PROM so a
# node scraper / textfile collector can watch the loop's progress. Both
# are best-effort: a missing python or empty log skips silently.
TELEMETRY=${TELEMETRY:-${LOG%.jsonl}_telemetry.jsonl}
PROM=${PROM:-${TELEMETRY%.jsonl}.prom}
export NETREP_TELEMETRY="$TELEMETRY"
# Perf-regression ledger (ISSUE 5): every bench step and telemetry-enabled
# engine run appends a throughput fingerprint to $PERF_LEDGER, and after
# each step `perf --check` compares the newest entry against the robust
# median of its matching history — a regressed step is flagged in the log
# the moment it lands, not five rounds later. Best-effort like $PROM: a
# check failure warns, it never marks a step failed (the measurement is
# real; the regression is for a human or CI to act on).
PERF_LEDGER=${PERF_LEDGER:-${LOG%.jsonl}_perf_ledger.jsonl}
export NETREP_PERF_LEDGER="$PERF_LEDGER"
# Diagnostic bundles on failed/flagged steps (ISSUE 20): a nonzero step
# rc or a perf-regression flag drops a `netrep_tpu bundle --collect`
# artifact (flight ring, env, ledger tail, stacks) beside the log, so
# the step's last minutes survive the tunnel that killed it. Loud but
# never fatal; BUNDLE_STEP=0 disables; default 'auto' is on in
# production and off under the QUEUE_FILE state-machine test hook.
BUNDLE_DIR=${BUNDLE_DIR:-${LOG%.jsonl}_bundles}
BUNDLE_STEP=${BUNDLE_STEP:-auto}
step_bundle() {
  # $1 = step key, $2 = why (failed | perf-regression | selftest-halt)
  case "$BUNDLE_STEP" in
    0) return 0 ;;
    auto) [ -n "${QUEUE_FILE:-}" ] && return 0 ;;
  esac
  mkdir -p "$BUNDLE_DIR" 2>/dev/null || true
  if bpath=$(timeout 60 python -m netrep_tpu bundle \
      --collect "$BUNDLE_DIR/$1-$(date -u +%Y%m%dT%H%M%SZ)" \
      --reason "step-$1-$2" 2>/dev/null); then
    echo "--- diagnostic bundle for $1 ($2): $bpath ---" | tee -a "$LOG"
  else
    echo "--- diagnostic bundle for $1 ($2) FAILED to collect (non-fatal) ---" | tee -a "$LOG"
  fi
}
# 45/45 defaults (was 60/150): windows run ~5-7 min, so a dead-tunnel
# probe cycle must stay well under a window or most of it is lost before
# the queue even starts (BASELINE.md measurement-session note). A live
# tunnel answers the probe in seconds; 45 s only bounds the hung case.
PROBE_TIMEOUT=${PROBE_TIMEOUT:-45}
SLEEP=${SLEEP:-45}
# Hard stop (epoch seconds): libtpu is exclusive per process, so the watcher
# must be gone before the driver's round-end bench needs the chip.
CUTOFF_EPOCH=${CUTOFF_EPOCH:-}
case "$CUTOFF_EPOCH" in
  ''|*[!0-9]*)
    if [ -n "$CUTOFF_EPOCH" ]; then
      echo "CUTOFF_EPOCH must be epoch seconds (got '$CUTOFF_EPOCH')" >&2
      exit 2
    fi ;;
esac
touch "$STATE"

# Queue: "<key> <timeout_s> <command...>" — keys are the resume identity;
# edit freely, completed keys are skipped via $STATE.
# Order = VERDICT r3 priority: headline row first, then the decision grid
# (tune: 13 reduced-count points — the highest information per second if
# the tunnel window is short), then full 10k-perm rows for the grid's
# modes, then the scale configs (D's two ~1h steps must never starve tune).
# Round-4 live-window learning (03:49-03:55 UTC): tunnel windows run ~5-7
# minutes and die mid-step. The headline north row therefore goes FIRST
# after smoke; the fused-parity gate that must precede any fused benchmark
# row is the cheap --parity-only step (2 compiles), not the full parts
# decomposition (many compiles — it ate the whole 7/31 window and timed
# out). bench.py now enables the persistent compile cache, so a step killed
# mid-compile resumes into cached programs next window.
QUEUE=(
  "smoke       300  python bench.py --smoke"
  "north       900  python bench.py"
  "parity      600  python benchmarks/microbench_parts.py --parity-only"
  "selftest    600  python -c 'import bench; bench.ensure_backend(); import netrep_tpu; r = netrep_tpu.selftest(max_shapes=1); assert r[\"backend\"] != \"cpu\", r'"
  "tune        2400 python benchmarks/tune_northstar.py"
  "north_bf16  900  python bench.py --dtype bfloat16"
  "north_dnet  900  python bench.py --derived-net"
  "north_bf16_dnet 900 python bench.py --dtype bfloat16 --derived-net"
  "north_fused 900  python bench.py --gather-mode fused"
  "north_fused_bf16_dnet 900 python bench.py --gather-mode fused --dtype bfloat16 --derived-net"
  "north_pallas 900 python bench.py --config pallas"
  "north_g8    900  python bench.py --cap-granularity 8"
  "bf16_drift  1200 python benchmarks/bf16_drift.py"
  "configB     900  python bench.py --config B"
  "configC     1200 python bench.py --config C"
  "configC15   1200 python bench.py --config C --genes 1500"
  "configE     1200 python bench.py --config E"
  "sharded     1200 python benchmarks/microbench_sharded_gather.py"
  "parts       900  python benchmarks/microbench_parts.py"
  "configD     3600 python bench.py --config D"
  "configD_dn  3600 python bench.py --config D --derived-net"
)

# Atlas tiled-network-plane step (ISSUE 9 + 11; opt-in: ATLAS_STEP=1):
# the tile-grid construction pass + data-only null at the synthetic
# 100k-gene shape, followed by the ISSUE 11 screened config — the
# screened-vs-unscreened tile-pass pair (bit-parity asserted in-bench)
# with the screened 1M-gene top-k headline row — a real measurement
# only on TPU (the CPU fallback emits the labeled reduced-n mechanism
# rows, same policy as pallas). Rides the existing gate pattern:
# ordinary queue step, tpu_fallback detection, perf-ledger rows under
# their own `atlas` / `atlas-screen` fingerprint prefixes.
if [ "${ATLAS_STEP:-0}" = "1" ]; then
  QUEUE+=("configAtlas 3600 python bench.py --config atlas")
fi

# Mixed-precision null screening step (ISSUE 16; opt-in: MIXED_STEP=1):
# the screened bf16 fast-pass vs the all-f32 null at the north-star
# shape — bf16/f32 bit-parity of the tail counts is asserted in-bench
# (materialized AND streaming) before any timed row, and the row carries
# the rescued fraction + wall-clock ratio vs f32. A real measurement
# only on TPU (the CPU fallback emits the labeled reduced-shape
# mechanism row with vs_baseline nulled: bf16 rounding is emulated on
# CPU, so the screen cannot pay off there). stat_mode is pinned 'xla'
# in-bench — the screen feeds the existing XLA chunk body — so this
# step deliberately does NOT ride the fused parity gate. Perf-ledger
# rows land under the row's own `mixed` metric fingerprint.
if [ "${MIXED_STEP:-0}" = "1" ]; then
  QUEUE+=("configMixed 1800 python bench.py --config mixed")
fi

# Test hooks (tests/test_tpu_watch_logic.py): QUEUE_FILE replaces the
# queue (one "<key> <timeout> <cmd...>" per line) and PROBE_CMD replaces
# the tunnel dial, so the state machine — resume, fallback, parity
# strikes, selftest halt, cutoff — is testable without a chip. Unset in
# production.
if [ -n "${QUEUE_FILE:-}" ]; then
  QUEUE=()
  while IFS= read -r line; do
    [ -n "$line" ] && QUEUE+=("$line")
  done < "$QUEUE_FILE"
fi

probe() {
  if [ -n "${PROBE_CMD:-}" ]; then
    # same timeout bound as production: the cutoff math budgets
    # now + PROBE_TIMEOUT, so a blocking stub must not hang past it
    timeout "$PROBE_TIMEOUT" bash -c "$PROBE_CMD" >/dev/null 2>&1
    return
  fi
  timeout "$PROBE_TIMEOUT" python -c "import jax; jax.devices()" >/dev/null 2>&1
}

# Elastic chaos drill (ISSUE 6): once per watch cycle, a CPU-only
# deterministic drill proves the whole recovery ladder still works —
# partial device loss → mesh shrink → capacity restored → mesh grow-back
# — bit-identically, and logs the recovery timeline. Runs regardless of
# tunnel state (it never touches the TPU) so a dead tunnel window still
# produces a useful robustness signal. ELASTIC_DRILL=0 disables;
# ELASTIC_PLAN overrides the injected plan.
# Default 'auto': on in production, off under the QUEUE_FILE test hook
# (the state-machine tests run with second-scale timeouts); set
# ELASTIC_DRILL=1/0 to force either way.
ELASTIC_DRILL=${ELASTIC_DRILL:-auto}
ELASTIC_PLAN=${ELASTIC_PLAN:-device_lost_partial@24;capacity_restored@40}
elastic_drill() {
  case "$ELASTIC_DRILL" in
    0) return 0 ;;
    auto) [ -n "${QUEUE_FILE:-}" ] && return 0 ;;
  esac
  echo "--- elastic drill ($(date -u +%FT%TZ)) ---" | tee -a "$LOG"
  : > "$TELEMETRY.elastic"   # fresh timeline per cycle
  if timeout 600 env JAX_PLATFORMS=cpu \
       XLA_FLAGS="--xla_force_host_platform_device_count=4" \
       NETREP_FAULT_PLAN="$ELASTIC_PLAN" \
       python -m netrep_tpu chaos --telemetry "$TELEMETRY.elastic" \
       >>"$LOG" 2>&1; then
    # the timeline of what the drill survived, via the offline CLI
    timeout 60 python -m netrep_tpu telemetry "$TELEMETRY.elastic" \
      --recovery 2>/dev/null | tee -a "$LOG" >/dev/null
  else
    echo "--- ELASTIC DRILL FAILED (recovery ladder regressed?) ---" | tee -a "$LOG"
  fi
}

# Serve drill (ISSUE 7, opt-in: SERVE_DRILL=auto or 1): once per watch
# cycle, run the `netrep serve` load generator on CPU (closed-/open-loop
# mixed-tenant traffic against the in-process server, rows into
# $PERF_LEDGER), gate it with `perf --check`, then boot the real
# unix-socket daemon and assert the clean-SIGTERM-drain contract
# (serve_load.py --drill: exit 0 + a final {"serve": "drained"} line).
# Default off — the serve path never touches the TPU, so it only earns
# cycle time when a serving deployment is being watched.
SERVE_DRILL=${SERVE_DRILL:-0}
serve_drill() {
  case "$SERVE_DRILL" in
    auto|1) ;;
    *) return 0 ;;
  esac
  # the state-machine tests run with second-scale timeouts; 'auto' stays
  # off under the QUEUE_FILE hook like the elastic drill
  [ "$SERVE_DRILL" = auto ] && [ -n "${QUEUE_FILE:-}" ] && return 0
  echo "--- serve drill ($(date -u +%FT%TZ)) ---" | tee -a "$LOG"
  if ! timeout 900 env JAX_PLATFORMS=cpu NETREP_BENCH_NO_SUBPROC=1 \
       python benchmarks/serve_load.py --smoke >>"$LOG" 2>&1; then
    echo "--- SERVE LOAD FAILED (packing/pool/scheduler regressed?) ---" | tee -a "$LOG"
  fi
  if [ -s "$PERF_LEDGER" ]; then
    if ! perf_out=$(timeout 60 python -m netrep_tpu perf "$PERF_LEDGER" --check 2>/dev/null); then
      echo "--- PERF REGRESSION after serve drill ---" | tee -a "$LOG"
      echo "$perf_out" | tee -a "$LOG"
    fi
  fi
  if ! timeout 600 env JAX_PLATFORMS=cpu python benchmarks/serve_load.py \
       --smoke --drill >>"$LOG" 2>&1; then
    echo "--- SERVE DRILL FAILED (daemon SIGTERM drain regressed?) ---" | tee -a "$LOG"
  fi
  # Observability artifacts (ISSUE 13), loud-never-fatal: the drill just
  # printed one `top --once --json`-shaped snapshot row into $LOG
  # (serve_load --drill captures it over the wire before the drain);
  # here the cycle's serve telemetry also exports as a merged Perfetto
  # trace artifact, so "what happened to request X" is one click away
  # from any watch log.
  if [ -s "$TELEMETRY" ]; then
    if ! timeout 120 python -m netrep_tpu telemetry "$TELEMETRY" \
         --trace "${LOG%.jsonl}_serve_trace.json" >>"$LOG" 2>&1; then
      echo "--- SERVE TRACE EXPORT FAILED (telemetry/trace regressed?) ---" | tee -a "$LOG"
    fi
  fi
}

# Invariant lint (ISSUE 12): once per watch cycle, run the repo's static
# contract linter (`python -m netrep_tpu lint --json`) — backend-free,
# seconds-scale, so it costs the window nothing. Findings are logged
# LOUDLY but never fail the step (a watch cycle's job is measurements;
# CI's tier-1 gate owns hard enforcement via tests/test_lint.py) — but a
# contract violation showing up mid-watch means new rows may not carry
# the bit-identity guarantees, so the banner says exactly that.
# LINT_CHECK=0 disables; default 'auto': on in production, off under the
# QUEUE_FILE state-machine test hook like the other drills.
LINT_CHECK=${LINT_CHECK:-auto}
lint_check() {
  case "$LINT_CHECK" in
    0) return 0 ;;
    auto) [ -n "${QUEUE_FILE:-}" ] && return 0 ;;
  esac
  echo "--- invariant lint ($(date -u +%FT%TZ)) ---" | tee -a "$LOG"
  if lint_out=$(timeout 120 python -m netrep_tpu lint --json 2>/dev/null); then
    echo "$lint_out" >>"$LOG"
  else
    echo "$lint_out" >>"$LOG"
    echo "--- LINT FINDINGS (an invariant contract is violated; rows from this tree may not carry the bit-identity guarantees — fix before transcribing) ---" | tee -a "$LOG"
  fi
}

# Serve CRASH drill (ISSUE 10, opt-in: SERVE_CRASH_DRILL=auto or 1):
# once per watch cycle, prove the crash-recovery contract end to end —
# `chaos --serve` boots the real daemon, SIGKILLs it mid-pack at a
# plan-injected permutation, restarts with --recover, and asserts every
# journaled request completes bit-identically; then the kill-recover
# load scenario measures time-to-recovery and the re-served/recomputed
# split into $PERF_LEDGER under its own `serve-recover` label (never
# mixed with steady-state serving fingerprints), gated by `perf --check`
# loudly but non-fatally. CPU-only; off under the QUEUE_FILE test hook.
SERVE_CRASH_DRILL=${SERVE_CRASH_DRILL:-0}
serve_crash_drill() {
  case "$SERVE_CRASH_DRILL" in
    auto|1) ;;
    *) return 0 ;;
  esac
  [ "$SERVE_CRASH_DRILL" = auto ] && [ -n "${QUEUE_FILE:-}" ] && return 0
  echo "--- serve crash drill ($(date -u +%FT%TZ)) ---" | tee -a "$LOG"
  if ! timeout 900 env JAX_PLATFORMS=cpu \
       python -m netrep_tpu chaos --serve --json >>"$LOG" 2>&1; then
    echo "--- SERVE CRASH DRILL FAILED (journal/recover parity regressed?) ---" | tee -a "$LOG"
  fi
  if ! timeout 600 env JAX_PLATFORMS=cpu python benchmarks/serve_load.py \
       --smoke --kill-recover >>"$LOG" 2>&1; then
    echo "--- SERVE KILL-RECOVER SCENARIO FAILED ---" | tee -a "$LOG"
  fi
  if [ -s "$PERF_LEDGER" ]; then
    if ! perf_out=$(timeout 60 python -m netrep_tpu perf "$PERF_LEDGER" --check 2>/dev/null); then
      echo "--- PERF REGRESSION after serve crash drill ---" | tee -a "$LOG"
      echo "$perf_out" | tee -a "$LOG"
    fi
  fi
}

# Fleet drill (ISSUE 14, opt-in: FLEET_DRILL=auto or 1): once per watch
# cycle, prove the replication story end to end — `chaos --fleet` boots
# the real coordinator + replica daemons, SIGKILLs a replica MID-PACK,
# and asserts the peer completes every request bit-identically via the
# shipped journal + shared checkpoints; then the serve_load fleet
# scenario measures p50/p99, failover time, and aggregate perms/s vs 1
# replica into $PERF_LEDGER under the `serve-fleet` label (its own
# fingerprint class), gated by `perf --check` loudly but non-fatally.
# CPU-only; off under the QUEUE_FILE test hook like the other drills.
FLEET_DRILL=${FLEET_DRILL:-0}
fleet_drill() {
  case "$FLEET_DRILL" in
    auto|1) ;;
    *) return 0 ;;
  esac
  [ "$FLEET_DRILL" = auto ] && [ -n "${QUEUE_FILE:-}" ] && return 0
  echo "--- fleet drill ($(date -u +%FT%TZ)) ---" | tee -a "$LOG"
  if ! timeout 900 env JAX_PLATFORMS=cpu \
       python -m netrep_tpu chaos --fleet --json >>"$LOG" 2>&1; then
    echo "--- FLEET CHAOS DRILL FAILED (shipping/failover parity regressed?) ---" | tee -a "$LOG"
  fi
  if ! timeout 900 env JAX_PLATFORMS=cpu python benchmarks/serve_load.py \
       --smoke --fleet 2 >>"$LOG" 2>&1; then
    echo "--- FLEET LOAD SCENARIO FAILED ---" | tee -a "$LOG"
  fi
  if [ -s "$PERF_LEDGER" ]; then
    if ! perf_out=$(timeout 60 python -m netrep_tpu perf "$PERF_LEDGER" --check 2>/dev/null); then
      echo "--- PERF REGRESSION after fleet drill ---" | tee -a "$LOG"
      echo "$perf_out" | tee -a "$LOG"
    fi
  fi
}

# Autoscale drill (ISSUE 19, opt-in: AUTOSCALE_DRILL=auto or 1): once
# per watch cycle, prove the elastic fleet story end to end — the
# serve_load autoscale scenario drives square-wave traffic (burst /
# quiet / burst) through an autoscaled fleet with forced noticed
# evictions landing mid-trace, and its row gates zero lost requests +
# fewer replica-seconds than the static peak fleet (label
# `serve-autoscale`, its own perf-ledger fingerprint class); then
# `chaos --fleet --evict` boots the real daemons, sends a replica an
# eviction NOTICE mid-pack, and asserts the handoff completed every
# request bit-identically with ZERO recomputed packs (evict_handoff_done
# on the timeline, failover_start absent). A failed assertion banners
# LOUDLY but never fails the step; CPU-only; off under the QUEUE_FILE
# state-machine test hook like the other drills.
AUTOSCALE_DRILL=${AUTOSCALE_DRILL:-0}
autoscale_drill() {
  case "$AUTOSCALE_DRILL" in
    auto|1) ;;
    *) return 0 ;;
  esac
  [ "$AUTOSCALE_DRILL" = auto ] && [ -n "${QUEUE_FILE:-}" ] && return 0
  echo "--- autoscale drill ($(date -u +%FT%TZ)) ---" | tee -a "$LOG"
  if ! timeout 900 env JAX_PLATFORMS=cpu python benchmarks/serve_load.py \
       --smoke --autoscale >>"$LOG" 2>&1; then
    echo "--- AUTOSCALE LOAD SCENARIO FAILED (scale-up/retire/scale-to-zero or eviction handoff regressed?) ---" | tee -a "$LOG"
  fi
  if ! timeout 900 env JAX_PLATFORMS=cpu \
       python -m netrep_tpu chaos --fleet --evict --json >>"$LOG" 2>&1; then
    echo "--- EVICTION DRILL FAILED (noticed eviction recomputed or lost work?) ---" | tee -a "$LOG"
  fi
  if [ -s "$PERF_LEDGER" ]; then
    if ! perf_out=$(timeout 60 python -m netrep_tpu perf "$PERF_LEDGER" --check 2>/dev/null); then
      echo "--- PERF REGRESSION after autoscale drill ---" | tee -a "$LOG"
      echo "$perf_out" | tee -a "$LOG"
    fi
  fi
}

# Warm-start step (ISSUE 15, opt-in: WARMSTART=auto or 1): once per
# watch cycle, prove the zero-compile warm start end to end — the
# serve_load warmstart scenario exports the program grid into a fresh
# AOT store, then measures a FRESH process's first-request compile span
# against it and asserts `compile_span ~0` with `source: aot` (and warm
# < cold). The row (metric label `serve-warmstart`, its own perf-ledger
# fingerprint class) also reports the delta vs the PR 14
# serve-fleet-coldstart baseline. A failed assertion banners LOUDLY but
# never fails the step; CPU-only; off under the QUEUE_FILE test hook
# like the other drills.
WARMSTART=${WARMSTART:-0}
warmstart_step() {
  case "$WARMSTART" in
    auto|1) ;;
    *) return 0 ;;
  esac
  [ "$WARMSTART" = auto ] && [ -n "${QUEUE_FILE:-}" ] && return 0
  echo "--- warmstart step ($(date -u +%FT%TZ)) ---" | tee -a "$LOG"
  if ! timeout 900 env JAX_PLATFORMS=cpu \
       python benchmarks/serve_load.py --smoke --warmstart >>"$LOG" 2>&1; then
    echo "--- WARMSTART FAILED (first request compiled instead of loading from the AOT store — export/fingerprint/fallback regressed?) ---" | tee -a "$LOG"
  fi
  if [ -s "$PERF_LEDGER" ]; then
    if ! perf_out=$(timeout 60 python -m netrep_tpu perf "$PERF_LEDGER" --check 2>/dev/null); then
      echo "--- PERF REGRESSION after warmstart step ---" | tee -a "$LOG"
      echo "$perf_out" | tee -a "$LOG"
    fi
  fi
}

# All-pairs grid step (ISSUE 17, opt-in: GRID_STEP=auto or 1): once per
# watch cycle, bench the D×D preservation atlas at the smoke shape —
# cold packed grid vs the sequential per-pair baseline, then the
# one-cohort digest-delta re-analysis. The bench itself asserts every
# cell bit-identical to solo module_preservation and the delta under
# 25% of the cold permutation work, so a pass here certifies packing,
# dedup, manifest reuse and warm-start priors in one row (perf-ledger
# fingerprint prefix `grid`). Runs on the chip when one is up (bench.py
# falls back to a labeled CPU row otherwise). A failed assertion
# banners LOUDLY but never fails the step; off under the QUEUE_FILE
# test hook like the other drills.
GRID_STEP=${GRID_STEP:-0}
grid_step() {
  case "$GRID_STEP" in
    auto|1) ;;
    *) return 0 ;;
  esac
  [ "$GRID_STEP" = auto ] && [ -n "${QUEUE_FILE:-}" ] && return 0
  echo "--- grid step ($(date -u +%FT%TZ)) ---" | tee -a "$LOG"
  if ! timeout 1800 python bench.py --smoke --config grid >>"$LOG" 2>&1; then
    echo "--- GRID STEP FAILED (cell/solo bit-parity or the <25% delta re-analysis bound regressed?) ---" | tee -a "$LOG"
  fi
  if [ -s "$PERF_LEDGER" ]; then
    if ! perf_out=$(timeout 60 python -m netrep_tpu perf "$PERF_LEDGER" --check 2>/dev/null); then
      echo "--- PERF REGRESSION after grid step ---" | tee -a "$LOG"
      echo "$perf_out" | tee -a "$LOG"
    fi
  fi
}

# Roofline drift gate (ISSUE 18): once per watch cycle, run the
# speed-of-light check over the cycle's perf ledger — the newest
# roofline-bearing entry's utilisation (achieved perms/s on device kinds
# without a peak entry, i.e. CPU mechanism rows) against the robust
# median of its matching history. Exit 2 = the same program family is
# now further from the roofline than it historically was — a perf
# regression wall-clock alone can hide behind shape drift. Logged LOUDLY
# but never fails the cycle (the measurements are real; the drift is for
# a human or CI to act on). ROOFLINE_CHECK=0 disables; default 'auto':
# on in production, off under the QUEUE_FILE state-machine test hook
# like the other drills.
ROOFLINE_CHECK=${ROOFLINE_CHECK:-auto}
roofline_check() {
  case "$ROOFLINE_CHECK" in
    0) return 0 ;;
    auto) [ -n "${QUEUE_FILE:-}" ] && return 0 ;;
  esac
  [ -s "$PERF_LEDGER" ] || return 0
  echo "--- roofline check ($(date -u +%FT%TZ)) ---" | tee -a "$LOG"
  if roofline_out=$(timeout 60 python -m netrep_tpu roofline \
       --ledger "$PERF_LEDGER" --check 2>/dev/null); then
    echo "$roofline_out" >>"$LOG"
  else
    echo "--- ROOFLINE DRIFT (utilisation regressed vs this fingerprint's history) ---" | tee -a "$LOG"
    echo "$roofline_out" | tee -a "$LOG"
  fi
}

echo "== watcher start $(date -u +%FT%TZ) (log=$LOG state=$STATE) ==" | tee -a "$LOG"
while :; do
  lint_check
  elastic_drill
  serve_drill
  serve_crash_drill
  fleet_drill
  autoscale_drill
  warmstart_step
  grid_step
  roofline_check
  # drained first: with a cutoff set, an empty queue would otherwise be
  # reported as "no step can finish before cutoff" (review r5 — the test
  # harness caught the misleading exit line)
  remaining=0
  for entry in "${QUEUE[@]}"; do
    key=${entry%% *}
    grep -qx "$key" "$STATE" || remaining=$((remaining + 1))
  done
  if [ "$remaining" -eq 0 ]; then
    echo "== queue drained $(date -u +%FT%TZ) ==" | tee -a "$LOG"
    exit 0
  fi
  # exit when the cutoff is reached, when the next probe could not finish
  # before it, or when no unfinished step could ever start before it
  if [ -n "$CUTOFF_EPOCH" ]; then
    now=$(date +%s)
    if [ "$((now + PROBE_TIMEOUT))" -ge "$CUTOFF_EPOCH" ]; then
      echo "== cutoff window reached $(date -u +%FT%TZ); watcher exiting ==" | tee -a "$LOG"
      exit 0
    fi
    startable=0
    for entry in "${QUEUE[@]}"; do
      read -r key tmo _ <<<"$entry"
      grep -qx "$key" "$STATE" && continue
      [ "$((now + tmo))" -lt "$CUTOFF_EPOCH" ] && startable=$((startable + 1))
    done
    if [ "$startable" -eq 0 ]; then
      echo "== no step can finish before cutoff; watcher exiting $(date -u +%FT%TZ) ==" | tee -a "$LOG"
      exit 0
    fi
  fi
  if probe; then
    for entry in "${QUEUE[@]}"; do
      read -r key tmo cmd <<<"$entry"
      grep -qx "$key" "$STATE" && continue
      if [ -n "$CUTOFF_EPOCH" ] && \
         [ "$(($(date +%s) + tmo))" -ge "$CUTOFF_EPOCH" ]; then
        # a step whose timeout could cross the cutoff must not start: it
        # would hold the exclusive TPU when the driver's bench needs it
        echo "--- $key skipped: would cross cutoff ---" | tee -a "$LOG"
        continue
      fi
      # Fused decision rows are only trustworthy after the parity gate has
      # genuinely PASSED on real Mosaic (review finding: queue order alone
      # does not stop a fused step from running after a parity failure).
      # "parity PASS" is written only by a real success; a bare "parity"
      # line without it means the gate failed twice and was retired.
      # north_pallas (the fused-STATS mega-kernel, ISSUE 8) rides the same
      # gate: its kernel shares the gather kernel's DMA/select machinery,
      # so a gather-parity retirement retires it too; its own counts
      # parity is additionally asserted in-bench before any row.
      case "$key" in
        tune|north_fused*|north_pallas)
          if ! grep -qx "parity PASS" "$STATE"; then
            if grep -qx "parity MOSAICFAIL" "$STATE"; then
              # only a REAL kernel failure (assertion/compile error on the
              # chip, marked below) retires the fused grid — transient
              # tunnel flaps leave the gate pending and the steps deferred
              echo "--- $key skipped permanently: fused parity gate FAILED on Mosaic ---" | tee -a "$LOG"
              echo "$key" >>"$STATE"
            elif grep -qx "parity SKIPRETIRE" "$STATE"; then
              # distinct retirement class (ADVICE r5): the kernel twice
              # REFUSED to compile with the tunnel alive — no wrong
              # numbers were ever produced, Mosaic just cannot build it
              echo "--- $key skipped permanently: fused parity SKIPPED twice (Mosaic compile-refusal, not wrong numbers) ---" | tee -a "$LOG"
              echo "$key" >>"$STATE"
            else
              echo "--- $key deferred: fused parity gate not yet passed ---" | tee -a "$LOG"
            fi
            continue
          fi ;;
      esac
      echo "--- $key: $cmd ($(date -u +%FT%TZ)) ---" | tee -a "$LOG"
      step_out=$(mktemp)
      # NO_SUBPROC: the watcher IS the timeout layer; bench.py's subprocess
      # shield would otherwise orphan a chip-holding child when this
      # timeout fires (timeout signals only the direct child)
      timeout "$tmo" env NETREP_BENCH_NO_SUBPROC=1 PYTHONUNBUFFERED=1 bash -c "$cmd" 2>&1 \
        | grep -v WARNING | tee -a "$LOG" "$step_out"
      rc=${PIPESTATUS[0]}
      # refresh the Prometheus exposition from the telemetry log (scrape
      # surface of the loop); never lets a render failure mark a step
      if [ -s "$TELEMETRY" ]; then
        timeout 60 python -m netrep_tpu telemetry "$TELEMETRY" --prom \
          >"$PROM.tmp" 2>/dev/null && mv "$PROM.tmp" "$PROM" || rm -f "$PROM.tmp"
      fi
      # per-step perf regression gate (ISSUE 5): the newest ledger entry
      # vs the robust median of its fingerprint's history; exit 2 =
      # regression — logged loudly but never fails the step (the
      # measurement itself is real and already appended)
      if [ -s "$PERF_LEDGER" ]; then
        if ! perf_out=$(timeout 60 python -m netrep_tpu perf "$PERF_LEDGER" --check 2>/dev/null); then
          echo "--- PERF REGRESSION after $key ---" | tee -a "$LOG"
          echo "$perf_out" | tee -a "$LOG"
          step_bundle "$key" perf-regression
        fi
      fi
      # bench.py exits 0 on its own probe-race CPU-fallback rows, and the
      # benchmark scripts that share bench.ensure_backend print its stderr
      # "falling back to CPU" warning without the JSON marker; marking
      # either done would silently lose the TPU measurement (ADVICE r3)
      fellback=0
      grep -qE '"tpu_fallback": true|falling back to CPU' "$step_out" \
        && fellback=1
      # real on-chip parity failure. The explicit FAILED assertion with a
      # live reprobe retires the fused grid immediately (the kernel ran
      # and produced wrong numbers — definitive). A 'SKIPPED' line is
      # ambiguous: it can mean Mosaic genuinely refused to compile the
      # kernel, OR the generic except-branch caught a tunnel death
      # mid-compile (advisor r4) — so SKIPPED gets one free retry: the
      # first SKIPPED-with-live-reprobe records a strike, the second
      # retires. A SKIPPED whose reprobe fails is a tunnel death: no
      # strike, retry next window.
      mosaicfail=0
      skipstrike=0
      skipretire=0
      if [ "$key" = parity ] && [ "$rc" -ne 0 ] && [ "$fellback" -eq 0 ]; then
        if grep -q 'pallas fused parity FAILED' "$step_out" && probe; then
          mosaicfail=1
        elif grep -q 'pallas fused gather: SKIPPED' "$step_out" && probe; then
          if grep -qx "parity SKIP1" "$STATE"; then
            # second SKIPPED with the tunnel alive: retire, but as its OWN
            # class (ADVICE r5) — a compile-refusal is not the definitive
            # wrong-numbers verdict the FAILED path records, and the two
            # must not share a log line or a state marker
            skipretire=1
          else
            echo "parity SKIP1" >>"$STATE"
            echo "--- parity SKIPPED with tunnel alive; one more strike retires the fused grid ---" | tee -a "$LOG"
            skipstrike=1
          fi
        fi
      fi
      # genuine on-device numerical-validation failure (not a flap/CPU
      # drop): every subsequent row from this device would be untrusted —
      # halt the queue loudly rather than fill BASELINE from broken math
      if [ "$key" = selftest ] && [ "$rc" -ne 0 ] && [ "$fellback" -eq 0 ] && \
         grep -q 'selftest FAILED' "$step_out"; then
        echo "== DEVICE FAILED NUMERICAL SELFTEST; halting queue $(date -u +%FT%TZ) ==" | tee -a "$LOG"
        echo '{"warning": "device failed numerical selftest; queue halted - rows after this point would be untrusted"}' >>"$LOG"
        step_bundle "$key" selftest-halt
        rm -f "$step_out"
        exit 3
      fi
      # any other genuinely failed step (nonzero rc, not a probe-race CPU
      # fallback) gets its forensics bundle before the state machine
      # decides what to do with it
      if [ "$rc" -ne 0 ] && [ "$fellback" -eq 0 ]; then
        step_bundle "$key" failed
      fi
      rm -f "$step_out"
      if [ "$rc" -eq 0 ] && [ "$fellback" -eq 0 ]; then
        echo "$key" >>"$STATE"
        # PASS marker distinguishes a genuine success from the retired-
        # after-two-failures bare key; the parity gate above keys off it
        echo "$key PASS" >>"$STATE"
      elif [ "$fellback" -eq 1 ]; then
        echo "--- $key emitted a CPU-fallback row (probe race); reprobing ---" | tee -a "$LOG"
        break   # treat like a tunnel death: leave unmarked, fall back to probing
      elif [ "$mosaicfail" -eq 1 ]; then
        echo "--- parity FAILED on real Mosaic; retiring fused steps ---" | tee -a "$LOG"
        echo "parity" >>"$STATE"
        echo "parity MOSAICFAIL" >>"$STATE"
      elif [ "$skipretire" -eq 1 ]; then
        echo "--- parity SKIPPED twice with tunnel alive; retiring fused grid (Mosaic compile-refusal, not wrong numbers) ---" | tee -a "$LOG"
        echo "parity" >>"$STATE"
        echo "parity SKIPRETIRE" >>"$STATE"
      elif [ "$skipstrike" -eq 1 ]; then
        # strike already recorded and logged above; skip the generic
        # handler so the same event is not re-probed (45 s of a short
        # window) and re-classified as a transient flap (review r5)
        :
      elif probe; then
        # tunnel alive after the failure: could be a genuinely broken step
        # OR a mid-step outage whose tunnel recovered before the timeout
        # killed us. Retry once (FAIL marker); only a second failure with
        # the tunnel alive is skipped permanently. Exception: the parity
        # gate retries every window — retiring it on transient flaps would
        # otherwise silently forfeit the whole fused decision grid, and a
        # REAL kernel failure is caught by the mosaicfail branch above.
        if [ "$key" = parity ]; then
          echo "--- parity failed transiently (flap/timeout); will retry next window ---" | tee -a "$LOG"
        elif grep -qx "$key FAIL" "$STATE"; then
          echo "--- $key FAILED twice with tunnel alive; skipping permanently ---" | tee -a "$LOG"
          echo "$key" >>"$STATE"
        else
          echo "--- $key FAILED with tunnel alive; will retry once ---" | tee -a "$LOG"
          echo "$key FAIL" >>"$STATE"
        fi
      else
        echo "--- $key FAILED/timed out; reprobing tunnel ---" | tee -a "$LOG"
        break   # tunnel died mid-step; fall back to probing
      fi
    done
  fi
  sleep "$SLEEP"
done
