"""Summarize a TPU-watcher results log into BASELINE-ready rows.

The watcher (`tpu_watch.sh`) appends raw step output — JSON result lines
interleaved with step headers and warnings — to its log. This tool pulls
out the parseable result rows, drops CPU-fallback/error rows AND any row
whose device field names a CPU (a probe race can let a step run on the
fallback backend) — those must never be transcribed as TPU numbers
(BASELINE.md provenance note) — and prints one compact line per
measurement plus a markdown table snippet for BASELINE.md's Measured
section. Rows without a device field are listed separately as
unknown-provenance, never as clean results.

Telemetry event lines (the `netrep_tpu.utils.telemetry` JSONL schema —
``{"v": 1, ..., "ev": ..., "data": {...}}``; the watcher points bench at a
``*_telemetry.jsonl`` sibling via NETREP_TELEMETRY, but mixed logs work
too) are recognized and summarized as a per-phase time split — so watch
summaries show where each measurement window's wall-clock went (observed
vs chunks vs superchunks vs checkpoints), not just the final number.

Usage: python benchmarks/summarize_watch.py [logfile ...]
       (default: benchmarks/tpu_results_r5.jsonl + r4)
"""

from __future__ import annotations

import json
import sys

#: telemetry event-schema version this summarizer understands (mirrors
#: netrep_tpu.utils.telemetry.SCHEMA_VERSION; kept literal so the script
#: stays standalone-runnable without the package on sys.path)
TELEMETRY_SCHEMA = 1

#: perf-ledger entry version this summarizer understands (mirrors
#: netrep_tpu.utils.perfledger.ENTRY_VERSION, literal for the same
#: standalone reason) — ledger entries drive the "perf trend" section,
#: replacing the old habit of re-parsing raw bench tails by hand
PERF_LEDGER_SCHEMA = 1

#: invariant-lint report version this summarizer understands (mirrors
#: netrep_tpu.analysis.linter.LINT_SCHEMA, literal for the same
#: standalone reason) — the watcher appends one `lint --json` line per
#: cycle; a non-ok line means rows from that tree may not carry the
#: bit-identity guarantees and is surfaced in its own section
LINT_SCHEMA = 1


def rows_from(path: str) -> list[dict]:
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(row, dict):
                    continue
                rows.append(row)
    except FileNotFoundError:
        print(f"(no log at {path})", file=sys.stderr)
    return rows


def classify(row: dict) -> str:
    if (row.get("v") == TELEMETRY_SCHEMA and isinstance(row.get("ev"), str)
            and isinstance(row.get("data"), dict)):
        # structured telemetry event (netrep_tpu.utils.telemetry): not a
        # measurement row — aggregated into the per-phase split instead
        return "telemetry"
    if (row.get("perf_v") == PERF_LEDGER_SCHEMA
            and isinstance(row.get("fingerprint"), str)
            and isinstance(row.get("perms_per_sec"), (int, float))):
        # perf-ledger entry (netrep_tpu.utils.perfledger): feeds the
        # "perf trend" section, never the BASELINE result table
        return "ledger"
    if (row.get("lint_v") == LINT_SCHEMA
            and isinstance(row.get("findings"), list)):
        # invariant-lint report (netrep_tpu.analysis): never a
        # measurement — summarized in its own contract-health section
        return "lint"
    if isinstance(row.get("top"), dict) and "tenants" in row["top"]:
        # `top --once --json` snapshot captured by the serve drill
        # (ISSUE 13): an ops artifact, never a TPU measurement —
        # summarized in the serve-observability section. Checked BEFORE
        # the CPU drop below: the serve plane runs on CPU by design.
        return "serve-top"
    if (isinstance(row.get("metric"), str)
            and row["metric"].startswith("serve-cost")
            and isinstance(row.get("cost"), dict)):
        # per-tenant attributed-cost row (ISSUE 13): surfaced as the
        # cost table, not a BASELINE measurement (CPU by design)
        return "serve-cost"
    if (isinstance(row.get("metric"), str)
            and row["metric"].startswith("serve-warmstart")):
        # warm-start proof rows (ISSUE 15): fresh-process first-request
        # compile span against a populated AOT store — a robustness/
        # latency signal (CPU by design), never a BASELINE measurement
        return "serve-warmstart"
    if ((isinstance(row.get("metric"), str)
         and row["metric"].startswith("serve-autoscale"))
            or "evicted_replica" in row):
        # autoscale / noticed-eviction rows (ISSUE 19): the serve_load
        # --autoscale square-wave row and the chaos --fleet --evict
        # handoff summary — checked BEFORE the serve-fleet classifier
        # below so an eviction verdict never folds into the
        # kill-failover story. Robustness signals (CPU by design),
        # never BASELINE measurements.
        return "serve-autoscale"
    if ((isinstance(row.get("metric"), str)
         and row["metric"].startswith("serve-fleet"))
            or "killed_replica" in row):
        # fleet drill rows (ISSUE 14): the serve_load --fleet
        # kill-failover row and the chaos --fleet summary — robustness
        # signals (CPU by design), never BASELINE measurements
        return "serve-fleet"
    if (isinstance(row.get("metric"), str)
            and row["metric"].startswith("mixed ")
            and "rescued_fraction" in row
            and (row.get("vs_baseline") is None
                 or row.get("tpu_fallback")
                 or "cpu" in str(row.get("device", "")).lower())):
        # mixed-precision screened null (ISSUE 16), CPU/fallback run: a
        # deliberate parity/mechanism row — bf16 rounding is emulated on
        # CPU so the in-bench bit-parity assertion and rescued fraction
        # are real signals while the timing is not (vs_baseline nulled
        # in-bench). Surfaced in its own screening-health section instead
        # of silently dropped with the CPU rows; a real TPU measurement
        # falls through to the result table below.
        return "mixed"
    if (isinstance(row.get("metric"), str)
            and row["metric"].startswith("grid ")
            and "bit_identical_to_solo" in row
            and (row.get("tpu_fallback")
                 or "cpu" in str(row.get("device", "")).lower())):
        # all-pairs atlas (ISSUE 17), CPU/fallback run: the in-bench
        # cell-vs-solo bit-parity gate and the <25% delta re-analysis
        # bound are real signals on any backend (same policy as "mixed"
        # above — the timing isn't a TPU number, the mechanism verdict
        # is). Surfaced in its own atlas-health section instead of
        # silently dropped with the CPU rows; a real TPU measurement
        # falls through to the result table below.
        return "grid"
    if row.get("tpu_fallback") or "error" in row or "warning" in row:
        return "dropped"
    if row.get("cached"):
        # tune resume replay: the measurement already appears once as a
        # fresh row in an earlier watcher attempt — transcribing each
        # rerun's replay would list one measurement as if independently
        # reproduced
        return "dropped"
    if row.get("ok") is False:
        return "dropped"  # tune point that failed validation mid-run
    dev = str(row.get("device", ""))
    if "cpu" in dev.lower():
        # probe race: step ran on the CPU fallback backend (applies to the
        # tune sweep's final best line too — its points were CPU-timed)
        return "dropped"
    if not dev:
        # parseable but unattributable — surface it, never as a clean
        # result, a trusted best line, or a transcribe-me "other" row
        return "unknown"
    if "best" in row:
        return "result" if row["best"] else "dropped"  # null = failed sweep
    if "metric" in row and "value" in row:
        return "result"
    if "perms_per_sec" in row or "s" in row:
        return "result"  # tune-sweep grid point (device checked above)
    # device-attributed but no standard value field (e.g. bf16_drift's
    # table row) — listed by main() so no measurement silently vanishes
    return "other"


def telemetry_split(rows: list[dict]) -> dict:
    """Per-phase time split of telemetry events: ``{ev: [n, total_s]}``
    over every event carrying a numeric ``s`` duration (chunk, superchunk,
    observed, pair, null_run_end, allgather, backend_probe...)."""
    per: dict[str, list] = {}
    for r in rows:
        s = (r.get("data") or {}).get("s")
        if isinstance(s, (int, float)) and not isinstance(s, bool):
            agg = per.setdefault(r["ev"], [0, 0.0])
            agg[0] += 1
            agg[1] += float(s)
    return per


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def perf_trend(entries: list[dict]) -> list[str]:
    """Per-fingerprint throughput trend lines from perf-ledger entries
    (ISSUE 5): entry count, median, newest, newest/median ratio — the
    cross-round perf story in four numbers per config, sourced from the
    ledger instead of re-parsing raw bench tails."""
    groups: dict[str, list[float]] = {}
    order: list[str] = []
    for e in entries:
        fp = e["fingerprint"]
        if fp not in groups:
            groups[fp] = []
            order.append(fp)
        groups[fp].append(float(e["perms_per_sec"]))
    lines = []
    for fp in order:
        vals = groups[fp]
        med = _median(vals)
        ratio = vals[-1] / med if med > 0 else float("nan")
        flag = "  <-- REGRESSED" if ratio < 0.6 else ""
        lines.append(f"{fp}: n={len(vals)} median={med:g} "
                     f"newest={vals[-1]:g} newest/median={ratio:.3f}{flag}")
    return lines


def lint_lines(rows: list[dict]) -> list[str]:
    """Contract-health section from `lint --json` lines: per-cycle
    ok/finding counts plus the per-rule split of the NEWEST non-ok
    report (the actionable one)."""
    lines = []
    bad = [r for r in rows if not r.get("ok")]
    lines.append(
        f"{len(rows)} lint cycle(s): {len(rows) - len(bad)} clean, "
        f"{len(bad)} with findings"
    )
    if bad:
        per_rule: dict[str, int] = {}
        for f in bad[-1].get("findings", []):
            rule = f.get("rule", "?")
            per_rule[rule] = per_rule.get(rule, 0) + 1
        split = ", ".join(f"{k}: {n}" for k, n in sorted(per_rule.items()))
        lines.append(
            f"newest findings ({split}) — rows from this tree may not "
            "carry the bit-identity guarantees; fix before transcribing"
        )
    return lines


def serve_cost_lines(cost_rows: list[dict],
                     top_rows: list[dict]) -> list[str]:
    """Serve-observability section (ISSUE 13): the newest per-tenant
    attributed-cost table per mode label, plus the newest `top` snapshot
    headline (brownout / burn rates) — cost signals for the fleet, never
    BASELINE measurements."""
    lines = []
    newest: dict[str, dict] = {}
    for r in cost_rows:
        newest[str(r["metric"]).split(" (", 1)[0]] = r
    for label in sorted(newest):
        r = newest[label]
        lines.append(f"{r['metric']}: {r['value']}{r.get('unit', '')}")
        for t, c in sorted(r["cost"].items()):
            lines.append(
                f"  {t}: device_s={c.get('device_s')} "
                f"perms={c.get('perms')} bytes={c.get('bytes_to_host')} "
                f"requests={c.get('requests')}"
            )
    if top_rows:
        snap = top_rows[-1]["top"]
        burn = ", ".join(
            f"{t['tenant']}={t.get('burn_rate', 0):g}"
            for t in snap.get("tenants", [])
        )
        lines.append(
            f"newest top snapshot: {len(snap.get('tenants', []))} "
            f"tenant(s), brownout={snap.get('brownout')}, "
            f"burn rates [{burn}] ({len(top_rows)} snapshot(s) total)"
        )
    return lines


def warmstart_lines(rows: list[dict]) -> list[str]:
    """Warm-start section (ISSUE 15): the newest fresh-process proof row
    — warm vs cold first-request compile span, the acquisition source,
    and the delta against the PR 14 coldstart baseline."""
    r = rows[-1]
    verdict = "OK" if r.get("warm_ok") else "FAILED"
    line = (
        f"{r['metric']}: warm compile_span {r.get('value')}s "
        f"(source={r.get('warm_source')}) vs cold "
        f"{r.get('cold_compile_span_s')}s — {verdict}"
    )
    lines = [line]
    if r.get("coldstart_baseline_s") is not None:
        lines.append(
            f"  vs serve-fleet-coldstart baseline "
            f"{r['coldstart_baseline_s']}s: delta "
            f"{r.get('coldstart_delta_s')}s "
            f"({len(rows)} warmstart row(s) total)"
        )
    return lines


def fleet_lines(rows: list[dict]) -> list[str]:
    """Fleet-drill section (ISSUE 14): the newest kill-failover load row
    (p50/p99, failover time, aggregate vs 1 replica) and the newest
    ``chaos --fleet`` verdict — the replication-health story in two
    lines."""
    lines = []
    loads = [r for r in rows if "failover_s" in r]
    if loads:
        r = loads[-1]
        lines.append(
            f"{r['metric']}: {r['value']}{r.get('unit', '')} · "
            f"p50={r.get('p50_ms')}ms p99={r.get('p99_ms')}ms · "
            f"failover={r.get('failover_s')}s · "
            f"vs_1_replica={r.get('vs_1_replica')}"
        )
    drills = [r for r in rows if "killed_replica" in r]
    if drills:
        r = drills[-1]
        verdict = "PASSED" if r.get("ok") else "FAILED"
        lines.append(
            f"chaos --fleet {verdict}: killed={r.get('killed_replica')} "
            f"recovered={r.get('recovered')} "
            f"bit_identical={r.get('bit_identical')} "
            f"({len(drills)} drill(s) total)"
        )
    return lines


def autoscale_lines(rows: list[dict]) -> list[str]:
    """Autoscale section (ISSUE 19): the newest square-wave load row —
    autoscaled p99 vs the static peak fleet, the replica-seconds each
    consumed, and the zero-lost gate across forced evictions — plus the
    newest ``chaos --fleet --evict`` verdict (zero recomputed packs =
    ``evict_handoff_done`` on the timeline with no ``failover_start``).
    The elastic-fleet health story in two lines."""
    lines = []
    loads = [r for r in rows if "replica_seconds" in r]
    if loads:
        r = loads[-1]
        lines.append(
            f"{r['metric']}: {r.get('value')}{r.get('unit', '')} · "
            f"p99={r.get('p99_ms')}ms vs static {r.get('p99_static_ms')}ms "
            f"(within_2x={r.get('p99_within_2x')}) · "
            f"replica_s={r.get('replica_seconds')} vs static "
            f"{r.get('replica_seconds_static')} "
            f"(saved={r.get('replica_seconds_saved')}) · "
            f"lost={r.get('lost_requests')} "
            f"evictions={r.get('evictions')}"
        )
    drills = [r for r in rows if "evicted_replica" in r]
    if drills:
        r = drills[-1]
        verdict = "PASSED" if r.get("ok") else "FAILED"
        lines.append(
            f"chaos --fleet --evict {verdict}: "
            f"evicted={r.get('evicted_replica')} "
            f"zero_recompute={r.get('zero_recompute')} "
            f"bit_identical={r.get('bit_identical')} "
            f"({len(drills)} drill(s) total)"
        )
    return lines


def mixed_lines(rows: list[dict]) -> list[str]:
    """Mixed-precision screening section (ISSUE 16): the newest
    bf16-screened null mechanism row — rescued fraction, wall-clock ratio
    vs the all-f32 loop, and the bit-parity verdict (parity is asserted
    in-bench before the row is ever emitted, so a row that reached the
    log with counts_parity false means the assertion itself regressed)."""
    r = rows[-1]
    parity = ("counts bit-identical" if r.get("counts_parity")
              else "COUNTS PARITY FAILED")
    return [
        f"{r['metric']}: {r.get('value')}{r.get('unit', '')} · "
        f"rescued_fraction={r.get('rescued_fraction')} · "
        f"vs f32 {r.get('mixed_vs_f32_x')}x (f32 {r.get('f32_s')}s) · "
        f"{parity} ({len(rows)} row(s) total)"
    ]


def grid_lines(rows: list[dict]) -> list[str]:
    """All-pairs atlas section (ISSUE 17): the newest D×D grid bench row
    — cold packed grid vs the sequential per-pair baseline, the
    one-cohort digest-delta fraction (bounded <25% in-bench), reuse /
    warm-start / dedup counters, and the bit-parity verdict (asserted
    in-bench per cell before the row is ever emitted, so a row reaching
    the log with the flag false means the assertion itself regressed)."""
    r = rows[-1]
    parity = ("cells bit-identical to solo" if r.get("bit_identical_to_solo")
              else "CELL/SOLO PARITY FAILED")
    return [
        f"{r['metric']}: {r.get('value')}{r.get('unit', '')} · "
        f"vs sequential {r.get('vs_baseline')}x "
        f"(seq {r.get('sequential_s')}s) · "
        f"delta_perm_fraction={r.get('delta_perm_fraction')} "
        f"(reused={r.get('cells_reused_on_delta')} "
        f"warmstarted={r.get('cells_warmstarted_on_delta')} of "
        f"{r.get('cells')} cells) · dedup_hits={r.get('dedup_hits')} "
        f"packs={r.get('packs')} · {parity} ({len(rows)} row(s) total)"
    ]


def roofline_lines(events: list[dict]) -> list[str]:
    """Roofline section (ISSUE 18) from ``roofline`` telemetry events:
    per program family, the newest achieved-vs-speed-of-light verdict.
    Rows whose ``device_kind`` has no peak entry (CPU, unknown —
    utilisation null, never a guess) are classified as MECHANISM checks:
    the cost accounting ran and reconciled, but the utilisation number is
    not a TPU measurement and must never be transcribed as one. Rows
    with a real utilisation are the measured roofline story BASELINE's
    hand-written predictions graduate into."""
    measured: dict[str, dict] = {}
    mechanism: dict[str, dict] = {}
    for e in events:
        d = e.get("data") or {}
        fam = d.get("family")
        if not isinstance(fam, str):
            continue
        if isinstance(d.get("utilisation"), (int, float)):
            measured[fam] = d
        else:
            mechanism[fam] = d
    lines = []
    for fam in sorted(measured):
        d = measured[fam]
        lines.append(
            f"{fam} [{d.get('device_kind')}]: utilisation "
            f"{d.get('utilisation')} of speed of light "
            f"({d.get('achieved_pps')} / {d.get('sol_pps')} perms/s, "
            f"{d.get('flops_per_perm')} flops/perm, "
            f"{d.get('bytes_per_perm')} bytes/perm)"
        )
    for fam in sorted(mechanism):
        d = mechanism[fam]
        lines.append(
            f"{fam} [{d.get('device_kind')}]: MECHANISM row — cost "
            f"accounting ran ({d.get('flops_per_perm')} flops/perm, "
            f"{d.get('achieved_pps')} perms/s) but no peak entry for "
            "this device kind; utilisation null, never transcribe as a "
            "TPU measurement"
        )
    return lines


def anomaly_lines(events: list[dict]) -> list[str]:
    """Anomalies section (ISSUE 20) from ``anomaly_detected`` telemetry
    events — the pinned detector registry's firings during the watch
    window, grouped per detector with the count and the newest
    occurrence's detail. This is the triage headline: a cycle whose rows
    all parsed can still have burned SLO budget, degraded to CPU, or
    refused a checkpoint resume, and those verdicts must never be
    scrolled past."""
    by_det: dict[str, list[dict]] = {}
    for e in events:
        d = e.get("data") or {}
        by_det.setdefault(str(d.get("detector", "-")), []).append(e)
    lines = []
    for det in sorted(by_det):
        evs = by_det[det]
        last = evs[-1].get("data") or {}
        detail = " ".join(
            f"{k}={v}" for k, v in last.items()
            if k not in ("detector", "span", "parent")
        )
        lines.append(f"{det}: fired x{len(evs)}"
                     + (f" — last: {detail}" if detail else ""))
    return lines


def main(paths: list[str]) -> int:
    results, unknown, other, dropped, telemetry = [], [], [], 0, []
    ledger, lint, serve_cost, serve_top = [], [], [], []
    fleet = []
    autoscale = []
    warmstart = []
    mixed = []
    grid = []
    for p in paths:
        for r in rows_from(p):
            kind = classify(r)
            if kind == "dropped":
                dropped += 1
            elif kind == "unknown":
                unknown.append((p, r))
            elif kind == "other":
                other.append((p, r))
            elif kind == "result":
                results.append((p, r))
            elif kind == "telemetry":
                telemetry.append(r)
            elif kind == "ledger":
                ledger.append(r)
            elif kind == "lint":
                lint.append(r)
            elif kind == "serve-cost":
                serve_cost.append(r)
            elif kind == "serve-top":
                serve_top.append(r)
            elif kind == "serve-fleet":
                fleet.append(r)
            elif kind == "serve-autoscale":
                autoscale.append(r)
            elif kind == "serve-warmstart":
                warmstart.append(r)
            elif kind == "mixed":
                mixed.append(r)
            elif kind == "grid":
                grid.append(r)
    if grid:
        print("## all-pairs atlas (grid packing + delta re-analysis health)")
        for line in grid_lines(grid):
            print(line)
        print()
    if mixed:
        print("## mixed-precision screening (bf16 fast-pass health)")
        for line in mixed_lines(mixed):
            print(line)
        print()
    if warmstart:
        print("## warm start (zero-compile first request)")
        for line in warmstart_lines(warmstart):
            print(line)
        print()
    if autoscale:
        print("## autoscale drills (elastic-fleet + noticed-eviction health)")
        for line in autoscale_lines(autoscale):
            print(line)
        print()
    if fleet:
        print("## fleet drills (kill-failover health)")
        for line in fleet_lines(fleet):
            print(line)
        print()
    if serve_cost or serve_top:
        print("## serve observability (attributed cost + top snapshots)")
        for line in serve_cost_lines(serve_cost, serve_top):
            print(line)
        print()
    if lint:
        print("## invariant lint (contract health)")
        for line in lint_lines(lint):
            print(line)
        print()
    if ledger:
        print(f"## perf trend ({len(ledger)} ledger entries)")
        for line in perf_trend(ledger):
            print(line)
        print()
    anomalies = [r for r in telemetry if r.get("ev") == "anomaly_detected"]
    if anomalies:
        print(f"## anomalies ({len(anomalies)} detector firing(s) — "
              "triage before transcribing any row above)")
        for line in anomaly_lines(anomalies):
            print(line)
        print()
    roofline = [r for r in telemetry if r.get("ev") == "roofline"]
    if roofline:
        print(f"## roofline (achieved vs speed of light, "
              f"{len(roofline)} run(s))")
        for line in roofline_lines(roofline):
            print(line)
        print()
    if telemetry:
        split = telemetry_split(telemetry)
        print(f"## telemetry per-phase time split ({len(telemetry)} events)")
        total = sum(v[1] for v in split.values()) or 1.0
        for ev in sorted(split, key=lambda k: -split[k][1]):
            n, s = split[ev]
            print(f"{ev}: {s:.3f}s over {n} event(s) "
                  f"({100 * s / total:.0f}% of timed phases)")
        print()
    if dropped:
        print(f"# dropped {dropped} fallback/error/warning/CPU/not-ok rows "
              "(never transcribe those as TPU numbers)", file=sys.stderr)
    if unknown:
        print("## unknown-provenance rows (no device field — attribute "
              "before use)")
        for p, r in unknown:
            print(f"{p}: {json.dumps(r)}")
        print()
    if other:
        print("## other parseable rows (non-standard shape, e.g. drift "
              "tables — transcribe manually)")
        for p, r in other:
            print(f"{p}: {json.dumps(r)}")
        print()
    if not results:
        print("# no clean result rows yet")
        return 0
    print("## raw rows")
    for p, r in results:
        print(f"{p}: {json.dumps(r)}")
    print()
    print("## BASELINE.md table snippet (verify device column before use)")
    print("| Config | Device | Result | Command |")
    print("|---|---|---|---|")
    for _, r in results:
        if "metric" not in r or "value" not in r:
            continue
        extra = []
        if "perms_per_sec" in r:
            extra.append(f"{r['perms_per_sec']} perms/s")
        if "vs_baseline" in r:
            extra.append(f"vs_baseline {r['vs_baseline']}")
        print(f"| {r['metric']} | {r.get('device', '?')} | "
              f"**{r['value']} {r.get('unit', '')}** "
              f"({'; '.join(extra)}) | — |")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:] or ["benchmarks/tpu_results_r5.jsonl", "benchmarks/tpu_results_r4.jsonl"]))
