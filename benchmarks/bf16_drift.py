"""Statistic-level bf16-vs-f32 drift at north-star scale (one JSON line).

bf16 matrix storage halves the HBM traffic of the bandwidth-bound gather
(BASELINE.md roofline); this measures what it costs in accuracy: the same
64-permutation null at 20k genes / 50 modules under both dtypes, reporting
the max and RMS statistic-level deviation alongside the null's own
Monte-Carlo scale (the std of each statistic across permutations). The
deviation is acceptable when it sits far below the Monte-Carlo scale —
the criterion BASELINE.md's precision note applies to the mxu gather.
"""

import json
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root, cwd-independent
from bench import build_problem, ensure_backend, make_specs_auto  # noqa: E402


def main(genes=20_000, modules=50, perms=64, samples=128):
    devices = ensure_backend()
    from netrep_tpu.parallel.engine import PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = build_problem(
        genes, modules, samples
    )
    specs = make_specs_auto(genes, modules)
    pool = np.arange(genes, dtype=np.int32)

    nulls = {}
    for dtype in ("float32", "bfloat16"):
        eng = PermutationEngine(
            d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
            config=EngineConfig(chunk_size=perms, power_iters=40, dtype=dtype),
        )
        arr, done = eng.run_null(perms, key=0)
        assert done == perms
        nulls[dtype] = np.asarray(arr)

    from netrep_tpu.ops.oracle import STAT_NAMES

    diff = nulls["bfloat16"] - nulls["float32"]
    mc_scale = nulls["float32"].std(axis=0)  # (modules, 7) null spread
    # Per-statistic breakdown: a mean-of-rounded-values statistic (e.g.
    # avg.weight) carries bf16 rounding as a systematic BIAS that does not
    # attenuate with module size, while correlation-type statistics see
    # near-zero-mean rounding that does — one aggregate max hides which
    # regime dominates, and the bf16-default decision hinges on it.
    per_stat = {}
    for si, name in enumerate(STAT_NAMES):
        d = np.abs(diff[..., si])
        # worst drift RELATIVE to the same module's own null spread
        ratio = d / np.maximum(mc_scale[None, :, si], 1e-12)
        per_stat[name] = {
            "max_drift": float(np.nanmax(d)),
            "rms_drift": float(np.sqrt(np.nanmean(d ** 2))),
            "max_drift_over_own_mc": float(np.nanmax(ratio)),
            "rms_drift_over_own_mc": float(np.sqrt(np.nanmean(ratio ** 2))),
        }
    print(json.dumps({
        "metric": f"bf16-vs-f32 statistic drift ({genes} genes / {modules} "
                  f"modules, {perms} perms)",
        "max_abs_drift": float(np.nanmax(np.abs(diff))),
        "rms_drift": float(np.sqrt(np.nanmean(diff ** 2))),
        "median_mc_scale": float(np.nanmedian(mc_scale)),
        # worst drift normalized by the SAME (module, statistic)'s null
        # spread — dividing one statistic's drift by the cross-statistic
        # median scale (the old aggregate) mixed units and overstated the
        # drift ~5x
        "max_drift_over_own_mc": float(np.nanmax(
            [s["max_drift_over_own_mc"] for s in per_stat.values()]
        )),
        "per_statistic": per_stat,
        "device": str(devices[0]),
    }))


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--genes", type=int, default=20_000)
    ap.add_argument("--modules", type=int, default=50)
    ap.add_argument("--perms", type=int, default=64)
    ap.add_argument("--samples", type=int, default=128)
    a = ap.parse_args()
    main(a.genes, a.modules, a.perms, a.samples)
