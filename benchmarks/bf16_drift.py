"""Statistic-level bf16-vs-f32 drift at north-star scale (one JSON line).

bf16 matrix storage halves the HBM traffic of the bandwidth-bound gather
(BASELINE.md roofline); this measures what it costs in accuracy: the same
64-permutation null at 20k genes / 50 modules under both dtypes, reporting
the max and RMS statistic-level deviation alongside the null's own
Monte-Carlo scale (the std of each statistic across permutations). The
deviation is acceptable when it sits far below the Monte-Carlo scale —
the criterion BASELINE.md's precision note applies to the mxu gather.
"""

import json
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root, cwd-independent
from bench import build_problem, ensure_backend, make_specs  # noqa: E402


def main(genes=20_000, modules=50, perms=64, samples=128):
    devices = ensure_backend()
    from netrep_tpu.parallel.engine import PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = build_problem(
        genes, modules, samples
    )
    specs = make_specs(genes, modules, 30, 200)
    pool = np.arange(genes, dtype=np.int32)

    nulls = {}
    for dtype in ("float32", "bfloat16"):
        eng = PermutationEngine(
            d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
            config=EngineConfig(chunk_size=perms, power_iters=40, dtype=dtype),
        )
        arr, done = eng.run_null(perms, key=0)
        assert done == perms
        nulls[dtype] = np.asarray(arr)

    diff = nulls["bfloat16"] - nulls["float32"]
    mc_scale = nulls["float32"].std(axis=0)  # (modules, 7) null spread
    print(json.dumps({
        "metric": f"bf16-vs-f32 statistic drift ({genes} genes / {modules} "
                  f"modules, {perms} perms)",
        "max_abs_drift": float(np.nanmax(np.abs(diff))),
        "rms_drift": float(np.sqrt(np.nanmean(diff ** 2))),
        "median_mc_scale": float(np.nanmedian(mc_scale)),
        "drift_over_mc": float(
            np.nanmax(np.abs(diff)) / np.nanmedian(mc_scale)
        ),
        "device": str(devices[0]),
    }))


if __name__ == "__main__":
    main()
