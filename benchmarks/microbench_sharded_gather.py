"""Micro-bench: row-sharded gather modes vs the replicated mxu path
(VERDICT r1 item 3 "Done" evidence).

Multi-chip hardware isn't available here, so the sharded path runs on a
1×1 device mesh on the real chip — the shard_map machinery, index
arithmetic, psum and unsort all execute, isolating the per-device gather
kernel cost that the old forced-'direct' configuration paid. Semantics on a
real multi-device mesh are covered by tests/test_sharding.py on the 8-dev
CPU mesh; per-device speed is what this measures.

Usage: python benchmarks/microbench_sharded_gather.py [--genes N] [--perms P]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench import build_problem, ensure_backend, make_specs_auto  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--genes", type=int, default=20_000)
    ap.add_argument("--modules", type=int, default=50)
    ap.add_argument("--perms", type=int, default=512)
    ap.add_argument("--chunk", type=int, default=128)
    ap.add_argument("--samples", type=int, default=128)
    args = ap.parse_args()

    import jax

    ensure_backend()
    from jax.sharding import Mesh

    from netrep_tpu.parallel.engine import PermutationEngine
    from netrep_tpu.parallel.mesh import PERM_AXIS, ROW_AXIS
    from netrep_tpu.utils.config import EngineConfig

    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = build_problem(
        args.genes, args.modules, args.samples
    )
    # shared spec builder (review r5): the hand-rolled copy here lacked
    # make_specs' oversubscription assert, so small --genes runs could
    # silently clip module indices into duplicated rows
    specs = make_specs_auto(args.genes, args.modules)
    pool = np.arange(args.genes, dtype=np.int32)

    mesh1 = Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1), (PERM_AXIS, ROW_AXIS)
    )

    def run(tag, sharding, gather_mode, mesh):
        cfg = EngineConfig(
            chunk_size=args.chunk, summary_method="power", power_iters=40,
            matrix_sharding=sharding, gather_mode=gather_mode,
        )
        eng = PermutationEngine(
            d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
            config=cfg, mesh=mesh,
        )
        _ = eng.run_null(args.chunk, key=99)  # compile warm-up
        t0 = time.perf_counter()
        nulls, done = eng.run_null(args.perms, key=0)
        dt = time.perf_counter() - t0
        assert done == args.perms and np.isfinite(nulls).all()
        return {"config": tag, "s": round(dt, 3),
                "perms_per_sec": round(args.perms / dt, 2)}

    rows = [
        run("replicated-mxu (north-star path)", "replicated", "auto", None),
        run("row-sharded direct (old forced mode)", "row", "direct", mesh1),
        run("row-sharded mxu (new)", "row", "mxu", mesh1),
    ]
    base = rows[0]["perms_per_sec"]
    for r in rows:
        r["vs_replicated"] = round(r["perms_per_sec"] / base, 3)
        print(json.dumps(r))
    return 0


if __name__ == "__main__":
    sys.exit(main())
