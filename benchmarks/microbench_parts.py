"""Decompose the mxu-gather chunk cost into parts, and measure precision/
dtype variants of the one-hot selection matmul (VERDICT round-1 item 1).

Parts per (perm, module): argsort -> row gather -> one-hot colsel matmul ->
unsort matmuls. Plus: perm draw, data slice, standardize+power-iteration
stats. Variants: f32 default precision, f32 HIGHEST, bf16, and a hi+lo
two-pass bf16 "exact-ish" selection.

Usage: python benchmarks/microbench_parts.py [--cap C] [--K K] [--batch B]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# bench.ensure_backend, not a local copy: it adds the killable-subprocess
# tunnel probe (a hung-dead axon dial becomes a fast CPU fallback instead
# of eating this step's whole watcher timeout) and enables the persistent
# compile cache, so a parity/parts step killed mid-compile resumes into
# cached programs in the next tunnel window.
from bench import ensure_backend  # noqa: E402


# bench()'s default warmup count, exported so variant-list sizing at call
# sites (here and microbench_gather) can never drift from the enforcement
# threshold below (review r5: a hard-coded '+ 2' would silently break if
# this default changed)
DEFAULT_WARMUP = 2


def _value_digest(a):
    """Cheap per-argument value identity for the variant-enforcement guard:
    shape + dtype + the first few elements (one small host transfer per
    argument — setup cost, not timed). Object identity alone is not enough
    (ADVICE r5): value-identical copies like ``[(M.copy(),) for _ in
    range(9)]`` are distinct objects, but every timed rep still executes
    the same computation the tunnel short-circuits."""
    if hasattr(a, "shape") and hasattr(a, "dtype"):
        try:
            lead = a[(0,) * max(a.ndim - 1, 0)] if a.ndim else a
            head = np.asarray(lead[:8] if getattr(lead, "ndim", 0) else lead)
            return (str(a.shape), str(a.dtype), head.tobytes())
        except Exception:
            return ("opaque-array", id(a))
    return ("scalar", repr(a))


def bench(fn, *args, reps=5, warmup=DEFAULT_WARMUP, variants=None):
    """Average wall-clock per call. ``variants`` — arg tuples cycled across
    reps so no two timed calls are the identical (fn, args) execution: the
    axon tunnel appears to short-circuit repeated identical executions
    (BASELINE.md "microbench-timing caveat" — plain rep loops printed
    physically impossible rates in the 7/31 window). Every variant shares
    shapes/dtypes, so per-call cost is unchanged; only the values differ.
    Warmup consumes the END of the variant cycle so the timed reps
    (cycling from the start) never repeat a warmup execution when at least
    reps+warmup variants are supplied; the single output reference is
    rebound per rep (device buffers free as execution drains — holding all
    reps' outputs would multiply peak HBM by reps), and the final
    block_until_ready covers the whole in-order stream.

    On accelerators this is ENFORCED (VERDICT r4 item 2): fewer than
    reps+warmup distinct variants means some timed call repeats a prior
    execution, which the tunnel can short-circuit into a fabricated rate
    — raise instead of printing a number that is not a measurement. CPU
    runs (CI, local smoke) are exempt; there is no tunnel to fool."""
    calls = [tuple(v) for v in variants] if variants else [tuple(args)]
    if jax.default_backend() != "cpu":
        # identity-distinct AND value-distinct: [(M, idx)] * 7 satisfies a
        # bare count check, and [(M.copy(), idx.copy()) for _ in range(7)]
        # satisfies an id check (ADVICE r5) — while every timed call is
        # still the identical execution the tunnel short-circuits. The
        # value digest (shape/dtype + leading elements) rejects both.
        distinct = {tuple(id(a) for a in c) for c in calls}
        distinct_vals = {tuple(_value_digest(a) for a in c) for c in calls}
        if (len(calls) < reps + warmup or len(distinct) < len(calls)
                or len(distinct_vals) < len(calls)):
            raise RuntimeError(
                f"bench() on an accelerator requires >= reps+warmup "
                f"({reps}+{warmup}) DISTINCT input variants, got "
                f"{len(distinct)} id-distinct / {len(distinct_vals)} "
                f"value-distinct of {len(calls)}: repeated identical "
                "executions are short-circuited by the TPU "
                "tunnel and produce physically impossible rates "
                "(BASELINE.md microbench-timing caveat)"
            )
    for w in range(warmup):
        jax.block_until_ready(fn(*calls[-1 - (w % len(calls))]))
    out = None
    t0 = time.perf_counter()
    for r in range(reps):
        out = fn(*calls[r % len(calls)])
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def fused_parity(M, M16, idx, B, K, cap, n, reps=5, FL=None, time_it=True,
                 idx_variants=None):
    """Parity-first check of the Pallas fused gather under real Mosaic
    (VERDICT r3 item 3): the first fused-kernel step on hardware must be a
    small correctness check, not a benchmark — a silent miscompile here
    would poison every fused row after it. Timings follow only when
    ``time_it`` and the backend is a real accelerator. Returns True when
    parity actually ran and passed, False when the kernel was unavailable
    (import/compile failure) — callers acting as a gate must treat False
    as a failure, not a pass."""
    try:
        from netrep_tpu.ops.fused_gather import gather_submatrix_fused

        idx_flat = idx.reshape(B * K, cap)
        on_cpu = jax.default_backend() == "cpu"  # interpreter there, like
        # the engine's make_fused_gather — so a CPU run still exercises the
        # parity code below instead of skipping the whole section
        for name, Mx in [("f32", M), ("bf16", M16)]:
            f = jax.jit(
                lambda Mm, ix: gather_submatrix_fused(Mm, ix, interpret=on_cpu)
            )
            # bf16/f32 MXU selection rounding bounds the tolerance (exact
            # would be == for bf16 storage).
            got = np.asarray(f(Mx, idx_flat))   # ALL B*K grid entries — a
            # miscompile limited to g>0 grid steps must not slip through
            ih = np.asarray(idx_flat)
            want = np.asarray(Mx)[ih[:, :, None], ih[:, None, :]]
            err = np.abs(got - want.astype(np.float32)).max()
            scale = max(1e-9, np.abs(want.astype(np.float32)).max())
            assert err / scale < 2e-2, (
                f"pallas fused parity FAILED ({name}): rel err {err/scale:.2e}"
            )
            print(f"pallas fused parity {name}: rel err {err/scale:.2e} ok",
                  flush=True)
            if on_cpu or not time_it:
                # parity is the point here; interpreter timings would land
                # in the shared log in the same format as real TPU decision
                # rows and poison the gather_mode flip data
                print(f"pallas fused gather {name}: parity-only "
                      "(timing suppressed)", flush=True)
                continue
            # drop variant 0: the parity check above already executed it,
            # so a timed rep reusing it would hit the tunnel short-circuit
            flats = (
                [(Mx, iv.reshape(B * K, cap)) for iv in idx_variants[1:]]
                if idx_variants else None
            )
            t = bench(f, Mx, idx_flat, reps=reps, variants=flats)
            nb = B * K * cap * n * Mx.dtype.itemsize
            print(f"pallas fused gather {name}:    {t*1e3:8.2f} ms  "
                  f"({nb/t/1e9:6.1f} GB/s rows, {FL/t/1e12:5.1f} TFLOP/s eq)")
    except AssertionError:
        raise  # parity failure must be LOUD, never a SKIPPED line
    except Exception as e:  # pallas unavailable on this backend
        print(f"pallas fused gather: SKIPPED ({type(e).__name__}: {e})")
        return False
    return True


def dispatch_overhead(n: int, cap: int, K: int, B: int, reps: int,
                      fuse: int = 8):
    """Dispatch-amortization microbench (ISSUE 2): the SAME per-chunk
    computation issued as ``fuse`` separate jitted dispatches vs ONE
    ``lax.scan``-fused dispatch of all ``fuse`` chunks — the isolated
    measurement of what the superchunk executor saves per backend (on the
    tunneled TPU backend each dispatch costs ~1 s of host round-trip; on
    CPU the gap is Python/jit-call overhead only). The chunk body mirrors
    the engine's hot shape (row gather + one-hot column-select matmul +
    reduce) without its full statistics, keeping the sweep inside a
    tunnel window. Prints per-chunk ms for both and the overhead delta."""
    key = jax.random.key(7)
    M = jax.random.normal(key, (n, n), dtype=jnp.float32)

    def chunk_body(ix):
        rows = jnp.take(M, ix, axis=0)           # (B, K, cap, n)
        oh = (
            jax.lax.broadcasted_iota(jnp.int32, (B, K, n, cap), 2)
            == ix[:, :, None, :]
        ).astype(jnp.float32)
        sub = jnp.matmul(rows, oh, preferred_element_type=jnp.float32)
        return sub.sum(axis=(2, 3))              # (B, K) reduce → tiny out

    one = jax.jit(chunk_body)

    @jax.jit
    def fused(ix_stack):                          # (fuse, B, K, cap)
        def body(carry, ix):
            return carry + chunk_body(ix).sum(), None
        out, _ = jax.lax.scan(body, jnp.float32(0), ix_stack)
        return out

    def make_idx(seed):
        return jnp.sort(jax.random.randint(
            jax.random.key(seed), (B, K, cap), 0, n, dtype=jnp.int32
        ), axis=-1)

    n_var = max(1, reps) + DEFAULT_WARMUP
    # each variant: fuse distinct chunk index sets — pre-split for the
    # serial path (an eager per-call slice would add dispatches the real
    # chunk loop does not issue) and pre-stacked for the fused path
    groups = [
        [make_idx(1000 + v * fuse + j) for j in range(fuse)]
        for v in range(n_var)
    ]
    stacks = [jnp.stack(g) for g in groups]

    def serial(*ixs):
        out = None
        for ix in ixs:  # fuse separate dispatches
            out = one(ix)
        return out

    t_serial = bench(serial, *groups[0], reps=reps,
                     variants=[tuple(g) for g in groups])
    t_fused = bench(fused, stacks[0], reps=reps,
                    variants=[(s,) for s in stacks])
    per_serial = t_serial / fuse * 1e3
    per_fused = t_fused / fuse * 1e3
    print(f"dispatch overhead ({fuse} chunks): separate "
          f"{t_serial*1e3:8.2f} ms ({per_serial:6.2f} ms/chunk)  "
          f"scan-fused {t_fused*1e3:8.2f} ms ({per_fused:6.2f} ms/chunk)  "
          f"amortization {per_serial/per_fused:5.2f}x, "
          f"{per_serial-per_fused:6.2f} ms/chunk saved")


def stats_dispatch_overhead(n: int, cap: int, K: int, B: int, reps: int,
                            samples: int = 64):
    """Fused-STATS dispatch comparison (ISSUE 8 satellite): the SAME
    per-chunk null computation — per-module submatrix gather, the seven
    preservation statistics, and the (hi, lo, eff) exceedance fold —
    issued as the XLA composition (mxu gather → stats kernels → count
    reduction, the stat_mode='xla' streaming chunk) vs ONE
    ``ops/fused_stats`` mega-kernel dispatch whose tally fold happens in
    VMEM (stat_mode='fused'). This is the PR-2/PR-5 yardstick applied to
    the statistics path: dispatch_overhead above isolates host-round-trip
    amortization; this isolates the HBM round-trips BETWEEN the gather,
    statistic, and fold stages that the kernel removes. Judged per
    backend; on CPU the kernel runs the Pallas interpreter, so only the
    TPU rows are decision-grade (labelled). Counts parity is asserted
    before any timing prints."""
    from netrep_tpu.ops import stats as jstats
    from netrep_tpu.ops.fused_stats import fused_stats_counts

    on_cpu = jax.default_backend() == "cpu"
    key = jax.random.key(11)
    M = jax.random.normal(key, (n, n), dtype=jnp.float32)
    dataT = jax.random.normal(jax.random.key(12), (n, samples),
                              dtype=jnp.float32)
    rng = np.random.default_rng(13)
    didx = jnp.asarray(np.stack([
        rng.choice(n, cap, replace=False).astype(np.int32) for _ in range(K)
    ]))
    mask = jnp.ones((K, cap), jnp.float32)
    sub = lambda mat, ix: mat[ix[:, None], ix[None, :]]
    corr_b = jax.vmap(lambda ix: sub(M, ix))(didx)
    net_b = jstats.derived_net(corr_b, 2.0)
    data_b = jax.vmap(lambda ix: jnp.take(dataT.T, ix, axis=1))(didx)
    disc = jstats.make_disc_props(corr_b, net_b, data_b, mask)
    obs = jnp.zeros((K, 7), jnp.float32)
    pv = jnp.ones((B,), jnp.int32)

    def make_idx(seed):
        return jax.random.randint(jax.random.key(seed), (B, K, cap), 0, n,
                                  dtype=jnp.int32)

    n_var = max(1, reps) + DEFAULT_WARMUP + 1
    idxs = [make_idx(500 + v) for v in range(n_var)]

    kernel = functools.partial(
        jstats.gather_and_stats_mxu, n_iter=60, summary_method="power",
        net_beta=2.0,
    )

    @jax.jit
    def xla_chunk(ix, pvm):
        def per_perm(ixp):
            return jax.vmap(kernel, in_axes=(0, 0, None, None, None))(
                disc, ixp, M, None, dataT
            )
        vals = jax.lax.map(per_perm, ix)
        sel = (pvm > 0)[:, None, None]
        hi = jnp.sum((vals >= obs[None]) & sel, axis=0, dtype=jnp.int32)
        lo = jnp.sum((vals <= obs[None]) & sel, axis=0, dtype=jnp.int32)
        eff = jnp.sum(~jnp.isnan(vals) & sel, axis=0, dtype=jnp.int32)
        return hi, lo, eff

    @jax.jit
    def fused_chunk(ix, pvm):
        _v, hi, lo, eff = fused_stats_counts(
            M, None, dataT, disc, ix, pvm, obs, net_beta=2.0, n_iter=60,
            interpret=on_cpu,
        )
        return hi, lo, eff

    try:
        # counts-parity gate before any timing row: fast-but-wrong numbers
        # must never reach the decision log (same policy as fused_parity)
        hx = [np.asarray(a) for a in xla_chunk(idxs[0], pv)]
        hf = [np.asarray(a) for a in fused_chunk(idxs[0], pv)]
        mism = sum(int((a != b).sum()) for a, b in zip(hx, hf))
        tag = "interpret/CPU — parity row only" if on_cpu else "Mosaic"
        print(f"fused_stats counts parity ({tag}): "
              f"{mism} mismatched cells of {3 * K * 7}", flush=True)
        assert mism == 0 or not on_cpu, "fused_stats parity FAILED on CPU"
        # idxs[0] executed in the parity gate above: rotate it to the END
        # of both variant lists (warmup slots) so no TIMED rep repeats a
        # prior execution the tunnel could short-circuit
        rolled = [(i, pv) for i in idxs[1:]] + [(idxs[0], pv)]
        t_x = bench(xla_chunk, idxs[0], pv, reps=reps, variants=rolled)
        t_f = bench(fused_chunk, idxs[0], pv, reps=reps, variants=rolled)
        print(f"stats dispatch fused_stats [{tag}]: "
              f"xla gather+stats+fold {t_x*1e3:8.2f} ms/chunk  "
              f"mega-kernel {t_f*1e3:8.2f} ms/chunk  "
              f"speedup {t_x/t_f:5.2f}x", flush=True)
    except AssertionError:
        raise
    except Exception as e:
        print(f"fused_stats overhead: SKIPPED ({type(e).__name__}: {e})")
        return False
    return True


def flightrec_overhead(genes=2000, n_perm=512, chunk=128, reps=3,
                       bound=0.02):
    """The always-on tax, measured where it bites (ISSUE 20): a real
    streaming null loop with the flight recorder installed vs fully
    uninstalled. The recorder is host-side only (ring append per emitted
    event, nothing device-side), so the measured overhead must stay under
    ``bound`` — asserted BEFORE any row is printed, so a regression can
    never ride the ledger as a legitimate measurement. The recorder-on
    rate is the row (that is the shipped configuration), under the
    ``flightrec`` metric label."""
    from netrep_tpu.data import make_mixed_pair
    from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
    from netrep_tpu.utils import flightrec, perfledger
    from netrep_tpu.utils.config import EngineConfig

    mixed = make_mixed_pair(genes, 3, n_samples=16, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, i, i) for lab, i in mixed["specs"]]
    eng = PermutationEngine(
        dc, dn, dd, tc, tn, td, specs, mixed["pool"],
        config=EngineConfig(chunk_size=chunk, autotune=False),
    )
    observed = np.asarray(eng.observed())

    def run():
        sc = eng.run_null_streaming(n_perm, observed, key=0)
        assert sc.completed == n_perm
        return sc

    def timed():
        t0 = time.perf_counter()
        run()
        return time.perf_counter() - t0

    assert flightrec.recorder() is not None, \
        "flightrec_overhead needs the recorder installed (the default)"
    run()                                 # warmup: compile + caches
    # interleave the arms (on, off, on, off, ...) and keep each arm's
    # best: a sequential A-then-B layout hands arm B every cache the
    # warmup missed and fabricates an "overhead" that is really drift
    on_s, off_s = [], []
    try:
        for _ in range(reps):
            flightrec.install()
            on_s.append(timed())
            flightrec.uninstall()
            off_s.append(timed())
    finally:
        flightrec.install()
    t_on, t_off = min(on_s), min(off_s)
    overhead = t_on / t_off - 1.0
    assert overhead < bound, (
        f"flight recorder overhead {overhead * 100:.2f}% exceeds the "
        f"{bound * 100:.0f}% bound (on={t_on:.4f}s off={t_off:.4f}s "
        f"over {reps} interleaved rep(s) each) — fix the ring before "
        "publishing a rate"
    )
    row = {
        "metric": "flightrec",
        "device": str(jax.devices()[0]),
        "chunk": chunk,
        "perms_per_sec": n_perm / t_on,
        "perms_per_sec_off": n_perm / t_off,
        "overhead_pct": round(overhead * 100, 3),
        "bound_pct": bound * 100,
        "n_perm": n_perm,
        "genes": genes,
    }
    if os.environ.get("NETREP_PERF_LEDGER"):
        entry = perfledger.entry_from_bench_row(row)
        if entry is not None:
            perfledger.append_entry(entry,
                                    os.environ["NETREP_PERF_LEDGER"])
    print(json.dumps(row), flush=True)
    print(f"flightrec overhead: {overhead * 100:+.2f}% "
          f"(on {n_perm / t_on:,.0f} perms/s, off {n_perm / t_off:,.0f} "
          f"perms/s, bound {bound * 100:.0f}%)", flush=True)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--genes", type=int, default=20_000)
    ap.add_argument("--cap", type=int, default=128)
    ap.add_argument("--K", type=int, default=21)
    ap.add_argument("--batch", type=int, default=8, help="perm batch")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument(
        "--parity-only", action="store_true",
        help="run ONLY the Pallas fused-kernel parity check (2 compiles, "
        "~1 min on TPU) — the cheap gate tpu_watch.sh runs before trusting "
        "any fused benchmark row, sized to fit the short (~5-7 min) tunnel "
        "windows that the full decomposition sweep does not",
    )
    ap.add_argument(
        "--flightrec-only", action="store_true",
        help="measure ONLY the flight recorder's streaming-loop overhead "
        "(ISSUE 20): recorder-on vs recorder-off perms/s, asserted under "
        "its bound before the row is printed/ledgered",
    )
    args = ap.parse_args()
    ensure_backend()
    if args.flightrec_only:
        flightrec_overhead(reps=max(1, args.reps))
        return
    print(f"device={jax.devices()[0]} matmul_default={jax.config.jax_default_matmul_precision}")

    n, cap, K, B = args.genes, args.cap, args.K, args.batch
    FL = 2 * B * K * cap * cap * n
    print(f"n={n} cap={cap} K={K} batch={B}  colsel GFLOP={FL/1e9:.1f}")

    key = jax.random.key(0)
    M = jax.random.normal(key, (n, n), dtype=jnp.float32)
    def make_idx(seed):
        raw = jax.random.randint(
            jax.random.key(seed), (B, K, cap), 0, n, dtype=jnp.int32
        )
        return jnp.sort(raw, axis=-1)

    # distinct index draws cycled across bench reps (see bench(): the
    # tunnel short-circuits repeated identical executions). reps + warmup
    # + 1 draws: timed reps cycle from the start, warmup consumes the
    # tail, and one spare covers fused_parity dropping variant 0 (its
    # parity check already executed that one) — no timed call ever
    # repeats any prior execution. Each draw is a (B, K, cap) int32 —
    # negligible memory.
    idxs = [make_idx(1 + r) for r in range(max(1, args.reps) + DEFAULT_WARMUP + 1)]
    idx = idxs[0]

    if args.parity_only:
        ran = fused_parity(M, M.astype(jnp.bfloat16), idx, B, K, cap, n,
                           reps=args.reps, FL=FL, time_it=False)
        if not ran:
            # a SKIPPED parity check is a gate FAILURE: exiting 0 here
            # would let tpu_watch.sh mark the gate done and run every
            # fused benchmark row with no parity ever proven on Mosaic
            sys.exit(2)
        if jax.default_backend() == "cpu":
            # interpret-mode parity is NOT a Mosaic proof: if a fast
            # tunnel-registration error dropped us to CPU after the
            # watcher's probe succeeded (race), exiting 0 would record
            # 'parity PASS' without the kernel ever compiling on TPU
            print("parity-only ran on CPU (interpret mode) — not a "
                  "Mosaic proof; exiting nonzero so no gate PASS is "
                  "recorded", flush=True)
            sys.exit(3)
        return

    # --- parts ---------------------------------------------------------------
    rowg = jax.jit(lambda Mx, ix: jnp.take(Mx, ix, axis=0))
    t = bench(rowg, M, idx, reps=args.reps,
              variants=[(M, i) for i in idxs])
    nbytes = B * K * cap * n * 4
    print(f"row gather (B,K,cap,n):      {t*1e3:8.2f} ms  ({nbytes/t/1e9:6.1f} GB/s)")

    rows = rowg(M, idx)  # (B, K, cap, n)

    def onehot_of(ix, dtype):
        return (
            jax.lax.broadcasted_iota(jnp.int32, (B, K, n, cap), 2) == ix[:, :, None, :]
        ).astype(dtype)

    oh_build = jax.jit(lambda ix: onehot_of(ix, jnp.float32))
    t = bench(oh_build, idx, reps=args.reps,
              variants=[(i,) for i in idxs])
    print(f"onehot materialize:          {t*1e3:8.2f} ms  ({B*K*n*cap*4/t/1e9:6.1f} GB/s)")

    def colsel(rws, ix, prec):
        return jnp.matmul(rws, onehot_of(ix, rws.dtype),
                          preferred_element_type=jnp.float32, precision=prec)

    for prec in ["default", "highest"]:
        f = jax.jit(lambda r, ix, p=prec: colsel(r, ix, p))
        t = bench(f, rows, idx, reps=args.reps,
                  variants=[(rows, i) for i in idxs])
        print(f"colsel matmul f32 {prec:8s}:  {t*1e3:8.2f} ms  ({FL/t/1e12:6.1f} TFLOP/s)")

    rows16 = rows.astype(jnp.bfloat16)
    f = jax.jit(lambda r, ix: colsel(r, ix, "default"))
    t = bench(f, rows16, idx, reps=args.reps,
              variants=[(rows16, i) for i in idxs])
    print(f"colsel matmul bf16:          {t*1e3:8.2f} ms  ({FL/t/1e12:6.1f} TFLOP/s)")

    # hi/lo two-pass exact selection: x = hi + lo with hi = bf16(x)
    def colsel_hilo(rws, ix):
        hi = rws.astype(jnp.bfloat16)
        lo = (rws - hi.astype(jnp.float32)).astype(jnp.bfloat16)
        oh = onehot_of(ix, jnp.bfloat16)
        s = jnp.matmul(hi, oh, preferred_element_type=jnp.float32)
        s += jnp.matmul(lo, oh, preferred_element_type=jnp.float32)
        return s

    f = jax.jit(colsel_hilo)
    t = bench(f, rows, idx, reps=args.reps,
              variants=[(rows, i) for i in idxs])
    print(f"colsel matmul hi/lo 2-pass:  {t*1e3:8.2f} ms  ({2*FL/t/1e12:6.1f} TFLOP/s eq)")

    # fused gather+colsel (what the engine actually runs)
    def fused(Mx, ix, prec):
        rws = jnp.take(Mx, ix, axis=0)
        return colsel(rws, ix, prec)

    for prec in ["default", "highest"]:
        f = jax.jit(lambda Mx, ix, p=prec: fused(Mx, ix, p))
        t = bench(f, M, idx, reps=args.reps,
                  variants=[(M, i) for i in idxs])
        print(f"fused gather+colsel {prec:8s}: {t*1e3:6.2f} ms  ({FL/t/1e12:6.1f} TFLOP/s)")

    M16 = M.astype(jnp.bfloat16)
    f = jax.jit(lambda Mx, ix: fused(Mx, ix, "default"))
    t = bench(f, M16, idx, reps=args.reps,
              variants=[(M16, i) for i in idxs])
    print(f"fused gather+colsel bf16:    {t*1e3:8.2f} ms  ({FL/t/1e12:6.1f} TFLOP/s)")

    # bf16 take row: is XLA's gather byte-limited (bf16 ≈ 2× f32 GB/s-
    # equivalent) or row-descriptor-limited (no gain)? Decides whether bf16
    # storage alone buys the roofline factor. Independent of Pallas.
    t = bench(rowg, M16, idx, reps=args.reps,
              variants=[(M16, i) for i in idxs])
    print(f"row gather bf16:             {t*1e3:8.2f} ms  "
          f"({B*K*cap*n*2/t/1e9:6.1f} GB/s)")

    # fused Pallas kernel (ops/fused_gather): per-row DMA + in-VMEM one-hot
    # select — ONE HBM pass over the row set vs the take+matmul passes above.
    # The decision row for flipping gather_mode auto to 'fused' on TPU.
    fused_parity(M, M16, idx, B, K, cap, n, reps=args.reps, FL=FL,
                 idx_variants=idxs)

    # 1-vs-K dispatch amortization: the superchunk executor's win, pinned
    # per backend (ISSUE 2 — dispatch-overhead microbench)
    dispatch_overhead(n, cap, K, B, args.reps)

    # XLA gather→stats→fold composition vs the ops/fused_stats mega-kernel
    # at the same chunk shape (ISSUE 8 — the stat_mode decision row)
    stats_dispatch_overhead(n, cap, K, B, args.reps)

    # correctness check of selection variants vs true gather
    sub_true = np.asarray(M)[np.asarray(idx)[0, 0][:, None], np.asarray(idx)[0, 0][None, :]]

    def unsorted_err(fn, Mx):
        s = np.asarray(fn(Mx, idx))[0, 0]
        # colsel output is rows[:, selected] in sorted order == true since idx sorted
        return np.abs(s - sub_true).max() / np.abs(sub_true).max()

    f_def = jax.jit(lambda Mx, ix: fused(Mx, ix, "default"))
    f_hi = jax.jit(lambda Mx, ix: fused(Mx, ix, "highest"))
    f_hl = jax.jit(lambda Mx, ix: colsel_hilo(jnp.take(Mx, ix, axis=0), ix))
    print(f"rel err f32-default: {unsorted_err(lambda Mx, ix=idx: f_def(Mx, ix), M):.2e}")
    print(f"rel err f32-highest: {unsorted_err(lambda Mx, ix=idx: f_hi(Mx, ix), M):.2e}")
    print(f"rel err hi/lo:       {unsorted_err(lambda Mx, ix=idx: f_hl(Mx, ix), M):.2e}")
    print(f"rel err bf16 mat:    {unsorted_err(lambda Mx, ix=idx: f_def(Mx, ix), M16):.2e}")


if __name__ == "__main__":
    main()
