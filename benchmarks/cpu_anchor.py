"""Second, independently-measured anchor for the traffic model (VERDICT r4
item 9 / weak #1): the model's sustained-bandwidth estimate previously
rested entirely on the single unreproduced 27.14 s TPU row. This script
measures, on the local CPU at the REAL north-star shape (n=20k, the
engine's own bucket caps):

1. a STREAM-like sustained copy bandwidth (the host's achievable peak),
2. XLA's row-gather sustained bytes/s over the same matrices the mxu
   path gathers (the bandwidth-bound part of the hot loop — the colsel
   matmul is FLOP-bound on CPU and says nothing about bytes/s there).

Their ratio is the *structural* gather efficiency XLA reaches at these
shapes (descriptor overhead vs streaming) — a property of the lowered
gather, not of the part — and `efficiency × TPU peak` is a sustained-BW
estimate that does not depend on the 27.14 s row. traffic_model.py reads
the JSON this writes and prints both anchors and their disagreement.

Run on an OTHERWISE IDLE machine (1-core box: a concurrent pytest run
poisons both measurements): python benchmarks/cpu_anchor.py
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cpu_anchor.json")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# the ONE enforced timing helper (review r5: a private rep loop here would
# be invisible to the cache-busting enforcement and CI sweep that guard
# every other timed site in this directory)
from microbench_parts import DEFAULT_WARMUP, bench  # noqa: E402


def main():
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")

    from bench import make_specs
    from netrep_tpu.utils.config import EngineConfig

    genes, modules, reps = 20_000, 50, 7
    cfg = EngineConfig()
    specs = make_specs(genes, modules)
    caps = np.array([cfg.rounded_cap(len(s.disc_idx)) for s in specs])
    sum_cap = int(caps.sum())

    n_var = reps + DEFAULT_WARMUP

    # --- 1) STREAM-like copy: sustained bytes/s the host can actually move.
    # 400 MB operands (far beyond LLC); y = x + 1.0 streams one read + one
    # write per element.
    n_el = 100_000_000
    xs = [jnp.arange(v, v + n_el, dtype=jnp.float32) for v in range(n_var)]
    add1 = jax.jit(lambda x: x + 1.0)
    t_stream = bench(add1, xs[0], reps=reps, variants=[(x,) for x in xs])
    stream_bw = 2 * n_el * 4 / t_stream

    # --- 2) XLA row gather at north-star shape: the engine's mxu path
    # gathers Σ_b K_b·cap_b sorted rows of each (n, n) matrix per
    # permutation. Values don't matter for bandwidth; one big uniform
    # matrix stands in for corr/net.
    M = jax.random.normal(jax.random.key(0), (genes, genes),
                          dtype=jnp.float32)
    jax.block_until_ready(M)

    def make_idx(seed):
        raw = jax.random.choice(jax.random.key(seed), genes, (sum_cap,),
                                replace=True)
        return jnp.sort(raw).astype(jnp.int32)

    idxs = [make_idx(v) for v in range(n_var)]
    rowg = jax.jit(lambda Mx, ix: jnp.take(Mx, ix, axis=0))
    t_gather = bench(rowg, M, idxs[0], reps=reps,
                     variants=[(M, ix) for ix in idxs])
    # Two accountings, both reported (review r5: the choice moves the
    # efficiency 2x, so hiding it would cook the anchor):
    # - read-only: the gather's useful HBM READ traffic (what the traffic
    #   model's one-pass byte count measures on the TPU side);
    # - read+write: the gather also materializes a (sum_cap, genes)
    #   output, so the bytes it physically moves are ~2x — the
    #   symmetric-accounting twin of the STREAM denominator (which
    #   counts one read + one write per element).
    gather_bytes = sum_cap * genes * 4
    eff_read = (gather_bytes / t_gather) / stream_bw
    eff_rw = (2 * gather_bytes / t_gather) / stream_bw

    out = {
        "machine": "cpu-1core" if os.cpu_count() == 1 else f"cpu-{os.cpu_count()}core",
        "genes": genes,
        "modules": modules,
        "sum_cap": sum_cap,
        "stream_copy_GBps": round(stream_bw / 1e9, 2),
        "row_gather_read_GBps": round(gather_bytes / t_gather / 1e9, 2),
        "gather_efficiency_read_only": round(eff_read, 4),
        "gather_efficiency_rw": round(eff_rw, 4),
        "gather_bytes_per_call_GB": round(gather_bytes / 1e9, 4),
        "t_stream_s": round(t_stream, 4),
        "t_gather_s": round(t_gather, 4),
        "reps": reps,
        "note": (
            "efficiencies = XLA row-gather rate over STREAM copy rate at "
            "north-star shape on this host, under read-only vs "
            "read+write byte accounting (the gather materializes its "
            "output, so rw ~= 2x read-only); traffic_model.py uses "
            "[read_only, rw] * TPU peak as the second sustained-BW "
            "anchor BRACKET"
        ),
    }
    with open(OUT_PATH, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
