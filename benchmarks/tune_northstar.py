"""North-star tuning sweep, two stages on the real chip: (1) the round-3
DECISION grid — gather_mode (mxu/fused) × dtype (f32/bf16) × derived-net —
8 points; (2) a chunk/perm_batch refinement around the stage-1 winner —
4 more points. 12 points total, each paying a fresh jit compile (~20-40 s
on TPU) plus the reduced-count run: budget ~15-20 min (the 2400 s timeouts
in run_all_tpu.sh and tpu_watch.sh's "tune" entry allow it). Prints one
JSON line per point plus a final "best" line — the winner decides what
EngineConfig's accelerator defaults become.

Resumable: completed real-accelerator points persist to --state (keyed by
the full sweep+point params), so a tunnel death mid-sweep only costs the
in-flight point when the watcher reruns the command — a cold ~6-min
window cannot fit the whole grid, a resumed one can.

Usage: python benchmarks/tune_northstar.py [--perms 2048] [--state FILE]
"""

from __future__ import annotations

import argparse
import hashlib
import itertools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench import build_problem, ensure_backend, make_specs_auto  # noqa: E402

#: perf-relevant sources hashed into every resume key: a row measured
#: against old engine code must never replay as fresh decision data after
#: the hot path changes (the state file persists across sessions).
_FINGERPRINT_SOURCES = (
    "bench.py",
    "netrep_tpu/parallel/engine.py",
    "netrep_tpu/parallel/sharded.py",
    "netrep_tpu/parallel/multitest.py",
    "netrep_tpu/ops/stats.py",
    "netrep_tpu/ops/fused_gather.py",
    "netrep_tpu/utils/config.py",
)


def code_fingerprint() -> str:
    h = hashlib.sha256()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in _FINGERPRINT_SOURCES:
        with open(os.path.join(root, rel), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:12]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--perms", type=int, default=2048)
    ap.add_argument("--genes", type=int, default=20_000)
    ap.add_argument("--modules", type=int, default=50)
    ap.add_argument("--samples", type=int, default=128)
    ap.add_argument(
        "--state",
        default=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tune_state.jsonl"),
        help="resume file: completed points (keyed by full sweep+point "
        "params) are reused across restarts, so a tunnel death mid-sweep "
        "only costs the in-flight point — a ~6-min window cannot fit the "
        "whole grid cold, and the watcher reruns this command verbatim. "
        "Only real-accelerator rows are ever cached. Pass '' to disable.",
    )
    args = ap.parse_args()

    import jax

    ensure_backend()
    from netrep_tpu.parallel.engine import PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = build_problem(
        args.genes, args.modules, args.samples
    )
    specs = make_specs_auto(args.genes, args.modules)
    pool = np.arange(args.genes, dtype=np.int32)

    # each point pays a fresh jit compile (~20-40s on TPU) — keep the grid
    # small. Primary sweep: the round-3 DECISION grid (gather_mode × dtype ×
    # derived-net — which combination should become the accelerator default,
    # VERDICT r2 item 3); then a refinement sweep of chunk/perm_batch around
    # the winner.
    # Resume cache: completed real-accelerator points keyed by the full
    # sweep+point parameters. A tunnel death mid-sweep then only costs the
    # in-flight point on the next watcher rerun (the compile cache already
    # makes recompiles cheap; this skips the measured runs too).
    sweep_id = {"perms": args.perms, "genes": args.genes,
                "modules": args.modules, "samples": args.samples,
                "code": code_fingerprint()}
    done_points: dict[str, dict] = {}
    if args.state and os.path.exists(args.state):
        with open(args.state) as f:
            for line in f:
                try:
                    entry = json.loads(line)
                    done_points[entry["key"]] = entry["row"]
                except (json.JSONDecodeError, KeyError):
                    continue

    def measure(chunk, pb, dt, pi, gm, derived, exact=False, cap_g=32):
        cfg = EngineConfig(
            chunk_size=chunk, perm_batch=pb, dtype=dt, power_iters=pi,
            summary_method="power", gather_mode=gm, fused_exact=exact,
            network_from_correlation=2.0 if derived else None,
            cap_granularity=cap_g,
        )
        label = {"chunk": chunk, "perm_batch": pb, "dtype": dt,
                 "gather_mode": gm, "derived_net": derived, "power_iters": pi,
                 **({"fused_exact": True} if exact else {}),
                 **({"cap_granularity": cap_g} if cap_g != 32 else {}),
                 # per-row provenance: a probe-race CPU fallback must be
                 # identifiable row-by-row (summarize_watch drops non-TPU)
                 "device": str(jax.devices()[0])}
        point_key = json.dumps({**sweep_id, **label, "device": None},
                               sort_keys=True)
        if point_key in done_points:
            row = done_points[point_key]
            print(json.dumps({**row, "cached": True}), flush=True)
            return row
        try:
            eng = PermutationEngine(
                d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
                config=cfg,
            )
            _ = eng.run_null(chunk, key=99)  # compile
            t0 = time.perf_counter()
            nulls, done = eng.run_null(args.perms, key=0)
            dt_s = time.perf_counter() - t0
            ok = done == args.perms and np.isfinite(np.asarray(nulls)).all()
        except Exception as e:  # OOM, Mosaic compile failure etc: move on
            print(json.dumps({**label, "error": f"{type(e).__name__}: {e}"}),
                  flush=True)
            return None
        row = {**label, "s": round(dt_s, 2),
               "perms_per_sec": round(args.perms / dt_s, 1), "ok": bool(ok)}
        print(json.dumps(row), flush=True)
        if not ok:
            return None
        # cache only real-accelerator rows: a probe-race CPU-fallback row
        # must never be resumed into a later TPU sweep as a decision point
        if args.state and "cpu" not in str(label["device"]).lower():
            done_points[point_key] = row
            with open(args.state, "a") as f:
                f.write(json.dumps({"key": point_key, "row": row}) + "\n")
        return row

    best = None
    for gm, dt, derived in itertools.product(
        ["mxu", "fused"], ["float32", "bfloat16"], [False, True]
    ):
        row = measure(256, None, dt, 40, gm, derived)
        if row and (best is None or row["perms_per_sec"] > best["perms_per_sec"]):
            best = row
    if best is not None:
        for chunk, pb in [(128, None), (512, None), (256, 4), (256, 64)]:
            row = measure(chunk, pb, best["dtype"], 40,
                          best["gather_mode"], best["derived_net"])
            if row and row["perms_per_sec"] > best["perms_per_sec"]:
                best = row
        # finer bucket granularity trims ~16% of Σcap row traffic for more
        # compiled bucket programs — worth one measured point at the winner
        row = measure(best["chunk"], best["perm_batch"], best["dtype"], 40,
                      best["gather_mode"], best["derived_net"], cap_g=8)
        if row and row["perms_per_sec"] > best["perms_per_sec"]:
            best = row
    # price exactness (not a default candidate — informational for the
    # README/BASELINE precision sections): the hi/lo split on the fused
    # f32 path is claimed ~2x non-dominant FLOPs; measure it once
    if best is not None and best["gather_mode"] == "fused" \
            and best["dtype"] == "float32":
        measure(best["chunk"], best["perm_batch"], "float32", 40,
                "fused", best["derived_net"], exact=True,
                cap_g=best.get("cap_granularity", 32))
    print(json.dumps({"best": best, "device": str(jax.devices()[0])}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
