"""North-star tuning sweep: chunk size × perm_batch × dtype × power_iters
on the real chip, at a reduced permutation count per point so the whole
sweep stays under ~10 min. Prints one JSON line per point plus a final
"best" line — feed the winner back into bench.py defaults if it beats them.

Usage: python benchmarks/tune_northstar.py [--perms 2048]
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench import build_problem, ensure_backend, make_specs  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--perms", type=int, default=2048)
    ap.add_argument("--genes", type=int, default=20_000)
    ap.add_argument("--modules", type=int, default=50)
    ap.add_argument("--samples", type=int, default=128)
    args = ap.parse_args()

    import jax

    ensure_backend()
    from netrep_tpu.parallel.engine import PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = build_problem(
        args.genes, args.modules, args.samples
    )
    lo, hi = (30, 200) if args.genes >= 10_000 else (8, 24)
    specs = make_specs(args.genes, args.modules, lo, hi)
    pool = np.arange(args.genes, dtype=np.int32)

    # each point pays a fresh jit compile (~20-40s on TPU) — keep the grid
    # small: chunk × perm_batch around the current defaults, plus the bf16
    # matrix variant the config supports but no bench has measured
    grid = {
        "chunk_size": [256, 512],
        "perm_batch": [None, 4],
        "dtype": ["float32", "bfloat16"],
        "power_iters": [40],
    }
    best = None
    for chunk, pb, dt, pi in itertools.product(
        grid["chunk_size"], grid["perm_batch"], grid["dtype"],
        grid["power_iters"],
    ):
        cfg = EngineConfig(chunk_size=chunk, perm_batch=pb, dtype=dt,
                           power_iters=pi, summary_method="power")
        try:
            eng = PermutationEngine(
                d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
                config=cfg,
            )
            _ = eng.run_null(chunk, key=99)  # compile
            t0 = time.perf_counter()
            nulls, done = eng.run_null(args.perms, key=0)
            dt_s = time.perf_counter() - t0
            ok = done == args.perms and np.isfinite(nulls).all()
        except Exception as e:  # OOM etc: record and move on
            print(json.dumps({"chunk": chunk, "perm_batch": pb, "dtype": dt,
                              "power_iters": pi,
                              "error": f"{type(e).__name__}"}))
            continue
        pps = args.perms / dt_s
        row = {"chunk": chunk, "perm_batch": pb, "dtype": dt,
               "power_iters": pi, "s": round(dt_s, 2),
               "perms_per_sec": round(pps, 1), "ok": bool(ok)}
        print(json.dumps(row), flush=True)
        if ok and (best is None or pps > best["perms_per_sec"]):
            best = row
    print(json.dumps({"best": best, "device": str(jax.devices()[0])}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
