"""Load generator for `netrep serve` (ISSUE 7).

Drives the in-process client with mixed multi-tenant traffic — small and
large networks, mixed permutation budgets, a slice of adaptive requests,
two tenants sharing identical registered data (so cross-TENANT packs
form) — in two arrival disciplines:

- **closed loop**: one worker per tenant submits its requests
  back-to-back, waiting for each result (concurrency = tenant count);
  measured from a cold server, so the first same-shape request pays the
  compile and every later one must hit the warm pool;
- **open loop**: every request is submitted asynchronously on a fixed
  arrival schedule against the now-warm server — the steady-state
  latency picture.

Each mode emits ONE bench-style JSON row: wall-clock, aggregate perms/s,
p50/p99 latency, pack statistics, pool hit counts, and the
``compile_span`` cold/warm split read back from the run's telemetry (the
PR 5 proof metric: warm ≈ 0). ``vs_baseline`` divides the serial
one-request-at-a-time baseline's wall-clock (direct
``module_preservation()`` per request — the pre-serve workflow) by the
served wall-clock; the ISSUE 7 acceptance asks ≥ 2× on CPU for the
closed loop. Before any number is emitted, one served request is
asserted bit-identical to its direct call — a fast-but-wrong row is
impossible.

Rows feed the perf-regression ledger when ``NETREP_PERF_LEDGER`` is set
(``source="serve"`` entries; the engine runs inside the server also
append their own ``packed:<G>``-fingerprinted entries).

``--drill`` runs the daemon lifecycle check instead: boot
``python -m netrep_tpu serve --socket ...`` as a subprocess, serve one
request over the socket, SIGTERM it, and assert the graceful-drain
contract (exit 0 + a final ``{"serve": "drained"}`` line) — the
``tpu_watch.sh`` SERVE_DRILL cycle.

``--kill-recover`` (ISSUE 10) measures the crash-recovery story instead:
a journaled in-process server is killed mid-pack (the ``crash`` fault
plan — the SIGKILL stand-in), a fresh server boots with ``recover=True``,
and the row reports **time-to-recovery** (boot + replay + finishing every
request) plus the re-served/recomputed split — requests that finished
before the kill are answered from their journaled ``done`` records, the
rest resume/recompute bit-identically (parity asserted in-bench before
the row is emitted). Rows carry the ``serve-recover`` metric label, so
their perf-ledger fingerprints never mix with steady-state serving
history.

Usage: python benchmarks/serve_load.py [--smoke] [--mode both|closed|open]
                                       [--requests N] [--rate R] [--drill]
                                       [--kill-recover]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(row: dict) -> None:
    if os.environ.get("NETREP_PERF_LEDGER"):
        from netrep_tpu.utils import perfledger

        entry = perfledger.entry_from_bench_row(row, source="serve")
        if entry is not None:
            perfledger.append_entry(entry,
                                    os.environ["NETREP_PERF_LEDGER"])
    print(json.dumps(row), flush=True)


def build_workload(args):
    """(tenant registrations, request list). Tenants alpha+beta share the
    SAME fixture data (cross-tenant packs must form); gamma brings the
    large network. Mixed n_perm and a slice of adaptive requests exercise
    ceiling and rule retirement inside shared dispatches."""
    from netrep_tpu.data import make_mixed_pair

    def fixture(genes, modules, seed):
        mixed = make_mixed_pair(genes, modules, n_samples=args.samples,
                                seed=seed)
        assign = {f"node_{i}": "0" for i in range(genes)}
        for lab, idx in mixed["specs"]:
            for i in idx:
                assign[f"node_{i}"] = str(lab)
        return mixed, assign

    small = fixture(args.genes_small, args.modules_small, 7)
    large = fixture(args.genes_large, args.modules_large, 11)
    tenants = {
        "alpha": {"weight": 2, "fixture": small},
        "beta": {"weight": 1, "fixture": small},   # same data as alpha
        "gamma": {"weight": 1, "fixture": large},
    }
    requests = []
    budgets = (args.n_perm_lo, args.n_perm_hi)
    for ti, name in enumerate(tenants):
        for i in range(args.requests):
            requests.append({
                "tenant": name,
                "n_perm": budgets[i % len(budgets)],
                "seed": 1000 * ti + i,
                "adaptive": (i % 3 == 2),
            })
    return tenants, requests


def make_server(args, tenants, tel_path):
    from netrep_tpu.serve import InProcessClient, PreservationServer, ServeConfig
    from netrep_tpu.utils.config import EngineConfig

    srv = PreservationServer(ServeConfig(
        engine=EngineConfig(chunk_size=args.chunk, autotune=False),
        max_pack=args.max_pack, pool_size=args.pool_size,
        pack_window_s=0.1, telemetry=tel_path,
    ))
    client = InProcessClient(srv)
    for name, spec in tenants.items():
        client.register_tenant(name, spec["weight"])
        mixed, assign = spec["fixture"]
        (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
        client.register_dataset(name, "d", network=dn, correlation=dc,
                                data=dd, assignments=assign)
        client.register_dataset(name, "t", network=tn, correlation=tc,
                                data=td)
    return srv, client


def run_serial_baseline(args, tenants, requests):
    """The pre-serve workflow: one direct ``module_preservation()`` call
    per request, one at a time — every call builds (and compiles) a fresh
    engine. Returns (wall_s, total_perms, one direct result for the
    parity gate)."""
    from netrep_tpu import module_preservation
    from netrep_tpu.utils.config import EngineConfig

    cfg = EngineConfig(chunk_size=args.chunk, autotune=False)
    total_perms = 0
    first = None
    t0 = time.perf_counter()
    for r in requests:
        mixed, assign = tenants[r["tenant"]]["fixture"]
        (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
        res = module_preservation(
            network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
            data={"d": dd, "t": td}, module_assignments=assign,
            discovery="d", test="t", n_perm=r["n_perm"], seed=r["seed"],
            adaptive=r["adaptive"], config=cfg,
        )
        total_perms += int(res.completed)
        if first is None:
            first = res
    return time.perf_counter() - t0, total_perms, first


def run_closed_loop(client, requests):
    """Per-tenant submit-wait-submit workers; returns (wall_s, results,
    latencies)."""
    by_tenant: dict[str, list] = {}
    for r in requests:
        by_tenant.setdefault(r["tenant"], []).append(r)
    results, lats = [], []
    lock = threading.Lock()
    errors = []

    def worker(items):
        for r in items:
            try:
                res = client.analyze(
                    r["tenant"], "d", "t", n_perm=r["n_perm"],
                    seed=r["seed"], adaptive=r["adaptive"], timeout=1200,
                )
            except Exception as e:  # surfaced after join
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                results.append((r, res))
                lats.append(res["latency_s"])

    threads = [
        threading.Thread(target=worker, args=(items,), daemon=True)
        for items in by_tenant.values()
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError("closed-loop worker failed: " + errors[0])
    return wall, results, lats


def run_open_loop(client, requests, rate: float):
    """Fixed-rate asynchronous arrivals; returns (wall_s, results,
    latencies)."""
    handles = []
    gap = 1.0 / rate if rate > 0 else 0.0
    t0 = time.perf_counter()
    for r in requests:
        handles.append((r, client.submit(
            r["tenant"], "d", "t", n_perm=r["n_perm"], seed=r["seed"],
            adaptive=r["adaptive"],
        )))
        if gap:
            time.sleep(gap)
    results, lats = [], []
    for r, h in handles:
        res = client.result(h, timeout=1200)
        results.append((r, res))
        lats.append(res["latency_s"])
    return time.perf_counter() - t0, results, lats


COST_FIELDS = ("device_s", "transfer_s", "perms", "bytes_to_host",
               "compile_s_amortized")


def check_cost_conservation(results):
    """The ISSUE 13 in-bench gate, run BEFORE any row is emitted: every
    pack's member costs must sum bit-exactly (f64, ``==``) to its pack
    totals — a fast-but-misattributed cost row is impossible. Returns the
    per-tenant attributed rollup table."""
    packs = {}
    for r, res in results:
        c = res.get("cost")
        if c is not None:
            packs.setdefault(res["pack_id"], []).append(c)
    assert packs, "no attributed costs on served results (telemetry on?)"
    for pid, members in packs.items():
        totals = members[0]["pack_totals"]
        for f in COST_FIELDS:
            s = members[0][f]
            for m in members[1:]:
                s = s + m[f]
            assert s == totals[f], (
                f"cost conservation violated in pack {pid}: "
                f"{f} members={s!r} totals={totals[f]!r}"
            )
    tenants = {}
    for r, res in results:
        c = res.get("cost")
        if c is None:
            continue
        t = tenants.setdefault(r["tenant"], {
            "requests": 0, "device_s": 0.0, "perms": 0, "bytes_to_host": 0,
        })
        t["requests"] += 1
        t["device_s"] += float(c["device_s"])
        t["perms"] += int(c["perms"])
        t["bytes_to_host"] += int(c["bytes_to_host"])
    return tenants


def cost_row(mode, args, wall, tenants_cost, device, tel_path):
    """The per-tenant attributed-cost row (``serve-cost`` metric label:
    its perf-ledger fingerprints never mix with the load rows; the
    ``cost`` dict rides into the ledger as a ``cost_v`` block — the
    fleet-admission signal)."""
    total_dev = sum(t["device_s"] for t in tenants_cost.values())
    total_perms = sum(t["perms"] for t in tenants_cost.values())
    return {
        "metric": (
            f"serve-cost per-tenant attributed [{mode}] "
            f"({len(tenants_cost)} tenants, chunk {args.chunk})"
        ),
        "value": round(total_dev, 4),
        "unit": "device_s",
        "perms_per_sec": round(total_perms / wall, 2) if wall > 0 else 0,
        "cost": {
            t: {"device_s": round(v["device_s"], 6), "perms": v["perms"],
                "bytes_to_host": v["bytes_to_host"],
                "requests": v["requests"]}
            for t, v in sorted(tenants_cost.items())
        },
        "telemetry": tel_path,
        "device": device,
        "chunk": args.chunk,
    }


def compile_split(tel_path):
    """(cold_total_s, warm_max_s) over the run's ``compile_span`` events:
    first event per fingerprint is the cold compile, every later one must
    be ~0 on a warm pool."""
    cold, warm = 0.0, 0.0
    seen = set()
    try:
        with open(tel_path, encoding="utf-8") as f:
            for line in f:
                if '"compile_span"' not in line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if e.get("ev") != "compile_span":
                    continue
                key = e["data"].get("key")
                s = float(e["data"].get("s", 0.0))
                if key in seen:
                    warm = max(warm, s)
                else:
                    seen.add(key)
                    cold += s
    except OSError:
        pass
    return cold, warm


def row_from(mode, args, wall, results, lats, serial_s, srv, tel_path,
             device):
    st = srv.stats()
    total_perms = sum(int(res["completed"]) for _r, res in results)
    packs = max(1, st["packs"])
    cold, warm = compile_split(tel_path)
    return {
        "metric": (
            f"serve load {mode} ({len(st['tenants'])} tenants x "
            f"{args.requests} req, mixed n_perm "
            f"{args.n_perm_lo}/{args.n_perm_hi}, chunk {args.chunk})"
        ),
        "value": round(wall, 3),
        "unit": "s",
        # acceptance: packed+warm serving vs the serial direct-call
        # workflow on the SAME request list — >= 2x on CPU for closed loop
        "vs_baseline": round(serial_s / wall, 3),
        "serial_s": round(serial_s, 3),
        "perms_per_sec": round(total_perms / wall, 2),
        "requests": len(results),
        "p50_ms": round(1000 * float(np.percentile(lats, 50)), 1),
        "p99_ms": round(1000 * float(np.percentile(lats, 99)), 1),
        "packs": st["packs"],
        "mean_pack_size": round(
            sum(res["pack_size"] for _r, res in results) / len(results), 2
        ),
        "pool_hits": st["pool"]["hits"],
        "pool_misses": st["pool"]["misses"],
        "compile_span_cold_s": round(cold, 3),
        "compile_span_warm_max_s": round(warm, 4),
        "telemetry": tel_path,
        "device": device,
        "chunk": args.chunk,
    }


def run_drill(args) -> int:
    """Daemon lifecycle drill: boot the socket daemon, serve one request,
    SIGTERM, assert graceful drain (exit 0 + drained line)."""
    import signal
    import subprocess

    tmp = tempfile.mkdtemp(prefix="netrep_serve_")
    sock = os.path.join(tmp, "serve.sock")
    proc = subprocess.Popen(
        [sys.executable, "-m", "netrep_tpu", "serve", "--socket", sock,
         "--chunk", str(args.chunk),
         "--journal", os.path.join(tmp, "journal.jsonl")],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env={**os.environ, "JAX_PLATFORMS":
                        os.environ.get("JAX_PLATFORMS", "cpu") or "cpu"},
    )
    try:
        deadline = time.monotonic() + 120
        while not os.path.exists(sock):
            if time.monotonic() > deadline or proc.poll() is not None:
                print(json.dumps({
                    "metric": "serve drill", "error":
                    "daemon never opened its socket",
                }))
                return 1
            time.sleep(0.2)
        from netrep_tpu.serve.client import SocketClient

        client = SocketClient(sock, timeout=600)
        client.ping()
        client.register_fixture("drill", genes=args.genes_small,
                                modules=args.modules_small, seed=7)
        res = client.analyze("drill", "fx_d", "fx_t",
                             n_perm=args.n_perm_lo, seed=1)
        ok_served = res["completed"] == args.n_perm_lo
        # live-dashboard snapshot over the wire (ISSUE 13): the same
        # `top --once --json` surface, captured before the drain so the
        # watch loop archives one per drill cycle
        from netrep_tpu.serve.top import snapshot

        snap = snapshot(client.stats())
        print(json.dumps({"metric": "serve top snapshot", "value": 1,
                          "unit": "snapshot", "top": snap}), flush=True)
        client.close()
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=args.drain_wait)
        drained = any(
            '"serve": "drained"' in line for line in out.splitlines()
        )
        ok = proc.returncode == 0 and drained and ok_served
        print(json.dumps({
            "metric": "serve drill (daemon boot -> analyze -> SIGTERM "
                      "drain)",
            "value": 1 if ok else 0,
            "unit": "ok",
            "served_ok": ok_served,
            "drained": drained,
            "returncode": proc.returncode,
        }))
        if not ok:
            sys.stderr.write(err[-2000:] + "\n")
        return 0 if ok else 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def run_kill_recover(args) -> int:
    """Kill→recover scenario (ISSUE 10): journaled server, one request
    completed before a mid-pack crash, the rest in flight or queued;
    measure the recovered server's time to finish everything and the
    re-served vs recomputed split."""
    import time as _time

    from netrep_tpu import module_preservation
    from netrep_tpu.data import make_mixed_pair
    from netrep_tpu.serve import (
        InProcessClient, PreservationServer, ServeConfig,
    )
    from netrep_tpu.utils.config import EngineConfig, FaultPolicy

    import jax

    device = str(jax.devices()[0])
    cfg = EngineConfig(chunk_size=args.chunk, autotune=False)
    mixed = make_mixed_pair(args.genes_small, args.modules_small,
                            n_samples=args.samples, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)

    def register(client):
        client.register_dataset("alpha", "d", network=dn, correlation=dc,
                                data=dd, assignments=assign)
        client.register_dataset("alpha", "t", network=tn, correlation=tc,
                                data=td)

    tmp = tempfile.mkdtemp(prefix="netrep_kill_recover_")
    jpath = os.path.join(tmp, "journal.jsonl")
    # request 0 is SHORT (finishes below the crash perm: re-served from
    # the journal); the rest span the crash point and die with the server
    n_kill = 3 * args.chunk // 4
    reqs = [{"key": "kr-0", "n_perm": args.chunk // 2, "seed": 100}] + [
        {"key": f"kr-{i}", "n_perm": args.n_perm_lo, "seed": 100 + i}
        for i in range(1, args.requests + 1)
    ]

    srv = PreservationServer(ServeConfig(
        engine=cfg, journal=jpath, checkpoint_every=args.chunk,
        telemetry=os.path.join(tmp, "tel_kill.jsonl"),
        fault_policy=FaultPolicy(plan=f"crash@{n_kill}",
                                 backoff_base_s=0.0, backoff_jitter=0.0),
    ), start=False)
    client = InProcessClient(srv)
    register(client)
    h0 = client.submit("alpha", "d", "t", n_perm=reqs[0]["n_perm"],
                       seed=reqs[0]["seed"], idempotency_key=reqs[0]["key"])
    srv.start()
    client.result(h0, timeout=600)         # completed before the kill
    for r in reqs[1:]:
        client.submit("alpha", "d", "t", n_perm=r["n_perm"],
                      seed=r["seed"], idempotency_key=r["key"])
    deadline = _time.monotonic() + 600
    while srv._worker.is_alive() and _time.monotonic() < deadline:
        _time.sleep(0.05)
    if srv._worker.is_alive():
        print(json.dumps({"metric": "serve-recover", "error":
                          "injected crash never fired"}))
        return 1
    done_before = sum(
        t["done"] for t in srv.stats()["tenants"].values()
    )

    t0 = _time.perf_counter()
    srv2 = PreservationServer(ServeConfig(
        engine=cfg, journal=jpath, recover=True,
        checkpoint_every=args.chunk,
        telemetry=os.path.join(tmp, "tel_recover.jsonl"),
    ))
    client2 = InProcessClient(srv2)
    results = {
        r["key"]: client2.analyze("alpha", "d", "t", n_perm=r["n_perm"],
                                  seed=r["seed"],
                                  idempotency_key=r["key"], timeout=1200)
        for r in reqs
    }
    recovery_s = _time.perf_counter() - t0
    st = srv2.stats()
    srv2.close()
    # parity gate before any number is emitted: recovered == direct
    d = module_preservation(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td}, module_assignments=assign,
        discovery="d", test="t", n_perm=reqs[1]["n_perm"],
        seed=reqs[1]["seed"], config=cfg,
    )
    assert np.array_equal(results[reqs[1]["key"]]["p_values"],
                          np.asarray(d.p_values)), \
        "recovered/direct p-value mismatch"
    recomputed = len(reqs) - done_before
    recomputed_perms = sum(
        int(results[r["key"]]["completed"]) for r in reqs[1:]
    )
    emit({
        "metric": (
            f"serve-recover kill-recover ({len(reqs)} req, "
            f"kill@{n_kill}, chunk {args.chunk})"
        ),
        "value": round(recovery_s, 3),
        "unit": "s",
        "time_to_recovery_s": round(recovery_s, 3),
        "requests_reserved": done_before,
        "requests_recomputed": recomputed,
        "perms_per_sec": round(recomputed_perms / recovery_s, 2),
        "packs": st["packs"],
        "device": device,
        "chunk": args.chunk,
    })
    return 0


def _coldstart_baseline(ledger_path: str | None) -> float | None:
    """Median compile span of the PR 14 ``serve-fleet-coldstart``
    perf-ledger history — the recorded baseline the warm start must
    beat. None when no ledger or no matching entries exist."""
    if not ledger_path:
        return None
    try:
        from netrep_tpu.utils.perfledger import read_entries

        vals = [float(e["compile_s"]) for e in read_entries(ledger_path)
                if str(e.get("fingerprint", "")).startswith(
                    "serve-fleet-coldstart|")
                and isinstance(e.get("compile_s"), (int, float))]
    except OSError:
        return None
    if not vals:
        return None
    vals.sort()
    return vals[len(vals) // 2]


def _first_compile_spans(tel_paths) -> tuple[float, str | None]:
    """(max first-fingerprint compile span, its source) across a set of
    telemetry files — the worst replica cold start of a fleet run."""
    worst, src = 0.0, None
    for p in tel_paths:
        seen = set()
        try:
            with open(p, encoding="utf-8") as f:
                for line in f:
                    if '"compile_span"' not in line:
                        continue
                    try:
                        e = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if e.get("ev") != "compile_span":
                        continue
                    key = e["data"].get("key")
                    if key in seen:
                        continue
                    seen.add(key)
                    s = float(e["data"].get("s", 0.0))
                    if s >= worst:
                        worst = s
                        src = e["data"].get("source")
        except OSError:
            continue
    return worst, src


def run_warmstart(args) -> int:
    """Warm-start scenario (ISSUE 15): the zero-compile proof, measured
    the honest way — in FRESH processes.

    1. cold reference: ``warmup --measure`` against an empty store — the
       first-request compile span every PR<15 boot paid;
    2. export: ``warmup`` populates the store (+ persistent compile
       cache) for the same shape;
    3. warm proof: ``warmup --measure`` again in a fresh process — the
       store now serves the programs and ``compile_span ~0`` with
       ``source: aot``.

    One ``serve-warmstart`` row reports both numbers, the speedup, and
    the delta against the PR 14 ``serve-fleet-coldstart`` ledger
    baseline. ``warm_ok`` is the in-row verdict (source == aot and warm
    < cold); the tpu_watch step banners on it loudly, never fatally."""
    import subprocess

    tmp = tempfile.mkdtemp(prefix="netrep_warmstart_")
    store = os.path.join(tmp, "aot")
    ledger_baseline = _coldstart_baseline(
        os.environ.get("NETREP_PERF_LEDGER")
    )
    shape = ["--genes", str(args.genes_small), "--modules",
             str(args.modules_small), "--samples", str(args.samples),
             "--chunk", str(args.chunk), "--n-perm",
             str(max(2 * args.chunk, args.n_perm_lo))]
    env = {**os.environ,
           "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")
           or "cpu",
           "NETREP_AOT_STORE": store}

    def run(cmd, extra_env=None):
        p = subprocess.run(
            [sys.executable, "-m", "netrep_tpu", "warmup", *cmd],
            cwd=REPO, env={**env, **(extra_env or {})},
            capture_output=True, text=True, timeout=900,
        )
        if p.returncode != 0:
            raise RuntimeError(
                f"warmup {' '.join(cmd)} failed: {p.stderr[-2000:]}"
            )
        return json.loads(p.stdout.strip().splitlines()[-1])

    # honest cold reference: store and persistent compile cache both off
    # — exactly what every pre-warmstart boot paid
    cold = run(["--measure", "--json", *shape],
               {"NETREP_PERSISTENT_CACHE": "0", "NETREP_AOT": "0"})
    t0 = time.perf_counter()
    export = run(["--json", *shape])
    export_s = time.perf_counter() - t0
    warm = run(["--measure", "--json", *shape])

    import jax

    warm_ok = (warm.get("source") == "aot"
               and (cold["compile_span_s"] is None
                    or warm["compile_span_s"] is None
                    or warm["compile_span_s"] < cold["compile_span_s"]))
    row = {
        "metric": (
            f"serve-warmstart fresh-process first-request "
            f"({args.genes_small}g/{args.modules_small}m, "
            f"chunk {args.chunk})"
        ),
        "value": warm["compile_span_s"],
        "unit": "s",
        "cold_compile_span_s": cold["compile_span_s"],
        "warm_source": warm.get("source"),
        "cold_source": cold.get("source"),
        "warm_first_run_s": warm["first_run_s"],
        "cold_first_run_s": cold["first_run_s"],
        "export_s": round(export_s, 3),
        "store_entries": (export.get("store") or {}).get("entries"),
        "coldstart_baseline_s": ledger_baseline,
        "coldstart_delta_s": (
            round(ledger_baseline - (warm["compile_span_s"] or 0.0), 4)
            if ledger_baseline is not None else None
        ),
        "warm_ok": bool(warm_ok),
        "device": str(jax.devices()[0]),
        "chunk": args.chunk,
    }
    emit(row)
    return 0 if warm_ok else 1


def run_fleet(args) -> int:
    """Fleet scenario (ISSUE 14): the same mixed-tenant workload driven
    through an in-process fleet coordinator, with a replica SIGKILL
    stand-in (the ``crash`` fault plan, armed on the replica that OWNS
    the busiest pair) landing MID-RUN. One row reports p50/p99 latency,
    the measured failover time (``failover_done.s`` from the
    coordinator's telemetry), and aggregate perms/s vs the SAME workload
    on a 1-replica fleet — under the ``serve-fleet`` metric label, so
    its perf-ledger fingerprints never mix with single-server history.
    Parity is asserted in-bench before any row: a fast-but-wrong fleet
    row is impossible."""
    import tempfile as _tf

    from netrep_tpu import module_preservation
    from netrep_tpu.serve import FleetConfig, ServeConfig, build_inprocess_fleet
    from netrep_tpu.utils.config import EngineConfig, FaultPolicy

    import jax

    device = str(jax.devices()[0])
    cfg = EngineConfig(chunk_size=args.chunk, autotune=False)
    tenants, requests = build_workload(args)

    def boot(n, tag, kill=False):
        tmp = _tf.mkdtemp(prefix=f"netrep_fleet_{tag}_")
        tel = os.path.join(tmp, "coord_tel.jsonl")

        def mk(rid, jpath, ckpt):
            return ServeConfig(
                engine=cfg, journal=jpath, checkpoint_dir=ckpt,
                checkpoint_every=args.chunk, max_pack=args.max_pack,
                pool_size=args.pool_size, pack_window_s=0.1,
                fleet_label=rid,
                telemetry=os.path.join(tmp, f"{rid}_tel.jsonl"),
            )

        fleet = build_inprocess_fleet(
            n, os.path.join(tmp, "fleet"), make_config=mk,
            fleet_config=FleetConfig(telemetry=tel, heartbeat_s=0.1),
        )
        for name, spec in tenants.items():
            fleet.register_tenant(name, spec["weight"])
            mixed, assign = spec["fixture"]
            (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
            fleet.register_dataset(name, "d", network=dn, correlation=dc,
                                   data=dd, assignments=assign)
            fleet.register_dataset(name, "t", network=tn, correlation=tc,
                                   data=td)
        if kill:
            home = fleet.route("alpha", "d", "t")
            home.arm_fault_plan(FaultPolicy(
                plan=f"crash@{3 * args.chunk // 4}",
                backoff_base_s=0.0, backoff_jitter=0.0,
            ))
        return fleet, tel

    def drive(fleet):
        results, lats, errors = [], [], []
        lock = threading.Lock()

        def worker(r):
            try:
                res = fleet.analyze(
                    r["tenant"], "d", "t", n_perm=r["n_perm"],
                    seed=r["seed"], adaptive=r["adaptive"], timeout=1200,
                )
            except Exception as e:  # surfaced after join
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                results.append((r, res))
                lats.append(res["latency_s"])

        threads = [threading.Thread(target=worker, args=(r,), daemon=True)
                   for r in requests]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errors:
            raise RuntimeError("fleet worker failed: " + errors[0])
        return wall, results, lats

    # PR 14 coldstart baseline BEFORE this run appends its own entries
    coldstart_baseline = _coldstart_baseline(
        os.environ.get("NETREP_PERF_LEDGER")
    )

    # 1-replica reference: same workload, same coordinator overheads —
    # the denominator of the aggregate-perms/s comparison
    fleet1, _tel1 = boot(1, "one")
    try:
        wall1, results1, _lats1 = drive(fleet1)
    finally:
        fleet1.close()
    perms1 = sum(int(res["completed"]) for _r, res in results1)

    n_rep = max(2, int(args.fleet))
    fleetN, telN = boot(n_rep, "n", kill=True)
    try:
        wallN, resultsN, latsN = drive(fleetN)
    finally:
        fleetN.close()
    permsN = sum(int(res["completed"]) for _r, res in resultsN)

    # parity gate before any row: served-through-failover == direct
    r0 = requests[0]
    mixed, assign = tenants[r0["tenant"]]["fixture"]
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    d = module_preservation(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td}, module_assignments=assign,
        discovery="d", test="t", n_perm=r0["n_perm"], seed=r0["seed"],
        adaptive=r0["adaptive"], config=cfg,
    )
    served0 = next(res for r, res in resultsN
                   if r["tenant"] == r0["tenant"]
                   and r["seed"] == r0["seed"])
    assert np.array_equal(served0["p_values"], np.asarray(d.p_values)), \
        "fleet-served/direct p-value mismatch"

    import glob as _glob

    coldstart_s, coldstart_src = _first_compile_spans(
        _glob.glob(os.path.join(os.path.dirname(telN), "r*_tel.jsonl"))
    )

    failover_s = None
    killed = False
    try:
        with open(telN, encoding="utf-8") as f:
            for line in f:
                if '"failover_done"' not in line:
                    continue
                e = json.loads(line)
                if e.get("ev") == "failover_done":
                    failover_s = float(e["data"].get("s", 0.0))
                    killed = True
    except (OSError, json.JSONDecodeError):
        pass
    assert killed, "the replica kill never fired (no failover_done)"

    emit({
        "metric": (
            f"serve-fleet {n_rep} replicas kill-failover "
            f"({len(requests)} req, chunk {args.chunk})"
        ),
        "value": round(wallN, 3),
        "unit": "s",
        "requests": len(resultsN),
        "perms_per_sec": round(permsN / wallN, 2),
        "perms_per_sec_1replica": round(perms1 / wall1, 2),
        "vs_1_replica": round((permsN / wallN) / (perms1 / wall1), 3),
        "p50_ms": round(1000 * float(np.percentile(latsN, 50)), 1),
        "p99_ms": round(1000 * float(np.percentile(latsN, 99)), 1),
        "failover_s": round(failover_s, 4),
        "replicas": n_rep,
        # warm-start accounting (ISSUE 15): the first completed request's
        # latency, the worst replica's first compile span (+ its
        # acquisition source — `aot` once a warm store serves the fleet),
        # and the delta against the PR 14 coldstart ledger baseline
        "first_request_ms": round(1000 * float(latsN[0]), 1),
        "coldstart_compile_s": round(coldstart_s, 4),
        "coldstart_src": coldstart_src,
        "coldstart_baseline_s": coldstart_baseline,
        "coldstart_delta_s": (
            round(coldstart_baseline - coldstart_s, 4)
            if coldstart_baseline is not None else None
        ),
        "device": device,
        "chunk": args.chunk,
    })
    return 0


def _replica_seconds(tel_path) -> float:
    """Total replica-up seconds billed from the coordinator's
    ``replica_state`` stream: each replica is billed from its ``ready``
    transition to its ``dead`` one (close transitions every survivor to
    dead, so nothing is left unbilled). The autoscale row's
    replica-hours metric."""
    ready: dict[str, float] = {}
    total = 0.0
    try:
        with open(tel_path, encoding="utf-8") as f:
            for line in f:
                if '"replica_state"' not in line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if e.get("ev") != "replica_state":
                    continue
                d = e.get("data", {})
                rid, t = d.get("replica"), float(e.get("t", 0.0))
                if d.get("to") == "ready":
                    ready[rid] = t
                elif d.get("to") == "dead" and rid in ready:
                    total += t - ready.pop(rid)
    except OSError:
        pass
    return total


def run_autoscale(args) -> int:
    """Autoscale scenario (ISSUE 19): the SAME square-wave arrival trace
    driven through (a) a static fleet of the peak size and (b) an
    autoscaled fleet (min 1, max peak) with N forced eviction notices
    landing mid-trace. One ``serve-autoscale`` row reports p99 for both
    fleets, the replica-seconds each consumed (billed ready→dead from
    the lifecycle telemetry), and the zero-lost-requests count across
    the evictions. Parity is asserted in-bench (one served result per
    tenant vs its direct call) before any number is emitted; the row's
    ``ok`` requires zero lost requests, every forced eviction performed,
    and measurably fewer replica-seconds than the static fleet."""
    import tempfile as _tf

    from netrep_tpu import module_preservation
    from netrep_tpu.serve import FleetConfig, ServeConfig, build_inprocess_fleet
    from netrep_tpu.serve.fleet import Autoscaler, AutoscaleConfig, inprocess_spawner
    from netrep_tpu.utils.config import EngineConfig

    import jax

    device = str(jax.devices()[0])
    cfg = EngineConfig(chunk_size=args.chunk, autotune=False)
    tenants, requests = build_workload(args)
    peak = max(2, int(args.autoscale_peak))
    evictions_target = max(0, int(args.evictions))

    # square-wave arrivals: bursts of back-to-back submissions separated
    # by idle gaps — the 10x traffic swing in miniature
    cycles = 2
    per = max(1, len(requests) // cycles)
    burst_gap = 1.0 / float(args.burst_rate)
    quiet_s = float(args.quiet_s)
    offsets = []
    for i in range(len(requests)):
        cyc, j = divmod(i, per)
        offsets.append(cyc * (per * burst_gap + quiet_s) + j * burst_gap)
    trace_s = offsets[-1] + quiet_s

    def boot(n, tag, autoscale):
        tmp = _tf.mkdtemp(prefix=f"netrep_autoscale_{tag}_")
        tel = os.path.join(tmp, "coord_tel.jsonl")
        fdir = os.path.join(tmp, "fleet")

        def mk(rid, jpath, ckpt):
            return ServeConfig(
                engine=cfg, journal=jpath, checkpoint_dir=ckpt,
                checkpoint_every=args.chunk, max_pack=args.max_pack,
                pool_size=args.pool_size, pack_window_s=0.1,
                fleet_label=rid,
            )

        fleet = build_inprocess_fleet(
            n, fdir, make_config=mk,
            fleet_config=FleetConfig(telemetry=tel, heartbeat_s=0.25,
                                     rate_pps=200.0),
        )
        for name, spec in tenants.items():
            fleet.register_tenant(name, spec["weight"])
            mixed, assign = spec["fixture"]
            (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
            fleet.register_dataset(name, "d", network=dn, correlation=dc,
                                   data=dd, assignments=assign)
            fleet.register_dataset(name, "t", network=tn, correlation=tc,
                                   data=td)
        scaler = None
        if autoscale:
            scaler = Autoscaler(
                fleet, inprocess_spawner(fdir, make_config=mk),
                AutoscaleConfig(
                    scale_up_drain_s=0.5, scale_down_idle_s=0.75,
                    min_replicas=1, max_replicas=peak,
                    cooldown_s=0.25, tick_s=0.05,
                ),
            )
        return fleet, tel, scaler

    def drive(fleet, evict=0):
        results, lats, errors, evicted = [], [], [], []
        lock = threading.Lock()
        t0 = time.perf_counter()

        def worker(r, offset):
            delay = offset - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            try:
                res = fleet.analyze(
                    r["tenant"], "d", "t", n_perm=r["n_perm"],
                    seed=r["seed"], adaptive=r["adaptive"], timeout=1200,
                )
            except Exception as e:  # surfaced after join
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                results.append((r, res))
                lats.append(res["latency_s"])

        def evictor(at_s):
            delay = at_s - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            # evict the newest live replica — revoked capacity does not
            # get to choose a convenient victim, but the notice makes
            # the departure a handoff either way
            deadline = time.perf_counter() + trace_s
            while time.perf_counter() < deadline:
                live = sorted(fleet.live_replicas())
                if live:
                    out = fleet.evict_notice(live[-1], grace_s=30.0)
                    if out is not None:
                        with lock:
                            evicted.append(out["replica"])
                        return
                time.sleep(0.05)

        threads = [
            threading.Thread(target=worker, args=(r, off), daemon=True)
            for r, off in zip(requests, offsets)
        ]
        threads += [
            threading.Thread(target=evictor,
                             args=(trace_s * (0.25 + 0.35 * k),),
                             daemon=True)
            for k in range(evict)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errors:
            raise RuntimeError("autoscale worker failed: " + errors[0])
        return wall, results, lats, evicted

    # static reference: the peak-size fleet for the whole trace
    fleet_s, tel_s, _ = boot(peak, "static", autoscale=False)
    try:
        wall_s, results_s, lats_s, _ev = drive(fleet_s, evict=0)
    finally:
        fleet_s.close()

    # autoscaled run: min 1 / max peak, forced evictions mid-trace
    fleet_a, tel_a, scaler = boot(1, "auto", autoscale=True)
    try:
        wall_a, results_a, lats_a, evicted = drive(
            fleet_a, evict=evictions_target)
    finally:
        if scaler is not None:
            scaler.stop()
        fleet_a.close()

    # parity gate before any number: one served result per tenant from
    # the AUTOSCALED run (the one that survived evictions) vs direct
    for name in tenants:
        r0 = next(r for r in requests if r["tenant"] == name)
        mixed, assign = tenants[name]["fixture"]
        (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
        d = module_preservation(
            network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
            data={"d": dd, "t": td}, module_assignments=assign,
            discovery="d", test="t", n_perm=r0["n_perm"], seed=r0["seed"],
            adaptive=r0["adaptive"], config=cfg,
        )
        served = next(res for r, res in results_a
                      if r["tenant"] == name and r["seed"] == r0["seed"])
        assert np.array_equal(served["p_values"], np.asarray(d.p_values)), \
            f"autoscaled/direct p-value mismatch (tenant {name})"

    rs_static = _replica_seconds(tel_s)
    rs_auto = _replica_seconds(tel_a)
    p99_s = float(np.percentile(lats_s, 99))
    p99_a = float(np.percentile(lats_a, 99))
    lost = len(requests) - len(results_a)
    ok = (lost == 0 and len(evicted) == evictions_target
          and rs_auto < rs_static)
    emit({
        "metric": (
            f"serve-autoscale square-wave min1/max{peak} "
            f"({len(requests)} req, {evictions_target} evictions, "
            f"chunk {args.chunk})"
        ),
        "value": round(wall_a, 3),
        "unit": "s",
        "requests": len(requests),
        "lost_requests": lost,
        "evictions": len(evicted),
        "evicted": evicted,
        "p99_ms": round(1000 * p99_a, 1),
        "p99_static_ms": round(1000 * p99_s, 1),
        "p99_vs_static": (round(p99_a / p99_s, 3) if p99_s > 0
                          else None),
        "p99_within_2x": bool(p99_a <= 2.0 * p99_s),
        "replica_seconds": round(rs_auto, 3),
        "replica_seconds_static": round(rs_static, 3),
        "replica_seconds_saved": round(rs_static - rs_auto, 3),
        "static_wall_s": round(wall_s, 3),
        "peak_replicas": peak,
        "ok": bool(ok),
        "device": device,
        "chunk": args.chunk,
    })
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for CI")
    ap.add_argument("--mode", default="both",
                    choices=["both", "closed", "open"])
    ap.add_argument("--requests", type=int, default=None,
                    help="requests per tenant (default 6; smoke 3)")
    ap.add_argument("--rate", type=float, default=None,
                    help="open-loop arrival rate, req/s (default 4)")
    ap.add_argument("--chunk", type=int, default=None)
    ap.add_argument("--max-pack", type=int, default=4)
    ap.add_argument("--pool-size", type=int, default=8)
    ap.add_argument("--samples", type=int, default=24)
    ap.add_argument("--genes-small", type=int, default=None)
    ap.add_argument("--genes-large", type=int, default=None)
    ap.add_argument("--modules-small", type=int, default=None)
    ap.add_argument("--modules-large", type=int, default=None)
    ap.add_argument("--n-perm-lo", type=int, default=None)
    ap.add_argument("--n-perm-hi", type=int, default=None)
    ap.add_argument("--telemetry", default=None)
    ap.add_argument("--drill", action="store_true",
                    help="daemon SIGTERM-drain drill instead of the load "
                         "run")
    ap.add_argument("--kill-recover", action="store_true",
                    help="kill→recover scenario instead of the load run: "
                         "time-to-recovery + re-served/recomputed split "
                         "after a mid-pack crash (rows labeled "
                         "serve-recover in the perf ledger)")
    ap.add_argument("--fleet", type=int, default=None, metavar="N",
                    help="fleet scenario instead of the load run (ISSUE "
                         "14): the workload through an N-replica "
                         "in-process fleet with a mid-run replica kill; "
                         "reports p50/p99, failover time, and aggregate "
                         "perms/s vs 1 replica (rows labeled serve-fleet "
                         "in the perf ledger)")
    ap.add_argument("--autoscale", action="store_true",
                    help="autoscale scenario instead of the load run "
                         "(ISSUE 19): a square-wave arrival trace "
                         "through an autoscaled fleet (min 1, max "
                         "--autoscale-peak) vs a static fleet of the "
                         "peak size, with --evictions forced eviction "
                         "notices mid-trace; the row (labeled "
                         "serve-autoscale) reports p99 vs static, "
                         "replica-seconds consumed, and zero lost "
                         "requests")
    ap.add_argument("--autoscale-peak", type=int, default=3,
                    help="[--autoscale] static fleet size and the "
                         "autoscaler's max_replicas")
    ap.add_argument("--evictions", type=int, default=2,
                    help="[--autoscale] forced eviction notices during "
                         "the autoscaled trace")
    ap.add_argument("--burst-rate", type=float, default=12.0,
                    help="[--autoscale] arrival rate inside a burst, "
                         "req/s")
    ap.add_argument("--quiet-s", type=float, default=None,
                    help="[--autoscale] idle gap between bursts "
                         "(default 1.5; smoke 1.0)")
    ap.add_argument("--warmstart", action="store_true",
                    help="warm-start scenario instead of the load run "
                         "(ISSUE 15): cold fresh-process first-request "
                         "compile span vs the same measurement against a "
                         "warmup-populated AOT store; the row (labeled "
                         "serve-warmstart) asserts source=aot and "
                         "warm < cold, and reports the delta vs the "
                         "PR 14 serve-fleet-coldstart ledger baseline")
    ap.add_argument("--drain-wait", type=float, default=120.0)
    args = ap.parse_args()

    small_defaults = (
        dict(requests=3, chunk=32, genes_small=100, genes_large=160,
             modules_small=3, modules_large=4, n_perm_lo=64, n_perm_hi=128,
             rate=4.0)
        if args.smoke else
        dict(requests=6, chunk=64, genes_small=300, genes_large=600,
             modules_small=6, modules_large=10, n_perm_lo=512,
             n_perm_hi=1024, rate=2.0)
    )
    for k, v in small_defaults.items():
        if getattr(args, k) is None:
            setattr(args, k, v)
    if args.quiet_s is None:
        args.quiet_s = 1.0 if args.smoke else 1.5

    from netrep_tpu.utils.backend import (
        enable_persistent_cache, resolve_backend_or_cpu,
    )

    enable_persistent_cache()
    resolve_backend_or_cpu()
    import jax

    if args.drill:
        return run_drill(args)
    if args.kill_recover:
        return run_kill_recover(args)
    if args.fleet:
        return run_fleet(args)
    if args.autoscale:
        return run_autoscale(args)
    if args.warmstart:
        return run_warmstart(args)

    device = str(jax.devices()[0])
    tenants, requests = build_workload(args)

    serial_s, _serial_perms, first_direct = run_serial_baseline(
        args, tenants, requests
    )

    tel_path = args.telemetry or os.path.join(
        tempfile.mkdtemp(prefix="netrep_serve_load_"), "serve.jsonl"
    )
    srv, client = make_server(args, tenants, tel_path)
    rc = 0
    try:
        if args.mode in ("both", "closed"):
            wall, results, lats = run_closed_loop(client, requests)
            # parity gate before any number is emitted: the first request
            # of the list, served vs direct (same seed) — bit-identical
            r0 = requests[0]
            served0 = next(
                res for r, res in results
                if r["tenant"] == r0["tenant"] and r["seed"] == r0["seed"]
            )
            assert np.array_equal(
                served0["p_values"], np.asarray(first_direct.p_values)
            ), "served/direct p-value mismatch"
            # conservation gate BEFORE any row (ISSUE 13), then the
            # per-tenant attributed-cost table beside p50/p99
            tenants_cost = check_cost_conservation(results)
            emit(row_from("closed loop", args, wall, results, lats,
                          serial_s, srv, tel_path, device))
            emit(cost_row("closed", args, wall, tenants_cost, device,
                          tel_path))
        if args.mode in ("both", "open"):
            # one unreported warm-up pass: open-loop arrivals queue deeper
            # than the closed loop and mint larger pack compositions —
            # steady state (what the row claims) starts once those few
            # canonical shapes are compiled into the warm pool
            run_open_loop(client, requests, args.rate)
            wall, results, lats = run_open_loop(client, requests,
                                               args.rate)
            tenants_cost = check_cost_conservation(results)
            emit(row_from("open loop (steady state)", args, wall, results,
                          lats, serial_s, srv, tel_path, device))
            emit(cost_row("open", args, wall, tenants_cost, device,
                          tel_path))
    finally:
        srv.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
