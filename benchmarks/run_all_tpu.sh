#!/bin/bash
# One-shot: every pending TPU measurement for BASELINE.md (VERDICT r1 items
# 1/3/4). Run when the axon tunnel is up; each line is appended to the log
# as it lands so a mid-run tunnel death loses nothing.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_bench_results.jsonl}
echo "== $(date -u +%FT%TZ) TPU bench sweep ==" | tee -a "$LOG"

run() {
  echo "--- $* ---" | tee -a "$LOG"
  # this script IS the timeout layer (like tpu_watch.sh): disable bench.py's
  # subprocess shield, whose larger budgets would never engage under the
  # shorter outer T values and whose extra layer buys nothing here
  NETREP_BENCH_NO_SUBPROC=1 timeout "${T:-900}" "$@" 2>&1 \
    | grep -v WARNING | tee -a "$LOG"
}

T=300  run python bench.py --smoke                     # tunnel sanity
T=600  run python bench.py --config B
T=900  run python bench.py --config C
T=600  run python bench.py --config E
T=900  run python benchmarks/microbench_sharded_gather.py
T=2400 run python benchmarks/tune_northstar.py
T=600  run python bench.py                             # north-star, current
T=600  run python bench.py --derived-net               # |corr|^2 derived mode
T=2400 run python bench.py --config D                  # 100k perms, stored net
T=2400 run python bench.py --config D --derived-net    # 100k perms, derived
echo "== done $(date -u +%FT%TZ) ==" | tee -a "$LOG"
