#!/bin/bash
# One-shot manual sweep: every pending TPU measurement for BASELINE.md.
# Prefer `tpu_watch.sh` (resumable, probe-gated, parity/selftest-gated) —
# this script is the no-state fallback for a human sitting on a live
# tunnel. Order = the watcher queue's priority order: the headline
# north-star row first after the smoke sanity, gates before anything
# fused, scale configs last (BASELINE.md "measurement-session note":
# windows run ~5-7 min, so later lines may never execute).
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_bench_results.jsonl}
echo "== $(date -u +%FT%TZ) TPU bench sweep ==" | tee -a "$LOG"

run() {
  echo "--- $* ---" | tee -a "$LOG"
  # this script IS the timeout layer (like tpu_watch.sh): disable bench.py's
  # subprocess shield, whose larger budgets would never engage under the
  # shorter outer T values and whose extra layer buys nothing here.
  # Returns the COMMAND's status (grep/tee must not mask it — the gate
  # lines below depend on it).
  NETREP_BENCH_NO_SUBPROC=1 PYTHONUNBUFFERED=1 timeout "${T:-900}" "$@" 2>&1 \
    | grep -v WARNING | tee -a "$LOG"
  return "${PIPESTATUS[0]}"
}

halt() {
  # a failed gate means every later row would be untrusted (CPU fallback,
  # miscompiled kernel, broken device math) — same policy as tpu_watch.sh
  echo "== GATE FAILED ($1); halting sweep $(date -u +%FT%TZ) ==" | tee -a "$LOG"
  echo '{"warning": "'"$1"' gate failed; sweep halted - rows after this point would be untrusted"}' >>"$LOG"
  exit 3
}

T=300  run python bench.py --smoke                     # tunnel sanity
T=900  run python bench.py                             # north-star FIRST
T=600  run python benchmarks/microbench_parts.py --parity-only \
  || halt "fused-parity"                               # Mosaic gate
T=600  run python -c 'import bench; bench.ensure_backend(); import netrep_tpu; r = netrep_tpu.selftest(max_shapes=1); assert r["backend"] != "cpu", r' \
  || halt "device-selftest"                            # 1 shape: window budget
T=2400 run python benchmarks/tune_northstar.py         # decision grid (resumable)
T=900  run python bench.py --derived-net               # |corr|^2 derived mode
T=900  run python bench.py --dtype bfloat16
T=1200 run python benchmarks/bf16_drift.py
T=600  run python bench.py --config B
T=900  run python bench.py --config C
T=600  run python bench.py --config E
T=900  run python benchmarks/microbench_sharded_gather.py
T=2400 run python bench.py --config D                  # 100k perms, stored net
T=2400 run python bench.py --config D --derived-net    # 100k perms, derived
echo "== done $(date -u +%FT%TZ) ==" | tee -a "$LOG"
