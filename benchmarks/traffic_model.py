"""Analytic HBM-traffic model for the north-star hot loop (VERDICT r3 item
6a: verify the fused kernel's one-pass traffic claim without a chip).

Everything here is computed from the engine's own constants — module sizes
from ``bench.make_specs``, bucket capacities from
``EngineConfig.rounded_cap``, the fused kernel's DMA pattern from
:mod:`netrep_tpu.ops.fused_gather` — plus exactly one measured anchor: the
27.14 s north-star row (BASELINE.md, TPU v5 lite, 2026-07-29, mxu path).
No reference numbers exist (SURVEY.md §0); the model's claims are:

1. **One-pass bytes.** The fused kernel reads each selected row once
   (HBM→VMEM DMA, skipping un-owned slots) and writes only the (cap, cap)
   submatrix: per permutation ``Σ_b K_b·cap_b·n·itemsize`` per gathered
   matrix plus ``Σ_b K_b·cap_b²·4`` out. The script recomputes this from
   the caps and cross-checks it against the kernel's ``CostEstimate``
   formula (same constants path the Mosaic scheduler sees).
2. **Implied XLA pass count.** From the measured 2.714 ms/perm and the
   one-pass byte count, back out how many effective HBM passes the XLA mxu
   path makes at a given sustained bandwidth — the multiplier the fused
   kernel removes.
3. **Predicted fused north-star.** One-pass bytes at the same sustained
   bandwidth the mxu measurement implies, for each (dtype, derived-net)
   variant — the numbers ``benchmarks/tune_northstar.py`` will confirm or
   refute the moment the tunnel returns.

Usage: python benchmarks/traffic_model.py  (pure CPU arithmetic, instant).
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import make_specs  # noqa: E402
from netrep_tpu.utils.config import EngineConfig  # noqa: E402

# Measured anchor: BASELINE.md north-star row (mxu path, f32, two matrices).
MEASURED_S = 27.14
N_PERM = 10_000
GENES = 20_000
MODULES = 50
SAMPLES = 128
# v5e peak HBM bandwidth (public spec, ~819 GB/s); the sustained fraction is
# DERIVED from the anchor below, not assumed.
PEAK_BW = 819e9


def caps_for(genes, modules, cap_granularity=32):
    cfg = EngineConfig(cap_granularity=cap_granularity)
    specs = make_specs(genes, modules)
    return np.array([cfg.rounded_cap(len(s.disc_idx)) for s in specs])


def one_pass_bytes(caps, n, itemsize, n_matrices, samples=None):
    """Fused-kernel traffic per permutation: row DMAs once per gathered
    matrix + (cap, cap) f32 outputs (+ the (cap, samples) data gather when
    node contribution/data statistics are on)."""
    rows = int(caps.sum()) * n * itemsize * n_matrices
    outs = int((caps**2).sum()) * 4 * n_matrices
    data = int(caps.sum()) * samples * 4 if samples else 0
    return rows + outs + data


def cost_estimate_bytes(caps, n, itemsize, n_matrices):
    """The kernel's own CostEstimate formula (fused_gather._run), summed
    over one permutation's instances (G=1 per module per matrix), using the
    kernel's REAL row-block selection (`fused_gather._row_block`, including
    the VMEM-guard downscale) so ``rpad`` — the padded out-block row count —
    is what a launch at these shapes would actually report, not an
    idealized rpad == cap."""
    from netrep_tpu.ops.fused_gather import _row_block

    total = 0
    for cap in caps:
        rb = _row_block(int(cap), n, itemsize)
        rpad = -(-int(cap) // rb) * rb
        total += n_matrices * (int(cap) * n * itemsize + rpad * int(cap) * 4)
    return total


def main():
    caps = caps_for(GENES, MODULES)
    t_perm = MEASURED_S / N_PERM

    # --- claim 1: one-pass bytes, cross-checked against CostEstimate ---
    # The kernel pads each bucket's out block to whole row blocks (rpad >=
    # cap, VMEM-guard rb), so its CostEstimate sits slightly ABOVE the
    # analytic ideal; the cross-check bounds that padding overhead instead
    # of pretending the two formulas are identical.
    b1_f32 = one_pass_bytes(caps, GENES, 4, 2, SAMPLES)
    ce = cost_estimate_bytes(caps, GENES, 4, 2) + int(caps.sum()) * SAMPLES * 4
    pad_overhead = ce / b1_f32 - 1.0
    assert 0.0 <= pad_overhead < 0.02, (b1_f32, ce)

    # --- claim 2: implied mxu pass count at the measured anchor ---
    # sustained = bytes_actually_moved / t; with k effective passes over the
    # one-pass row traffic, k = t * BW_sustained / b1. We bracket with the
    # round-2 microbench sustained rate (235 GB/s ≈ 29% of peak was the
    # ROOFLINE's estimate at its larger Σcap model; recompute both ways).
    implied_bw_if_one_pass = b1_f32 / t_perm          # BW needed were mxu 1-pass
    passes_at_60pct = t_perm * (0.6 * PEAK_BW) / b1_f32
    passes_at_29pct = t_perm * (0.29 * PEAK_BW) / b1_f32

    rows = [
        {
            "metric": "one-pass HBM bytes/perm, north-star f32 2-matrix "
                      "(fused kernel analytic == its CostEstimate)",
            "value": round(b1_f32 / 1e9, 4),
            "unit": "GB",
            "sum_cap": int(caps.sum()),
            "cross_check": (
                "kernel CostEstimate (real _row_block padding) exceeds the "
                f"analytic ideal by {100 * pad_overhead:.2f}% — out-block "
                "row padding only"
            ),
        },
        {
            "metric": "HBM bandwidth the 27.14s mxu row would need were it "
                      "one-pass (lower => XLA makes extra passes)",
            "value": round(implied_bw_if_one_pass / 1e9, 1),
            "unit": "GB/s",
            "peak_fraction": round(implied_bw_if_one_pass / PEAK_BW, 3),
            "implied_passes_at_60pct_peak": round(passes_at_60pct, 2),
            "implied_passes_at_29pct_peak": round(passes_at_29pct, 2),
        },
    ]

    # --- claim 3: predicted fused north-star per variant ---
    # Conservative sustained BW: whatever the mxu row achieved per byte of
    # ONE pass (i.e., assume mxu was already one-pass => fused wins only via
    # dtype/derived-net traffic cuts). Optimistic: 60% of peak (typical for
    # well-pipelined DMA streams; the mxu row implies >= this if it makes
    # >= implied_passes_at_60pct passes).
    for label, itemsize, n_mat in [
        ("f32 2-matrix", 4, 2),
        ("f32 derived-net", 4, 1),
        ("bf16 2-matrix", 2, 2),
        ("bf16 derived-net", 2, 1),
    ]:
        b = one_pass_bytes(caps, GENES, itemsize, n_mat, SAMPLES)
        rows.append({
            "metric": f"predicted fused north-star, {label}",
            "value": round(N_PERM * b / implied_bw_if_one_pass, 2),
            "unit": "s (conservative: mxu-row-implied sustained BW)",
            "optimistic_s": round(N_PERM * b / (0.6 * PEAK_BW), 2),
            "bytes_per_perm_GB": round(b / 1e9, 4),
        })
    # --- second, independently-measured anchor (VERDICT r4 weak #1) -----
    # benchmarks/cpu_anchor.py measures XLA's row-gather efficiency vs
    # STREAM on the local CPU at this exact shape; efficiency * TPU peak
    # estimates sustained BW without the 27.14 s row. Printing both
    # anchors and their disagreement keeps the model honest about how
    # much still hangs on the single TPU measurement.
    anchor_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "cpu_anchor.json")
    if os.path.exists(anchor_path):
        with open(anchor_path) as f:
            cpu_anchor = json.load(f)
        # BRACKET, not a point (review r5): read-only accounting matches
        # the one-pass byte count's read-traffic basis; read+write is the
        # symmetric twin of the STREAM denominator. The truth for the
        # TPU-side transfer lies between them.
        eff_lo = cpu_anchor["gather_efficiency_read_only"]
        eff_hi = cpu_anchor["gather_efficiency_rw"]
        bw2_lo, bw2_hi = eff_lo * PEAK_BW, eff_hi * PEAK_BW
        rows.append({
            "metric": "sustained-BW anchors: TPU-row-implied (were mxu "
                      "one-pass) vs CPU-gather-efficiency * TPU peak "
                      "[read-only, read+write accounting]",
            "anchor1_tpu_row_GBps": round(implied_bw_if_one_pass / 1e9, 1),
            "anchor2_cpu_eff_GBps": [round(bw2_lo / 1e9, 1),
                                     round(bw2_hi / 1e9, 1)],
            "cpu_gather_efficiency": [eff_lo, eff_hi],
            "cpu_stream_GBps": cpu_anchor["stream_copy_GBps"],
            "cpu_row_gather_read_GBps": cpu_anchor["row_gather_read_GBps"],
            # the claim the code actually tests (review r5): anchor1 does
            # not EXCEED the independently-derived achievable upper bound.
            # Sitting below the lower edge is expected — it just means the
            # mxu path makes >1 effective pass.
            "anchor1_below_anchor2_upper": bool(
                implied_bw_if_one_pass <= bw2_hi
            ),
            "disagreement_anchor2_over_anchor1": [
                round(bw2_lo / implied_bw_if_one_pass, 2),
                round(bw2_hi / implied_bw_if_one_pass, 2),
            ],
            "implied_mxu_passes_at_anchor2": [
                round(t_perm * bw2_lo / b1_f32, 2),
                round(t_perm * bw2_hi / b1_f32, 2),
            ],
            "unit": "GB/s",
        })
    else:
        rows.append({
            "metric": "second sustained-BW anchor",
            "value": "MISSING — run benchmarks/cpu_anchor.py on an idle "
                     "machine; until then every prediction above rests on "
                     "the single 27.14 s TPU row",
        })

    # --- bucket-granularity lever (EngineConfig.cap_granularity) ---------
    caps8 = caps_for(GENES, MODULES, cap_granularity=8)
    b8 = one_pass_bytes(caps8, GENES, 4, 2, SAMPLES)
    rows.append({
        "metric": "cap_granularity=8 vs 32: one-pass bytes/perm, f32 "
                  "2-matrix (padding share of the bandwidth-bound traffic)",
        "value": round(b8 / 1e9, 4),
        "unit": "GB",
        "sum_cap": int(caps8.sum()),
        "vs_g32": round(b8 / b1_f32, 4),
        "distinct_caps_g8": int(np.unique(caps8).size),
        "distinct_caps_g32": int(np.unique(caps).size),
    })
    for r in rows:
        print(json.dumps(r))
    return 0


if __name__ == "__main__":
    sys.exit(main())
