# netrep-tpu R shim (reticulate stub) — the `backend="tpu"` story
# (SURVEY.md §7 step 7; BASELINE.json:5): the reference package's exported
# surface, argument names and defaults preserved verbatim, forwarding to the
# netrep_tpu Python package. See docs/r-shim.md for the full mapping,
# including the result-object shape.
#
# R is not installed in the build image, so this file is a *specification
# stub*: it is exercised for name/default parity against the Python
# signatures by tests/test_r_shim.py (which parses this file), and is
# written to run unmodified in an R session that has reticulate + a Python
# environment with netrep_tpu on sys.path:
#
#   source("r/netrep_tpu.R")
#   res <- modulePreservation(network = list(d = dnet, t = tnet),
#                             data = list(d = ddat, t = tdat),
#                             correlation = list(d = dcor, t = tcor),
#                             moduleAssignments = labels,
#                             discovery = "d", test = "t", nPerm = 10000)

.netrep <- local({
  mod <- NULL
  function() {
    if (is.null(mod)) mod <<- reticulate::import("netrep_tpu")
    mod
  }
})

# Argument-name mapping, reference (camelCase) -> netrep_tpu (snake_case).
# Machine-readable: tests/test_r_shim.py asserts every right-hand side is a
# real parameter of the Python function and that defaults agree.
.modulePreservation_args <- list(
  network            = "network",
  data               = "data",
  correlation        = "correlation",
  moduleAssignments  = "module_assignments",
  modules            = "modules",
  backgroundLabel    = "background_label",
  discovery          = "discovery",
  test               = "test",
  selfPreservation   = "self_preservation",
  nThreads           = "n_threads",
  nPerm              = "n_perm",
  null               = "null",
  alternative        = "alternative",
  simplify           = "simplify",
  verbose            = "verbose"
)

#' Permutation test of network module preservation (reference signature).
#'
#' Arguments are the reference's, verbatim; TPU-only extras (seed, config,
#' mesh, profile, checkpoint.dir, backend) ride through `...` using the
#' Python names. NULL arguments are dropped so Python defaults apply.
modulePreservation <- function(network,
                               data = NULL,
                               correlation = NULL,
                               moduleAssignments = NULL,
                               modules = NULL,
                               backgroundLabel = "0",
                               discovery = NULL,
                               test = NULL,
                               selfPreservation = FALSE,
                               nThreads = NULL,
                               nPerm = NULL,
                               null = "overlap",
                               alternative = "greater",
                               simplify = TRUE,
                               verbose = FALSE,
                               ...) {
  args <- list(network = network, data = data, correlation = correlation,
               module_assignments = moduleAssignments, modules = modules,
               background_label = backgroundLabel, discovery = discovery,
               test = test, self_preservation = selfPreservation,
               n_threads = nThreads, n_perm = nPerm, null = null,
               alternative = alternative, simplify = simplify,
               verbose = verbose, ...)
  args <- args[!vapply(args, is.null, logical(1))]
  do.call(.netrep()$module_preservation, args)
}

.networkProperties_args <- list(
  network            = "network",
  data               = "data",
  correlation        = "correlation",
  moduleAssignments  = "module_assignments",
  modules            = "modules",
  backgroundLabel    = "background_label",
  discovery          = "discovery",
  test               = "test",
  selfPreservation   = "self_preservation",
  simplify           = "simplify"
)

networkProperties <- function(network,
                              data = NULL,
                              correlation = NULL,
                              moduleAssignments = NULL,
                              modules = NULL,
                              backgroundLabel = "0",
                              discovery = NULL,
                              test = NULL,
                              selfPreservation = TRUE,
                              simplify = TRUE) {
  args <- list(network = network, data = data, correlation = correlation,
               module_assignments = moduleAssignments, modules = modules,
               background_label = backgroundLabel, discovery = discovery,
               test = test, self_preservation = selfPreservation,
               simplify = simplify)
  args <- args[!vapply(args, is.null, logical(1))]
  do.call(.netrep()$network_properties, args)
}

.requiredPerms_args <- list(
  alpha       = "alpha",
  nTests      = "n_tests",
  alternative = "alternative"
)

requiredPerms <- function(alpha = 0.05, nTests = 1L,
                          alternative = "greater") {
  .netrep()$required_perms(alpha = alpha, n_tests = as.integer(nTests),
                           alternative = alternative)
}

#' Shared plot-call glue: drop NULL args (Python defaults apply), then
#' set the order-mode arguments — NULL is a real mode there (input
#' order), so it must reach Python as None, not be dropped. Single-bracket
#' list assignment stores NULL; $<- NULL would delete the element.
#' An order argument already present in args came through `...` under its
#' Python name (the documented extras channel) — that explicit value wins
#' over the camelCase argument, which is indistinguishable from its
#' R-level default here. Order arguments are exempt from the NULL-drop
#' for the same reason the camelCase path force-sets them: NULL is a real
#' mode (input order), so a `...`-supplied order NULL must survive to
#' Python as None rather than being dropped and defaulted.
.callPlot <- function(py_name, args, orderArgs) {
  plt <- reticulate::import("netrep_tpu.plot")
  is_order <- names(args) %in% names(orderArgs)
  args <- args[is_order | !vapply(args, is.null, logical(1))]
  for (nm in names(orderArgs)) {
    if (!nm %in% names(args)) args[nm] <- orderArgs[nm]
  }
  do.call(plt[[py_name]], args)
}

.nodeOrder_args <- list(
  network           = "network",
  data              = "data",
  correlation       = "correlation",
  moduleAssignments = "module_assignments",
  modules           = "modules",
  backgroundLabel   = "background_label",
  discovery         = "discovery",
  test              = "test",
  orderNodesBy      = "order_nodes_by"
)

#' Node plotting order by weighted degree (reference: nodeOrder).
#' orderNodesBy = NULL is a real mode (input order), so it is forwarded as
#' Python None rather than dropped.
nodeOrder <- function(network,
                      data = NULL,
                      correlation = NULL,
                      moduleAssignments = NULL,
                      modules = NULL,
                      backgroundLabel = "0",
                      discovery = NULL,
                      test = NULL,
                      orderNodesBy = "discovery") {
  .callPlot("node_order",
            list(network = network, data = data, correlation = correlation,
                 module_assignments = moduleAssignments, modules = modules,
                 background_label = backgroundLabel, discovery = discovery,
                 test = test),
            list(order_nodes_by = orderNodesBy))
}

.sampleOrder_args <- list(
  network           = "network",
  data              = "data",
  correlation       = "correlation",
  moduleAssignments = "module_assignments",
  modules           = "modules",
  backgroundLabel   = "background_label",
  discovery         = "discovery",
  test              = "test",
  orderSamplesBy    = "order_samples_by"
)

#' Sample plotting order by summary profile (reference: sampleOrder).
#' orderSamplesBy = NULL is a real mode (input order), so it is forwarded as
#' Python None rather than dropped.
sampleOrder <- function(network,
                        data,
                        correlation = NULL,
                        moduleAssignments = NULL,
                        modules = NULL,
                        backgroundLabel = "0",
                        discovery = NULL,
                        test = NULL,
                        orderSamplesBy = "test") {
  .callPlot("sample_order",
            list(network = network, data = data, correlation = correlation,
                 module_assignments = moduleAssignments, modules = modules,
                 background_label = backgroundLabel, discovery = discovery,
                 test = test),
            list(order_samples_by = orderSamplesBy))
}

.combineAnalyses_args <- list(
  allowDuplicateNulls = "allow_duplicate_nulls"
)

#' Combine two module-preservation analyses run with separate permutations
#' (reference: combineAnalyses, R/combineAnalyses.R) — null distributions are
#' pooled and exact p-values recomputed over the combined count. The inputs
#' must be results of the same analysis (same datasets, modules, alternative)
#' produced with different seeds; duplicated permutation streams are rejected
#' unless allowDuplicateNulls = TRUE.
combineAnalyses <- function(analysis1, analysis2,
                            allowDuplicateNulls = FALSE, ...) {
  .netrep()$combine_analyses(analysis1, analysis2,
                             allow_duplicate_nulls = allowDuplicateNulls, ...)
}

# Shared camelCase->snake_case map for plotModule and the five panel
# plots (one argument set across the suite, like the reference).
.panelArgs <- list(
  network           = "network",
  data              = "data",
  correlation       = "correlation",
  moduleAssignments = "module_assignments",
  modules           = "modules",
  backgroundLabel   = "background_label",
  discovery         = "discovery",
  test              = "test",
  orderNodesBy      = "order_nodes_by",
  orderSamplesBy    = "order_samples_by"
)

.plotModule_args <- .panelArgs

plotModule <- function(network,
                       data = NULL,
                       correlation = NULL,
                       moduleAssignments = NULL,
                       modules = NULL,
                       backgroundLabel = "0",
                       discovery = NULL,
                       test = NULL,
                       orderNodesBy = "discovery",
                       orderSamplesBy = "test",
                       ...) {
  .callPlot("plot_module",
            list(network = network, data = data, correlation = correlation,
                 module_assignments = moduleAssignments, modules = modules,
                 background_label = backgroundLabel, discovery = discovery,
                 test = test, ...),
            list(order_nodes_by = orderNodesBy,
                 order_samples_by = orderSamplesBy))
}

# Per-panel plot shims (reference: plotData / plotCorrelation / plotNetwork /
# plotContribution / plotDegree — SURVEY.md §2.1 "Plot suite"). One shared
# argument set, like the reference's panel plots; extras (showNodeNames via
# show_node_names, ax) ride through `...` using the Python names.

.plotData_args <- .panelArgs

plotData <- function(network,
                     data = NULL,
                     correlation = NULL,
                     moduleAssignments = NULL,
                     modules = NULL,
                     backgroundLabel = "0",
                     discovery = NULL,
                     test = NULL,
                     orderNodesBy = "discovery",
                     orderSamplesBy = "test",
                     ...) {
  .callPlot("plot_data",
            list(network = network, data = data, correlation = correlation,
                 module_assignments = moduleAssignments, modules = modules,
                 background_label = backgroundLabel, discovery = discovery,
                 test = test, ...),
            list(order_nodes_by = orderNodesBy,
                 order_samples_by = orderSamplesBy))
}

.plotCorrelation_args <- .panelArgs

plotCorrelation <- function(network,
                            data = NULL,
                            correlation = NULL,
                            moduleAssignments = NULL,
                            modules = NULL,
                            backgroundLabel = "0",
                            discovery = NULL,
                            test = NULL,
                            orderNodesBy = "discovery",
                            orderSamplesBy = "test",
                            ...) {
  .callPlot("plot_correlation",
            list(network = network, data = data, correlation = correlation,
                 module_assignments = moduleAssignments, modules = modules,
                 background_label = backgroundLabel, discovery = discovery,
                 test = test, ...),
            list(order_nodes_by = orderNodesBy,
                 order_samples_by = orderSamplesBy))
}

.plotNetwork_args <- .panelArgs

plotNetwork <- function(network,
                        data = NULL,
                        correlation = NULL,
                        moduleAssignments = NULL,
                        modules = NULL,
                        backgroundLabel = "0",
                        discovery = NULL,
                        test = NULL,
                        orderNodesBy = "discovery",
                        orderSamplesBy = "test",
                        ...) {
  .callPlot("plot_network",
            list(network = network, data = data, correlation = correlation,
                 module_assignments = moduleAssignments, modules = modules,
                 background_label = backgroundLabel, discovery = discovery,
                 test = test, ...),
            list(order_nodes_by = orderNodesBy,
                 order_samples_by = orderSamplesBy))
}

.plotContribution_args <- .panelArgs

plotContribution <- function(network,
                             data = NULL,
                             correlation = NULL,
                             moduleAssignments = NULL,
                             modules = NULL,
                             backgroundLabel = "0",
                             discovery = NULL,
                             test = NULL,
                             orderNodesBy = "discovery",
                             orderSamplesBy = "test",
                             ...) {
  .callPlot("plot_contribution",
            list(network = network, data = data, correlation = correlation,
                 module_assignments = moduleAssignments, modules = modules,
                 background_label = backgroundLabel, discovery = discovery,
                 test = test, ...),
            list(order_nodes_by = orderNodesBy,
                 order_samples_by = orderSamplesBy))
}

.plotDegree_args <- .panelArgs

plotDegree <- function(network,
                       data = NULL,
                       correlation = NULL,
                       moduleAssignments = NULL,
                       modules = NULL,
                       backgroundLabel = "0",
                       discovery = NULL,
                       test = NULL,
                       orderNodesBy = "discovery",
                       orderSamplesBy = "test",
                       ...) {
  .callPlot("plot_degree",
            list(network = network, data = data, correlation = correlation,
                 module_assignments = moduleAssignments, modules = modules,
                 background_label = backgroundLabel, discovery = discovery,
                 test = test, ...),
            list(order_nodes_by = orderNodesBy,
                 order_samples_by = orderSamplesBy))
}
