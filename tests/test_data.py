"""Tests for the bundled example-data module (SURVEY.md §2.1 "Example data"):
determinism, shape contract, and end-to-end usability as the vignette fixture
(Config A, BASELINE.json:7)."""

import numpy as np

import netrep_tpu
from netrep_tpu.data import load_example, make_example_pair


def test_load_example_deterministic():
    a = load_example()
    b = load_example()
    for k in ("discovery_data", "discovery_correlation", "discovery_network",
              "test_data", "test_correlation", "test_network"):
        np.testing.assert_array_equal(a[k], b[k])
    assert a["module_labels"] == b["module_labels"]
    assert load_example(seed=1)["discovery_data"][0, 0] != a["discovery_data"][0, 0]


def test_load_example_shapes_and_labels():
    ex = load_example()
    n_d = len(ex["discovery_names"])
    n_t = len(ex["test_names"])
    assert ex["discovery_correlation"].shape == (n_d, n_d)
    assert ex["discovery_network"].shape == (n_d, n_d)
    assert ex["discovery_data"].shape[1] == n_d
    assert ex["test_correlation"].shape == (n_t, n_t)
    assert set(ex["module_labels"]) == set(ex["discovery_names"])
    mods = {v for v in ex["module_labels"].values() if v != "0"}
    assert mods == {"1", "2", "3", "4"}
    # correlation matrices are valid
    assert np.allclose(ex["test_correlation"], ex["test_correlation"].T)
    assert np.abs(ex["test_correlation"]).max() <= 1 + 1e-9


def test_example_runs_end_to_end():
    """Config A smoke: the fixture drives module_preservation directly via
    the dict-of-DataFrames input form."""
    pd = __import__("pandas")
    ex = load_example(seed=0)

    def df(mat, names):
        return pd.DataFrame(mat, index=names, columns=names)

    res = netrep_tpu.module_preservation(
        network={
            "d": df(ex["discovery_network"], ex["discovery_names"]),
            "t": df(ex["test_network"], ex["test_names"]),
        },
        correlation={
            "d": df(ex["discovery_correlation"], ex["discovery_names"]),
            "t": df(ex["test_correlation"], ex["test_names"]),
        },
        data={
            "d": pd.DataFrame(ex["discovery_data"], columns=ex["discovery_names"]),
            "t": pd.DataFrame(ex["test_data"], columns=ex["test_names"]),
        },
        module_assignments=ex["module_labels"],
        discovery="d",
        test="t",
        n_perm=50,
        seed=7,
    )
    assert res.observed.shape == (4, 7)
    assert np.isfinite(res.p_values).all()
    # planted modules replicate: every statistic's observed value should sit
    # in the upper tail for at least the strongest module
    assert res.max_pvalue().min() < 0.2


def test_make_example_pair_custom_sizes():
    pair = make_example_pair(np.random.default_rng(3), module_sizes=(6, 5),
                             n_disc=40, n_test=35, n_overlap=30,
                             n_samples_disc=20, n_samples_test=18)
    assert pair["module_sizes"] == {"1": 6, "2": 5}
    assert len(pair["discovery"]["names"]) == 40
