"""Adaptive permutation engine: sequential early stopping
(ops/sequential.py), retirement re-bucketing (engine.rebucket), and the
API/results/checkpoint threading.

The oracle tests pin the ISSUE acceptance criteria: on a seeded mixed
half-preserved/half-random fixture the adaptive run must reach the SAME
per-module accept/reject decisions at alpha=0.05 as the full-n
Phipson–Smyth run while evaluating >= 3x fewer total permutations, active
modules' null rows must match the fixed run's bit-for-bit at the same
permutation indices (the ``fold_in(key, i)`` RNG contract survives
re-bucketing), and a checkpoint written mid-run must resume to the same
final result as an uninterrupted run.
"""

import numpy as np
import pytest

from netrep_tpu.data import make_mixed_pair
from netrep_tpu.ops import pvalues as pv
from netrep_tpu.ops.sequential import StopMonitor, StopRule
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils.config import EngineConfig

CFG = EngineConfig(chunk_size=64, summary_method="eigh")
N_PERM = 1200


@pytest.fixture(scope="module")
def mixed():
    return make_mixed_pair(320, 6, n_samples=40, seed=7)


def _engine(mixed, config=CFG):
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    return PermutationEngine(
        dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=config
    )


@pytest.fixture(scope="module")
def runs(mixed):
    """One fixed + one adaptive run shared by the oracle assertions."""
    eng = _engine(mixed)
    observed = np.asarray(eng.observed())
    nulls_f, done_f = eng.run_null(N_PERM, key=0)
    eng2 = _engine(mixed)
    nulls_a, done_a, finished = eng2.run_null_adaptive(
        N_PERM, observed, key=0
    )
    return dict(observed=observed, nulls_f=np.asarray(nulls_f),
                done_f=done_f, nulls_a=np.asarray(nulls_a), done_a=done_a,
                finished=finished)


# ---------------------------------------------------------------------------
# StopMonitor / StopRule units
# ---------------------------------------------------------------------------

def test_stop_rule_validation():
    with pytest.raises(ValueError, match="h must be"):
        StopRule(h=0)
    with pytest.raises(ValueError, match="alpha"):
        StopRule(alpha=1.5)
    with pytest.raises(ValueError, match="confidence"):
        StopRule(confidence=0.2)
    with pytest.raises(ValueError, match="min_perms"):
        StopRule(min_perms=0)
    with pytest.raises(ValueError, match="alternative"):
        StopMonitor(np.zeros((2, 3)), "sideways", StopRule())


def test_two_sided_tallies_are_per_tail_additive():
    """Two-sided exceedance is min(hi, lo) of the TOTAL tallies: folding
    per-chunk min-tail counts instead would under-count (min of sums !=
    sum of mins) — the monitor must keep both tails."""
    rng = np.random.default_rng(0)
    obs = np.zeros((2, 3))
    nulls = rng.standard_normal((96, 2, 3))
    mon = StopMonitor(obs, "two.sided", StopRule(min_perms=10_000))
    for i in range(0, 96, 32):
        mon.update(nulls[i: i + 32], 32)
    want, _eff = pv.exceedance_counts(obs, nulls, "two.sided")
    np.testing.assert_array_equal(mon.counts(), want)
    # one-sided tallies agree with exceedance_counts too
    mon_g = StopMonitor(obs, "greater", StopRule(min_perms=10_000))
    mon_g.update(nulls, 96)
    want_g, _ = pv.exceedance_counts(obs, nulls, "greater")
    np.testing.assert_array_equal(mon_g.counts(), want_g)


def test_monitor_state_roundtrip_and_fixed_checkpoint_rejection():
    obs = np.zeros((3, 2))
    mon = StopMonitor(obs, "greater", StopRule(min_perms=8, h=4))
    mon.update(np.ones((8, 3, 2)), 8)
    state = mon.state_arrays()
    mon2 = StopMonitor(obs, "greater", StopRule(min_perms=8, h=4))
    mon2.restore_state(state)
    np.testing.assert_array_equal(mon2.hi, mon.hi)
    np.testing.assert_array_equal(mon2.active, mon.active)
    assert mon2.folded == mon.folded
    # a fixed-run checkpoint has no sequential state: informative error
    with pytest.raises(ValueError, match="non-adaptive"):
        mon2.restore_state({})
    # different problem shape: refuse
    mon3 = StopMonitor(np.zeros((4, 2)), "greater", StopRule())
    with pytest.raises(ValueError, match="different"):
        mon3.restore_state(state)


def test_nan_observed_cells_never_block_retirement():
    obs = np.array([[0.0, np.nan]])
    mon = StopMonitor(obs, "greater", StopRule(h=4, min_perms=8))
    vals = np.ones((32, 1, 2))  # every draw exceeds the computable cell
    newly = mon.update(vals, 32)
    assert newly.tolist() == [0] and not mon.any_active()


# ---------------------------------------------------------------------------
# Oracle: decisions, permutation budget, RNG contract (ISSUE acceptance)
# ---------------------------------------------------------------------------

def test_adaptive_decisions_match_fixed_at_alpha(runs):
    """Sequential estimator decisions agree with full-n Phipson–Smyth at
    alpha=0.05 for every module on the mixed fixture."""
    p_f = pv.permutation_pvalues(runs["observed"],
                                 runs["nulls_f"][: runs["done_f"]])
    p_a, n_used = pv.sequential_pvalues(runs["observed"],
                                        runs["nulls_a"][: runs["done_a"]])
    dec_f = np.nanmax(p_f, axis=1) < 0.05
    dec_a = np.nanmax(p_a, axis=1) < 0.05
    np.testing.assert_array_equal(dec_f, dec_a)
    # the fixture separates cleanly: preserved modules significant,
    # random modules not — so the agreement above is a real decision test
    assert dec_f.tolist() == [True] * 3 + [False] * 3


def test_adaptive_cuts_total_permutations_3x(runs):
    assert runs["finished"]
    n_used = pv.effective_nperm(runs["nulls_a"][: runs["done_a"]])
    total_adaptive = int(n_used.sum())
    total_fixed = runs["done_f"] * n_used.size
    assert total_adaptive * 3 <= total_fixed, (total_adaptive, total_fixed)
    # every module paid at least the rule's floor sample
    assert (n_used >= StopRule().min_perms).all()


def test_rebucketing_preserves_rng_contract(runs):
    """Active modules' null rows are identical to the fixed run's at the
    same permutation indices, across every retirement re-bucketing: the
    per-permutation draw is fold_in(key, i) over the full pool and
    surviving modules keep their original slice offsets."""
    n_used = pv.effective_nperm(runs["nulls_a"][: runs["done_a"]])
    for m, k in enumerate(n_used):
        np.testing.assert_allclose(
            runs["nulls_a"][:k, m], runs["nulls_f"][:k, m],
            rtol=0, atol=1e-12,
        )
        # and NaN past retirement — per-module counts are recoverable
        assert np.isnan(runs["nulls_a"][k:, m]).all()


def test_rebucket_validation(mixed):
    eng = _engine(mixed)
    with pytest.raises(ValueError, match="at least one"):
        eng.rebucket([])
    with pytest.raises(ValueError, match="unknown module positions"):
        eng.rebucket([99])
    # restoring the full set leaves the original bucket objects intact
    eng.rebucket([0, 2])
    assert sum(len(b.module_pos) for b in eng.buckets) == 2
    eng.rebucket(range(eng.n_modules))
    assert sum(len(b.module_pos) for b in eng.buckets) == eng.n_modules


# ---------------------------------------------------------------------------
# Checkpoint / resume
# ---------------------------------------------------------------------------

def test_adaptive_checkpoint_resume_equals_uninterrupted(mixed, tmp_path):
    eng = _engine(mixed)
    observed = np.asarray(eng.observed())
    ref_nulls, ref_done, ref_fin = _engine(mixed).run_null_adaptive(
        N_PERM, observed, key=3
    )
    assert ref_fin

    ck = str(tmp_path / "adaptive.npz")
    chunks_seen = []

    def interrupt_after_two(done, total):
        chunks_seen.append(done)
        if len(chunks_seen) == 2:
            raise KeyboardInterrupt

    part_nulls, part_done, part_fin = _engine(mixed).run_null_adaptive(
        N_PERM, observed, key=3, progress=interrupt_after_two,
        checkpoint_path=ck, checkpoint_every=64,
    )
    assert not part_fin and 0 < part_done < ref_done

    fin_nulls, fin_done, fin_fin = _engine(mixed).run_null_adaptive(
        N_PERM, observed, key=3, checkpoint_path=ck, checkpoint_every=64,
    )
    assert fin_fin and fin_done == ref_done
    np.testing.assert_allclose(
        np.asarray(fin_nulls), np.asarray(ref_nulls), rtol=0, atol=1e-12
    )


def test_adaptive_refuses_fixed_run_checkpoint(mixed, tmp_path):
    ck = str(tmp_path / "fixed.npz")
    eng = _engine(mixed)
    observed = np.asarray(eng.observed())
    eng.run_null(128, key=3, checkpoint_path=ck)
    with pytest.raises(ValueError, match="non-adaptive"):
        _engine(mixed).run_null_adaptive(
            N_PERM, observed, key=3, checkpoint_path=ck
        )


# ---------------------------------------------------------------------------
# sequential p-values / results threading
# ---------------------------------------------------------------------------

def test_sequential_pvalues_are_permp_at_module_counts():
    rng = np.random.default_rng(1)
    obs = np.array([[0.5, 0.2], [0.1, 0.9]])
    nulls = rng.uniform(size=(100, 2, 2))
    nulls[60:, 1] = np.nan  # module 1 retired at 60
    p, n_used = pv.sequential_pvalues(obs, nulls)
    assert n_used.tolist() == [100, 60]
    counts, _ = pv.exceedance_counts(obs, nulls)
    np.testing.assert_allclose(p[0], pv.permp(counts[0], 100))
    np.testing.assert_allclose(p[1], pv.permp(counts[1], 60))


def test_module_preservation_adaptive_api(toy_pair_module, tmp_path):
    """adaptive=True through the public API: sequential p_type, per-module
    n_perm_used recorded, decisions match the fixed run, and the result
    round-trips through .npz and combine_analyses."""
    from netrep_tpu import module_preservation
    from netrep_tpu.data import pair_frames
    from netrep_tpu.models.results import (
        PreservationResult, combine_analyses,
    )

    d, t = pair_frames(toy_pair_module)
    kw = dict(
        network={"disc": d["network"], "test": t["network"]},
        data={"disc": d["data"], "test": t["data"]},
        correlation={"disc": d["correlation"], "test": t["correlation"]},
        module_assignments=dict(toy_pair_module["labels"]),
        discovery="disc", test="test", n_perm=600, seed=11,
        config=EngineConfig(chunk_size=64),
    )
    fixed = module_preservation(**kw)
    res = module_preservation(**kw, adaptive=True)
    assert res.p_type == "sequential"
    assert res.n_perm_used is not None and (res.n_perm_used >= 1).all()
    assert int(res.n_perm_used.sum()) < fixed.completed * len(res.module_labels)
    assert res.preserved_modules() == fixed.preserved_modules()
    assert "n_perm_used" in res.to_frame().columns
    np.testing.assert_array_equal(res.module_n_perm(), res.n_perm_used)
    assert (fixed.module_n_perm() == fixed.completed).all()

    path = str(tmp_path / "adaptive_result.npz")
    res.save(path)
    back = PreservationResult.load(path)
    assert back.p_type == "sequential"
    np.testing.assert_array_equal(back.n_perm_used, res.n_perm_used)
    np.testing.assert_array_equal(back.nulls, res.nulls)

    other = module_preservation(**{**kw, "seed": 12}, adaptive=True)
    comb = combine_analyses(res, other)
    assert comb.p_type == "sequential"
    np.testing.assert_array_equal(
        comb.n_perm_used,
        pv.effective_nperm(comb.nulls),
    )
    # pooled counts are the sum of the inputs' per-module counts
    np.testing.assert_array_equal(
        comb.n_perm_used, res.n_perm_used + other.n_perm_used
    )


def test_adaptive_rejects_native_backend(toy_pair_module):
    from netrep_tpu import module_preservation
    from netrep_tpu.data import pair_frames

    d, t = pair_frames(toy_pair_module)
    with pytest.raises(ValueError, match="adaptive=True requires"):
        module_preservation(
            network={"disc": d["network"], "test": t["network"]},
            correlation={"disc": d["correlation"],
                         "test": t["correlation"]},
            module_assignments=dict(toy_pair_module["labels"]),
            discovery="disc", test="test", n_perm=10,
            backend="native", adaptive=True,
        )


def test_multitest_adaptive_matches_fixed_decisions():
    """MultiTestEngine.run_null_adaptive: a module retires only when
    decided in every cohort; active rows match the fixed multitest run."""
    from netrep_tpu.parallel.multitest import MultiTestEngine

    mixed = make_mixed_pair(200, 4, n_samples=36, seed=5)
    (dd, dc, dn) = mixed["discovery"]
    (td, tc, tn) = mixed["test"]
    # second cohort: an independently-seeded test side, same node universe
    (td2, tc2, tn2) = make_mixed_pair(200, 4, n_samples=36, seed=6)["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    cfg = EngineConfig(chunk_size=64, summary_method="eigh")

    def make():
        return MultiTestEngine(
            dc, dn, dd, np.stack([tc, tc2]), np.stack([tn, tn2]),
            [td, td2], specs, mixed["pool"], config=cfg,
        )

    eng = make()
    observed = np.asarray(eng.observed())       # (2, K, 7)
    nulls_f, done_f = eng.run_null(600, key=0)
    nulls_a, done_a, finished = make().run_null_adaptive(
        600, observed, key=0
    )
    assert finished
    nulls_f, nulls_a = np.asarray(nulls_f), np.asarray(nulls_a)
    for ti in range(2):
        p_f = pv.permutation_pvalues(observed[ti], nulls_f[ti, :done_f])
        p_a, n_used = pv.sequential_pvalues(observed[ti],
                                            nulls_a[ti, :done_a])
        np.testing.assert_array_equal(
            np.nanmax(p_f, axis=1) < 0.05, np.nanmax(p_a, axis=1) < 0.05
        )
        for m, k in enumerate(n_used):
            np.testing.assert_allclose(
                nulls_a[ti, :k, m], nulls_f[ti, :k, m], rtol=0, atol=1e-12
            )
    total = pv.effective_nperm(
        np.moveaxis(nulls_a[:, :done_a], 0, 2).reshape(done_a, 4, -1)
    ).sum()
    assert total < done_f * 4  # strictly less work than fixed
