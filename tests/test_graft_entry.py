"""Driver-entry regression tests (VERDICT r2 item 1): both
``__graft_entry__`` functions must complete on a tunnel-less machine —
round 2's MULTICHIP artifact went red because ``dryrun_multichip`` dialed
the axon TPU plugin (which hangs, not errors, when the tunnel is down) for
a dryrun that needs zero TPU devices.

Each entry runs in a subprocess with the driver's hostile environment
(``JAX_PLATFORMS=axon``) reproduced, under a hard wall budget. A hang here
is exactly the round-2 failure mode; the subprocess kill turns it into a
test failure instead of a CI freeze.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The driver pins JAX_PLATFORMS=axon. Reproduce that; the entries must
# neutralize it themselves (the point of the test). Drop the conftest's
# cpu forcing for the child. The hang this guards against only reproduces
# where the axon plugin actually registers (sitecustomize requires
# /root/.axon_site on PYTHONPATH); pin that explicitly so the test doesn't
# silently degrade to a plain budget check on machines that happen to have
# the site but not the PYTHONPATH entry. Where the site is absent entirely,
# the tests still assert the entries complete within budget.
_AXON_SITE = "/root/.axon_site"
_DRIVER_ENV = {
    **os.environ,
    "JAX_PLATFORMS": "axon",
    "JAX_NUM_CPU_DEVICES": "8",
    # shrink entry()'s dead-tunnel probe from the driver-facing 90 s default
    # so the suite doesn't idle on a known-dead tunnel; the hang-detection
    # semantics are identical, only the budget changes
    "NETREP_BACKEND_PROBE_TIMEOUT": "25",
}
if os.path.isdir(_AXON_SITE) and _AXON_SITE not in _DRIVER_ENV.get("PYTHONPATH", ""):
    _DRIVER_ENV["PYTHONPATH"] = (
        _AXON_SITE + os.pathsep + _DRIVER_ENV.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)


def _run(code: str, timeout: float) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=_DRIVER_ENV,
        timeout=timeout,
        capture_output=True,
        text=True,
    )


@pytest.mark.slow
def test_dryrun_multichip_completes_within_budget():
    # 120 s wall budget per VERDICT r2 "Next round" item 1. The verified
    # fixed runtime is ~8 s; the budget absorbs cold XLA compiles.
    try:
        proc = _run(
            "import __graft_entry__ as g; g.dryrun_multichip(8); print('OK')",
            timeout=120,
        )
    except subprocess.TimeoutExpired as e:
        pytest.fail(
            f"dryrun_multichip(8) exceeded the 120 s wall budget (the "
            f"round-2 rc=124 hang): {e}"
        )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "OK" in proc.stdout


@pytest.mark.slow
def test_entry_compiles_within_budget():
    code = (
        "import __graft_entry__ as g\n"
        "import jax\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "jax.block_until_ready(out)\n"
        "print('OK')\n"
    )
    try:
        proc = _run(code, timeout=240)
    except subprocess.TimeoutExpired as e:
        pytest.fail(f"entry() compile check hung past its wall budget: {e}")
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "OK" in proc.stdout
