"""R-shim parity (VERDICT r1 item 6, SURVEY.md §7 step 7): r/netrep_tpu.R
preserves the reference's argument names and defaults; these tests parse the
stub and enforce that every mapped Python parameter exists with matching
defaults, so the spec cannot drift from the live signatures."""

import inspect
import os
import re

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
R_FILE = os.path.join(ROOT, "r", "netrep_tpu.R")


def _r_source():
    return open(R_FILE).read()


def _parse_r_list(body):
    out = {}
    for rname, pyname in re.findall(r"(\w+)\s*=\s*\"([\w.]+)\"", body):
        out[rname] = pyname
    assert out
    return out


def _mapping(name):
    """Parse `.name_args <- list(rName = "py_name", ...)` from the stub.
    One level of indirection is followed: `.name_args <- .sharedVar` looks
    up `.sharedVar <- list(...)` (the panel plots share one map)."""
    alias = re.search(
        rf"\.{name}_args\s*<-\s*(\.\w+)\s*\n", _r_source()
    )
    if alias:
        shared = re.escape(alias.group(1))
        m = re.search(
            rf"{shared}\s*<-\s*list\((.*?)\)\s*\n", _r_source(), flags=re.S
        )
        assert m, f"shared map {alias.group(1)} not found in r/netrep_tpu.R"
        return _parse_r_list(m.group(1))
    m = re.search(
        rf"\.{name}_args\s*<-\s*list\((.*?)\)\s*\n", _r_source(), flags=re.S
    )
    assert m, f".{name}_args list not found in r/netrep_tpu.R"
    return _parse_r_list(m.group(1))


def _r_defaults(fn_name):
    """Parse the R function's argument defaults."""
    m = re.search(
        rf"^{fn_name}\s*<-\s*function\((.*?)\)\s*\{{",
        _r_source(), flags=re.S | re.M,
    )
    assert m, f"{fn_name} not found in r/netrep_tpu.R"
    args = {}
    for part in re.split(r",(?![^()]*\))", m.group(1)):
        part = part.strip()
        if not part or part == "...":
            continue
        if "=" in part:
            k, v = part.split("=", 1)
            args[k.strip()] = v.strip()
        else:
            args[part] = None  # required, no default
    return args


_R_TO_PY = {"NULL": None, "TRUE": True, "FALSE": False}


def _as_py(r_default):
    if r_default is None:
        return inspect.Parameter.empty
    if r_default in _R_TO_PY:
        return _R_TO_PY[r_default]
    if r_default.startswith('"'):
        return r_default.strip('"')
    if re.fullmatch(r"\d+L?", r_default):
        return int(r_default.rstrip("L"))
    if re.fullmatch(r"[\d.]+", r_default):
        return float(r_default)
    pytest.fail(f"unparsed R default: {r_default}")


CASES = [
    ("modulePreservation", "netrep_tpu.models.preservation",
     "module_preservation"),
    ("networkProperties", "netrep_tpu.models.properties",
     "network_properties"),
    ("requiredPerms", "netrep_tpu.ops.pvalues", "required_perms"),
    ("plotModule", "netrep_tpu.plot", "plot_module"),
    ("plotData", "netrep_tpu.plot", "plot_data"),
    ("plotCorrelation", "netrep_tpu.plot", "plot_correlation"),
    ("plotNetwork", "netrep_tpu.plot", "plot_network"),
    ("plotContribution", "netrep_tpu.plot", "plot_contribution"),
    ("plotDegree", "netrep_tpu.plot", "plot_degree"),
    ("nodeOrder", "netrep_tpu.plot", "node_order"),
    ("sampleOrder", "netrep_tpu.plot", "sample_order"),
]


@pytest.mark.parametrize("r_name,module,py_name", CASES)
def test_mapped_args_exist_with_matching_defaults(r_name, module, py_name):
    import importlib

    py_fn = getattr(importlib.import_module(module), py_name)
    sig = inspect.signature(py_fn)
    mapping = _mapping(r_name)
    r_defaults = _r_defaults(r_name)

    # every R argument is mapped, and every mapped target is a real parameter
    assert set(r_defaults) == set(mapping), (
        f"{r_name}: R signature args {sorted(r_defaults)} != mapped "
        f"args {sorted(mapping)}"
    )
    for rname, pyname in mapping.items():
        assert pyname in sig.parameters, (
            f"{r_name}.{rname} maps to {py_name}.{pyname}, which does not "
            "exist"
        )
        want = _as_py(r_defaults[rname])
        got = sig.parameters[pyname].default
        assert got == want or (got is inspect.Parameter.empty) == (
            want is inspect.Parameter.empty
        ) and got == want, (
            f"{r_name}.{rname} default {want!r} != {py_name}.{pyname} "
            f"default {got!r}"
        )


def test_reference_surface_is_complete():
    """The four reference entry points (SURVEY.md §2.1) all have shim
    functions and docs/r-shim.md documents each."""
    src = _r_source()
    doc = open(os.path.join(ROOT, "docs", "r-shim.md")).read()
    for fn in ("modulePreservation", "networkProperties", "requiredPerms",
               "plotModule", "plotData", "plotCorrelation", "plotNetwork",
               "plotContribution", "plotDegree", "combineAnalyses",
               "nodeOrder", "sampleOrder"):
        assert re.search(rf"^{fn}\s*<-\s*function", src, flags=re.M), fn
        assert fn in doc, f"{fn} undocumented in docs/r-shim.md"


def test_combine_analyses_shim_override():
    """combineAnalyses takes two positional results (the Python side is
    variadic, so no positional mapping exists) plus the camelCase override,
    which must map onto a real keyword with a matching default."""
    from netrep_tpu.models.results import combine_analyses

    assert _mapping("combineAnalyses") == {
        "allowDuplicateNulls": "allow_duplicate_nulls"
    }
    r_defaults = _r_defaults("combineAnalyses")
    assert list(r_defaults) == ["analysis1", "analysis2", "allowDuplicateNulls"]
    assert r_defaults["allowDuplicateNulls"] == "FALSE"
    p = inspect.signature(combine_analyses).parameters["allow_duplicate_nulls"]
    assert p.kind is inspect.Parameter.KEYWORD_ONLY and p.default is False


def test_reference_argument_names_preserved():
    """The reference's documented modulePreservation argument list
    (SURVEY.md §2.1) appears verbatim in the shim."""
    reference_args = [
        "network", "data", "correlation", "moduleAssignments", "modules",
        "backgroundLabel", "discovery", "test", "selfPreservation",
        "nThreads", "nPerm", "null", "alternative", "simplify", "verbose",
    ]
    assert list(_mapping("modulePreservation")) == reference_args
