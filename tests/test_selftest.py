"""netrep_tpu.selftest: the on-device numerical sanity check must pass on
a healthy backend and FAIL LOUDLY when device math diverges from the
oracle — a selftest that cannot fail is worse than none."""

import numpy as np
import pytest

import netrep_tpu


def test_selftest_passes_on_cpu(capsys):
    out = netrep_tpu.selftest(n_perm=8, verbose=True)
    assert out["ok"] and out["backend"] == "cpu"
    # CPU is the oracle-exactness tier: deviations are float32 rounding,
    # far under the cross-device tolerance
    assert out["observed_max_abs_dev"] < 1e-4
    assert out["null_reconstruction_max_abs_dev"] < 1e-4
    assert "selftest OK" in capsys.readouterr().out


def test_selftest_catches_small_regression_on_cpu(monkeypatch):
    """Backend-conditional tolerance (VERDICT r4 item 8): on CPU the bound
    is ~1e-4, so a 1e-3 device-math regression — which the old uniform
    2e-2 MXU-sized bound waved through — must now fail."""
    from netrep_tpu.parallel.engine import PermutationEngine

    orig = PermutationEngine.observed
    monkeypatch.setattr(
        PermutationEngine, "observed",
        lambda self: np.asarray(orig(self)) + 1e-3,
    )
    with pytest.raises(RuntimeError, match="observed statistics deviate"):
        netrep_tpu.selftest(n_perm=8, verbose=False)


def test_selftest_runs_multiple_shapes():
    out = netrep_tpu.selftest(n_perm=8, verbose=False)
    assert out["n_shapes"] >= 2
    assert out["atol"] == 1e-4  # CPU tier


def test_tolerance_tier_table():
    """ISSUE 12 closes the ADVICE r5 hole for good: the loose ~2e-2 MXU
    tier is keyed to backends KNOWN to truncate f32 matmuls to bf16
    (tpu, and the axon tunnel — the same MXU behind a gRPC dial);
    everything else — cuda, rocm, cpu, and any accelerator this table
    has never seen — gets the tight 1e-4 exact-f32 tier, so a 100×
    GPU-math regression cannot wave through under hardware-rounding
    headroom. A genuinely truncating new backend fails loudly and is
    added here deliberately."""
    from netrep_tpu.utils.selftest import (
        _ATOL_EXACT, _ATOL_MXU, _TRUNCATING_BACKENDS, tolerance_for,
    )

    assert _TRUNCATING_BACKENDS == ("tpu", "axon")
    assert _ATOL_MXU == 2e-2 and _ATOL_EXACT == 1e-4
    for backend in _TRUNCATING_BACKENDS:
        assert tolerance_for(backend) == _ATOL_MXU
    for backend in ("cpu", "cuda", "rocm", "gpu", "some_future_npu"):
        assert tolerance_for(backend) == _ATOL_EXACT


def test_selftest_max_shapes_bounds_work():
    """The watcher's on-chip gate runs max_shapes=1 to fit a short tunnel
    window; the bound must actually limit the shapes executed."""
    out = netrep_tpu.selftest(n_perm=8, verbose=False, max_shapes=1)
    assert out["ok"] and out["n_shapes"] == 1


def test_selftest_detects_wrong_observed(monkeypatch):
    from netrep_tpu.parallel.engine import PermutationEngine

    orig = PermutationEngine.observed
    monkeypatch.setattr(
        PermutationEngine, "observed",
        lambda self: np.asarray(orig(self)) + 0.1,
    )
    with pytest.raises(RuntimeError, match="observed statistics deviate"):
        netrep_tpu.selftest(n_perm=8, verbose=False)


def test_selftest_detects_nan_observed(monkeypatch):
    """A NaN in one observed statistic must fail the selftest — nanmax
    would silently skip it (review-caught hole)."""
    from netrep_tpu.parallel.engine import PermutationEngine

    orig = PermutationEngine.observed

    def nan_one(self):
        o = np.asarray(orig(self)).copy()
        o[0, 0] = np.nan
        return o

    monkeypatch.setattr(PermutationEngine, "observed", nan_one)
    with pytest.raises(RuntimeError, match="non-finite"):
        netrep_tpu.selftest(n_perm=8, verbose=False)


def test_selftest_detects_wrong_null(monkeypatch):
    from netrep_tpu.parallel.engine import PermutationEngine

    orig = PermutationEngine.run_null

    def bad(self, n_perm, key=0, **kw):
        nulls, done = orig(self, n_perm, key=key, **kw)
        return np.asarray(nulls) + 0.1, done

    monkeypatch.setattr(PermutationEngine, "run_null", bad)
    with pytest.raises(RuntimeError, match="deviates from the oracle"):
        netrep_tpu.selftest(n_perm=8, verbose=False)


def test_selftest_rejects_degenerate_n_perm():
    with pytest.raises(ValueError, match="n_perm must be >= 1"):
        netrep_tpu.selftest(n_perm=0)


@pytest.mark.slow
def test_selftest_on_perm_mesh():
    """mesh=: the sharded null (perm axis) must pass the same oracle
    cross-check — the deployment story for validating a pod's collective
    path before a large run.

    Slow tier (ISSUE 15 wall-clock satellite): perm-axis null parity is
    pinned by test_sharding/test_distributed, and the harder row-sharded
    selftest battery stays tier-1 — this full extra battery re-proves
    their composition."""
    import jax

    mesh = netrep_tpu.make_mesh()
    out = netrep_tpu.selftest(n_perm=8, verbose=False, mesh=mesh)
    assert out["ok"] and out["mesh"] == {"perm": len(jax.devices()), "row": 1}
    assert out["null_reconstruction_max_abs_dev"] < 1e-4


def test_selftest_on_row_sharded_mesh():
    """mesh= with row shards: collective module gathers (psum assembly)
    validate against the oracle too."""
    import jax

    n_dev = len(jax.devices())
    if n_dev < 4:
        pytest.skip("needs >= 4 virtual devices")
    mesh = netrep_tpu.make_mesh(n_perm_shards=n_dev // 2, n_row_shards=2)
    out = netrep_tpu.selftest(n_perm=8, verbose=False, mesh=mesh)
    assert out["ok"] and out["mesh"]["row"] == 2
    # on the virtual CPU mesh the collective assembly is f32-rounding
    # exact: pin the row-sharded path as tightly as the perm-mesh path
    assert out["null_reconstruction_max_abs_dev"] < 1e-4
