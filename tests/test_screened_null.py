"""Mixed-precision null screening (ISSUE 16) — the bf16 fast pass with
exact f32 rescue must be *bit-identical by construction* to the all-f32
loops: decided exceedance comparisons carry a forward-error cushion wider
than any bf16-rounding drift, and every ambiguous permutation re-runs
through the unchanged f32 chunk program. Pinned here on CPU (bf16
rounding is emulated in-program, so the screen's decisions are the real
TPU decisions): counts/p-values/retirement parity in all four null modes,
the checkpoint fingerprint + RescueState round-trip, the perm-mesh
shard_map case, the per-run precision resolution ladder, and the
telemetry envelope (``rescue_dispatch`` / ``null_pass_end``).

Two fixture regimes exercise both screen outcomes (both proven necessary:
the toy pair's null bulk overlaps its observed values, so nearly every
permutation is ambiguous → rescued; shifting the screened observed by
+0.5 separates them, so most rows decide in bf16):
  * engine-computed observed  → rescue-dominant path
  * observed + 0.5            → decided-dominant path
"""

import json

import numpy as np
import pytest

from netrep_tpu.data import make_mixed_pair
from netrep_tpu.ops import pvalues as pv
from netrep_tpu.parallel import mesh as meshmod
from netrep_tpu.parallel import screened as scr
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils.config import EngineConfig
from netrep_tpu.utils.telemetry import Telemetry

CFG_F32 = EngineConfig(chunk_size=64, summary_method="eigh", superchunk=3,
                       autotune=False)
# explicit bf16_rescue: 'auto' resolves to f32 on the CPU backend these
# tests run on — the explicit setting is the portable way to engage the
# screen (the rounding is applied in-program, so CPU decisions are the
# TPU decisions)
CFG_BF16 = EngineConfig(chunk_size=64, summary_method="eigh", superchunk=3,
                        autotune=False, null_precision="bf16_rescue")
N_PERM = 300


@pytest.fixture(scope="module")
def mixed():
    return make_mixed_pair(320, 6, n_samples=40, seed=7)


def _engine(mixed, config=CFG_F32, mesh=None):
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    return PermutationEngine(
        dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=config,
        mesh=mesh,
    )


@pytest.fixture(scope="module")
def ref(mixed):
    """The f32 ground truth both screen regimes are pinned against."""
    eng = _engine(mixed)
    observed = np.asarray(eng.observed())
    nulls, done = eng.run_null(N_PERM, key=0)
    assert done == N_PERM
    return dict(observed=observed, nulls=np.asarray(nulls))


def _counts(obs, nulls):
    return pv.tail_counts(obs, nulls)


# ---------------------------------------------------------------------------
# materialized
# ---------------------------------------------------------------------------

def test_materialized_counts_bit_identical(mixed, ref):
    """Rescue-dominant regime: same key, screened loop — identical
    exceedance counts and Phipson–Smyth p-values."""
    obs = ref["observed"]
    nulls, done = _engine(mixed, CFG_BF16).run_null(
        N_PERM, key=0, observed=obs
    )
    assert done == N_PERM
    for a, b in zip(_counts(obs, ref["nulls"]), _counts(obs, nulls)):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(
        pv.permutation_pvalues(obs, ref["nulls"], "greater"),
        pv.permutation_pvalues(obs, np.asarray(nulls), "greater"),
    )


def test_materialized_decided_rows_stay_exact(mixed, ref):
    """Decided-dominant regime (observed + 0.5 clears the null bulk):
    decided rows carry bf16-screened values — the stored nulls genuinely
    differ from f32 — yet every comparison against the screened observed
    is identical (the cushion guarantee)."""
    obs = ref["observed"] + 0.5
    nulls, done = _engine(mixed, CFG_BF16).run_null(
        N_PERM, key=0, observed=obs
    )
    assert done == N_PERM
    nulls = np.asarray(nulls)
    # the screen decided rows in bf16 (not a silent all-rescue run)
    assert not np.array_equal(nulls, ref["nulls"])
    for a, b in zip(_counts(obs, ref["nulls"]), _counts(obs, nulls)):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# streaming superchunks
# ---------------------------------------------------------------------------

def test_streaming_tallies_bit_identical(mixed, ref):
    obs = ref["observed"]
    f32 = _engine(mixed).run_null_streaming(N_PERM, obs, key=0)
    bf16 = _engine(mixed, CFG_BF16).run_null_streaming(N_PERM, obs, key=0)
    assert bf16.completed == N_PERM
    np.testing.assert_array_equal(bf16.hi, f32.hi)
    np.testing.assert_array_equal(bf16.lo, f32.lo)
    np.testing.assert_array_equal(bf16.eff, f32.eff)
    # and both equal the materialized ground truth
    hi, lo, eff = _counts(obs, ref["nulls"])
    np.testing.assert_array_equal(bf16.hi, hi)
    np.testing.assert_array_equal(bf16.lo, lo)
    np.testing.assert_array_equal(bf16.eff, eff)


def test_streaming_decided_rows_stay_exact(mixed, ref):
    obs = ref["observed"] + 0.5
    f32 = _engine(mixed).run_null_streaming(N_PERM, obs, key=0)
    bf16 = _engine(mixed, CFG_BF16).run_null_streaming(N_PERM, obs, key=0)
    np.testing.assert_array_equal(bf16.hi, f32.hi)
    np.testing.assert_array_equal(bf16.lo, f32.lo)
    np.testing.assert_array_equal(bf16.eff, f32.eff)


# ---------------------------------------------------------------------------
# adaptive (materialized + streaming)
# ---------------------------------------------------------------------------

def test_adaptive_retirement_bit_identical(mixed):
    eng = _engine(mixed)
    obs = np.asarray(eng.observed())
    ref_nulls, ref_done, ref_fin = eng.run_null_adaptive(1200, obs, key=3)
    nulls, done, fin = _engine(mixed, CFG_BF16).run_null_adaptive(
        1200, obs, key=3
    )
    assert (done, fin) == (ref_done, ref_fin)
    ref_nulls, nulls = np.asarray(ref_nulls), np.asarray(nulls)
    # retirement pattern (NaN rows) identical per module and statistic
    np.testing.assert_array_equal(np.isnan(nulls), np.isnan(ref_nulls))
    for a, b in zip(_counts(obs, ref_nulls), _counts(obs, nulls)):
        np.testing.assert_array_equal(a, b)


def test_adaptive_streaming_bit_identical(mixed):
    eng = _engine(mixed)
    obs = np.asarray(eng.observed())
    f32 = eng.run_null_adaptive_streaming(1200, obs, key=3)
    bf16 = _engine(mixed, CFG_BF16).run_null_adaptive_streaming(
        1200, obs, key=3
    )
    assert bf16.completed == f32.completed
    np.testing.assert_array_equal(bf16.hi, f32.hi)
    np.testing.assert_array_equal(bf16.lo, f32.lo)
    np.testing.assert_array_equal(bf16.eff, f32.eff)
    np.testing.assert_array_equal(bf16.n_perm_used, f32.n_perm_used)


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------

def _interrupt_after(n):
    seen = []

    def cb(done, total):
        seen.append(done)
        if len(seen) == n:
            raise KeyboardInterrupt

    return cb


def test_streaming_checkpoint_resume_screened(mixed, ref, tmp_path):
    """A screened run interrupted mid-stream resumes to the uninterrupted
    (= f32) tallies: the checkpoint fingerprint is precision-namespaced
    and the RescueState tally rides the extras, so the resumed run's
    accounting includes the pre-interrupt rescues."""
    obs = ref["observed"]
    ck = str(tmp_path / "screened.npz")
    part = _engine(mixed, CFG_BF16).run_null_streaming(
        N_PERM, obs, key=0, progress=_interrupt_after(1),
        checkpoint_path=ck, checkpoint_every=64,
    )
    assert 0 < part.completed < N_PERM
    # an f32 engine must refuse the screened checkpoint (and never
    # silently continue without the screen): precision is part of the
    # resume fingerprint
    with pytest.raises(ValueError):
        _engine(mixed).run_null_streaming(
            N_PERM, obs, key=0, checkpoint_path=ck, checkpoint_every=64,
        )
    fin = _engine(mixed, CFG_BF16).run_null_streaming(
        N_PERM, obs, key=0, checkpoint_path=ck, checkpoint_every=64,
    )
    assert fin.completed == N_PERM
    hi, lo, eff = _counts(obs, ref["nulls"])
    np.testing.assert_array_equal(fin.hi, hi)
    np.testing.assert_array_equal(fin.lo, lo)
    np.testing.assert_array_equal(fin.eff, eff)


def test_rescue_state_round_trip():
    st = scr.RescueState()
    st.total, st.rescued, st.dispatches = 640, 17, 3
    extras = st.state_arrays()
    st2 = scr.RescueState()
    st2.restore_state(extras)
    assert (st2.total, st2.rescued, st2.dispatches) == (640, 17, 3)
    assert st2.fraction() == pytest.approx(17 / 640)


# ---------------------------------------------------------------------------
# perm-mesh shard_map
# ---------------------------------------------------------------------------

def test_perm_mesh_counts_bit_identical(mixed, ref):
    """The screened programs under the perm-axis mesh (virtual 8-device
    CPU mesh from conftest): same counts as the single-device f32 run —
    the screen shards with the chunk and the rescue gathers global
    worklists."""
    obs = ref["observed"]
    mesh = meshmod.make_mesh()
    eng = _engine(mixed, CFG_BF16, mesh=mesh)
    nulls, done = eng.run_null(N_PERM, key=0, observed=obs)
    assert done == N_PERM
    for a, b in zip(_counts(obs, ref["nulls"]),
                    _counts(obs, np.asarray(nulls))):
        np.testing.assert_array_equal(a, b)
    stream = _engine(mixed, CFG_BF16, mesh=mesh).run_null_streaming(
        N_PERM, obs, key=0
    )
    hi, lo, eff = _counts(obs, ref["nulls"])
    np.testing.assert_array_equal(stream.hi, hi)
    np.testing.assert_array_equal(stream.lo, lo)
    np.testing.assert_array_equal(stream.eff, eff)


# ---------------------------------------------------------------------------
# precision resolution ladder + init validation
# ---------------------------------------------------------------------------

def test_resolution_ladder(mixed, ref):
    obs = ref["observed"]
    # 'auto' resolves per backend: screen on TPU-class, f32 elsewhere
    assert CFG_F32.resolved_null_precision("tpu") == "bf16_rescue"
    assert CFG_F32.resolved_null_precision("cpu") == "f32"
    assert CFG_BF16.resolved_null_precision("cpu") == "bf16_rescue"
    # per-run ladder on the explicit engine
    eng = _engine(mixed, CFG_BF16)
    assert eng._resolve_null_precision(obs) == "bf16_rescue"
    # explicit bf16_rescue without observed is a caller error, not a
    # silent f32 downgrade
    with pytest.raises(ValueError, match="observed"):
        eng._resolve_null_precision(None)
    # non-single-test cell shapes (packed serve monitors) stay f32
    assert eng._resolve_null_precision(np.zeros((3, 7))) == "f32"
    # 'auto' without observed runs f32 quietly
    assert _engine(mixed)._resolve_null_precision(None) == "f32"


def test_init_refuses_unscreenable_paths(mixed):
    cfg = EngineConfig(chunk_size=64, summary_method="eigh",
                       autotune=False, null_precision="bf16_rescue",
                       gather_mode="fused")
    with pytest.raises(ValueError, match="fused"):
        _engine(mixed, cfg)
    cfg = EngineConfig(chunk_size=64, summary_method="power",
                       power_iters=40, autotune=False,
                       null_precision="bf16_rescue", stat_mode="fused")
    with pytest.raises(ValueError, match="fused"):
        _engine(mixed, cfg)
    cfg = EngineConfig(chunk_size=64, summary_method="eigh",
                       autotune=False, null_precision="bf16_rescue",
                       matrix_sharding="row")
    with pytest.raises(ValueError, match="row"):
        _engine(mixed, cfg, mesh=meshmod.make_mesh(n_row_shards=4))


def test_autotune_key_is_precision_suffixed(mixed):
    """Screened and f32 throughput histories must never mix: the
    autotune key carries the precision while the screen is active."""
    eng = _engine(mixed, CFG_BF16)
    base = eng.autotune_key(extra="superchunk")
    eng._screen_active = True
    try:
        screened = eng.autotune_key(extra="superchunk")
    finally:
        eng._screen_active = False
    assert screened != base
    assert "bf16rescue" in screened


# ---------------------------------------------------------------------------
# telemetry envelope
# ---------------------------------------------------------------------------

def test_telemetry_rescue_events(mixed, ref, tmp_path):
    from netrep_tpu.utils import telemetry as tm

    assert {"rescue_dispatch", "null_pass_end", "tail_fit"} <= set(
        tm.KNOWN_EVENTS
    )
    path = str(tmp_path / "tel.jsonl")
    tel = Telemetry(path)
    try:
        _engine(mixed, CFG_BF16).run_null(
            N_PERM, key=0, observed=ref["observed"], telemetry=tel
        )
    finally:
        tel.close()
    events = [json.loads(l) for l in open(path)]
    ends = [e for e in events if e["ev"] == "null_pass_end"]
    assert len(ends) == 1
    d = ends[0]["data"]
    assert d["mode"] == "materialized"
    assert d["precision"] == "bf16_rescue"
    assert 0.0 <= d["fraction"] <= 1.0
    assert d["total"] >= N_PERM and d["rescued"] <= d["total"]
    rescues = [e for e in events if e["ev"] == "rescue_dispatch"]
    # rescue-dominant fixture: the worklist genuinely dispatched
    assert rescues and d["rescue_dispatches"] == len(rescues)
    assert all(e["data"]["rescued"] >= 1 for e in rescues)


# ---------------------------------------------------------------------------
# screened.py units
# ---------------------------------------------------------------------------

def test_cushion_bounds_bf16_drift():
    """The per-cell cushion dominates the worst-case forward error of
    bf16-rounding the operands: statistics recomputed from rounded
    operands stay inside the cushion band, so a decided comparison can
    never flip against exact f32."""
    rng = np.random.default_rng(0)
    obs = rng.standard_normal((4, 7))
    cush = scr.null_cushions(obs, operand_amp=2.0)
    assert (cush >= scr.CUSHION_FLOOR).all()
    # scales with amplitude and |observed|
    big = scr.null_cushions(obs * 100, operand_amp=2.0)
    assert (big >= cush).all()
    amp = scr.null_cushions(obs, operand_amp=20.0)
    assert (amp >= cush).all()


def test_ambiguous_perms_masks_band_only():
    obs = np.zeros((2, 7), np.float32)
    cush = np.full((2, 7), 0.1, np.float32)
    import jax.numpy as jnp

    outs = [jnp.asarray(np.array(
        [[[0.5] * 7, [-0.5] * 7],      # clearly decided both modules
         [[0.05] * 7, [0.5] * 7]],     # ambiguous in module 0
        np.float32))]
    amb = np.asarray(scr.ambiguous_perms(
        outs, [jnp.asarray(obs)], [jnp.asarray(cush)]
    ))
    np.testing.assert_array_equal(amb, [False, True])


def test_pad_worklist_and_host_tail_counts():
    idx = np.array([3, 9], np.int32)
    pad = np.asarray(scr.pad_worklist(idx, 8))
    assert pad.shape == (8,)
    np.testing.assert_array_equal(pad[:2], idx)
    obs = np.array([[0.0, np.nan]], np.float64)
    vals = np.array([[[1.0, 1.0]], [[-1.0, np.nan]]], np.float32)
    hi, lo, eff = scr.host_tail_counts(vals, obs)
    np.testing.assert_array_equal(hi, [[1, 0]])   # NaN obs never exceeds
    np.testing.assert_array_equal(lo, [[1, 0]])
    np.testing.assert_array_equal(eff, [[2, 1]])  # NaN draw drops from eff


# ---------------------------------------------------------------------------
# preservation end-to-end + GPD tail persistence
# ---------------------------------------------------------------------------

def test_preservation_pvalues_bit_identical(toy_pair_module, tmp_path):
    """module_preservation with null_precision='bf16_rescue' returns the
    exact f32 p-values (counts identity end-to-end through the model
    layer), and the GPD tail columns computed on it round-trip through
    save/load and to_frame."""
    from netrep_tpu import module_preservation
    from netrep_tpu.data import pair_frames
    from netrep_tpu.models.results import PreservationResult

    d, t = pair_frames(toy_pair_module)
    kwargs = dict(
        network={"disc": d["network"], "test": t["network"]},
        data={"disc": d["data"], "test": t["data"]},
        correlation={"disc": d["correlation"], "test": t["correlation"]},
        module_assignments=toy_pair_module["labels"],
        discovery="disc", test="test", n_perm=96, seed=5,
    )
    base = EngineConfig(chunk_size=32, summary_method="eigh",
                        autotune=False)
    res_f32 = module_preservation(
        **kwargs, config=base
    )
    res_bf16 = module_preservation(
        **kwargs,
        config=EngineConfig(chunk_size=32, summary_method="eigh",
                            autotune=False,
                            null_precision="bf16_rescue"),
    )
    np.testing.assert_array_equal(res_f32.p_values, res_bf16.p_values)

    # ISSUE 17 satellite (the ISSUE 16 caveat): counts are exact but the
    # screened run's STORED null values are bf16-rounded for decided
    # permutations — the GPD tail fit must refuse them, before and after
    # a save/load round-trip (the flag is persisted meta)
    assert res_bf16.nulls_exact is False and res_f32.nulls_exact is True
    with pytest.raises(ValueError, match="bf16"):
        res_bf16.tail_pvalues()
    bpath = str(tmp_path / "res_bf16.npz")
    res_bf16.save(bpath)
    with pytest.raises(ValueError, match="null_precision='f32'"):
        PreservationResult.load(bpath).tail_pvalues()

    p_tail, tail_ok = res_f32.tail_pvalues()
    assert p_tail.shape == res_f32.p_values.shape
    assert tail_ok.dtype == bool
    assert np.isnan(p_tail[~tail_ok]).all()
    path = str(tmp_path / "res.npz")
    res_f32.save(path)
    loaded = PreservationResult.load(path)
    np.testing.assert_array_equal(loaded.p_tail, p_tail)
    np.testing.assert_array_equal(loaded.tail_ok, tail_ok)
    try:
        frame = loaded.to_frame()
    except ImportError:
        pytest.skip("pandas not installed")
    assert "p_tail" in frame.columns and "tail_ok" in frame.columns
