"""Plot-suite tests (SURVEY.md §2.1 "Plot suite", §3.3): panel composition,
node/sample ordering semantics, data-less variant, and per-panel functions.
Rendering is validated structurally (axes, artists, saved bytes) — visual
regression is out of scope, matching the reference's own test strategy
(plots are exercised, not pixel-compared)."""

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np
import pytest

from netrep_tpu import plot as nplot
from netrep_tpu.data import load_example
from netrep_tpu.ops import oracle


@pytest.fixture(scope="module")
def ex():
    return load_example(seed=5)


def _inputs(ex, with_data=True):
    kw = dict(
        network={"d": ex["discovery_network"], "t": ex["test_network"]},
        correlation={"d": ex["discovery_correlation"], "t": ex["test_correlation"]},
        module_assignments={"d": {nm: ex["module_labels"].get(nm, "0")
                                  for nm in ex["discovery_names"]}},
    )
    # ndarray inputs carry no names → attach via pandas for alignment
    import pandas as pd

    def df(m, names):
        return pd.DataFrame(m, index=names, columns=names)

    kw["network"] = {"d": df(ex["discovery_network"], ex["discovery_names"]),
                     "t": df(ex["test_network"], ex["test_names"])}
    kw["correlation"] = {"d": df(ex["discovery_correlation"], ex["discovery_names"]),
                         "t": df(ex["test_correlation"], ex["test_names"])}
    if with_data:
        kw["data"] = {"d": pd.DataFrame(ex["discovery_data"], columns=ex["discovery_names"]),
                      "t": pd.DataFrame(ex["test_data"], columns=ex["test_names"])}
    return kw


def test_plot_module_composite(ex, tmp_path):
    fig, axes = nplot.plot_module(
        **_inputs(ex), discovery="d", test="t", modules=["1", "2"],
    )
    assert set(axes) == {"data", "summary", "correlation", "network",
                        "contribution", "degree"}
    out = tmp_path / "module.png"
    fig.savefig(out, dpi=60)
    assert out.stat().st_size > 10_000
    plt.close(fig)


def test_plot_module_dataless(ex):
    kw = _inputs(ex, with_data=False)
    fig, axes = nplot.plot_module(**kw, discovery="d", test="t", modules=["1"])
    assert set(axes) == {"correlation", "network", "degree"}
    assert "data" not in axes
    plt.close(fig)


def test_node_order_is_discovery_degree(ex):
    """Default ordering: within each module, nodes sorted by *discovery*
    weighted degree, descending (SURVEY.md §3.3)."""
    layout = nplot._prepare(
        **_inputs(ex), discovery="d", test="t", modules=["1"],
    )
    dn = ex["discovery_names"]
    dmat = ex["discovery_network"]
    mod_nodes = [nm for nm in dn if ex["module_labels"][nm] == "1"]
    tset = set(ex["test_names"])
    present = [nm for nm in mod_nodes if nm in tset]
    didx = [dn.index(nm) for nm in present]
    deg = oracle.weighted_degree(dmat[np.ix_(didx, didx)])
    expect = [present[i] for i in np.argsort(-deg, kind="stable")]
    assert layout.node_names == expect


def test_input_order_when_none(ex):
    layout = nplot._prepare(
        **_inputs(ex), discovery="d", test="t", modules=["1"],
        order_nodes_by=None,
    )
    # input (test-dataset) order preserved within the module
    tpos = {nm: i for i, nm in enumerate(ex["test_names"])}
    idx = [tpos[nm] for nm in layout.node_names]
    # node_idx should follow discovery-module listing order, not sorted degree
    assert list(layout.node_idx) == idx


def test_per_panel_functions(ex):
    kw = _inputs(ex)
    for fn in (nplot.plot_correlation, nplot.plot_network, nplot.plot_degree):
        ax = fn(kw["network"], kw.get("data"), kw["correlation"],
                kw["module_assignments"], discovery="d", test="t",
                modules=["1"])
        assert ax.figure is not None
        plt.close(ax.figure)
    for fn in (nplot.plot_data, nplot.plot_contribution, nplot.plot_summary):
        ax = fn(kw["network"], kw["data"], kw["correlation"],
                kw["module_assignments"], discovery="d", test="t",
                modules=["1"])
        assert ax.figure is not None
        plt.close(ax.figure)


def test_dataless_data_panel_raises(ex):
    kw = _inputs(ex, with_data=False)
    with pytest.raises(ValueError, match="no data matrix"):
        nplot.plot_data(kw["network"], None, kw["correlation"],
                        kw["module_assignments"], discovery="d", test="t")


def test_bad_order_dataset_raises(ex):
    with pytest.raises(ValueError, match="order_nodes_by"):
        nplot._prepare(**_inputs(ex), discovery="d", test="t",
                       order_nodes_by="nope")


def test_plot_module_sparse():
    """Sparse composite plot: densifies only the module subgraph and reuses
    the dense panel stack (Config E visualization)."""
    from netrep_tpu.ops.sparse import SparseAdjacency
    from netrep_tpu.plot import plot_module_sparse

    r = np.random.default_rng(3)
    n, k = 60, 5
    x = r.standard_normal((20, n))
    x[:, :12] += 1.1 * r.standard_normal(20)[:, None]
    aff = np.abs(np.corrcoef(x, rowvar=False))
    np.fill_diagonal(aff, 0.0)
    rows = np.repeat(np.arange(n), k)
    cols = np.argsort(aff, axis=1)[:, -k:].ravel()
    adj = SparseAdjacency.from_coo(rows, cols, aff[rows, cols], n)
    labels = ["M1"] * 12 + ["M2"] * 8 + ["0"] * (n - 20)

    fig, axes = plot_module_sparse(
        adj, data=x, module_assignments=labels, modules=["M1"],
    )
    assert set(axes) >= {"data", "correlation", "network", "degree"}
    plt.close(fig)

    # data-less with a precomputed sparse correlation
    c = np.corrcoef(x, rowvar=False)
    cg = SparseAdjacency.from_coo(rows, cols, c[rows, cols], n)
    fig2, axes2 = plot_module_sparse(
        adj, correlation=cg, module_assignments=labels,
    )
    assert "data" not in axes2 and "correlation" in axes2
    plt.close(fig2)

    with pytest.raises(ValueError, match="data= and/or correlation="):
        plot_module_sparse(adj, module_assignments=labels)
    with pytest.raises(ValueError, match="max_nodes"):
        plot_module_sparse(adj, data=x, module_assignments=labels,
                           max_nodes=5)
    with pytest.raises(TypeError, match="SparseAdjacency"):
        plot_module_sparse(adj.to_dense(), data=x,
                           module_assignments=labels)


def test_node_order_public(ex):
    """node_order() (reference: exported nodeOrder) returns the same order
    the composite plot lays out."""
    names = nplot.node_order(
        **_inputs(ex), discovery="d", test="t", modules=["1", "2"],
    )
    layout = nplot._prepare(
        **_inputs(ex), discovery="d", test="t", modules=["1", "2"],
    )
    assert names == layout.node_names
    assert len(names) == len(set(names)) > 0
    # data-less call works (degree is a topology statistic)
    dataless = nplot.node_order(
        **_inputs(ex, with_data=False), discovery="d", test="t",
        modules=["1", "2"],
    )
    assert dataless == names


def test_sample_order_public(ex):
    """sample_order() (reference: exported sampleOrder) matches the data
    heatmap's row order: argsort of the first module's summary profile."""
    order = nplot.sample_order(
        **_inputs(ex), discovery="d", test="t", modules=["1"],
    )
    layout = nplot._prepare(
        **_inputs(ex), discovery="d", test="t", modules=["1"],
    )
    assert len(order) == ex["test_data"].shape[0]
    expect = np.argsort(
        oracle.summary_profile(
            np.asarray(ex["test_data"])[:, layout.node_idx[: int(layout.boundaries[1])]]
        ),
        kind="stable",
    )
    got_idx = order if not isinstance(order, list) else [
        list(layout.target.sample_names).index(s) for s in order
    ]
    np.testing.assert_array_equal(np.asarray(got_idx), expect)

    with pytest.raises(TypeError):
        nplot.sample_order(**_inputs(ex, with_data=False), discovery="d",
                           test="t")


def test_sample_order_missing_test_data_raises(ex):
    """data provided but not for the plotted dataset → layout has no summary
    → the informative ValueError (not a silent None)."""
    import pandas as pd

    kw = _inputs(ex, with_data=False)
    kw["data"] = {"d": pd.DataFrame(ex["discovery_data"],
                                    columns=ex["discovery_names"])}
    with pytest.raises(ValueError, match="requires `data`"):
        nplot.sample_order(**kw, discovery="d", test="t")
