"""Worker process for the 2-process multi-host test
(``tests/test_multihost.py``). Each rank joins a localhost coordination
service, builds the global (perm,) mesh spanning both processes' virtual CPU
devices, runs a small sharded permutation null, and writes the gathered
(global) null to ``--out`` — the parent asserts both ranks produced the
identical full null via ``gather_to_host``'s ``process_allgather`` branch.
"""

import argparse
import os
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--local-devices", type=int, default=4)
    ap.add_argument("--out", required=True)
    args = ap.parse_args()

    # Env before any jax backend init: virtual CPU devices per process.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={args.local_devices}"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from netrep_tpu.parallel import distributed
    from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
    from netrep_tpu.parallel.mesh import make_mesh
    from netrep_tpu.utils.config import EngineConfig

    info = distributed.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert info["process_count"] == args.num_processes, info
    assert info["global_device_count"] == args.num_processes * args.local_devices

    # identical problem on every rank (SPMD contract)
    rng = np.random.default_rng(0)
    n, ns = 64, 12

    def build():
        x = rng.standard_normal((ns, n))
        c = np.corrcoef(x, rowvar=False)
        return x, c, np.abs(c) ** 2

    d_data, d_corr, d_net = build()
    t_data, t_corr, t_net = build()
    sizes = (6, 9)
    specs, pos = [], 0
    for k, sz in enumerate(sizes):
        idx = np.arange(pos, pos + sz, dtype=np.int32)
        specs.append(ModuleSpec(str(k + 1), idx, idx))
        pos += sz
    pool = np.arange(n, dtype=np.int32)

    n_dev = info["global_device_count"]
    mesh = make_mesh(n_perm_shards=n_dev, n_row_shards=1)
    engine = PermutationEngine(
        d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
        config=EngineConfig(chunk_size=2 * n_dev, summary_method="power",
                            power_iters=30),
        mesh=mesh,
    )
    nulls, done = engine.run_null(4 * n_dev, key=21)
    assert done == 4 * n_dev
    assert np.isfinite(nulls).all()

    # second engine, fused Pallas path on the same cross-process mesh: the
    # shard_map-wrapped chunk must execute across processes and reproduce
    # the same-seed null (interpret-mode kernel on CPU devices)
    fused = PermutationEngine(
        d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
        config=EngineConfig(chunk_size=2 * n_dev, summary_method="power",
                            power_iters=30, gather_mode="fused"),
        mesh=mesh,
    )
    fnulls, fdone = fused.run_null(2 * n_dev, key=21)
    assert fdone == 2 * n_dev
    np.testing.assert_allclose(fnulls, nulls[: 2 * n_dev], atol=1e-4)

    np.save(args.out, nulls)
    print(f"rank {args.process_id}: OK shape={nulls.shape} fused-parity-ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
