"""Native C++ backend tests (netrep_tpu/native): oracle parity of the
statistic kernels, determinism of the threaded permutation procedure, and
end-to-end ``module_preservation(backend='native')``.

Mirrors the reference's test strategy (SURVEY.md §4): the native kernels are
cross-checked against the slow pure-NumPy oracle, and determinism across
thread counts given the same seed is enforced as an explicit contract.
"""

import numpy as np
import pytest

from netrep_tpu.ops import oracle
from netrep_tpu.parallel.engine import ModuleSpec

native = pytest.importorskip("netrep_tpu.native")

if not native.available():  # pragma: no cover - g++ is baked into the image
    pytest.skip("no C++ toolchain available", allow_module_level=True)


def _problem(rng, n_disc=40, n_test=36, s_d=30, s_t=24,
             module_sizes=(8, 6, 5), with_data=True):
    def build(n, s):
        x = rng.standard_normal((s, n))
        pos = 0
        for sz in module_sizes:
            latent = rng.standard_normal(s)
            x[:, pos:pos + sz] = latent[:, None] + 0.6 * x[:, pos:pos + sz]
            pos += sz
        c = np.corrcoef(x, rowvar=False)
        return x, c, np.abs(c) ** 2

    d_data, d_corr, d_net = build(n_disc, s_d)
    t_data, t_corr, t_net = build(n_test, s_t)
    specs, pos = [], 0
    for k, sz in enumerate(module_sizes):
        idx = np.arange(pos, pos + sz, dtype=np.int32)
        specs.append(ModuleSpec(str(k + 1), idx, idx))
        pos += sz
    pool = np.arange(n_test, dtype=np.int32)
    if not with_data:
        d_data = t_data = None
    return (d_corr, d_net, d_data), (t_corr, t_net, t_data), specs, pool


def _oracle_observed(disc, test, specs):
    d_corr, d_net, d_data = disc
    t_corr, t_net, t_data = test
    rows = []
    for m in specs:
        di, ti = np.asarray(m.disc_idx), np.asarray(m.test_idx)
        dp = oracle.DiscoveryProps(
            d_corr[np.ix_(di, di)], d_net[np.ix_(di, di)],
            d_data[:, di] if d_data is not None else None,
        )
        rows.append(oracle.module_stats(
            dp, t_corr[np.ix_(ti, ti)], t_net[np.ix_(ti, ti)],
            t_data[:, ti] if t_data is not None else None,
        ))
    return np.stack(rows)


@pytest.mark.parametrize("with_data", [True, False])
def test_observed_matches_oracle(rng, with_data):
    disc, test, specs, pool = _problem(rng, with_data=with_data)
    core = native.NativeCore(*disc, *test, specs, pool)
    got = core.observed()
    want = _oracle_observed(disc, test, specs)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9)
    if not with_data:
        # data-less variant: only the three topology statistics are defined
        assert np.isnan(got[:, [1, 4, 5, 6]]).all()
        assert np.isfinite(got[:, [0, 2, 3]]).all()


def test_null_statistics_match_oracle_per_permutation(rng):
    """Feed the native library's own sampled index sets back through the
    oracle: each null row must match the oracle stats exactly (separates
    kernel correctness from RNG-stream differences)."""
    disc, test, specs, pool = _problem(rng)
    core = native.NativeCore(*disc, *test, specs, pool)
    nulls, done = core.null(16, seed=11, n_threads=2)
    assert done == 16

    # Re-derive the sampled node sets: not exposed by the ABI, so instead
    # verify the distributional contract — every row is finite and within
    # the statistics' ranges (correlations in [-1, 1]).
    assert np.isfinite(nulls).all()
    for col in (2, 3, 4):  # cor.cor, cor.degree, cor.contrib
        assert (np.abs(nulls[:, :, col]) <= 1 + 1e-12).all()


def test_determinism_across_threads_and_chunking(rng):
    disc, test, specs, pool = _problem(rng)
    core = native.NativeCore(*disc, *test, specs, pool)
    a, _ = core.null(48, seed=7, n_threads=1)
    b, _ = core.null(48, seed=7, n_threads=8)
    np.testing.assert_array_equal(a, b)
    # chunked calls with perm_offset reproduce the same stream
    c1, _ = core.null(20, seed=7, perm_offset=0)
    c2, _ = core.null(28, seed=7, perm_offset=20)
    np.testing.assert_array_equal(np.concatenate([c1, c2]), a)
    # different seed ⇒ different null
    d, _ = core.null(48, seed=8)
    assert not np.array_equal(a, d)


def test_null_distribution_agrees_with_oracle_null(rng):
    """Statistical equivalence (SURVEY.md §7 'RNG semantics'): the native
    null and the oracle null use different RNGs but must agree in
    distribution — compare means within generous Monte-Carlo error."""
    disc, test, specs, pool = _problem(rng)
    core = native.NativeCore(*disc, *test, specs, pool)
    n = 400
    native_null, _ = core.null(n, seed=3)

    d_corr, d_net, d_data = disc
    dps = [
        oracle.DiscoveryProps(
            d_corr[np.ix_(m.disc_idx, m.disc_idx)],
            d_net[np.ix_(m.disc_idx, m.disc_idx)],
            d_data[:, m.disc_idx],
        )
        for m in specs
    ]
    oracle_null = oracle.permutation_null(
        dps, [m.size for m in specs], *test, pool, n,
        np.random.default_rng(99),
    )
    nm, om = native_null.mean(0), oracle_null.mean(0)
    nsd = native_null.std(0) + oracle_null.std(0) + 1e-9
    z = np.abs(nm - om) / (nsd / np.sqrt(n))
    assert (z < 6).all(), f"null means diverge: max z={z.max():.2f}"


def test_engine_end_to_end_and_checkpoint(rng, tmp_path):
    disc, test, specs, pool = _problem(rng)
    eng = native.NativePermutationEngine(*disc, *test, specs, pool)
    obs = eng.observed()
    assert obs.shape == (3, 7)

    path = str(tmp_path / "null.npz")
    full, done = eng.run_null(96, key=5)
    assert done == 96

    # write a partial checkpoint, then resume to the full count
    partial_eng = native.NativePermutationEngine(*disc, *test, specs, pool)
    partial_eng.chunk = 64
    nulls_a, done_a = partial_eng.run_null(
        64, key=5, checkpoint_path=path, checkpoint_every=32
    )
    assert done_a == 64
    resumed, done_b = partial_eng.run_null(
        96, key=5, checkpoint_path=path, checkpoint_every=32
    )
    assert done_b == 96
    np.testing.assert_array_equal(resumed, full)


def test_module_preservation_native_backend(rng):
    """End-to-end ``backend='native'`` run: plain arrays get positional
    ``node_{i}`` names, so the 36 test nodes overlap the first 36 of the 40
    discovery nodes by name (the planted modules live in that prefix)."""
    from netrep_tpu import module_preservation

    (d_corr, d_net, d_data), (t_corr, t_net, t_data), specs, _ = _problem(rng)
    labels = {}
    pos = 0
    for k, sz in enumerate((8, 6, 5)):
        for i in range(pos, pos + sz):
            labels[f"node_{i}"] = str(k + 1)
        pos += sz
    for i in range(d_corr.shape[0]):
        labels.setdefault(f"node_{i}", "0")

    res = module_preservation(
        {"d": d_net, "t": t_net},
        data={"d": d_data, "t": t_data},
        correlation={"d": d_corr, "t": t_corr},
        module_assignments=labels,
        discovery="d", test="t", n_perm=200, seed=1, backend="native",
        n_threads=4,
    )
    assert res.observed.shape == (3, 7)
    assert res.completed == 200
    assert np.isfinite(res.p_values).all()
    # planted modules should look preserved: small p for avg.weight
    assert (res.p_values[:, 0] < 0.2).all()


def test_native_seed_handling(rng):
    """ADVICE r1: negative seeds must round-trip (masked to 64 bits, matching
    core.null) and a jax typed key must raise a clear TypeError rather than
    an opaque conversion error."""
    disc, test, specs, pool = _problem(rng)
    eng = native.NativePermutationEngine(*disc, *test, specs, pool)
    # negative seed: runs, deterministic, and equals its masked twin
    neg, done = eng.run_null(32, key=-7)
    assert done == 32
    masked, _ = eng.run_null(32, key=-7 & 0xFFFFFFFFFFFFFFFF)
    np.testing.assert_array_equal(neg, masked)
    # key_data masks too (checkpointed runs hit this path)
    kd = eng.key_data(eng.prepare_key(-7))
    assert kd.dtype == np.uint64
    assert int(kd[1]) == (-7 & 0xFFFFFFFFFFFFFFFF)
    # jax typed key → clear error naming the backend contract
    import jax

    with pytest.raises(TypeError, match="integer seed"):
        eng.run_null(8, key=jax.random.key(0))


def test_zero_copy_adoption(rng):
    """SURVEY.md §2.2 "Zero-copy matrix adoption": C-contiguous float64
    inputs are adopted without copying (the reference's Armadillo-advanced-
    constructor behavior); the engine reads the caller's memory directly."""
    disc, test, specs, pool = _problem(rng)
    t_corr = np.ascontiguousarray(test[0], dtype=np.float64)
    eng = native.NativePermutationEngine(
        disc[0], disc[1], disc[2], t_corr, test[1], test[2], specs, pool
    )
    assert eng.core.test_corr is t_corr  # same object, no copy
    # non-contiguous / wrong-dtype inputs are converted (a required copy)
    f32 = np.asarray(test[0], dtype=np.float32)
    eng2 = native.NativePermutationEngine(
        disc[0], disc[1], disc[2], f32, test[1], test[2], specs, pool
    )
    assert eng2.core.test_corr is not f32
    assert eng2.core.test_corr.dtype == np.float64
