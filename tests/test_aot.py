"""AOT warm start (ISSUE 15): the serialized-executable store.

Pins the contracts the fallback ladder and the zero-compile proof stand
on: bit-identity of AOT-loaded programs vs the jit path in all four
null-loop modes, the cache-identity discipline (any autotune_key /
constant / mesh component difference ⇒ a different entry), store hygiene
(corruption quarantined, env mismatch silently invalidated, LRU GC
bounded), the ``source`` tag on compile_span events and perf-ledger
fingerprints, resume-from-checkpoint parity under a warm store, and the
fresh-process warm-start proof itself (``compile_span ~0`` with
``source: aot``)."""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils import aot
from netrep_tpu.utils.config import EngineConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _problem(seed=0, sizes=(18, 6), n=48, s=12):
    r = np.random.default_rng(seed)

    def build(nn):
        x = r.standard_normal((s, nn))
        c = np.corrcoef(x, rowvar=False)
        return x, c, np.abs(c) ** 2

    d, t = build(n + 6), build(n)
    specs, pos = [], 0
    for k, sz in enumerate(sizes):
        idx = np.arange(pos, pos + sz, dtype=np.int32)
        specs.append(ModuleSpec(str(k + 1), idx, idx))
        pos += sz
    return d, t, specs, np.arange(n, dtype=np.int32)


def _engine(cfg=None, sizes=(18, 6), **kw):
    d, t, specs, pool = _problem(sizes=sizes)
    return PermutationEngine(
        d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
        config=cfg or EngineConfig(chunk_size=8, summary_method="eigh",
                                   autotune=False),
        **kw,
    )


@pytest.fixture
def store(tmp_path, monkeypatch):
    """A fresh, isolated store per test (and a fresh singleton)."""
    monkeypatch.setenv(aot.STORE_ENV, str(tmp_path / "aot"))
    monkeypatch.delenv(aot.DISABLE_ENV, raising=False)
    monkeypatch.delenv(aot.EXPORT_ENV, raising=False)
    aot.reset_store()
    yield aot.get_store()
    aot.reset_store()


def _cold_reference(monkeypatch, n_perm=24):
    """Results from the pure-jit path (store disabled)."""
    monkeypatch.setenv(aot.DISABLE_ENV, "0")
    aot.reset_store()
    eng = _engine()
    nulls, _ = eng.run_null(n_perm, key=7)
    obs = eng.observed()
    stream = eng.run_null_streaming(n_perm, obs, key=7)
    adapt = eng.run_null_adaptive_streaming(n_perm, obs, key=7)
    eng2 = _engine()
    mat_ad = eng2.run_null_adaptive(n_perm, obs, key=7)
    monkeypatch.delenv(aot.DISABLE_ENV, raising=False)
    aot.reset_store()
    return nulls, obs, stream, adapt, mat_ad


def test_aot_bit_identical_all_modes(store, monkeypatch):
    """The tentpole pin: AOT-loaded programs produce counts, observed
    statistics, and adaptive decisions bit-identical to the jit path in
    all four null-loop modes — after a store round-trip with a cleared
    in-process memo (the fresh-process condition minus the process)."""
    n_perm = 24
    cold = _cold_reference(monkeypatch, n_perm)

    # export the grid, then drop every in-process warm layer so the next
    # engines must deserialize from disk
    _engine().warmup_export(n_perm)
    assert store.stats()["entries"] > 0
    aot.reset_store()

    eng = _engine()
    nulls, _ = eng.run_null(n_perm, key=7)
    assert eng._program_sources["chunk"] == "aot"
    assert np.array_equal(nulls, cold[0])

    obs = eng.observed()
    assert eng._program_sources["observed"] == "aot"
    assert np.array_equal(obs, cold[1])

    stream = eng.run_null_streaming(n_perm, obs, key=7)
    assert eng._program_sources["super"] == "aot"
    for a, b in (("hi", "hi"), ("lo", "lo"), ("eff", "eff")):
        assert np.array_equal(getattr(stream, a), getattr(cold[2], b))

    adapt = eng.run_null_adaptive_streaming(n_perm, obs, key=7)
    assert np.array_equal(adapt.hi, cold[3].hi)
    assert np.array_equal(adapt.n_perm_used, cold[3].n_perm_used)

    eng2 = _engine()
    mat_ad = eng2.run_null_adaptive(n_perm, obs, key=7)
    assert np.array_equal(np.asarray(mat_ad[0]), np.asarray(cold[4][0]),
                          equal_nan=True)


def test_resume_from_checkpoint_warm_equals_cold(store, monkeypatch,
                                                 tmp_path):
    """Resume under a warm store is bit-identical to an uninterrupted
    cold run: the checkpoint identity and the per-permutation keys are
    AOT-independent."""
    n_perm = 24
    monkeypatch.setenv(aot.DISABLE_ENV, "0")
    aot.reset_store()
    full, _ = _engine().run_null(n_perm, key=7)
    monkeypatch.delenv(aot.DISABLE_ENV, raising=False)
    aot.reset_store()

    _engine().warmup_export(n_perm)
    aot.reset_store()

    ck = str(tmp_path / "resume.npz")
    eng = _engine()
    eng.run_null(n_perm // 2, key=7, checkpoint_path=ck)
    eng2 = _engine()
    resumed, completed = eng2.run_null(n_perm, key=7, checkpoint_path=ck)
    assert completed == n_perm
    # the half-run engine loaded the entry; the resuming engine shares
    # the process and memo-hits — both are warm sources
    assert eng2._program_sources["chunk"] in ("aot", "memo")
    assert np.array_equal(resumed, full)


def test_program_key_discipline(store):
    """Any fingerprint component difference ⇒ a different store entry:
    gather mode, stat mode, chunk size, bucket signature, data-only,
    mesh spec, and the packed engine's group structure all participate.
    """
    base = _engine().program_cache_key("chunk")

    def key_of(cfg=None, sizes=(18, 6), cls=None, groups=1):
        if cls == "packed":
            from netrep_tpu.serve.packer import PackedEngine

            d, t, specs, pool = _problem(sizes=sizes)
            e = PackedEngine(
                d[1], d[2], d[0], t[1], t[2], t[0],
                [specs] * groups, pool,
                config=cfg or EngineConfig(chunk_size=8,
                                           summary_method="eigh",
                                           autotune=False),
            )
            return e.program_cache_key("chunk")
        return _engine(cfg=cfg, sizes=sizes).program_cache_key("chunk")

    others = {
        "gather": key_of(EngineConfig(chunk_size=8, summary_method="eigh",
                                      autotune=False, gather_mode="mxu")),
        "chunk": key_of(EngineConfig(chunk_size=16,
                                     summary_method="eigh",
                                     autotune=False)),
        "summary": key_of(EngineConfig(chunk_size=8,
                                       summary_method="power",
                                       autotune=False)),
        "buckets": key_of(sizes=(18, 8)),
        "packed1": key_of(cls="packed"),
        "packed2": key_of(cls="packed", groups=2),
    }
    vals = [base, *others.values()]
    assert len(set(vals)) == len(vals), others

    # mesh spec: the spec string participates even though mesh paths
    # currently fall back to jit
    e = _engine()
    assert "mesh:none" in e._mesh_spec_str()


def test_store_corruption_quarantined(store, monkeypatch):
    """A truncated/corrupt entry is quarantined (renamed aside), the run
    proceeds on the jit path, and the next acquire re-exports cleanly."""
    eng = _engine()
    eng.warmup_export(16)
    aot.reset_store()
    store2 = aot.get_store()
    # corrupt every serialized blob
    n_bins = 0
    for name in os.listdir(store2.path):
        if name.endswith(".bin"):
            with open(os.path.join(store2.path, name), "wb") as f:
                f.write(b"corrupt")
            n_bins += 1
    assert n_bins > 0
    eng2 = _engine()
    nulls, _ = eng2.run_null(16, key=3)
    assert np.isfinite(np.asarray(nulls)).all()
    assert eng2._program_sources["chunk"] == "jit"   # never wrong, only slower
    assert store2.quarantined > 0
    bad = [n for n in os.listdir(store2.path) if n.endswith(".bad")]
    assert bad


def test_env_mismatch_invalidates_silently(store):
    """An entry written under a different jax/device/code environment is
    skipped (counted miss, jit fallback) — never deserialized."""
    eng = _engine()
    eng.warmup_export(16)
    aot.reset_store()
    store2 = aot.get_store()
    for name in os.listdir(store2.path):
        if name.endswith(".json"):
            p = os.path.join(store2.path, name)
            with open(p) as f:
                meta = json.load(f)
            meta["env"] = "jax:0.0.1|jaxlib:0.0.1|dev:tpu:v9|prng:x|code:0"
            with open(p, "w") as f:
                json.dump(meta, f)
    eng2 = _engine()
    eng2.run_null(16, key=3)
    assert eng2._program_sources["chunk"] == "jit"
    assert store2.misses > 0


def test_store_gc_lru_bound(store):
    """The size-bounded GC drops the least-recently-used entries (and
    quarantined files) once the store exceeds its bound."""
    eng = _engine()
    eng.warmup_export(16)
    st = store.stats()
    assert st["entries"] > 1
    store.max_bytes = 1  # force everything but nothing-fits
    removed = store.gc()
    assert removed > 0
    assert store.stats()["entries"] == 0


def test_compile_span_source_tag_and_ledger_split(store, tmp_path,
                                                  monkeypatch):
    """compile_span events carry ``source``; perf-ledger fingerprints get
    the ``|src:`` suffix so warm and cold histories never mix; the
    telemetry CLI's time split renders the src column."""
    from netrep_tpu.utils.perfledger import read_entries
    from netrep_tpu.utils.telemetry import Telemetry, read_events
    from netrep_tpu.utils.trace import render_time_split, time_split

    ledger = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("NETREP_PERF_LEDGER", str(ledger))
    tel_path = str(tmp_path / "tel.jsonl")
    eng = _engine()
    tel = Telemetry(tel_path)
    eng.run_null(24, key=1, telemetry=tel)
    tel.close()
    spans = [e for e in read_events(tel_path)
             if e["ev"] == "compile_span"]
    assert spans and spans[0]["data"]["source"] == "jit"
    entries = read_entries(str(ledger))
    assert entries and entries[-1]["fingerprint"].endswith("|src:jit")

    split = time_split(read_events(tel_path))
    assert "jit" in split["compile_by_src"]
    assert "src: jit" in render_time_split(tel_path)

    # warm store ⇒ the same run tags aot and lands a separate fingerprint
    eng.warmup_export(24)
    aot.reset_store()
    tel2_path = str(tmp_path / "tel2.jsonl")
    tel2 = Telemetry(tel2_path)
    _engine().run_null(24, key=1, telemetry=tel2)
    tel2.close()
    spans2 = [e for e in read_events(tel2_path)
              if e["ev"] == "compile_span"]
    assert spans2 and spans2[0]["data"]["source"] == "aot"
    e2 = read_entries(str(ledger))[-1]
    assert e2["fingerprint"].endswith("|src:aot")

    # in-process reuse on the SAME engine tags memo
    eng3 = _engine()
    eng3.run_null(24, key=1)
    tel3_path = str(tmp_path / "tel3.jsonl")
    tel3 = Telemetry(tel3_path)
    eng3.run_null(24, key=1, telemetry=tel3)
    tel3.close()
    spans3 = [e for e in read_events(tel3_path)
              if e["ev"] == "compile_span"]
    assert spans3 and spans3[0]["data"]["source"] == "memo"


def test_aot_events_registered():
    """The ISSUE 12 telemetry-registry lint must cover the new events."""
    from netrep_tpu.utils.telemetry import KNOWN_EVENTS

    assert {"aot_export", "aot_load", "aot_store_miss",
            "warmup_start", "warmup_end"} <= KNOWN_EVENTS


def test_store_disabled_env(monkeypatch):
    monkeypatch.setenv(aot.DISABLE_ENV, "0")
    aot.reset_store()
    assert aot.get_store() is None
    monkeypatch.delenv(aot.DISABLE_ENV, raising=False)
    aot.reset_store()


def test_serve_preload_and_export(store, tmp_path):
    """Serve side: a recovering boot preloads the warm-pool engine for
    its re-registered datasets on the background thread, and a server
    with ``aot_export=True`` persists the programs its packs compiled."""
    from netrep_tpu.serve.scheduler import PreservationServer, ServeConfig

    journal = str(tmp_path / "journal.jsonl")
    cfg = dict(engine=EngineConfig(chunk_size=8, autotune=False),
               journal=journal, aot_export=True)
    srv = PreservationServer(ServeConfig(**cfg))
    try:
        srv.register_fixture("t", genes=60, modules=2, n_samples=12,
                             seed=3)
        req = srv.submit("t", "fx_d", "fx_t", n_perm=16, seed=5)
        res = srv.wait(req, timeout=300)
        p_cold = np.asarray(res["p_values"])
    finally:
        srv.close()
    assert store.stats()["entries"] > 0

    aot.reset_store()
    srv2 = PreservationServer(ServeConfig(**cfg, recover=True,
                                          preload_max=2))
    try:
        with srv2._work:
            pt = srv2._preload_thread
        assert pt is not None
        pt.join(timeout=120)
        assert len(srv2.pool) >= 1      # the pair's engine is warm
        req = srv2.submit("t", "fx_d", "fx_t", n_perm=16, seed=5,
                          idempotency_key="fresh-key")
        res = srv2.wait(req, timeout=300)
        assert np.array_equal(np.asarray(res["p_values"]), p_cold)
        assert res["pool_hit"] is True  # preload built it, request hit it
    finally:
        srv2.close()


def test_fresh_process_warm_start_proof(tmp_path):
    """The pinned acceptance proof, measured the honest way: a FRESH
    process against a warmup-populated store answers its first run with
    ``compile_span ~0`` and ``source: aot``, bit-identity riding the
    in-process pins above."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "NETREP_AOT_STORE": str(tmp_path / "aot")}
    shape = ["--genes", "60", "--modules", "2", "--samples", "12",
             "--chunk", "8", "--n-perm", "16", "--json"]

    def run(extra):
        p = subprocess.run(
            [sys.executable, "-m", "netrep_tpu", "warmup", *shape,
             *extra],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=600,
        )
        assert p.returncode == 0, p.stderr[-2000:]
        return json.loads(p.stdout.strip().splitlines()[-1])

    export = run(["--target", "serve"])
    assert (export["store"]["entries"] or 0) > 0
    warm = run(["--measure"])
    cold_floor = warm["first_run_s"]
    assert warm["source"] == "aot"
    # ~0: the deserialized program's compile was done at acquire time,
    # before the run span — the estimate is steady-state noise, orders
    # of magnitude under any real compile
    assert warm["compile_span_s"] is not None
    assert warm["compile_span_s"] < max(0.25, 0.5 * cold_floor)
