"""Direct pins on small public API members that are otherwise only
exercised indirectly — a rename or a silent semantic change in any of
these would break user code without failing a test naming it."""

import math

import numpy as np
import pytest

import netrep_tpu
from netrep_tpu.ops.oracle import STAT_NAMES


# `result` is the session-scoped 250-perm run from conftest.py — shared
# with test_preservation_e2e so the suite pays for one engine pass


def test_observed_frame_and_stat_names(result):
    frame = result.observed_frame()
    assert tuple(frame.columns) == STAT_NAMES == result.stat_names
    assert list(frame.index) == list(result.module_labels)
    np.testing.assert_array_equal(frame.to_numpy(), result.observed)


def test_repr_is_the_s3_print_analogue(result):
    text = repr(result)
    assert "Module preservation" in text and "p-values:" in text
    for name in STAT_NAMES:
        assert name in text


def test_log_total_permutations():
    from netrep_tpu.ops.pvalues import (
        log_total_permutations, total_permutations,
    )

    # falling factorial 5!/(5-3)! = 60 for one 3-node module from 5
    assert math.isclose(log_total_permutations(5, [3]), math.log(60))
    assert math.isclose(total_permutations(5, [3]), 60.0)
    # oversubscribed pool -> inf (engine would reject it earlier)
    assert log_total_permutations(4, [3, 2]) == float("inf")


def test_sparse_adjacency_nnz():
    rows = np.array([0, 1, 2])
    cols = np.array([1, 2, 0])
    vals = np.array([0.5, 0.25, 0.125], dtype=np.float32)
    adj = netrep_tpu.SparseAdjacency.from_coo(rows, cols, vals, n=4)
    # symmetrized: each edge stored in both directions — k must be exactly
    # the max per-node degree after symmetrization (k >= 1 is tautological:
    # from_coo clamps k to 1)
    assert adj.nnz == 6
    assert adj.k == 2


def test_resolved_gather_mode_contract():
    from netrep_tpu.utils.config import EngineConfig

    cfg = EngineConfig()
    assert cfg.resolved_gather_mode("cpu") == "direct"
    assert cfg.resolved_gather_mode("tpu") == "mxu"
    assert EngineConfig(gather_mode="fused").resolved_gather_mode("cpu") == "fused"
    with pytest.raises(ValueError, match="gather_mode"):
        EngineConfig(gather_mode="bogus").resolved_gather_mode("cpu")
