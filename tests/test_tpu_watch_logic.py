"""State-machine tests for benchmarks/tpu_watch.sh via its QUEUE_FILE /
PROBE_CMD test hooks — no chip, no tunnel, no jax.

The watcher is the component that converts a rare ~5-7 min tunnel window
into BASELINE rows; logic bugs here have burned real windows (round 4's
parity-gate ambiguity, round 2's lost artifact). Pinned: resume skips
completed steps, CPU-fallback rows are never marked done, the parity
gate's SKIPPED strike discipline (one free retry, then retire + fused
steps skipped permanently), the on-device selftest halt, the generic
two-strike failure rule, and the cutoff exit.
"""

import os
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WATCH = os.path.join(REPO, "benchmarks", "tpu_watch.sh")


def run_watch(tmp_path, queue_lines, probe_cmd="true", cutoff_delta=3600,
              timeout=60, extra_env=None, tag="0"):
    qf = tmp_path / f"queue{tag}"
    qf.write_text("\n".join(queue_lines) + "\n")
    log = tmp_path / f"log{tag}.jsonl"
    state = tmp_path / "state"  # shared across tags: resume identity
    import time

    env = {
        **os.environ,
        "QUEUE_FILE": str(qf),
        "PROBE_CMD": probe_cmd,
        "SLEEP": "0",
        "PROBE_TIMEOUT": "1",
        "CUTOFF_EPOCH": str(int(time.time()) + cutoff_delta),
        **(extra_env or {}),
    }
    proc = subprocess.run(
        ["bash", WATCH, str(log), str(state)],
        env=env, timeout=timeout, capture_output=True, text=True,
    )
    state_text = state.read_text() if state.exists() else ""
    log_text = log.read_text() if log.exists() else ""
    return proc, state_text, log_text


def test_happy_path_marks_pass_and_resumes(tmp_path):
    proc, state, log = run_watch(
        tmp_path, ["one 30 echo ok-one", "two 30 echo ok-two"]
    )
    assert proc.returncode == 0
    assert "queue drained" in log
    for key in ("one", "two"):
        assert f"\n{key}\n" in "\n" + state or state.startswith(f"{key}\n")
        assert f"{key} PASS" in state
    # resume: completed keys must not rerun (fresh log, shared state)
    proc2, _, log2 = run_watch(
        tmp_path, ["one 30 echo ok-one", "two 30 echo ok-two",
                   "three 30 echo ok-three"], tag="resume",
    )
    assert proc2.returncode == 0
    assert "ok-three" in log2
    assert "ok-one" not in log2 and "ok-two" not in log2


def test_cpu_fallback_row_never_marked_done(tmp_path):
    # step exits 0 but its row is a tagged CPU fallback: the watcher must
    # treat it as a tunnel death (leave unmarked), not mark it done
    fall_cmd = """bash -c 'echo "{\\"tpu_fallback\\": true}"'"""
    proc, state, log = run_watch(
        tmp_path,
        [f"fall 1 {fall_cmd}"],
        cutoff_delta=6,  # bounded: the step would otherwise retry forever
    )
    assert proc.returncode == 0
    assert "emitted a CPU-fallback row" in log
    assert "fall" not in state


def test_parity_skipped_strike_then_retire(tmp_path):
    # SKIPPED with a live reprobe: first occurrence records a strike and
    # retries; the second retires the fused grid under its OWN marker
    # (SKIPRETIRE — a compile-refusal, NOT the wrong-numbers MOSAICFAIL
    # verdict; ADVICE r5) and tune is then skipped permanently with the
    # compile-refusal message
    parity_cmd = "bash -c 'echo pallas fused gather: SKIPPED; exit 2'"
    proc, state, log = run_watch(
        tmp_path,
        [f"parity 30 {parity_cmd}", "tune 30 echo tuned"],
        timeout=90,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "parity SKIP1" in state
    assert "parity SKIPRETIRE" in state
    assert "parity MOSAICFAIL" not in state  # distinct retirement class
    assert "one more strike retires" in log
    assert "SKIPPED twice with tunnel alive; retiring fused grid" in log
    assert "Mosaic compile-refusal, not wrong numbers" in log
    assert "tuned" not in log  # tune never executed


def test_parity_real_failure_retires_immediately(tmp_path):
    parity_cmd = "bash -c 'echo pallas fused parity FAILED (f32): rel err 1; exit 1'"
    proc, state, log = run_watch(
        tmp_path,
        [f"parity 30 {parity_cmd}", "tune 30 echo tuned"],
        timeout=90,
    )
    assert proc.returncode == 0
    assert "parity MOSAICFAIL" in state
    assert "parity SKIP1" not in state  # no strike detour on a hard failure
    assert "tuned" not in log


def test_parity_skipped_with_dead_reprobe_is_transient(tmp_path):
    # tunnel died mid-compile: SKIPPED but the reprobe fails — no strike,
    # no retirement; the gate stays pending for the next window
    parity_cmd = "bash -c 'echo pallas fused gather: SKIPPED; exit 2'"
    proc, state, log = run_watch(
        tmp_path,
        [f"parity 1 {parity_cmd}"],
        # probe succeeds for the queue entry but the post-failure reprobe
        # uses the same PROBE_CMD — use a one-shot marker file: first call
        # succeeds, later calls fail
        probe_cmd=f"bash -c 'test ! -e {tmp_path}/probed && touch {tmp_path}/probed'",
        cutoff_delta=6,
    )
    assert proc.returncode == 0
    assert "MOSAICFAIL" not in state and "SKIP1" not in state


def test_selftest_failure_halts_queue(tmp_path):
    self_cmd = "bash -c 'echo selftest FAILED on device: dev 1; exit 1'"
    proc, state, log = run_watch(
        tmp_path,
        [f"selftest 30 {self_cmd}", "after 30 echo should-not-run"],
        timeout=60,
    )
    assert proc.returncode == 3
    assert "DEVICE FAILED NUMERICAL SELFTEST" in log
    assert "should-not-run" not in log
    assert "selftest" not in state


def test_generic_failure_two_strikes_then_skip(tmp_path):
    bad_cmd = "bash -c 'echo boom; exit 1'"
    proc, state, log = run_watch(
        tmp_path, [f"wob 30 {bad_cmd}", "next 30 echo nxt"], timeout=90
    )
    assert proc.returncode == 0
    assert "wob FAIL" in state          # first strike
    assert "FAILED twice with tunnel alive; skipping permanently" in log
    assert "nxt" in log                  # queue continues past it


def test_cutoff_exits_immediately(tmp_path):
    proc, state, log = run_watch(
        tmp_path, ["one 30 echo ok"], cutoff_delta=-10
    )
    assert proc.returncode == 0
    assert "cutoff window reached" in log
    assert state.strip() == ""


def test_drained_queue_reports_drained_even_past_cutoff(tmp_path):
    """The drained check must run BEFORE the cutoff check: a completed
    queue with an expired cutoff exits 'queue drained', not the
    misleading 'no step can finish before cutoff' (the defect the
    check-reorder fixed)."""
    (tmp_path / "state").write_text("one\none PASS\n")
    proc, state, log = run_watch(
        tmp_path, ["one 30 echo ok"], cutoff_delta=-10
    )
    assert proc.returncode == 0
    assert "queue drained" in log
    assert "cutoff window reached" not in log
    assert "no step can finish" not in log


def test_warmstart_step_off_under_queue_hook_and_loud_never_fatal(tmp_path):
    """ISSUE 15: the warmstart step is off by default and under the
    QUEUE_FILE hook (auto); forced on, a failing scenario banners
    LOUDLY but never fails the cycle — the queue still drains."""
    # default off / auto under QUEUE_FILE: no warmstart banner
    proc, _, log = run_watch(tmp_path, ["one 30 echo ok-one"])
    assert proc.returncode == 0
    assert "warmstart step" not in log
    proc_a, _, log_a = run_watch(
        tmp_path, ["oneauto 30 echo ok-one"], tag="wsauto",
        extra_env={"WARMSTART": "auto"},
    )
    assert proc_a.returncode == 0
    assert "warmstart step" not in log_a
    # forced on with a python shim that fails the scenario: the step
    # banners and the cycle still completes (loud-never-fatal)
    shim_dir = tmp_path / "bin"
    shim_dir.mkdir()
    shim = shim_dir / "python"
    shim.write_text("#!/bin/sh\nexit 1\n")
    shim.chmod(0o755)
    proc2, _, log2 = run_watch(
        tmp_path, ["two 30 echo ok-two"], tag="ws",
        extra_env={"WARMSTART": "1",
                   "PATH": f"{shim_dir}:{os.environ['PATH']}"},
    )
    assert proc2.returncode == 0, proc2.stderr
    assert "warmstart step" in log2
    assert "WARMSTART FAILED" in log2
    assert "queue drained" in log2


def test_grid_step_off_under_queue_hook_and_loud_never_fatal(tmp_path):
    """ISSUE 17: the all-pairs atlas step is off by default and under
    the QUEUE_FILE hook (auto); forced on, a failing bench (cell/solo
    parity or the delta bound tripping in-bench) banners LOUDLY but
    never fails the cycle — the queue still drains."""
    # default off / auto under QUEUE_FILE: no grid banner
    proc, _, log = run_watch(tmp_path, ["one 30 echo ok-one"])
    assert proc.returncode == 0
    assert "grid step" not in log
    proc_a, _, log_a = run_watch(
        tmp_path, ["oneauto 30 echo ok-one"], tag="gridauto",
        extra_env={"GRID_STEP": "auto"},
    )
    assert proc_a.returncode == 0
    assert "grid step" not in log_a
    # forced on with a python shim that fails the bench: the step
    # banners and the cycle still completes (loud-never-fatal)
    shim_dir = tmp_path / "bin"
    shim_dir.mkdir()
    shim = shim_dir / "python"
    shim.write_text("#!/bin/sh\nexit 1\n")
    shim.chmod(0o755)
    proc2, _, log2 = run_watch(
        tmp_path, ["two 30 echo ok-two"], tag="grid",
        extra_env={"GRID_STEP": "1",
                   "PATH": f"{shim_dir}:{os.environ['PATH']}"},
    )
    assert proc2.returncode == 0, proc2.stderr
    assert "grid step" in log2
    assert "GRID STEP FAILED" in log2
    assert "queue drained" in log2


def test_autoscale_drill_off_under_queue_hook_and_loud_never_fatal(tmp_path):
    """ISSUE 19: the autoscale drill is off by default and under the
    QUEUE_FILE hook (auto); forced on, a failing scenario (scale loop,
    scale-to-zero, or the noticed-eviction handoff) banners LOUDLY but
    never fails the cycle — the queue still drains."""
    # default off / auto under QUEUE_FILE: no autoscale banner
    proc, _, log = run_watch(tmp_path, ["one 30 echo ok-one"])
    assert proc.returncode == 0
    assert "autoscale drill" not in log
    proc_a, _, log_a = run_watch(
        tmp_path, ["oneauto 30 echo ok-one"], tag="asauto",
        extra_env={"AUTOSCALE_DRILL": "auto"},
    )
    assert proc_a.returncode == 0
    assert "autoscale drill" not in log_a
    # forced on with a python shim that fails both scenarios: each step
    # banners and the cycle still completes (loud-never-fatal)
    shim_dir = tmp_path / "bin"
    shim_dir.mkdir()
    shim = shim_dir / "python"
    shim.write_text("#!/bin/sh\nexit 1\n")
    shim.chmod(0o755)
    proc2, _, log2 = run_watch(
        tmp_path, ["two 30 echo ok-two"], tag="as",
        extra_env={"AUTOSCALE_DRILL": "1",
                   "PATH": f"{shim_dir}:{os.environ['PATH']}"},
    )
    assert proc2.returncode == 0, proc2.stderr
    assert "autoscale drill" in log2
    assert "AUTOSCALE LOAD SCENARIO FAILED" in log2
    assert "EVICTION DRILL FAILED" in log2
    assert "queue drained" in log2


def test_lint_step_runs_when_forced_and_stays_off_under_queue_hook(tmp_path):
    """ISSUE 12: the per-cycle invariant lint is off under the
    QUEUE_FILE state-machine hook (auto), runs with LINT_CHECK=1, and
    NEVER fails the cycle — a clean tree logs its one `lint --json`
    line and the queue still drains."""
    # default (auto) under QUEUE_FILE: no lint banner in the log
    proc, _, log = run_watch(tmp_path, ["one 30 echo ok-one"])
    assert proc.returncode == 0
    assert "invariant lint" not in log
    # forced on: the banner and the machine line appear, queue drains
    proc2, _, log2 = run_watch(
        tmp_path, ["two 30 echo ok-two"], tag="lint",
        extra_env={"LINT_CHECK": "1", "JAX_PLATFORMS": "cpu"},
        timeout=180,
    )
    assert proc2.returncode == 0, proc2.stderr
    assert "invariant lint" in log2
    assert '"lint_v": 1' in log2
    assert "queue drained" in log2


def test_mixed_step_opt_in_joins_production_queue(tmp_path):
    """ISSUE 16: MIXED_STEP=1 appends the configMixed step to the
    PRODUCTION queue. The QUEUE_FILE hook replaces the queue entirely
    (which is also why the opt-in is inert under the other state-machine
    tests), so this runs the real queue against a stub `python` that
    answers every step with one clean TPU-attributed row — end to end
    through the gate/cutoff machinery, seconds not hours."""
    import time

    shim_dir = tmp_path / "bin"
    shim_dir.mkdir()
    shim = shim_dir / "python"
    shim.write_text('#!/bin/sh\necho \'{"metric": "stub", "value": 1, '
                    '"device": "TPU v5 lite"}\'\n')
    shim.chmod(0o755)
    for flag, d in (("1", "on"), ("0", "off")):
        work = tmp_path / d
        work.mkdir()
        log, state = work / "log.jsonl", work / "state"
        proc = subprocess.run(
            ["bash", WATCH, str(log), str(state)],
            env={
                **os.environ,
                "PROBE_CMD": "true", "SLEEP": "0", "PROBE_TIMEOUT": "1",
                # past configD's 3600 s timeout so every step is startable
                "CUTOFF_EPOCH": str(int(time.time()) + 7200),
                "MIXED_STEP": flag,
                # the per-cycle drills would hit the stub python too —
                # their loud-never-fatal banners are not under test here
                "ELASTIC_DRILL": "0", "LINT_CHECK": "0",
                "PATH": f"{shim_dir}:{os.environ['PATH']}",
            },
            timeout=120, capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stderr
        log_text = log.read_text()
        state_text = state.read_text()
        assert "queue drained" in log_text
        if flag == "1":
            assert "configMixed: python bench.py --config mixed" in log_text
            assert "configMixed PASS" in state_text
        else:
            assert "configMixed" not in log_text + state_text


def test_roofline_check_off_under_queue_hook_and_loud_never_fatal(tmp_path):
    """ISSUE 18: the per-cycle roofline drift gate is off by default and
    under the QUEUE_FILE hook (auto), skips silently on an empty ledger,
    and — forced on over a non-empty ledger with a failing check —
    banners the drift LOUDLY but never fails the cycle."""
    ledger = tmp_path / "perf_ledger.jsonl"
    ledger.write_text('{"perf_v": 1}\n')
    # default off / auto under QUEUE_FILE: no roofline banner
    proc, _, log = run_watch(
        tmp_path, ["one 30 echo ok-one"],
        extra_env={"PERF_LEDGER": str(ledger)},
    )
    assert proc.returncode == 0
    assert "roofline check" not in log
    proc_a, _, log_a = run_watch(
        tmp_path, ["oneauto 30 echo ok-one"], tag="rfauto",
        extra_env={"ROOFLINE_CHECK": "auto", "PERF_LEDGER": str(ledger)},
    )
    assert proc_a.returncode == 0
    assert "roofline check" not in log_a
    # forced on but the cycle produced no ledger yet: silent skip
    proc_e, _, log_e = run_watch(
        tmp_path, ["oneempty 30 echo ok-one"], tag="rfempty",
        extra_env={"ROOFLINE_CHECK": "1",
                   "PERF_LEDGER": str(tmp_path / "empty_ledger.jsonl")},
    )
    assert proc_e.returncode == 0
    assert "roofline check" not in log_e
    # forced on over a non-empty ledger with a python shim that fails the
    # drift gate: the banner appears and the cycle still completes
    shim_dir = tmp_path / "bin"
    shim_dir.mkdir()
    shim = shim_dir / "python"
    shim.write_text("#!/bin/sh\nexit 2\n")
    shim.chmod(0o755)
    proc2, _, log2 = run_watch(
        tmp_path, ["two 30 echo ok-two"], tag="rf",
        extra_env={"ROOFLINE_CHECK": "1", "PERF_LEDGER": str(ledger),
                   "PATH": f"{shim_dir}:{os.environ['PATH']}"},
    )
    assert proc2.returncode == 0, proc2.stderr
    assert "roofline check" in log2
    assert "ROOFLINE DRIFT" in log2
    assert "queue drained" in log2
