"""Progress rendering (SURVEY.md §5 "Metrics / logging": the reference's
``verbose=TRUE`` textual progress bar). Rendering is tested directly with
fake streams/clocks; wiring is tested through `module_preservation`."""

import io

import numpy as np
import pandas as pd

from netrep_tpu import module_preservation
from netrep_tpu.utils.config import EngineConfig
from netrep_tpu.utils.progress import make_progress_printer


class _Tty(io.StringIO):
    def isatty(self):
        return True


def test_non_tty_logs_decile_lines():
    out = io.StringIO()
    cb = make_progress_printer(stream=out)
    total = 100
    for done in range(10, 101, 10):
        cb(done, total)
    lines = out.getvalue().strip().splitlines()
    assert len(lines) == 10                       # one per decile
    assert lines[0].startswith("permutations: 10/100 (10%)")
    assert "100/100 (100%)" in lines[-1]
    # repeated calls within the same decile stay silent
    out2 = io.StringIO()
    cb2 = make_progress_printer(stream=out2)
    cb2(11, 100); cb2(12, 100); cb2(19, 100)
    assert len(out2.getvalue().strip().splitlines()) == 1


def test_tty_bar_throttles_and_finishes_with_newline():
    t = {"now": 0.0}
    clock = lambda: t["now"]
    out = _Tty()
    cb = make_progress_printer(stream=out, min_interval=0.5, _clock=clock)
    cb(1, 50)                  # first call renders
    cb(2, 50)                  # within min_interval: suppressed
    t["now"] = 1.0
    cb(10, 50)                 # renders with rate/ETA
    t["now"] = 2.0
    cb(50, 50)                 # finish: always renders, ends with newline
    s = out.getvalue()
    assert s.count("\r") == 3
    assert s.endswith("\n")
    assert "50/50" in s and "100.0%" in s
    assert "ETA" in s


def test_zero_total_does_not_divide():
    cb = make_progress_printer(stream=io.StringIO())
    cb(0, 0)  # no ZeroDivisionError


def test_verbose_installs_progress(capsys, caplog):
    import logging

    rng = np.random.default_rng(1)
    n, s = 30, 12
    z = rng.standard_normal((s, n))
    corr = np.corrcoef(z, rowvar=False)
    net = np.abs(corr) ** 2
    names = [f"g{i}" for i in range(n)]
    df = lambda m: pd.DataFrame(m, index=names, columns=names)
    with caplog.at_level(logging.INFO, logger="netrep_tpu"):
        res = module_preservation(
            network={"d": df(net), "t": df(net)},
            data={"d": pd.DataFrame(z, columns=names),
                  "t": pd.DataFrame(z, columns=names)},
            correlation={"d": df(corr), "t": df(corr)},
            module_assignments={nm: str(1 + i % 2) for i, nm in enumerate(names)},
            discovery="d", test="t", n_perm=32, seed=0, verbose=True,
            config=EngineConfig(chunk_size=16, summary_method="power",
                                power_iters=30),
        )
    assert res.completed == 32
    err = capsys.readouterr().err
    assert "permutations:" in err or "\r[" in err   # bar reached stderr
    assert any("2 modules" in r.message for r in caplog.records)
