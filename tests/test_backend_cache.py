"""The persistent compile cache must be keyed by host CPU features.

Round 4's MULTICHIP artifact tail was full of ``cpu_aot_loader`` errors:
AOT executables compiled on a machine with ``amx-fp16``/``avx10.1`` were
loaded on a host lacking them — "could lead to execution errors such as
SIGILL". The fix embeds a fingerprint of the host's instruction-set
features in the cache path so entries can never cross machines with
different feature sets (VERDICT r4 item 3).
"""

import jax

from netrep_tpu.utils import backend


def test_fingerprint_is_short_stable_hex():
    a, b = backend.host_cpu_fingerprint(), backend.host_cpu_fingerprint()
    assert a == b
    assert len(a) == 12
    int(a, 16)  # hex


def test_cache_dir_is_keyed_by_cpu_fingerprint():
    # conftest already called enable_persistent_cache; re-invoking is
    # idempotent and lets this test read the configured value directly
    backend.enable_persistent_cache()
    cache_dir = jax.config.jax_compilation_cache_dir
    assert cache_dir.endswith(backend.host_cpu_fingerprint())
    parent = cache_dir.rsplit("/", 2)[-2]
    assert parent == ".jax_cache"


def test_fingerprint_changes_with_feature_set(monkeypatch, tmp_path):
    # simulate a different host by redirecting /proc/cpuinfo
    real = backend.host_cpu_fingerprint()
    fake = tmp_path / "cpuinfo"
    fake.write_text("flags\t\t: fpu sse sse2 hypothetical-isa-ext\n")
    orig_open = open

    def fake_open(path, *a, **kw):
        if path == "/proc/cpuinfo":
            return orig_open(fake, *a, **kw)
        return orig_open(path, *a, **kw)

    monkeypatch.setattr("builtins.open", fake_open)
    assert backend.host_cpu_fingerprint() != real
