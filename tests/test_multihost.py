"""True 2-process multi-host test (VERDICT r2 "Next round" item 4): spawn
two JAX processes against a localhost coordination service, run a sharded
permutation null whose perm-axis shards live on BOTH processes' devices, and
assert every rank returns the identical full null — exercising
``gather_to_host``'s ``process_allgather`` branch, which single-process CI
can never reach (SURVEY.md §2.3 "DCN between hosts", §4 "multi-node without
a real cluster").
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_mh_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_null_identical(tmp_path):
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    outs = [str(tmp_path / f"null_rank{r}.npy") for r in range(2)]
    env = {
        **os.environ,
        # children configure their own platform/devices; scrub the parent's
        "JAX_PLATFORMS": "cpu",
        "JAX_NUM_CPU_DEVICES": "",
    }
    env.pop("JAX_NUM_CPU_DEVICES")
    procs = [
        subprocess.Popen(
            [
                sys.executable, WORKER,
                "--coordinator", coord,
                "--num-processes", "2",
                "--process-id", str(r),
                "--local-devices", "4",
                "--out", outs[r],
            ],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for r in range(2)
    ]
    logs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            logs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(
            "multi-host workers timed out (coordination or collective "
            f"hang). partial logs: {[p.stdout.read() if p.stdout else '' for p in procs]}"
        )
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"rank failed:\n{log[-4000:]}"

    a, b = (np.load(o) for o in outs)
    # both ranks hold the FULL null (process_allgather assembled the remote
    # shards) and they agree exactly
    assert a.shape == b.shape == (32, 2, 7)  # 4 perms x 8 global devices
    np.testing.assert_array_equal(a, b)
    assert np.isfinite(a).all()

    # cross-check against a fresh single-process run: the engine's
    # mesh-invariance contract (same key => same null, SURVEY.md §7 "RNG
    # semantics") must span process topologies too
    single = subprocess.run(
        [
            sys.executable, WORKER,
            "--coordinator", f"127.0.0.1:{_free_port()}",
            "--num-processes", "1",
            "--process-id", "0",
            "--local-devices", "8",
            "--out", str(tmp_path / "null_single.npy"),
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420,
    )
    assert single.returncode == 0, single.stdout + single.stderr
    s = np.load(tmp_path / "null_single.npy")
    np.testing.assert_allclose(a, s, atol=1e-4)
