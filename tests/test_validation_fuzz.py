"""Adversarial input-validation sweep (SURVEY.md §4: "input-validation tests
assert informative errors on malformed matrices"): every malformed variant of
a valid call must fail with ValueError/TypeError carrying a non-empty message
— never an IndexError/KeyError/opaque crash from deeper in the stack, and
never a silent success."""

import numpy as np
import pytest

from netrep_tpu import module_preservation


def _valid_kwargs(rng, n=24, s=10):
    z = rng.standard_normal((s, n))
    corr = np.corrcoef(z, rowvar=False)
    net = np.abs(corr) ** 2
    names = [f"g{i}" for i in range(n)]
    import pandas as pd

    df = lambda m: pd.DataFrame(m, index=names, columns=names)
    labels = {nm: str(1 + (i % 2)) for i, nm in enumerate(names)}
    return dict(
        network={"d": df(net), "t": df(net + 0.0)},
        data={"d": pd.DataFrame(z, columns=names),
              "t": pd.DataFrame(z, columns=names)},
        correlation={"d": df(corr), "t": df(corr)},
        module_assignments=labels,
        discovery="d", test="t", n_perm=8,
    )


def _mutations(rng, kw):
    """Yield (description, mutated-kwargs) pairs, each invalid in one way."""
    import copy

    import pandas as pd

    def clone():
        return copy.deepcopy(kw)

    m = clone()
    m["network"]["t"].iloc[0, 1] += 0.5  # breaks symmetry
    yield "asymmetric network", m

    m = clone()
    m["correlation"]["d"].iloc[2, 3] = np.nan
    m["correlation"]["d"].iloc[3, 2] = np.nan
    yield "NaN in correlation", m

    m = clone()
    m["data"]["t"] = m["data"]["t"].iloc[:, :-1]  # drops a column
    yield "data/network column mismatch", m

    m = clone()
    bad = m["network"]["d"].copy()
    bad.columns = [f"x{i}" for i in range(bad.shape[1])]
    bad.index = bad.columns
    m["network"]["d"] = bad
    yield "node names disagree across matrices", m

    m = clone()
    m["discovery"] = "nope"
    yield "unknown discovery name", m

    m = clone()
    m["modules"] = ["99"]
    yield "unknown module label", m

    m = clone()
    m["module_assignments"] = {k: v for k, v in list(kw["module_assignments"].items())[:-3]}
    yield "assignments missing nodes", m

    m = clone()
    m["module_assignments"] = "0"  # all-background scalar nonsense
    yield "assignments wrong type", m

    m = clone()
    m["network"]["t"] = pd.DataFrame(
        np.ones((3, 4)), index=list("abc"), columns=list("wxyz")
    )
    yield "non-square network", m

    m = clone()
    m["alternative"] = "both"
    yield "bad alternative", m

    m = clone()
    m["null"] = "everything"
    yield "bad null mode", m

    m = clone()
    m["network"] = None
    yield "missing network", m

    m = clone()
    dup = m["network"]["d"].copy()
    dup.columns = ["g0"] * dup.shape[1]
    dup.index = dup.columns
    m["network"]["d"] = dup
    yield "duplicate node names", m


def test_malformed_inputs_fail_informatively():
    rng = np.random.default_rng(0)
    kw = _valid_kwargs(rng)
    # sanity: the unmutated call succeeds
    res = module_preservation(**kw, seed=1)
    assert res.completed == 8

    failures = []
    for desc, mkw in _mutations(rng, kw):
        try:
            module_preservation(**mkw, seed=1)
        except (ValueError, TypeError) as e:
            if not str(e).strip():
                failures.append(f"{desc}: empty error message")
        except Exception as e:  # wrong exception class = leaked internal error
            failures.append(f"{desc}: {type(e).__name__}: {e}")
        else:
            failures.append(f"{desc}: silently succeeded")
    assert not failures, "\n".join(failures)
